"""MNMG weak-scaling benchmarks on the virtual 8-device CPU mesh —
the BASELINE.md config-5 shape ("MNMG brute-force kNN via comms
allreduce over a pod", reference std_comms.hpp:55 +
knn_brute_force_faiss.cuh:365) made measurable without pod hardware.

Methodology: rows-per-device held CONSTANT while the device count grows
1 -> 2 -> 4 -> 8 (weak scaling): perfect scaling = flat time per step.
Caveats on the virtual mesh: all "devices" share one host's cores, so
the curve conflates collective overhead with compute CONTENTION and
upper-bounds both; absolute numbers are XLA:CPU numbers. The
topology-portable artifact is the per-step collective-byte accounting
(payload shapes are identical on a pod, where the same program rides
ICI) plus the program structure itself, which the multichip dryrun
compiles and executes.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
          python -m bench.bench_mnmg
"""

import json
import os
import time

if __name__ == "__main__" and "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import numpy as np  # noqa: E402

if __name__ == "__main__":
    # only the CLI entry forces CPU; importing this module must not
    # silently retarget the host process's JAX platform
    jax.config.update("jax_platforms", "cpu")


def _bytes_gb(b):
    return round(b / 1e9, 4)


def bench_weak_scaling():
    from raft_tpu.cluster.kmeans import KMeansParams
    from raft_tpu.comms.comms import Comms
    from raft_tpu.comms.mnmg import mnmg_kmeans_fit, mnmg_knn
    from raft_tpu.comms.mnmg_ivf import (
        mnmg_ivf_pq_build, mnmg_ivf_pq_search,
    )
    from raft_tpu.comms.ring import ring_knn
    from raft_tpu.spatial.ann import IVFPQParams

    devs = jax.devices()
    rows_per_dev, d, k_clusters, nq, topk = 16_384, 64, 64, 512, 10
    rng = np.random.default_rng(0)

    for P in (1, 2, 4, 8):
        if P > len(devs):
            break
        comms = Comms(devices=devs[:P])
        n = rows_per_dev * P
        x = rng.standard_normal((n, d)).astype(np.float32)
        q = rng.standard_normal((nq, d)).astype(np.float32)

        # ---- kmeans: time/iter via two-program difference ------------
        def fit(iters):
            t0 = time.perf_counter()
            out = mnmg_kmeans_fit(
                comms, x,
                KMeansParams(n_clusters=k_clusters, max_iter=iters,
                             tol=0.0, seed=0, init="random"),
            )
            jax.block_until_ready(out.centroids)
            return time.perf_counter() - t0, int(out.n_iter)

        fit(2), fit(8)                       # compile both programs
        t2, i2 = fit(2)
        t8, i8 = fit(8)
        s_per_iter = max(t8 - t2, 1e-9) / max(i8 - i2, 1)
        # collective bytes per iteration: psum(sums (k,d) f32) +
        # psum(counts (k,)) + psum(residual) + reseed allgathers
        # ((P*k) + (P*k, d) f32). ring-allreduce wire bytes/device =
        # 2 * (P-1)/P * payload.
        payload = (k_clusters * d + k_clusters + 1) * 4 \
            + P * k_clusters * (d + 1) * 4
        wire = 2 * (P - 1) / max(P, 1) * payload
        print(json.dumps({
            "name": f"mnmg/kmeans_weak/P{P}",
            "rows_total": n,
            "s_per_iter": round(s_per_iter, 4),
            "iters_per_s": round(1.0 / s_per_iter, 2),
            "collective_gb_per_iter_per_dev": _bytes_gb(wire),
        }))

        # ---- kNN: index sharded, queries replicated ------------------
        def run_knn(fn, name):
            fn(comms, x, q, topk)            # compile
            t0 = time.perf_counter()
            dv, iv = fn(comms, x, q, topk)
            jax.block_until_ready(dv)
            dt = time.perf_counter() - t0
            print(json.dumps({
                "name": f"mnmg/{name}_weak/P{P}",
                "rows_total": n,
                "ms": round(dt * 1e3, 1),
                "qps": round(nq / dt, 1),
            }))

        run_knn(mnmg_knn, "knn_allgather")
        run_knn(ring_knn, "knn_ring")

        # ---- sharded IVF-PQ: lists shard, quantizers replicate -------
        idx = mnmg_ivf_pq_build(comms, x, IVFPQParams(
            n_lists=32, pq_dim=8, pq_bits=6, kmeans_n_iters=6, seed=0,
        ))

        def run_ivf(_c, _x, _q, _k):
            return mnmg_ivf_pq_search(
                _c, idx, _q, _k, n_probes=8, refine_ratio=4.0,
                qcap=nq,
            )

        run_knn(run_ivf, "ivf_pq_sharded")

        # ---- sharded IVF-Flat: exact scoring at list granularity -----
        from raft_tpu.comms.mnmg_ivf_flat import (
            mnmg_ivf_flat_build, mnmg_ivf_flat_search,
        )
        from raft_tpu.spatial.ann import IVFFlatParams

        fidx = mnmg_ivf_flat_build(
            comms, x, IVFFlatParams(n_lists=32, kmeans_n_iters=6, seed=0),
            metric="sqeuclidean",
        )

        def run_flat(_c, _x, _q, _k):
            return mnmg_ivf_flat_search(
                _c, fidx, _q, _k, n_probes=8, qcap=nq,
            )

        run_knn(run_flat, "ivf_flat_sharded")


def cross_host_row(n=131_072, d=64, nq=512, k=10, n_probes=8,
                   n_lists=64, chain=(2, 8), escalate=1):
    """The ISSUE 9 cross-host serving row: host-sim 2x4 (two 4-chip
    "hosts" over the dcn axis) vs flat 1x8 on IDENTICAL shards — e2e
    QPS of the fused program, the DCN byte model per query, and the
    standalone merge-tail latency of both structures, plus the
    whole-host die -> failover -> heal flip audited for zero retraces
    (docs/multihost.md "Bench methodology").

    On real multi-host hardware the dcn axis rides actual DCN; on one
    host (TPU v5e-8 or the 8-device virtual CPU mesh) it is host-SIM:
    the program structure, byte accounting, and retrace behavior are
    the topology-portable artifacts, while the e2e QPS delta
    upper-bounds the hierarchical tail's compute overhead (its DCN win
    cannot appear on a mesh with no slow link).
    """
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from bench.common import chained_dispatch_stats
    from raft_tpu.comms import (
        build_comms,
        build_comms_hierarchical,
        dcn_merge_accounting,
        host_rank_mask,
        mnmg_ivf_flat_build,
        place_index,
    )
    from raft_tpu.comms import mnmg_ivf_flat as flat_mod
    from raft_tpu.comms.mnmg_ivf import _merge_across_shards
    from raft_tpu.comms.multihost import hier_axes
    from raft_tpu.resilience import FailoverPlan, ReplicaPlacement
    from raft_tpu.spatial.ann import IVFFlatParams

    devs = jax.devices()
    if len(devs) < 8:
        return {"metric": "mnmg_cross_host", "error":
                f"needs 8 devices for the 2x4 host-sim, have {len(devs)}"}
    flat8 = build_comms(devs[:8])
    hier24 = build_comms_hierarchical(devs[:8], mesh_shape=(2, 4))

    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((nq, d)).astype(np.float32)
    fidx = mnmg_ivf_flat_build(
        flat8, x, IVFFlatParams(
            n_lists=n_lists, kmeans_n_iters=6, seed=0,
        ),
        metric="sqeuclidean",
    )
    # R=2 host-aware placement on the 2-level mesh (the whole-host
    # failover below needs a live copy per shard on the OTHER host)
    hidx = place_index(hier24, fidx, replication=2)
    placement = ReplicaPlacement.striped(
        8, 2, int(hidx.replica_offset), inner_size=4,
    )

    # ---- DCN byte model (asserted against in tests/test_multihost.py)
    # and the whole-host die -> failover -> heal audit: zero retraces,
    # coverage, bit-identity vs the healthy mesh (the ISSUE 9
    # acceptance flips). Both are DETERMINISTIC — they run before any
    # timing so a jitter-dominated QPS measurement cannot drop them
    # from the bench artifact.
    acc = dcn_merge_accounting(k, 2, 4, wire="bf16")
    created = []
    orig = flat_mod._cached_search

    def recording(*a, **kw):
        fn = orig(*a, **kw)
        created.append(fn)
        return fn

    flat_mod._cached_search = recording
    try:
        kw = dict(n_probes=n_probes, qcap=nq, wire="f32")
        healthy = flat_mod.mnmg_ivf_flat_search(
            hier24, hidx, q, k, shard_mask=True, **kw,
        )
        fn0 = created[0]
        size0 = fn0._cache_size()
        plan = FailoverPlan.from_host_health(placement, [1, 0])
        down = flat_mod.mnmg_ivf_flat_search(
            hier24, hidx, q, k, shard_mask=host_rank_mask([1, 0], 4),
            failover=plan, **kw,
        )
        healed = flat_mod.mnmg_ivf_flat_search(
            hier24, hidx, q, k, shard_mask=True, **kw,
        )
        retraces = (
            (fn0._cache_size() - size0)
            + sum(1 for f in created if f is not fn0)
        )
        coverage = float(np.asarray(down.coverage).min())
        bitident = bool(
            (np.asarray(down.ids) == np.asarray(healthy.ids)).all()
            and (np.asarray(healed.ids) == np.asarray(healthy.ids)).all()
        )
    finally:
        flat_mod._cached_search = orig

    audit = {
        "wire": "bf16",
        "dcn_bytes_per_query": acc["hier_bytes_per_query"],
        "flat_dcn_bytes_per_query": acc["flat_bytes_per_query"],
        "dcn_bytes_ratio": round(acc["ratio"], 2),
        "health_flip_retraces": retraces,
        "coverage_host_down": coverage,
        "host_down_bitident": bitident,
    }

    def run_flat(qq):
        return flat_mod.mnmg_ivf_flat_search(
            flat8, fidx, qq, k, n_probes=n_probes, qcap=nq,
        )

    def run_hier(qq):
        return flat_mod.mnmg_ivf_flat_search(
            hier24, hidx, qq, k, n_probes=n_probes, qcap=nq,
            wire="bf16",
        )

    def qps_of(run):
        jax.block_until_ready(run(q))            # compile + warm
        st = chained_dispatch_stats(
            lambda salt: q * (1.0 + 1e-6 * salt), run,
            n1=chain[0], n2=chain[1], escalate=escalate,
        )
        if st is None:
            return None, None
        return round(nq / (st["ms"] / 1e3), 1), st

    flat_qps, _ = qps_of(run_flat)
    hier_qps, hst = qps_of(run_hier)
    if hier_qps is None or flat_qps is None:
        return {"metric": "mnmg_cross_host", "error":
                "timing jitter-dominated", **audit}

    # ---- merge stage standalone: the tail each structure dispatches --
    # identical per-chip (nq, k) top-k payloads, sharded one part per
    # chip; the flat tail allgathers at deployment width, the
    # hierarchical one runs ICI merge + compressed DCN exchange
    pv = np.sort(
        rng.standard_normal((8, nq, k)).astype(np.float32), axis=-1,
    )
    pi = rng.integers(0, n, (8, nq, k)).astype(np.int32)

    def merge_fn(comms):
        ax = comms.device_comms()
        hier = hier_axes(comms.mesh, comms.axis)
        spec = P(comms.axis, None, None)

        def body(vals, gids):
            md, mi = _merge_across_shards(
                ax, hier, vals[0], gids[0], k, None, "bf16",
            )
            return md, mi

        return jax.jit(comms.shard_map(
            body, in_specs=(spec, spec), out_specs=(P(), P()),
        ))

    def merge_ms(comms):
        fn = merge_fn(comms)
        ids = jnp.asarray(pi)
        jax.block_until_ready(fn(jnp.asarray(pv), ids))
        st = chained_dispatch_stats(
            lambda salt: jnp.asarray(pv) * (1.0 + 1e-6 * salt),
            lambda vals: fn(vals, ids),
            n1=4, n2=16, escalate=escalate,
        )
        return None if st is None else round(st["ms"], 4)

    flat_merge_ms = merge_ms(flat8)
    hier_merge_ms = merge_ms(hier24)

    return {
        "metric": f"mnmg_cross_host_{n}x{d}_q{nq}_k{k}_hostsim_2x4",
        "value": hier_qps,
        "unit": "QPS",
        "spread": hst["spread"],
        "repeats": hst["repeats"],
        "escalations": hst.get("escalations", 0),
        "flat_e2e_qps": flat_qps,
        "qps_ratio_vs_flat": round(hier_qps / flat_qps, 3),
        "merge_ms_hier": hier_merge_ms,
        "merge_ms_flat": flat_merge_ms,
        **audit,
    }


def main():
    bench_weak_scaling()
    print(json.dumps(cross_host_row()))


if __name__ == "__main__":
    main()
