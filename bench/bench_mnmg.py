"""MNMG weak-scaling benchmarks on the virtual 8-device CPU mesh —
the BASELINE.md config-5 shape ("MNMG brute-force kNN via comms
allreduce over a pod", reference std_comms.hpp:55 +
knn_brute_force_faiss.cuh:365) made measurable without pod hardware.

Methodology: rows-per-device held CONSTANT while the device count grows
1 -> 2 -> 4 -> 8 (weak scaling): perfect scaling = flat time per step.
Caveats on the virtual mesh: all "devices" share one host's cores, so
the curve conflates collective overhead with compute CONTENTION and
upper-bounds both; absolute numbers are XLA:CPU numbers. The
topology-portable artifact is the per-step collective-byte accounting
(payload shapes are identical on a pod, where the same program rides
ICI) plus the program structure itself, which the multichip dryrun
compiles and executes.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
          python -m bench.bench_mnmg
"""

import json
import os
import time

if __name__ == "__main__" and "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import numpy as np  # noqa: E402

if __name__ == "__main__":
    # only the CLI entry forces CPU; importing this module must not
    # silently retarget the host process's JAX platform
    jax.config.update("jax_platforms", "cpu")


def _bytes_gb(b):
    return round(b / 1e9, 4)


def bench_weak_scaling():
    from raft_tpu.cluster.kmeans import KMeansParams
    from raft_tpu.comms.comms import Comms
    from raft_tpu.comms.mnmg import mnmg_kmeans_fit, mnmg_knn
    from raft_tpu.comms.mnmg_ivf import (
        mnmg_ivf_pq_build, mnmg_ivf_pq_search,
    )
    from raft_tpu.comms.ring import ring_knn
    from raft_tpu.spatial.ann import IVFPQParams

    devs = jax.devices()
    rows_per_dev, d, k_clusters, nq, topk = 16_384, 64, 64, 512, 10
    rng = np.random.default_rng(0)

    for P in (1, 2, 4, 8):
        if P > len(devs):
            break
        comms = Comms(devices=devs[:P])
        n = rows_per_dev * P
        x = rng.standard_normal((n, d)).astype(np.float32)
        q = rng.standard_normal((nq, d)).astype(np.float32)

        # ---- kmeans: time/iter via two-program difference ------------
        def fit(iters):
            t0 = time.perf_counter()
            out = mnmg_kmeans_fit(
                comms, x,
                KMeansParams(n_clusters=k_clusters, max_iter=iters,
                             tol=0.0, seed=0, init="random"),
            )
            jax.block_until_ready(out.centroids)
            return time.perf_counter() - t0, int(out.n_iter)

        fit(2), fit(8)                       # compile both programs
        t2, i2 = fit(2)
        t8, i8 = fit(8)
        s_per_iter = max(t8 - t2, 1e-9) / max(i8 - i2, 1)
        # collective bytes per iteration: psum(sums (k,d) f32) +
        # psum(counts (k,)) + psum(residual) + reseed allgathers
        # ((P*k) + (P*k, d) f32). ring-allreduce wire bytes/device =
        # 2 * (P-1)/P * payload.
        payload = (k_clusters * d + k_clusters + 1) * 4 \
            + P * k_clusters * (d + 1) * 4
        wire = 2 * (P - 1) / max(P, 1) * payload
        print(json.dumps({
            "name": f"mnmg/kmeans_weak/P{P}",
            "rows_total": n,
            "s_per_iter": round(s_per_iter, 4),
            "iters_per_s": round(1.0 / s_per_iter, 2),
            "collective_gb_per_iter_per_dev": _bytes_gb(wire),
        }))

        # ---- kNN: index sharded, queries replicated ------------------
        def run_knn(fn, name):
            fn(comms, x, q, topk)            # compile
            t0 = time.perf_counter()
            dv, iv = fn(comms, x, q, topk)
            jax.block_until_ready(dv)
            dt = time.perf_counter() - t0
            print(json.dumps({
                "name": f"mnmg/{name}_weak/P{P}",
                "rows_total": n,
                "ms": round(dt * 1e3, 1),
                "qps": round(nq / dt, 1),
            }))

        run_knn(mnmg_knn, "knn_allgather")
        run_knn(ring_knn, "knn_ring")

        # ---- sharded IVF-PQ: lists shard, quantizers replicate -------
        idx = mnmg_ivf_pq_build(comms, x, IVFPQParams(
            n_lists=32, pq_dim=8, pq_bits=6, kmeans_n_iters=6, seed=0,
        ))

        def run_ivf(_c, _x, _q, _k):
            return mnmg_ivf_pq_search(
                _c, idx, _q, _k, n_probes=8, refine_ratio=4.0,
                qcap=nq,
            )

        run_knn(run_ivf, "ivf_pq_sharded")

        # ---- sharded IVF-Flat: exact scoring at list granularity -----
        from raft_tpu.comms.mnmg_ivf_flat import (
            mnmg_ivf_flat_build, mnmg_ivf_flat_search,
        )
        from raft_tpu.spatial.ann import IVFFlatParams

        fidx = mnmg_ivf_flat_build(
            comms, x, IVFFlatParams(n_lists=32, kmeans_n_iters=6, seed=0),
            metric="sqeuclidean",
        )

        def run_flat(_c, _x, _q, _k):
            return mnmg_ivf_flat_search(
                _c, fidx, _q, _k, n_probes=8, qcap=nq,
            )

        run_knn(run_flat, "ivf_flat_sharded")


def main():
    bench_weak_scaling()


if __name__ == "__main__":
    main()
