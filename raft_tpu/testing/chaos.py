"""Deterministic scripted chaos schedules + declarative invariant
checkers — the harness that PROVES the self-healing supervisor
(:mod:`raft_tpu.resilience.supervisor`) rather than eyeballing it.

Three pieces compose:

- :class:`ChaosSchedule` — a seeded DSL of timed fault events built
  from the :mod:`raft_tpu.testing.faults` injectors: rank kill / heal
  (actuated through a :class:`ScriptedHealth` truth the supervisor's
  probe reads), oscillating probes, straggler windows
  (``inject_straggler`` behind a gate), torn checkpoint writes
  (``inject_partial_write``), and fetcher-thread crashes
  (``inject_worker_crash``). Events are offsets from run start, fired
  replay-style (never early, catch-up when late — the same discipline
  as ``testing/load.replay``), so a schedule is one reproducible
  artifact a soak can rerun verbatim (ROADMAP item 5).
- :class:`Invariant` checkers — declarative predicates sampled
  CONTINUOUSLY during the run, not asserted once at the end:
  :class:`AlwaysInvariant` (must hold at every sample),
  :class:`FinalInvariant` (must hold at drain), :class:`BoundInvariant`
  (a count must never exceed a bound — e.g. compiled-program cache
  growth == 0, route pushes ≤ confirmed transitions), and
  :class:`ConvergenceInvariant` (every trigger increment must be
  matched within a deadline — e.g. route converges within
  ``deadline_s`` of each confirmed down).
- :func:`run_schedule` — the loop that fires due events, samples every
  checker between them, and returns a :class:`ChaosReport` whose
  ``ok``/``violations`` the test asserts — the checker framework IS the
  assertion, not ad-hoc test code.

The kill−9 leg (docs/robustness.md "Durability") goes one step harder
than any in-process fault: :func:`run_crash_ingest_cycle` spawns a
REAL subprocess (:mod:`raft_tpu.testing.crash_child`) that ingests
through a :class:`~raft_tpu.durability.wal.WalWriter` and prints each
ack strictly after its fsync, SIGKILLs it mid-ingest at a seeded
point (no cleanup, no atexit, no flush — a power cut as seen from
this host), then repairs + rereads the WAL so the test can assert
zero acked records lost and zero torn frames applied. The
:meth:`ChaosSchedule.kill9` composer scripts the same kill inside a
timed schedule.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu import errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.testing import faults

__all__ = [
    "ChaosEvent",
    "ChaosSchedule",
    "ChaosReport",
    "ChaosViolation",
    "ScriptedHealth",
    "StragglerGate",
    "Invariant",
    "AlwaysInvariant",
    "FinalInvariant",
    "BoundInvariant",
    "ConvergenceInvariant",
    "inject_worker_crash",
    "run_crash_ingest_cycle",
    "run_schedule",
]


class ScriptedHealth:
    """The scripted per-rank truth a chaos run feeds the supervisor:
    ``probe`` is exactly the ``{rank: up}`` callable
    :class:`~raft_tpu.resilience.supervisor.ServingSupervisor` takes,
    and schedule events actuate :meth:`set` — so the supervisor under
    test sees a probe stream indistinguishable from a heartbeat sweep,
    with the script as ground truth (thread-safe: events fire on the
    runner thread while the supervisor polls on its own)."""

    def __init__(self, n_ranks: int):
        errors.expects(n_ranks >= 1,
                       "ScriptedHealth: n_ranks=%d < 1", n_ranks)
        self._lock = lockcheck.make_lock("ScriptedHealth._lock")
        self._up = np.ones(n_ranks, dtype=bool)

    @property
    def n_ranks(self) -> int:
        # immutable array metadata (see ShardHealth.n_ranks)
        return self._up.shape[0]  # jaxlint: disable=unguarded-shared-state

    def set(self, rank: int, up: bool) -> None:
        errors.expects(0 <= rank < self.n_ranks,
                       "ScriptedHealth: rank %d out of range", rank)
        with self._lock:
            self._up[rank] = bool(up)

    def probe(self) -> Dict[int, bool]:
        with self._lock:
            return {r: bool(u) for r, u in enumerate(self._up)}


class StragglerGate:
    """A schedulable straggler window around a dispatch function: while
    enabled, calls route through ``faults.inject_straggler`` (every
    ``every``-th result polls not-ready for ``seconds``); while
    disabled, the wrapped function is called directly. The gate is what
    a :class:`ChaosSchedule` toggles to script a straggler BURST with a
    start and an end."""

    def __init__(self, fn, *, every: int = 2, seconds: float = 0.02):
        self._fn = fn
        self._straggling, self.audit = faults.inject_straggler(
            fn, every=every, seconds=seconds
        )
        self._lock = lockcheck.make_lock("StragglerGate._lock")
        self._on = False

    def enable(self) -> None:
        with self._lock:
            self._on = True

    def disable(self) -> None:
        with self._lock:
            self._on = False

    def __call__(self, *args, **kwargs):
        with self._lock:
            on = self._on
        return (self._straggling if on else self._fn)(*args, **kwargs)


def inject_worker_crash(store, *, times: int = 1,
                        exc_type=RuntimeError) -> Callable[[], None]:
    """Arm the fetcher-thread crash fault: wrap ``store.apply_moves``
    so the next ``times`` promotion batches raise ``exc_type`` inside
    the :class:`~raft_tpu.tier.fetch.SlabFetcher` worker — the fault
    its bounded-restart policy (``tier_fetcher_restarts_total``) must
    absorb. Returns a ``restore()`` callable that disarms the fault."""
    errors.expects(times >= 1, "inject_worker_crash: times=%d < 1", times)
    original = store.apply_moves
    remaining = [int(times)]

    def crashing(moves, **kwargs):
        if remaining[0] > 0:
            remaining[0] -= 1
            raise exc_type("chaos: injected fetcher worker crash")
        return original(moves, **kwargs)

    store.apply_moves = crashing

    def restore() -> None:
        store.apply_moves = original

    return restore


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One timed fault: ``fire()`` runs at ``at_s`` seconds after the
    run starts (replay-style: never early, catch-up when late)."""

    at_s: float
    name: str
    fire: Callable[[], None]


class ChaosSchedule:
    """A seeded, composable script of timed fault events. ``seed``
    derandomizes the composers that need randomness (none currently
    draw, but the seed is part of the schedule's identity so a soak
    artifact names it); the rank-health composers actuate the
    ``scripted`` truth passed at construction."""

    def __init__(self, *, scripted: Optional[ScriptedHealth] = None,
                 seed: int = 0):
        self.scripted = scripted
        self.seed = int(seed)
        self._events: List[ChaosEvent] = []

    @property
    def events(self) -> List[ChaosEvent]:
        return sorted(self._events, key=lambda e: e.at_s)

    def at(self, at_s: float, name: str,
           fire: Callable[[], None]) -> "ChaosSchedule":
        """Add one raw event; returns ``self`` for chaining."""
        errors.expects(at_s >= 0.0, "ChaosSchedule: at_s=%s < 0", at_s)
        self._events.append(ChaosEvent(float(at_s), str(name), fire))
        return self

    def _need_scripted(self) -> ScriptedHealth:
        errors.expects(
            self.scripted is not None,
            "ChaosSchedule: rank-health events need scripted=ScriptedHealth",
        )
        return self.scripted

    def kill_rank(self, at_s: float, rank: int, *,
                  wreck: Optional[Callable[[], None]] = None
                  ) -> "ChaosSchedule":
        """Rank death at ``at_s``: the scripted probe starts reporting
        it down; ``wreck`` (optional) destroys its served state at the
        same instant (e.g. zero its slabs) so bit-identity checks PROVE
        the reroute rather than accidentally reading dead-rank data."""
        scripted = self._need_scripted()

        def fire() -> None:
            if wreck is not None:
                wreck()
            scripted.set(rank, False)

        return self.at(at_s, f"kill_rank_{rank}", fire)

    def heal_rank(self, at_s: float, rank: int) -> "ChaosSchedule":
        """The external heal signal at ``at_s``: the scripted probe
        starts reporting the rank up — reintegration is the
        SUPERVISOR's job from here, the schedule never calls recovery
        primitives itself."""
        scripted = self._need_scripted()
        return self.at(at_s, f"heal_rank_{rank}",
                       lambda: scripted.set(rank, True))

    def oscillate(self, at_s: float, rank: int, *, period_s: float,
                  duration_s: float) -> "ChaosSchedule":
        """An oscillating (flapping) probe: toggle the rank's scripted
        state every ``period_s`` for ``duration_s``, ending UP — the
        fault the monitor's debounce must absorb without route churn."""
        scripted = self._need_scripted()
        errors.expects(period_s > 0.0,
                       "ChaosSchedule.oscillate: period_s=%s <= 0",
                       period_s)
        n = max(1, int(round(duration_s / period_s)))
        for i in range(n):
            up = i % 2 == 1  # start by dropping, alternate
            self.at(at_s + i * period_s, f"oscillate_rank_{rank}",
                    (lambda u: lambda: scripted.set(rank, u))(up))
        return self.at(at_s + n * period_s, f"oscillate_rank_{rank}_end",
                       lambda: scripted.set(rank, True))

    def straggler_window(self, at_s: float, gate: StragglerGate, *,
                         duration_s: float) -> "ChaosSchedule":
        """A straggler burst: enable ``gate`` at ``at_s``, disable it
        ``duration_s`` later."""
        self.at(at_s, "straggler_on", gate.enable)
        return self.at(at_s + duration_s, "straggler_off", gate.disable)

    def torn_checkpoint(self, at_s: float, path, *,
                        mode: str = "truncate",
                        boundary: Optional[int] = None,
                        seed: Optional[int] = None) -> "ChaosSchedule":
        """Tear a checkpoint file at ``at_s`` (``faults.
        inject_partial_write``) — a heal that recovers from it must
        fail CRC-clean and roll back, not serve a half-written splice."""
        s = self.seed if seed is None else int(seed)
        return self.at(
            at_s, "torn_checkpoint",
            lambda: faults.inject_partial_write(
                path, mode=mode, boundary=boundary, seed=s
            ),
        )

    def crash_fetcher(self, at_s: float, store, *,
                      times: int = 1) -> "ChaosSchedule":
        """Arm ``times`` fetcher-worker crashes at ``at_s`` (see
        :func:`inject_worker_crash`; the fault disarms itself after
        ``times`` batches)."""
        return self.at(at_s, "crash_fetcher",
                       lambda: inject_worker_crash(store, times=times))

    def kill9(self, at_s: float, proc) -> "ChaosSchedule":
        """SIGKILL a subprocess at ``at_s`` — the whole-process crash
        the WAL's durable-ack contract is proven against.  The victim
        gets no cleanup, no ``atexit``, no final flush: exactly a
        power cut as seen from this host.  ``proc`` is any object with
        ``poll()``/``kill()`` (``subprocess.Popen``)."""
        def fire() -> None:
            if proc.poll() is None:
                proc.kill()
        return self.at(at_s, "kill9", fire)


# ----------------------------------------------------------------------
# invariant checkers


@dataclasses.dataclass(frozen=True)
class ChaosViolation:
    t_s: float
    invariant: str
    message: str


class Invariant:
    """Base checker: ``sample(t)`` runs at every runner tick, ``
    finish(t)`` once at drain; both append to ``violations``. Concrete
    checkers below cover the common shapes; subclass for bespoke ones."""

    def __init__(self, name: str):
        self.name = str(name)
        self.violations: List[ChaosViolation] = []

    def _fail(self, t_s: float, message: str) -> None:
        self.violations.append(
            ChaosViolation(float(t_s), self.name, str(message))
        )

    def sample(self, t_s: float) -> None:  # pragma: no cover - override
        pass

    def finish(self, t_s: float) -> None:  # pragma: no cover - override
        pass


class AlwaysInvariant(Invariant):
    """``predicate()`` must hold at EVERY sample (and at finish).
    ``detail`` (optional) is called on failure for the message."""

    def __init__(self, name: str, predicate: Callable[[], bool], *,
                 detail: Optional[Callable[[], str]] = None):
        super().__init__(name)
        self._predicate = predicate
        self._detail = detail

    def _check(self, t_s: float) -> None:
        if not self._predicate():
            self._fail(t_s, self._detail() if self._detail else "violated")

    def sample(self, t_s: float) -> None:
        self._check(t_s)

    def finish(self, t_s: float) -> None:
        self._check(t_s)


class FinalInvariant(Invariant):
    """``predicate()`` must hold once the run has drained — for checks
    that are only meaningful at quiescence (bit-identity vs the healthy
    mesh, zero acked writes lost)."""

    def __init__(self, name: str, predicate: Callable[[], bool], *,
                 detail: Optional[Callable[[], str]] = None):
        super().__init__(name)
        self._predicate = predicate
        self._detail = detail

    def finish(self, t_s: float) -> None:
        if not self._predicate():
            self._fail(t_s, self._detail() if self._detail else "violated")


class BoundInvariant(Invariant):
    """``value_fn()`` must never exceed ``bound`` — zero-retrace
    (compiled-cache growth ≤ 0) and the flap invariant (route pushes −
    confirmed transitions ≤ 0) are both this shape."""

    def __init__(self, name: str, value_fn: Callable[[], float],
                 bound: float):
        super().__init__(name)
        self._value_fn = value_fn
        self.bound = float(bound)

    def _check(self, t_s: float) -> None:
        v = float(self._value_fn())
        if v > self.bound:
            self._fail(t_s, f"value {v} > bound {self.bound}")

    def sample(self, t_s: float) -> None:
        self._check(t_s)

    def finish(self, t_s: float) -> None:
        self._check(t_s)


class ConvergenceInvariant(Invariant):
    """Every increment of ``trigger_fn()`` must be answered by
    ``done_fn()`` reaching at least that count within ``deadline_s`` —
    the route-convergence bound: trigger = confirmed transitions, done
    = route pushes, deadline = the supervisor's configured convergence
    budget."""

    def __init__(self, name: str, trigger_fn: Callable[[], int],
                 done_fn: Callable[[], int], deadline_s: float):
        super().__init__(name)
        self._trigger_fn = trigger_fn
        self._done_fn = done_fn
        self.deadline_s = float(deadline_s)
        self._pending: List[Tuple[int, float]] = []  # (count, t_seen)
        self._seen = 0

    def _check(self, t_s: float, *, draining: bool) -> None:
        trig = int(self._trigger_fn())
        while self._seen < trig:
            self._seen += 1
            self._pending.append((self._seen, t_s))
        done = int(self._done_fn())
        still = []
        for count, t_seen in self._pending:
            if done >= count:
                continue
            if draining or t_s - t_seen > self.deadline_s:
                self._fail(
                    t_s,
                    f"trigger #{count} (t={t_seen:.3f}s) unanswered "
                    f"after {t_s - t_seen:.3f}s (deadline "
                    f"{self.deadline_s}s)",
                )
            else:
                still.append((count, t_seen))
        self._pending = still

    def sample(self, t_s: float) -> None:
        self._check(t_s, draining=False)

    def finish(self, t_s: float) -> None:
        self._check(t_s, draining=True)


# ----------------------------------------------------------------------
# the runner


@dataclasses.dataclass(frozen=True)
class ChaosReport:
    """What :func:`run_schedule` returns: the fired event log, every
    checker violation, and the wall duration. ``ok`` is the single
    assertion a chaos test makes."""

    fired: Tuple[Tuple[float, str], ...]
    violations: Tuple[ChaosViolation, ...]
    duration_s: float

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> str:
        lines = [
            f"chaos: {len(self.fired)} events over "
            f"{self.duration_s:.3f}s, "
            f"{len(self.violations)} violation(s)"
        ]
        for v in self.violations:
            lines.append(f"  [{v.t_s:8.3f}s] {v.invariant}: {v.message}")
        return "\n".join(lines)


def run_schedule(schedule: ChaosSchedule, *, duration_s: float,
                 invariants: Sequence[Invariant] = (),
                 tick: Optional[Callable[[float], None]] = None,
                 check_interval_s: float = 0.005,
                 clock=time.monotonic, sleep=time.sleep) -> ChaosReport:
    """Fire the schedule's events at their offsets while sampling every
    invariant continuously; after the last event (and at least
    ``duration_s``), run the finish checks and return the report.

    ``tick(t_s)`` (optional) runs between samples — the hook a
    deterministic test uses to drive ``supervisor.step()`` and the load
    loop from the runner thread instead of background threads. Events
    fire replay-style: never early; when the runner falls behind, due
    events fire back-to-back in schedule order (offsets, not absolute
    times, so a paused host skews the whole script uniformly)."""
    errors.expects(duration_s > 0.0,
                   "run_schedule: duration_s=%s <= 0", duration_s)
    events = schedule.events
    end_s = max([duration_s] + [e.at_s for e in events])
    fired: List[Tuple[float, str]] = []
    t0 = clock()
    i = 0
    while True:
        now_s = clock() - t0
        while i < len(events) and events[i].at_s <= now_s:
            events[i].fire()
            fired.append((now_s, events[i].name))
            i += 1
        if tick is not None:
            tick(now_s)
        for inv in invariants:
            inv.sample(now_s)
        if i >= len(events) and now_s >= end_s:
            break
        # sleep to the earlier of: next event, next check tick
        next_at = events[i].at_s if i < len(events) else end_s
        sleep(max(0.0, min(check_interval_s, next_at - now_s)))
    final_s = clock() - t0
    for inv in invariants:
        inv.finish(final_s)
    violations = tuple(
        v for inv in invariants for v in inv.violations
    )
    return ChaosReport(fired=tuple(fired), violations=violations,
                       duration_s=final_s)


# ----------------------------------------------------------------------
# the kill-9 crash-ingest cycle


def run_crash_ingest_cycle(wal_dir, *, kill_after_acks: int,
                           n_records: int = 64, d: int = 8,
                           seed: int = 0, flush_ms: float = 1.0,
                           timeout_s: float = 120.0) -> Dict[str, object]:
    """One seeded point of the kill−9 chaos gate: crash a real ingest
    process mid-flight, recover, and report what survived.

    Spawns :mod:`raft_tpu.testing.crash_child` (a subprocess that
    appends ``n_records`` seeded single-row upserts through a
    :class:`~raft_tpu.durability.wal.WalWriter` and prints
    ``ACK <lsn> <id>`` strictly AFTER each record's fsync returned),
    SIGKILLs it the moment the ``kill_after_acks``-th ack is read,
    then repairs the torn WAL in THIS process and decodes every
    surviving record.

    Returns a dict the test asserts on:

    * ``acked`` — ``[(lsn, id), ...]`` the child proved durable before
      the kill; the contract is ``set(acked) <= set(recovered)``
      (zero acked writes lost).
    * ``recovered`` — ``[(lsn, id), ...]`` actually readable after
      repair.  May exceed ``acked`` (records fsynced between the last
      ack we read and the kill) but never ``submitted``; every entry
      decoded from a CRC-clean frame, so nothing half-applied.
    * ``frontier`` — highest contiguous durable LSN after repair.
    * ``submitted`` — ``n_records``; ``returncode`` — the child's
      (``-9`` when the kill landed, ``0`` if it finished first).

    If ``kill_after_acks >= n_records`` the child simply completes —
    the zero-fault leg of the same gate.
    """
    errors.expects(kill_after_acks >= 1,
                   "run_crash_ingest_cycle: kill_after_acks=%s < 1",
                   kill_after_acks)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, "-m", "raft_tpu.testing.crash_child",
           str(wal_dir), str(int(n_records)), str(int(d)),
           str(int(seed)), str(float(flush_ms))]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True,
                            env=env)
    watchdog = threading.Timer(timeout_s, proc.kill)
    watchdog.daemon = True
    watchdog.start()
    acked: List[Tuple[int, int]] = []
    try:
        assert proc.stdout is not None
        for line in proc.stdout:
            parts = line.split()
            if len(parts) != 3 or parts[0] != "ACK":
                continue
            acked.append((int(parts[1]), int(parts[2])))
            if len(acked) >= kill_after_acks:
                proc.kill()   # SIGKILL: no cleanup, no flush
                break
        proc.wait(timeout=timeout_s)
    finally:
        watchdog.cancel()
        if proc.poll() is None:  # pragma: no cover - watchdog race
            proc.kill()
            proc.wait(timeout=10.0)
    from raft_tpu.durability import wal as _wal
    records, frontier = _wal.repair_wal(wal_dir, name="crash-cycle")
    recovered: List[Tuple[int, int]] = []
    for r in records:
        if r.op == _wal.OP_UPSERT:
            _vecs, ids = _wal.decode_upsert(r.payload)
            for gid in ids:
                recovered.append((int(r.lsn), int(gid)))
    return {
        "acked": acked,
        "recovered": recovered,
        "frontier": int(frontier),
        "submitted": int(n_records),
        "returncode": proc.returncode,
    }
