"""Deterministic fault injection for the resilience layer.

Chaos engineering that replays: every injector here is seeded or
fully specified, so a failing chaos run (tests/test_resilience.py)
reproduces bit-for-bit. The injectors cover the serving failure model
(docs/robustness.md):

* :func:`inject_delay` — a straggler/slow-chip surrogate: a jitted
  program whose completion is delayed host-side, with trace/dispatch
  audit counters (proves a retry re-dispatches without recompiling);
* :func:`inject_nonfinite` — poison query rows with NaN/Inf;
* :func:`corrupt_bytes` — silent checkpoint corruption: flips payload
  bytes inside a ``.npz`` and REWRITES the archive so the zip container
  stays self-consistent — only the format-v2 CRC32 manifest can catch
  it (``load_index`` → ``CorruptIndexError``);
* :func:`inject_partial_write` — a partial delta-checkpoint flush
  (torn-write truncation or a duplicated/stale block) at a chosen
  member boundary, or (``at_byte=``) a raw tear at an ARBITRARY byte
  offset of any file — the mutation tier's mid-ingest crash model and
  the WAL torn-tail fuzz's cutter (docs/mutation.md,
  docs/robustness.md "Durability");
* :func:`cancel_after` — arm a delayed cross-thread cancel against an
  in-flight ``Interruptible.synchronize``;
* :func:`fail_rank` — mark shard(s) down on a
  :class:`~raft_tpu.resilience.health.ShardHealth` (the degraded-search
  mask).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import zipfile
from typing import Optional, Sequence, Union

import jax
import numpy as np

from raft_tpu import compat, errors
from raft_tpu.core.interruptible import Interruptible
from raft_tpu.resilience.health import ShardHealth

__all__ = [
    "FaultAudit",
    "DelayedReady",
    "inject_delay",
    "inject_straggler",
    "inject_nonfinite",
    "corrupt_bytes",
    "inject_partial_write",
    "cancel_after",
    "fail_rank",
]


@dataclasses.dataclass
class FaultAudit:
    """Audit counters for an injected-fault program: ``traces`` counts
    jit traces (== compiles per shape), ``dispatches`` counts actual
    program EXECUTIONS (a host callback inside the program — proof each
    attempt really re-ran rather than read a cached value), ``calls``
    counts invocations of the wrapper. A deadline-retry that reuses the
    compiled program shows ``traces == 1, dispatches == attempts``."""

    traces: int = 0
    dispatches: int = 0
    calls: int = 0


class DelayedReady:
    """A straggler surrogate compatible with the readiness polling of
    ``Interruptible.synchronize`` (which walks tree leaves and polls
    ``is_ready()``): wraps a dispatched value and reports it not-ready
    until a host-side deadline, even after the real dispatch finished.

    Exists because CPU JAX runs jitted host callbacks synchronously at
    dispatch — a callback SLEEP would block the caller, never producing
    the dispatched-but-not-ready state a deadline must catch. Gating
    ``is_ready()`` on the host clock instead models the slow chip
    deterministically and load-independently (chaos runs replay).
    """

    def __init__(self, value, ready_at: float):
        self.value = value
        self._ready_at = ready_at

    def is_ready(self) -> bool:
        under = getattr(self.value, "is_ready", None)
        return time.monotonic() >= self._ready_at and (
            under is None or under()
        )

    def block_until_ready(self):
        time.sleep(max(0.0, self._ready_at - time.monotonic()))
        if hasattr(self.value, "block_until_ready"):
            self.value.block_until_ready()
        return self

    def __array__(self, dtype=None):
        import numpy as _np

        return _np.asarray(self.value, dtype=dtype)


def inject_delay(seconds: float, *, first_n: Optional[int] = None):
    """A slow-kernel surrogate: returns ``(fn, audit)`` where ``fn(x)``
    dispatches a jitted identity over ``x`` (audited via an in-program
    host callback) and returns a :class:`DelayedReady` that polls
    not-ready for ``seconds`` — exactly the shape
    ``Interruptible.synchronize``/``dispatch_with_deadline`` wait on, so
    a deadline expires against it like against a straggling chip.

    ``first_n``: only the first N calls are slow (a transient straggler
    — the retry-succeeds scenario); None = always slow. ``audit``
    counts traces/dispatches for the retry-without-recompile proof.
    """
    errors.expects(seconds >= 0, "inject_delay: seconds=%s < 0", seconds)
    audit = FaultAudit()

    def _count(x):
        audit.dispatches += 1
        return x

    @jax.jit
    def ident(x):
        audit.traces += 1
        return compat.pure_callback(
            _count, jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )

    def fn(x):
        audit.calls += 1
        slow = first_n is None or audit.calls <= first_n
        return DelayedReady(
            ident(x),
            time.monotonic() + (seconds if slow else 0.0),
        )

    return fn, audit


def inject_straggler(fn, *, every: int, seconds: float):
    """Wrap an arbitrary dispatch function so every ``every``-th call's
    result polls not-ready for ``seconds`` past dispatch — the periodic
    slow-chip surrogate for tail-latency experiments (the hedging bench
    drives its p99 measurement through this; ``dispatch_hedged``'s
    winner is deterministic against it because the straggle schedule is
    exactly periodic, not sampled).

    Unlike :func:`inject_delay` (which wraps its own audited identity
    program), this wraps the caller's real ``fn`` — the returned value
    is ``fn``'s output, wrapped in a :class:`DelayedReady` on straggling
    calls. Returns ``(wrapped, audit)``; ``audit.calls`` counts
    invocations (``audit.dispatches`` counts the straggled ones)."""
    errors.expects(every >= 1, "inject_straggler: every=%d < 1", every)
    errors.expects(
        seconds >= 0, "inject_straggler: seconds=%s < 0", seconds
    )
    audit = FaultAudit()

    def wrapped(*args, **kwargs):
        audit.calls += 1
        out = fn(*args, **kwargs)
        if audit.calls % every == 0:
            audit.dispatches += 1
            return DelayedReady(out, time.monotonic() + seconds)
        return out

    return wrapped, audit


def inject_nonfinite(x, rows: Sequence[int], *,
                     kind: str = "nan") -> np.ndarray:
    """Return a float copy of ``x`` with the given rows poisoned
    (``kind`` ∈ {"nan", "inf", "-inf"}) — the bad-input batch the
    serving entry must neutralize (``shard_mask=`` searches report such
    rows via ``row_valid``)."""
    vals = {"nan": np.nan, "inf": np.inf, "-inf": -np.inf}
    errors.expects(
        kind in vals, "inject_nonfinite: kind=%r not in %s",
        kind, sorted(vals),
    )
    arr = np.array(x, dtype=np.float32, copy=True)
    idx = np.asarray(list(rows), dtype=np.int64)
    errors.expects(
        idx.size == 0 or (0 <= idx.min() and idx.max() < arr.shape[0]),
        "inject_nonfinite: rows out of range [0, %d)", arr.shape[0],
    )
    arr[idx] = vals[kind]
    return arr


def corrupt_bytes(path, *, field: Optional[str] = None, n_bytes: int = 1,
                  seed: int = 0, skip_header_bytes: int = 128) -> str:
    """Silently corrupt a saved index checkpoint (``.npz``) in place.

    Flips ``n_bytes`` bytes (XOR 0xFF) inside one array member's DATA
    region — past the first ``skip_header_bytes`` so the ``.npy``
    dtype/shape header still parses — then rewrites the archive, which
    refreshes the zip container's own CRCs to match the damaged payload.
    The result models bit-rot beneath the container's checksums (a torn
    write, a bad DMA): only ``load_index``'s format-v2 per-array CRC32
    manifest can detect it, raising
    :class:`raft_tpu.errors.CorruptIndexError` naming the field.

    ``field``: the header-relative array key to damage (e.g.
    ``"sorted_ids"``); default picks one deterministically from
    ``seed``. Byte positions are drawn from ``seed``. Returns the
    damaged field name.
    """
    errors.expects(n_bytes >= 1, "corrupt_bytes: n_bytes=%d < 1", n_bytes)
    rng = np.random.default_rng(seed)
    with zipfile.ZipFile(path) as z:
        names = z.namelist()
        payload = {n: z.read(n) for n in names}
    candidates = sorted(n for n in names if n != "__header__.npy")
    errors.expects(
        bool(candidates), "corrupt_bytes: %s holds no array members", path
    )
    if field is None:
        target = candidates[int(rng.integers(len(candidates)))]
    else:
        target = field if field.endswith(".npy") else field + ".npy"
        errors.expects(
            target in payload,
            "corrupt_bytes: field %r not in archive (members: %s)",
            field, candidates,
        )
    buf = bytearray(payload[target])
    lo = min(skip_header_bytes, max(0, len(buf) - 1))
    errors.expects(
        len(buf) > lo,
        "corrupt_bytes: member %r too small (%d bytes) to damage past "
        "its header", target, len(buf),
    )
    positions = lo + rng.choice(
        len(buf) - lo, size=min(n_bytes, len(buf) - lo), replace=False
    )
    for p in positions:
        buf[int(p)] ^= 0xFF
    payload[target] = bytes(buf)
    # rewrite uncompressed, same member order: zipfile recomputes the
    # container CRCs, leaving a self-consistent archive whose bytes
    # disagree with the v2 integrity manifest
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as z:
        for n in names:
            z.writestr(n, payload[n])
    return target[:-len(".npy")]


def inject_partial_write(path, *, mode: str = "truncate",
                         boundary: Optional[int] = None,
                         at_byte: Optional[int] = None,
                         seed: int = 0) -> str:
    """Model a PARTIAL flush of a delta-segment checkpoint
    (:func:`raft_tpu.spatial.ann.mutation.save_delta_checkpoint`) — the
    mid-ingest crash the mutation tier's recovery story must survive
    (docs/mutation.md "Checkpoint v4"):

    * ``mode="truncate"`` — a torn write: the file ends at the
      ``boundary``-th archive member's header offset plus half its
      stored bytes (headers before the boundary still parse; the zip
      central directory is gone). ``load``/``apply`` must fail with
      :class:`raft_tpu.errors.CorruptIndexError`, never half-apply.
    * ``mode="duplicate"`` — a doubled/stale block write: the
      ``boundary``-th array member's payload is overwritten with the
      PREVIOUS member's bytes and the archive rewritten self-consistent
      (container CRCs match the damage) — only the v4 per-array CRC32
      manifest can catch it.

    ``boundary`` indexes the non-header members in archive order
    (default: the middle member, deterministic from ``seed`` when the
    archive has one candidate pair). Returns the damaged member name
    (without ``.npy``).

    ``at_byte`` (``mode="truncate"`` only) tears the RAW file at an
    ARBITRARY byte offset instead of a member boundary — no container
    parsing at all, so it works on any format (the WAL torn-tail fuzz
    cuts a segment log at EVERY offset, docs/robustness.md
    "Durability"). Returns the file's basename in that case.
    """
    errors.expects(
        mode in ("truncate", "duplicate"),
        "inject_partial_write: mode=%r not in ('truncate', 'duplicate')",
        mode,
    )
    if at_byte is not None:
        errors.expects(
            mode == "truncate",
            "inject_partial_write: at_byte requires mode='truncate', "
            "got %r", mode,
        )
        size = os.path.getsize(path)
        errors.expects(
            0 <= at_byte <= size,
            "inject_partial_write: at_byte=%d outside [0, %d]",
            at_byte, size,
        )
        with open(path, "rb+") as f:
            f.truncate(int(at_byte))
        return os.path.basename(path)
    with zipfile.ZipFile(path) as z:
        infos = z.infolist()
        payload = {i.filename: z.read(i.filename) for i in infos}
    members = [
        i.filename for i in infos if i.filename != "__header__.npy"
    ]
    errors.expects(
        bool(members),
        "inject_partial_write: %s holds no array members", path,
    )
    rng = np.random.default_rng(seed)
    if boundary is None:
        boundary = len(members) // 2 if len(members) > 1 else 0
    errors.expects(
        0 <= boundary < len(members),
        "inject_partial_write: boundary=%d out of range [0, %d)",
        boundary, len(members),
    )
    target = members[boundary]
    if mode == "duplicate":
        src = members[boundary - 1] if boundary > 0 else members[
            min(boundary + 1, len(members) - 1)
        ]
        if src == target and len(members) == 1:
            # single member: stale payload is a shuffled copy of itself
            buf = bytearray(payload[target])
            pos = 128 + rng.choice(max(len(buf) - 128, 1), size=1)[0]
            buf[int(pos)] ^= 0xFF
            payload[target] = bytes(buf)
        else:
            payload[target] = payload[src]
        with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as z:
            for i in infos:
                z.writestr(i.filename, payload[i.filename])
        return target[:-len(".npy")]
    # torn write: rewrite uncompressed, then cut the FILE at the target
    # member's data midpoint — everything after (later members, central
    # directory) is simply gone
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as z:
        for i in infos:
            z.writestr(i.filename, payload[i.filename])
    with zipfile.ZipFile(path) as z:
        info = next(i for i in z.infolist() if i.filename == target)
        cut = info.header_offset + max(
            1, (len(info.filename) + 30 + info.file_size) // 2
        )
    with open(path, "rb+") as f:
        f.truncate(cut)
    return target[:-len(".npy")]


def cancel_after(seconds: float, *,
                 thread_id: Optional[int] = None) -> threading.Timer:
    """Arm a delayed cross-thread cancel: after ``seconds``, the target
    thread's :class:`Interruptible` token is cancelled, breaking an
    in-flight ``synchronize`` with ``InterruptedException`` (the
    dispatched work still completes — cooperative semantics). Defaults
    to the CALLING thread. Returns the started ``threading.Timer``
    (``.cancel()`` it to disarm)."""
    tid = threading.get_ident() if thread_id is None else thread_id
    t = threading.Timer(seconds, Interruptible.cancel_thread, args=(tid,))
    t.daemon = True
    t.start()
    return t


def fail_rank(health: Union[ShardHealth, int], *ranks: int) -> ShardHealth:
    """Mark shard(s) down. ``health`` is an existing
    :class:`ShardHealth` (mutated in place) or a mesh size (a fresh
    tracker is created). Returns the tracker — pass it (or its
    ``mask()``) as the sharded searches' ``shard_mask=``."""
    h = health if isinstance(health, ShardHealth) else ShardHealth(health)
    for r in ranks:
        h.mark_down(r)
    return h
