"""Scripted ingest victim for the kill−9 chaos leg.

Run as ``python -m raft_tpu.testing.crash_child <wal_dir> <n> <d>
<seed> <flush_ms>`` (the parent is :func:`raft_tpu.testing.chaos.
run_crash_ingest_cycle`).  Appends ``n`` seeded single-row upsert
records through a real :class:`~raft_tpu.durability.wal.WalWriter`
and prints ``ACK <lsn> <id>`` — flushed, one per line — STRICTLY
after ``ack.wait()`` returned, i.e. after the record's fsync.  The
parent SIGKILLs this process mid-loop, so an ack line on its stdout
is a durability claim the recovered WAL must honour: that is the
entire point of the script.  Record ids are ``100000 + k`` so the
parent can map acks back to submissions without sharing state.

Imports nothing from JAX at module scope and journals host-side only
(the WAL path compiles nothing), so the child starts in well under a
second even on a cold cache.
"""

from __future__ import annotations

import sys

__all__ = ["main"]


def main(argv=None) -> int:
    args = sys.argv[1:] if argv is None else list(argv)
    if len(args) != 5:
        print("usage: crash_child <wal_dir> <n> <d> <seed> <flush_ms>",
              file=sys.stderr)
        return 64
    wal_dir = args[0]
    n, d, seed = int(args[1]), int(args[2]), int(args[3])
    flush_ms = float(args[4])

    import numpy as np

    from raft_tpu.durability import wal

    rng = np.random.default_rng(seed)
    writer = wal.WalWriter(wal_dir, flush_interval_s=flush_ms / 1e3,
                           name="crash-child")
    for k in range(n):
        vec = rng.standard_normal((1, d)).astype(np.float32)
        gid = 100000 + k
        payload = wal.encode_upsert(vec, np.asarray([gid], np.int32))
        ack = writer.append(wal.OP_UPSERT, payload, epoch=k)
        if not ack.wait(30.0):
            return 2   # fsync wedged: never claim durability
        print(f"ACK {ack.lsn} {gid}", flush=True)
    writer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
