"""Deterministic open-loop load generation for the serving executor.

Closed-loop measurement (dispatch, wait, dispatch) can never see the
failure mode production serving actually has: arrivals do not wait for
the server. An OPEN-LOOP generator fires requests on a schedule drawn
from the offered load regardless of completions, so queueing delay,
admission shedding, and the latency/throughput curve near saturation
become measurable (docs/serving.md "Open-loop serving"; the classic
closed-vs-open distinction — a closed loop at rate R self-throttles the
moment latency grows, hiding exactly the regime the p99 lives in).

Everything is SEEDED: a schedule is a pure function of
``(rate, n, seed)``, so a bench row or chaos test replays its arrival
process bit-for-bit (the same discipline as
:mod:`raft_tpu.testing.faults`).

* :func:`poisson_arrivals` — exponential inter-arrival gaps at the
  offered rate (memoryless arrivals — the standard open-loop traffic
  model), optional per-request size mix, optional ZIPF-skewed
  repeated-query mix (``zipf_s``/``n_templates``: each request draws a
  template id from a power-law over a query-template pool — the
  million-user hot-traffic shape the result cache and coalescer are
  built for, ISSUE 15 / docs/serving.md "Hot traffic");
* :class:`ArrivalSchedule` — the materialized schedule (offsets +
  per-request row counts + optional per-request template ids);
* :func:`replay` — fire ``submit(i, size)`` at each scheduled instant
  against the wall clock, NEVER waiting on results; when the generator
  falls behind (a stalled submit path) it fires immediately and
  records the lag rather than silently re-shaping the offered load.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu import errors

__all__ = ["ArrivalSchedule", "poisson_arrivals", "replay",
           "zipf_template_weights"]


@dataclasses.dataclass(frozen=True)
class ArrivalSchedule:
    """A deterministic open-loop arrival schedule.

    ``times_s`` are non-decreasing offsets from the replay start;
    ``sizes`` is the per-request query-row count (the executor packs
    them into shape buckets regardless — sizes model the client mix,
    not the dispatch shape). ``template_ids`` (optional) is the
    per-request QUERY-TEMPLATE id of a repeated-query mix
    (``poisson_arrivals(zipf_s=...)``): the driver maps each id to a
    fixed query vector from its template pool, so a Zipf-hot template
    re-arrives as the bitwise-identical query — exactly what the
    result cache's exact tier and the coalescer key on."""

    times_s: np.ndarray   # (n,) float64, non-decreasing, >= 0
    sizes: np.ndarray     # (n,) int64, >= 1
    template_ids: Optional[np.ndarray] = None   # (n,) int64, >= 0

    def __post_init__(self):
        errors.expects(
            self.times_s.ndim == 1 and self.sizes.shape ==
            self.times_s.shape,
            "ArrivalSchedule: times %s and sizes %s must be equal-length "
            "1-d", self.times_s.shape, self.sizes.shape,
        )
        errors.expects(
            self.times_s.size == 0 or (
                float(self.times_s[0]) >= 0.0
                and bool((np.diff(self.times_s) >= 0).all())
            ),
            "ArrivalSchedule: times must be non-decreasing and >= 0",
        )
        errors.expects(
            self.times_s.size == 0 or int(self.sizes.min()) >= 1,
            "ArrivalSchedule: sizes must be >= 1",
        )
        if self.template_ids is not None:
            errors.expects(
                self.template_ids.shape == self.times_s.shape,
                "ArrivalSchedule: template_ids %s must match times %s",
                self.template_ids.shape, self.times_s.shape,
            )
            errors.expects(
                self.template_ids.size == 0
                or int(self.template_ids.min()) >= 0,
                "ArrivalSchedule: template_ids must be >= 0",
            )

    @property
    def n_requests(self) -> int:
        return int(self.times_s.size)

    @property
    def n_rows(self) -> int:
        return int(self.sizes.sum())

    @property
    def duration_s(self) -> float:
        return float(self.times_s[-1]) if self.times_s.size else 0.0

    @property
    def offered_qps(self) -> float:
        """Offered load in query ROWS per second over the schedule span
        (the serving throughput unit: a size-8 request is 8 queries)."""
        span = self.duration_s
        return self.n_rows / span if span > 0 else float("inf")


def zipf_template_weights(n_templates: int, zipf_s: float) -> np.ndarray:
    """The normalized Zipf(``s``) popularity law over a template pool:
    ``p(rank i) ∝ (i + 1)^-s``. At s≈1.1 (the classic web-traffic
    skew) a few head templates carry most of the offered load — the
    regime where the result cache's hit rate comes from."""
    errors.expects(n_templates >= 1,
                   "zipf_template_weights: n_templates=%d < 1",
                   n_templates)
    errors.expects(zipf_s >= 0.0,
                   "zipf_template_weights: zipf_s=%s < 0 is not a "
                   "popularity skew", zipf_s)
    w = (np.arange(1, n_templates + 1, dtype=np.float64)
         ** -float(zipf_s))
    return w / w.sum()


def poisson_arrivals(rate_rps: float, n_requests: int, *, seed: int,
                     sizes: "int | Sequence[int]" = 1,
                     size_weights: Optional[Sequence[float]] = None,
                     zipf_s: Optional[float] = None,
                     n_templates: int = 0,
                     ) -> ArrivalSchedule:
    """A seeded Poisson arrival schedule: ``n_requests`` arrivals whose
    inter-arrival gaps are iid Exponential(``rate_rps``) — ``rate_rps``
    is REQUESTS per second (multiply by the mean size for rows/s).

    ``sizes``: a constant per-request row count, or a sequence to
    sample from (optionally ``size_weights``-weighted) — the client
    mix.

    ``zipf_s`` (with ``n_templates``): the REPEATED-QUERY mix
    (ISSUE 15) — each request additionally draws a template id from
    :func:`zipf_template_weights` over a pool of ``n_templates`` query
    templates, landed in ``template_ids``. The driver maps ids to
    fixed query vectors, so hot templates recur bitwise-identically —
    realistic Zipf-skewed traffic for the result cache / coalescing
    bench (``zipf_hot_traffic``).

    Fully deterministic in ``(rate_rps, n_requests, seed, sizes,
    size_weights, zipf_s, n_templates)`` — the template draw happens
    AFTER the gap and size draws on the same stream, so adding the mix
    never perturbs an existing schedule's times or sizes.
    """
    errors.expects(rate_rps > 0, "poisson_arrivals: rate_rps=%s <= 0",
                   rate_rps)
    errors.expects(n_requests >= 1,
                   "poisson_arrivals: n_requests=%d < 1", n_requests)
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / float(rate_rps), size=n_requests)
    times = np.cumsum(gaps)
    times -= times[0]                       # first arrival at t=0
    if isinstance(sizes, (int, np.integer)):
        sz = np.full(n_requests, int(sizes), np.int64)
    else:
        choices = np.asarray(list(sizes), np.int64)
        p = None
        if size_weights is not None:
            p = np.asarray(list(size_weights), np.float64)
            p = p / p.sum()
        sz = rng.choice(choices, size=n_requests, p=p)
    tmpl = None
    if zipf_s is not None:
        errors.expects(
            n_templates >= 1,
            "poisson_arrivals: zipf_s=%s needs n_templates >= 1 (the "
            "query-template pool the skew is drawn over)", zipf_s,
        )
        w = zipf_template_weights(n_templates, zipf_s)
        tmpl = rng.choice(np.arange(n_templates, dtype=np.int64),
                          size=n_requests, p=w)
    return ArrivalSchedule(times_s=times, sizes=sz, template_ids=tmpl)


def replay(schedule: ArrivalSchedule,
           submit: Callable[[int, int], object], *,
           clock: Callable[[], float] = time.monotonic,
           sleep: Callable[[float], None] = time.sleep,
           ) -> Tuple[List[object], np.ndarray, float]:
    """Drive ``submit(i, size)`` open-loop against the wall clock.

    Each call fires at its scheduled offset from the replay start; the
    loop NEVER waits on what ``submit`` returned (completions are the
    server's problem — that is the open loop). If the previous submit
    call itself ran long, the next one fires immediately — offered
    load is the schedule's, not the server's, and ``max_lag_s``
    reports how far the generator fell behind (a lag comparable to the
    inter-arrival gap means the measured rate is submit-bound, not
    schedule-bound).

    Returns ``(results, t_submit, max_lag_s)``: per-request submit
    return values (futures, or the exception instance when ``submit``
    raised — an admission shed is DATA in an open-loop run, not a
    failure), per-request actual submit stamps on ``clock``, and the
    worst scheduling lag.
    """
    results: List[object] = []
    stamps = np.zeros(schedule.n_requests, np.float64)
    max_lag = 0.0
    t0 = clock()
    for i in range(schedule.n_requests):
        target = t0 + float(schedule.times_s[i])
        now = clock()
        if now < target:
            sleep(target - now)
            now = clock()
        max_lag = max(max_lag, now - target)
        stamps[i] = now
        try:
            results.append(submit(i, int(schedule.sizes[i])))
        except Exception as exc:   # noqa: BLE001 — sheds are data here
            results.append(exc)
    return results, stamps, max_lag
