"""Testing utilities shipped with the library — deterministic fault
injection (:mod:`raft_tpu.testing.faults`) for exercising the resilience
layer (``raft_tpu.resilience``) without hardware faults, the seeded
open-loop load generator (:mod:`raft_tpu.testing.load`) that drives the
serving executor (``raft_tpu.serving``) with replayable Poisson arrival
streams, and the scripted chaos-schedule harness
(:mod:`raft_tpu.testing.chaos`) that composes the injectors into timed
fault scripts with declarative invariant checkers — the proof engine
for the self-healing supervisor. The reference ships its comms
self-tests as library code for the same reason: failure handling that
is only testable in production is not testable.
"""

from raft_tpu.testing import chaos, faults, load

__all__ = ["chaos", "faults", "load"]
