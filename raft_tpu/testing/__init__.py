"""Testing utilities shipped with the library — deterministic fault
injection (:mod:`raft_tpu.testing.faults`) for exercising the resilience
layer (``raft_tpu.resilience``) without hardware faults, and the seeded
open-loop load generator (:mod:`raft_tpu.testing.load`) that drives the
serving executor (``raft_tpu.serving``) with replayable Poisson arrival
streams. The reference ships its comms self-tests as library code for
the same reason: failure handling that is only testable in production
is not testable.
"""

from raft_tpu.testing import faults, load

__all__ = ["faults", "load"]
