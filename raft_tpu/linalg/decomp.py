"""Decompositions & solvers — analog of raft/linalg {eig,svd,rsvd,qr,lstsq,
cholesky_r1_update} (reference cpp/include/raft/linalg/detail/{eig,svd,rsvd,
qr,lstsq,cholesky_r1_update}.cuh over cuSOLVER).

XLA ships eigh/svd/qr natively (they run as HLO custom calls tuned per
backend), so the cuSOLVER variants (DC vs Jacobi) collapse onto one
implementation each; both names are kept so callers of the reference API land
somewhere sensible. rsvd and the lstsq family are composed the same way the
reference composes them.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu import errors
from raft_tpu.linalg.gemm import gemm


# -- symmetric eigen (reference linalg/detail/eig.cuh:32-231) ----------------

def eig_dc(cov, n_eig_vals: Optional[int] = None) -> Tuple[jax.Array, jax.Array]:
    """Eigendecomposition of a symmetric matrix, ascending eigenvalues
    (reference eigDC via cusolverDnsyevd). Returns (eig_vectors, eig_vals)
    with vectors in columns."""
    w, v = jnp.linalg.eigh(jnp.asarray(cov))
    if n_eig_vals is not None:
        w = w[:n_eig_vals]
        v = v[:, :n_eig_vals]
    return v, w


def eig_jacobi(cov, tol: float = 1e-7, sweeps: int = 15):
    """Jacobi-method variant (reference eigJacobi). XLA's eigh is used; tol
    and sweeps are accepted (and validated) for API parity only — eigh is
    exact to machine precision, strictly tighter than any positive tol."""
    errors.expects(tol > 0, "tol must be > 0, got %s", tol)
    errors.expects(sweeps >= 1, "sweeps must be >= 1, got %s", sweeps)
    return eig_dc(cov)


def eig_sel_dc(cov, n_eig_vals: int, largest: bool = True):
    """Selective eigensolve (reference eigSelDC via syevdx): top/bottom
    ``n_eig_vals`` pairs."""
    w, v = jnp.linalg.eigh(jnp.asarray(cov))
    if largest:
        return v[:, -n_eig_vals:], w[-n_eig_vals:]
    return v[:, :n_eig_vals], w[:n_eig_vals]


# -- QR (reference linalg/detail/qr.cuh) -------------------------------------

def qr_get_q(a) -> jax.Array:
    q, _ = jnp.linalg.qr(jnp.asarray(a), mode="reduced")
    return q


def qr_get_qr(a) -> Tuple[jax.Array, jax.Array]:
    return jnp.linalg.qr(jnp.asarray(a), mode="reduced")


# -- SVD (reference linalg/detail/svd.cuh:39-171) ----------------------------

def svd_qr(a, gen_left_vec: bool = True, gen_right_vec: bool = True):
    """SVD via the dense path (reference svdQR over cusolverDngesvd).

    Returns (u, s, v) where v holds right singular vectors in columns
    (NOT v^T), matching the reference convention.
    """
    a = jnp.asarray(a)
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    return (u if gen_left_vec else None, s, vt.T if gen_right_vec else None)


def svd_eig(a):
    """SVD via eigendecomposition of the gram matrix (reference svdEig —
    cheaper for tall-skinny a). Returns (u, s, v) with descending s."""
    a = jnp.asarray(a)
    g = gemm(a, a, trans_a=True)  # (n, n) gram
    w, v = jnp.linalg.eigh(g)
    # ascending -> descending
    w = w[::-1]
    v = v[:, ::-1]
    s = jnp.sqrt(jnp.maximum(w, 0))
    safe = jnp.where(s > 0, s, 1.0)
    u = gemm(a, v) / safe[None, :]
    return u, s, v


def svd_jacobi(a, tol: float = 1e-7, sweeps: int = 15):
    """Jacobi variant (reference svdJacobi via gesvdj); delegates to XLA svd.
    tol/sweeps validated for API parity (see :func:`eig_jacobi`)."""
    errors.expects(tol > 0, "tol must be > 0, got %s", tol)
    errors.expects(sweeps >= 1, "sweeps must be >= 1, got %s", sweeps)
    return svd_qr(a)


def svd_reconstruction(u, s, v):
    """u @ diag(s) @ v^T (reference svdReconstruction)."""
    return gemm(jnp.asarray(u) * jnp.asarray(s)[None, :], v, trans_b=True)


# -- randomized SVD (reference linalg/detail/rsvd.cuh:57,374) ----------------

def rsvd_fixed_rank(a, k: int, p: int = 10, n_iters: int = 2, key=None,
                    use_bbt: bool = False):
    """Randomized SVD with oversampling ``p`` and ``n_iters`` subspace/power
    iterations (reference rsvdFixedRank; QB decomposition + small dense SVD).

    Returns (u[:, :k], s[:k], v[:, :k]).
    """
    a = jnp.asarray(a)
    m, n = a.shape
    l = min(k + p, n)
    if key is None:
        key = jax.random.PRNGKey(0)
    omega = jax.random.normal(key, (n, l), dtype=a.dtype)
    y = gemm(a, omega)  # (m, l)
    q = qr_get_q(y)
    for _ in range(n_iters):
        z = gemm(a, q, trans_a=True)   # (n, l)
        q = qr_get_q(z)
        y = gemm(a, q)                  # (m, l)
        q = qr_get_q(y)
    b = gemm(q, a, trans_a=True)        # (l, n)
    ub, s, v = svd_qr(b)
    u = gemm(q, ub)
    return u[:, :k], s[:k], v[:, :k]


def rsvd_perc(a, perc: float, p: int = 10, n_iters: int = 2, key=None):
    """Rank chosen as a percentage of min(m,n) (reference rsvdPerc)."""
    a = jnp.asarray(a)
    k = max(1, int(perc * min(a.shape)))
    return rsvd_fixed_rank(a, k, p=p, n_iters=n_iters, key=key)


# -- least squares (reference linalg/detail/lstsq.cuh:120-355) ---------------

def lstsq_svd_qr(a, b):
    """minimize ||a w - b|| via SVD (reference lstsqSvdQR)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    safe = jnp.where(s > 1e-10 * s.max(), s, jnp.inf)
    return (vt.T * (1.0 / safe)[None, :]) @ (u.T @ b)


def lstsq_svd_jacobi(a, b):
    return lstsq_svd_qr(a, b)


def lstsq_eig(a, b):
    """Via eigendecomposition of a^T a (reference lstsqEig)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    g = gemm(a, a, trans_a=True)
    rhs = jnp.dot(a.T, b, precision="highest")
    w, v = jnp.linalg.eigh(g)
    safe = jnp.where(w > 1e-10 * jnp.maximum(w.max(), 1e-30), w, jnp.inf)
    return v @ ((v.T @ rhs) / safe)


def lstsq_qr(a, b):
    """Via QR factorization (reference lstsqQR)."""
    q, r = jnp.linalg.qr(jnp.asarray(a), mode="reduced")
    return jax.scipy.linalg.solve_triangular(r, q.T @ jnp.asarray(b), lower=False)


# -- Cholesky rank-1 update (reference linalg/detail/cholesky_r1_update.cuh) --

def cholesky_rank1_update(l, n: int, lower: bool = True, eps: float = 0.0):
    """Incremental Cholesky: given L for A[:n-1,:n-1] and A's new row/col
    already written into ``l``'s last row (as in the reference's in-place
    convention), return L for A[:n,:n].

    Functional version: ``l`` is an (n, n) array whose [:n-1,:n-1] block is
    the previous factor and whose last row (lower) holds A[n-1, :n].
    """
    l = jnp.asarray(l)
    if not lower:
        l = l.T
    l_prev = l[: n - 1, : n - 1]
    a_row = l[n - 1, : n - 1]
    a_nn = l[n - 1, n - 1]
    # solve L_prev y = a_row
    y = jax.scipy.linalg.solve_triangular(l_prev, a_row, lower=True) if n > 1 else a_row
    d = a_nn - jnp.dot(y, y)
    d = jnp.maximum(d, eps) if eps > 0 else d
    lnn = jnp.sqrt(d)
    out = l.at[n - 1, : n - 1].set(y).at[n - 1, n - 1].set(lnn)
    out = out.at[: n - 1, n - 1].set(0)
    return out if lower else out.T
