"""GEMM/GEMV — analog of raft/linalg/{gemm,gemv}.cuh over cuBLAS.

On TPU these are ``lax.dot_general`` hitting the MXU; we keep the
alpha/beta/trans surface of the reference API and force f32 accumulation via
``preferred_element_type`` (bf16 inputs still accumulate in f32 on the MXU).
"""

from __future__ import annotations

import jax.numpy as jnp


def _acc_dtype(*xs):
    dt = jnp.result_type(*[jnp.asarray(x).dtype for x in xs])
    return jnp.promote_types(dt, jnp.float32)


def gemm(a, b, trans_a: bool = False, trans_b: bool = False,
         alpha=1.0, beta=0.0, c=None, precision="highest"):
    """alpha * op(a) @ op(b) + beta * c  (reference linalg/gemm.cuh)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    out = jnp.dot(a, b, precision=precision, preferred_element_type=_acc_dtype(a, b))
    out = alpha * out
    if c is not None and beta != 0.0:
        out = out + beta * jnp.asarray(c)
    return out.astype(a.dtype)


def gemv(a, x, trans_a: bool = False, alpha=1.0, beta=0.0, y=None,
         precision="highest"):
    """alpha * op(a) @ x + beta * y  (reference linalg/gemv.cuh)."""
    a = jnp.asarray(a)
    x = jnp.asarray(x)
    if trans_a:
        a = a.T
    out = alpha * jnp.dot(a, x, precision=precision, preferred_element_type=_acc_dtype(a, x))
    if y is not None and beta != 0.0:
        out = out + beta * jnp.asarray(y)
    return out.astype(a.dtype)


def transpose(a):
    """Out-of-place transpose (reference linalg/transpose.cuh)."""
    return jnp.asarray(a).T
