"""Elementwise primitives — analog of raft/linalg {unary,binary,ternary}_op,
map, eltwise, axpy (reference cpp/include/raft/linalg/detail/{map,unary_op,
binary_op,ternary_op,eltwise,axpy}.cuh).

These exist in the reference because every fusion must be hand-launched as a
CUDA kernel with vectorized IO (TxN_t). Under XLA the compiler performs the
fusion, so each function is a one-liner — kept as named functions so the
algorithm layers (and downstream users of the reference API) have a stable
surface, and so every op is trivially differentiable/vmappable.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp


def unary_op(x, op: Callable):
    """out[i] = op(x[i])  (reference linalg/unary_op.cuh:unaryOp)."""
    return op(jnp.asarray(x))


def binary_op(a, b, op: Callable):
    """out[i] = op(a[i], b[i])  (reference linalg/binary_op.cuh)."""
    return op(jnp.asarray(a), jnp.asarray(b))


def ternary_op(a, b, c, op: Callable):
    """out[i] = op(a[i], b[i], c[i])  (reference linalg/ternary_op.cuh)."""
    return op(jnp.asarray(a), jnp.asarray(b), jnp.asarray(c))


def map_op(op: Callable, *arrays):
    """out[i] = op(x1[i], ..., xn[i])  (reference linalg/map.cuh:map)."""
    return op(*[jnp.asarray(a) for a in arrays])


def map_then_reduce(map_fn: Callable, *arrays, reduce_fn=jnp.sum, neutral=None):
    """Fused map + full reduction (reference linalg/map_then_reduce.cuh).

    ``neutral`` is accepted for API parity; XLA picks the identity itself.
    """
    mapped = map_fn(*[jnp.asarray(a) for a in arrays])
    return reduce_fn(mapped)


# -- arithmetic convenience (reference linalg/eltwise.cuh, add.cuh, ...) -----

def add(a, b):
    return jnp.asarray(a) + jnp.asarray(b)


def add_scalar(x, scalar):
    return jnp.asarray(x) + scalar


def subtract(a, b):
    return jnp.asarray(a) - jnp.asarray(b)


def subtract_scalar(x, scalar):
    return jnp.asarray(x) - scalar


def multiply_scalar(x, scalar):
    return jnp.asarray(x) * scalar


def divide_scalar(x, scalar):
    return jnp.asarray(x) / scalar


def scalar_multiply(x, scalar):
    return jnp.asarray(x) * scalar


def eltwise_multiply(a, b):
    return jnp.asarray(a) * jnp.asarray(b)


def eltwise_divide(a, b):
    return jnp.asarray(a) / jnp.asarray(b)


# -- matrix math ops (reference matrix/math.cuh:41-319) ----------------------

def power(x, scalar=None):
    x = jnp.asarray(x)
    return x * x if scalar is None else jnp.power(x, scalar)


def sqrt(x):
    return jnp.sqrt(jnp.asarray(x))


def reciprocal(x, scalar=1.0, setzero: bool = False, thres: float = 1e-15):
    """out = scalar / x, optionally zeroing small denominators
    (reference matrix/math.cuh reciprocal w/ setzero)."""
    x = jnp.asarray(x)
    r = scalar / x
    if setzero:
        r = jnp.where(jnp.abs(x) <= thres, jnp.zeros_like(r), r)
    return r


def sign_flip(x):
    """Flip sign of each *column* so its max-|.| element is positive
    (reference matrix/math.cuh:signFlip, used by svd/pca determinism)."""
    x = jnp.asarray(x)
    idx = jnp.argmax(jnp.abs(x), axis=0)
    signs = jnp.sign(x[idx, jnp.arange(x.shape[1])])
    signs = jnp.where(signs == 0, 1.0, signs)
    return x * signs[None, :]


def axpy(alpha, x, y):
    """y + alpha*x  (reference linalg/axpy.cuh over cublas)."""
    return jnp.asarray(y) + alpha * jnp.asarray(x)


def dot(x, y, precision="highest"):
    """Vector dot product (cublasDot analog), f32 accumulation."""
    x = jnp.asarray(x)
    return jnp.dot(x, jnp.asarray(y), precision=precision,
                   preferred_element_type=jnp.promote_types(x.dtype, jnp.float32))
