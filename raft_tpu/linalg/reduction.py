"""Reductions — analog of raft/linalg coalesced/strided reductions, norms,
reduce_{rows,cols}_by_key (reference cpp/include/raft/linalg/detail/
{reduce,coalesced_reduction,strided_reduction,norm,reduce_rows_by_key,
reduce_cols_by_key,mean_squared_error,divide}.cuh).

The reference distinguishes coalesced vs strided access patterns because CUDA
memory coalescing demands different kernels; XLA handles layout, so both map
to ``jnp`` reductions over the right axis. The *_by_key reductions become
segment-sums, which on TPU we implement as one-hot matmuls when the number of
keys is small (MXU-friendly) and ``jax.ops.segment_sum`` otherwise.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

# norm type tags (reference linalg/norm.cuh NormType)
L1Norm = "l1"
L2Norm = "l2"
LinfNorm = "linf"


def reduce(x, axis: int, main_op: Callable = lambda v: v,
           reduce_op=jnp.sum, final_op: Callable = lambda v: v, init=None):
    """Generic fused reduce (reference linalg/reduce.cuh): per-element
    ``main_op``, associative ``reduce_op`` over ``axis``, ``final_op`` on the
    result. ``init`` accepted for parity; XLA supplies identities."""
    x = jnp.asarray(x)
    return final_op(reduce_op(main_op(x), axis=axis))


def coalesced_reduction(x, main_op=lambda v: v, reduce_op=jnp.sum,
                        final_op=lambda v: v):
    """Reduce along the contiguous (last) axis — row-reduce for row-major
    (reference linalg/coalesced_reduction.cuh)."""
    return reduce(x, axis=-1, main_op=main_op, reduce_op=reduce_op, final_op=final_op)


def strided_reduction(x, main_op=lambda v: v, reduce_op=jnp.sum,
                      final_op=lambda v: v):
    """Reduce along the strided (first) axis — column-reduce for row-major
    (reference linalg/strided_reduction.cuh)."""
    return reduce(x, axis=0, main_op=main_op, reduce_op=reduce_op, final_op=final_op)


def norm(x, norm_type: str = L2Norm, axis: int = -1, do_sqrt: bool = False):
    """Row/col norms (reference linalg/norm.cuh rowNorm/colNorm).

    Note: as in the reference, L2 without ``do_sqrt`` returns the *squared*
    norm — that is what the expanded-distance trick consumes.
    """
    x = jnp.asarray(x)
    if norm_type == L1Norm:
        return jnp.sum(jnp.abs(x), axis=axis)
    if norm_type == L2Norm:
        sq = jnp.sum(x * x, axis=axis)
        return jnp.sqrt(sq) if do_sqrt else sq
    if norm_type == LinfNorm:
        return jnp.max(jnp.abs(x), axis=axis)
    raise ValueError(f"unknown norm type {norm_type}")


def row_norm(x, norm_type: str = L2Norm, do_sqrt: bool = False):
    return norm(x, norm_type, axis=-1, do_sqrt=do_sqrt)


def col_norm(x, norm_type: str = L2Norm, do_sqrt: bool = False):
    return norm(x, norm_type, axis=0, do_sqrt=do_sqrt)


def reduce_rows_by_key(x, keys, n_keys: int, weights=None):
    """sums[key, :] += w * x[row, :] (reference linalg/reduce_rows_by_key.cuh).

    TPU-native: one-hot matmul — (n_keys, n) @ (n, d) rides the MXU, which is
    how kmeans centroid accumulation stays dense and fast. Falls back to
    segment_sum for very large n_keys where the one-hot would dominate flops.
    """
    x = jnp.asarray(x)
    keys = jnp.asarray(keys)
    if weights is not None:
        x = x * jnp.asarray(weights)[:, None]
    if n_keys <= 4096:
        onehot = jax.nn.one_hot(keys, n_keys, dtype=x.dtype)
        acc_t = jnp.promote_types(x.dtype, jnp.float32)
        return jnp.dot(onehot.T, x, precision="highest",
                       preferred_element_type=acc_t).astype(x.dtype)
    return jax.ops.segment_sum(x, keys, num_segments=n_keys)


def reduce_cols_by_key(x, keys, n_keys: int):
    """out[i, key] += x[i, col] per column key (reference
    linalg/reduce_cols_by_key.cuh)."""
    x = jnp.asarray(x)
    keys = jnp.asarray(keys)
    onehot = jax.nn.one_hot(keys, n_keys, dtype=x.dtype)  # (d, n_keys)
    return jnp.dot(x, onehot, precision="highest",
                   preferred_element_type=jnp.promote_types(x.dtype, jnp.float32)).astype(x.dtype)


def mean_squared_error(a, b, weight: float = 1.0):
    """weight * mean((a-b)^2)  (reference linalg/mean_squared_error.cuh)."""
    a = jnp.asarray(a)
    d = a - jnp.asarray(b)
    return weight * jnp.mean(d * d)


def binary_div_skip_zero(a, b, return_zero: bool = False):
    """a / b skipping zero denominators (reference linalg/divide.cuh /
    matrix ops used by centroid division)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    zero = b == 0
    safe = jnp.where(zero, jnp.ones_like(b), b)
    out = a / safe
    return jnp.where(zero, jnp.zeros_like(out) if return_zero else a, out)
