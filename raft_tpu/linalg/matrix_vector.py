"""Matrix-vector broadcasting ops — analog of raft/linalg/matrix_vector_op.cuh
and matrix/linewise_op (reference cpp/include/raft/linalg/detail/
matrix_vector_op.cuh, cpp/include/raft/matrix/detail/linewise_op.cuh).

The reference needs vectorized row/col-broadcast kernels; XLA broadcasting
covers it. ``along_rows=True`` means the vector spans the row dimension
(length n_cols, broadcast to every row) matching the reference's
``bcastAlongRows``.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp


def matrix_vector_op(mat, vec, op: Callable, along_rows: bool = True):
    """out[i,j] = op(mat[i,j], vec[j]) if along_rows else op(mat[i,j], vec[i]).

    (reference linalg/matrix_vector_op.cuh:matrixVectorOp)
    """
    mat = jnp.asarray(mat)
    vec = jnp.asarray(vec)
    v = vec[None, :] if along_rows else vec[:, None]
    return op(mat, v)


def matrix_vector_binary(mat, vec1, vec2, op: Callable, along_rows: bool = True):
    """Two-vector variant (used by mean/std normalization in the reference)."""
    mat = jnp.asarray(mat)
    v1 = jnp.asarray(vec1)
    v2 = jnp.asarray(vec2)
    if along_rows:
        return op(mat, v1[None, :], v2[None, :])
    return op(mat, v1[:, None], v2[:, None])


def matrix_vector_add(mat, vec, along_rows: bool = True):
    return matrix_vector_op(mat, vec, lambda m, v: m + v, along_rows)


def matrix_vector_mul(mat, vec, along_rows: bool = True):
    return matrix_vector_op(mat, vec, lambda m, v: m * v, along_rows)


def linewise_op(mat, op: Callable, along_lines_rows: bool, *vecs):
    """Apply op(mat_element, *vec_elements) line-wise
    (reference matrix/detail/linewise_op.cuh:matrixLinewiseOp)."""
    mat = jnp.asarray(mat)
    if along_lines_rows:
        vs = [jnp.asarray(v)[None, :] for v in vecs]
    else:
        vs = [jnp.asarray(v)[:, None] for v in vecs]
    return op(mat, *vs)
