"""Dense linear algebra primitives — analog of raft/linalg (reference L3).

The reference (cpp/include/raft/linalg/, ~14.9 kLoC) wraps cuBLAS/cuSOLVER and
hand-written CUDA kernels. On TPU every BLAS-shaped op is an XLA builtin that
already targets the MXU, and elementwise/reduction kernels are XLA fusions —
so this layer is thin, functional and jit-friendly. Hand-written solver loops
(lanczos, rsvd power iterations) live in their own modules.
"""

from raft_tpu.linalg.elementwise import (
    unary_op,
    binary_op,
    ternary_op,
    map_op,
    map_then_reduce,
    add,
    add_scalar,
    subtract,
    subtract_scalar,
    multiply_scalar,
    divide_scalar,
    eltwise_multiply,
    eltwise_divide,
    scalar_multiply,
    power,
    sqrt,
    reciprocal,
    sign_flip,
    axpy,
    dot,
)
from raft_tpu.linalg.reduction import (
    reduce,
    coalesced_reduction,
    strided_reduction,
    norm,
    row_norm,
    col_norm,
    L1Norm,
    L2Norm,
    LinfNorm,
    reduce_rows_by_key,
    reduce_cols_by_key,
    mean_squared_error,
    binary_div_skip_zero,
)
from raft_tpu.linalg.gemm import gemm, gemv, transpose
from raft_tpu.linalg.matrix_vector import matrix_vector_op, matrix_vector_add, matrix_vector_mul
from raft_tpu.linalg.decomp import (
    eig_dc,
    eig_jacobi,
    eig_sel_dc,
    qr_get_q,
    qr_get_qr,
    svd_qr,
    svd_eig,
    svd_jacobi,
    svd_reconstruction,
    rsvd_fixed_rank,
    rsvd_perc,
    lstsq_svd_qr,
    lstsq_svd_jacobi,
    lstsq_eig,
    lstsq_qr,
    cholesky_rank1_update,
)
from raft_tpu.linalg.lanczos import (
    lanczos_smallest_eigenvectors,
    lanczos_largest_eigenvectors,
)

__all__ = [k for k in dir() if not k.startswith("_")]
