"""Lanczos eigensolver — analog of raft/linalg/detail/lanczos.cuh
(reference computeSmallestEigenvectors:745 / computeLargestEigenvectors:1089;
~1.4 kLoC of cublas spmv/dot/axpy orchestration).

TPU-native design: the Lanczos recurrence is a ``lax.scan`` over a fixed
Krylov width ``ncv`` with full reorthogonalization (a tall-skinny matmul —
MXU work, cheaper and more robust on TPU than the reference's selective
orthogonalization bookkeeping). The small (ncv x ncv) tridiagonal eigenproblem
is solved with XLA ``eigh`` inside the same jit, so the whole solve is one
compiled computation; restarting (the reference's memory optimization) is
unnecessary because V fits easily in HBM at these sizes.

``matvec`` may be any jit-compatible callable, e.g. a CSR/COO spmv from
raft_tpu.sparse.linalg or a dense gemv — mirroring how the reference takes
``sparse_matrix_t``.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp


def _lanczos_basis(matvec: Callable, n: int, ncv: int, v0, dtype):
    """Run ncv Lanczos steps with full reorthogonalization.

    Returns (V, alpha, beta): V is (ncv, n) rows = Lanczos vectors, alpha
    (ncv,), beta (ncv,) with beta[j] = ||r_j|| linking v_j -> v_{j+1}.
    """
    v0 = v0 / jnp.linalg.norm(v0)

    def step(carry, j):
        V, v_prev, v, beta_prev = carry
        w = matvec(v)
        alpha = jnp.dot(w, v)
        w = w - alpha * v - beta_prev * v_prev
        V = V.at[j].set(v)
        # full reorthogonalization against v_0..v_j (two passes of classical
        # Gram-Schmidt == MXU matmuls)
        for _ in range(2):
            coeffs = V @ w          # (ncv,)
            w = w - V.T @ coeffs
        beta = jnp.linalg.norm(w)
        v_next = jnp.where(beta > 1e-30, w / jnp.where(beta > 1e-30, beta, 1.0),
                           jnp.zeros_like(w))
        return (V, v, v_next, beta), (alpha, beta)

    V0 = jnp.zeros((ncv, n), dtype=dtype)
    (V, _, _, _), (alphas, betas) = jax.lax.scan(
        step, (V0, jnp.zeros(n, dtype), v0, jnp.asarray(0.0, dtype)),
        jnp.arange(ncv))
    return V, alphas, betas


def _eig_from_basis(V, alphas, betas, n_components: int, smallest: bool):
    ncv = alphas.shape[0]
    T = (jnp.diag(alphas)
         + jnp.diag(betas[:-1], 1)
         + jnp.diag(betas[:-1], -1))
    w, s = jnp.linalg.eigh(T)  # ascending
    if smallest:
        w_sel = w[:n_components]
        s_sel = s[:, :n_components]
    else:
        w_sel = w[-n_components:][::-1]
        s_sel = s[:, -n_components:][:, ::-1]
    # Ritz vectors: (n, ncv) @ (ncv, k)
    vecs = V.T @ s_sel
    return w_sel, vecs


def lanczos_solver(matvec: Callable, n: int, n_components: int,
                   ncv: Optional[int] = None, max_iter: int = 0,
                   tol: float = 1e-9, seed: int = 42, smallest: bool = True,
                   v0=None, dtype=jnp.float32) -> Tuple[jax.Array, jax.Array]:
    """Compute extreme eigenpairs of the symmetric operator ``matvec``.

    Returns (eigenvalues (k,), eigenvectors (n, k)); eigenvalues ascending
    for ``smallest``, descending otherwise — matching the reference outputs.

    ``max_iter`` and ``tol`` are accepted for signature parity with the
    reference (linalg/detail/lanczos.cuh:745 computeSmallestEigenvectors)
    but this is a single fixed-``ncv`` Lanczos pass, not a restarted
    iteration: accuracy is controlled by ``ncv``. Raise ``ncv`` if the
    returned pairs are unconverged.
    """
    if ncv is None or ncv <= 0:
        ncv = min(n, max(4 * n_components + 1, 32))
    ncv = min(ncv, n)
    if v0 is None:
        v0 = jax.random.normal(jax.random.PRNGKey(seed), (n,), dtype=dtype)
    else:
        v0 = jnp.asarray(v0, dtype=dtype)
    V, alphas, betas = _lanczos_basis(matvec, n, ncv, v0, dtype)
    return _eig_from_basis(V, alphas, betas, n_components, smallest)


def lanczos_smallest_eigenvectors(matvec, n, n_components, **kw):
    """Reference lanczos.cuh:745 computeSmallestEigenvectors."""
    return lanczos_solver(matvec, n, n_components, smallest=True, **kw)


def lanczos_largest_eigenvectors(matvec, n, n_components, **kw):
    """Reference lanczos.cuh:1089 computeLargestEigenvectors."""
    return lanczos_solver(matvec, n, n_components, smallest=False, **kw)
