"""Lanczos eigensolver — analog of raft/linalg/detail/lanczos.cuh
(reference computeSmallestEigenvectors:745 / computeLargestEigenvectors:1089;
~1.4 kLoC of cublas spmv/dot/axpy orchestration with restart + convergence
control).

TPU-native design: thick-restart Lanczos (Wu & Simon) as one compiled
computation — the inner recurrence is a ``lax.fori_loop`` writing into a
fixed-width (ncv, n) basis with full reorthogonalization (tall-skinny MXU
matmuls, cheaper and more robust on TPU than the reference's selective
orthogonalization bookkeeping), the projected (ncv, ncv) eigenproblem is
XLA ``eigh``, and restart cycles run under ``lax.while_loop`` with
beta-based Ritz residual convergence checks against ``tol`` — the same
stopping semantics as the reference's restarted solver. Static shapes
throughout: ncv and the thick-restart keep-count are compile-time.

``matvec`` may be any jit-compatible callable, e.g. a CSR/COO spmv from
raft_tpu.sparse.linalg or a dense gemv — mirroring how the reference takes
``sparse_matrix_t``.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def _reorth(V, w, j):
    """Two passes of classical Gram-Schmidt of w against rows 0..j of V
    (rows > j are zero, so the full matmul is safe — MXU work)."""
    mask = (jnp.arange(V.shape[0]) <= j)[:, None]
    Vm = V * mask
    for _ in range(2):
        w = w - Vm.T @ (Vm @ w)
    return w


def _lanczos_extend(matvec, V, B, v_start, start: int, key):
    """Extend an orthonormal basis V (rows < ``start`` filled) with
    standard Lanczos steps ``start`` .. ncv-1, writing alpha/beta into the
    projected matrix B. Returns (V, B, v_next, beta_last): the residual
    direction and norm linking to the (ncv+1)-th vector.

    Breakdown recovery: when the residual norm collapses (an invariant
    subspace was hit), the next vector restarts a fresh Krylov branch
    from a deterministic random vector orthogonalized against V with
    ZERO coupling written to B — normalizing the collapsed residual
    would inject a nearly-linearly-dependent direction and make the
    Gram-Schmidt projections explode (observed: ||w|| 3x the spectral
    radius on a 16-node two-clique graph)."""
    ncv = V.shape[0]
    n = V.shape[1]

    def body(j, carry):
        V, B, v, _beta = carry
        V = V.at[j].set(v)
        w = matvec(v)
        w_scale = jnp.linalg.norm(w)     # ~||A v||: the operator's scale
        alpha = jnp.dot(w, v)
        B = B.at[j, j].set(alpha)
        w = _reorth(V, w, j)
        beta = jnp.linalg.norm(w)
        # breakdown iff the residual collapsed RELATIVE to the operator
        # scale (an absolute floor would misfire on legitimately
        # small-normed operators, flagging every step)
        broke = beta <= jnp.maximum(1e-6 * w_scale, 1e-30)
        fresh = _reorth(
            V, jax.random.normal(jax.random.fold_in(key, j), (n,), V.dtype), j
        )
        w = jnp.where(broke, fresh, w)
        beta_eff = jnp.where(broke, 0.0, beta)      # deflated: no coupling
        nrm = jnp.linalg.norm(w)
        v_next = w / jnp.where(nrm > 1e-30, nrm, 1.0)
        nxt = jnp.minimum(j + 1, ncv - 1)
        in_range = (j + 1 < ncv).astype(B.dtype)
        B = B.at[j, nxt].add(in_range * beta_eff * (nxt != j))
        B = B.at[nxt, j].add(in_range * beta_eff * (nxt != j))
        return (V, B, v_next, beta_eff)

    V, B, v_next, beta_last = lax.fori_loop(
        start, ncv, body, (V, B, v_start, jnp.asarray(0.0, V.dtype))
    )
    return V, B, v_next, beta_last


def _thick_restart_lanczos(matvec, n, n_components, ncv, keep, max_restarts,
                           tol, v0, smallest, dtype=jnp.float32):
    # NOT jitted at this level: matvec would have to be a static argument,
    # and every in-repo caller passes a per-call closure — each solve
    # would retrace AND pin the closure (with its captured arrays) in the
    # jit cache forever. The lax control flow below still compiles as
    # single XLA computations; callers wanting cross-call caching can jit
    # a wrapper with a stable matvec themselves.
    v0 = v0 / jnp.linalg.norm(v0)
    key = jax.random.PRNGKey(1811)               # breakdown-recovery seeds
    V0 = jnp.zeros((ncv, n), dtype)
    B0 = jnp.zeros((ncv, ncv), dtype)
    V, B, v_next, beta_last = _lanczos_extend(matvec, V0, B0, v0, 0, key)

    def ritz(B, V, beta_last):
        w, Z = jnp.linalg.eigh(B)            # ascending
        res = jnp.abs(beta_last * Z[ncv - 1, :])
        return w, Z, res

    def wanted_converged(w, res):
        # residual check on the wanted end of the spectrum; tolerance is
        # relative to the Ritz value magnitude with an absolute floor
        # (graph Laplacians legitimately have lambda ~ 0). The working
        # dtype's epsilon times the spectral-scale estimate floors the
        # achievable residual — without it a tighter-than-machine tol
        # (e.g. the 1e-9 default under f32) would spin the full restart
        # budget with no accuracy gain.
        eps = jnp.finfo(dtype).eps
        scale = jnp.max(jnp.abs(w))
        eff_tol = jnp.maximum(tol, 10.0 * eps)
        thr = jnp.maximum(
            eff_tol * jnp.maximum(jnp.abs(w), 1.0), 10.0 * eps * scale
        )
        ok = res <= thr
        if smallest:
            return jnp.all(ok[:n_components])
        return jnp.all(ok[ncv - n_components:])

    def cond(state):
        it, V, B, v_next, beta_last = state
        w, Z, res = ritz(B, V, beta_last)
        return (it < max_restarts) & ~wanted_converged(w, res)

    def restart(state):
        it, V, B, v_next, beta_last = state
        w, Z, res = ritz(B, V, beta_last)
        # thick restart: keep the `keep` Ritz pairs nearest the wanted
        # end, collapse the projected matrix to diag(theta) with the
        # beta*Z[last] coupling row to the carried residual vector
        sel = (
            jnp.arange(keep)
            if smallest
            else ncv - 1 - jnp.arange(keep)
        )
        theta = w[sel]
        Zs = Z[:, sel]                        # (ncv, keep)
        s = beta_last * Zs[ncv - 1, :]        # coupling coefficients
        Vk = (V.T @ Zs).T                     # (keep, n) kept Ritz vectors
        Vn = jnp.zeros((ncv, n), dtype).at[:keep].set(Vk)
        Vn = Vn.at[keep].set(v_next)
        Bn = jnp.zeros((ncv, ncv), dtype)
        Bn = Bn.at[jnp.arange(keep), jnp.arange(keep)].set(theta)
        Bn = Bn.at[keep, :keep].set(s).at[:keep, keep].set(s)
        Vn, Bn, v2, b2 = _lanczos_extend(
            matvec, Vn, Bn, v_next, keep, jax.random.fold_in(key, it)
        )
        return (it + 1, Vn, Bn, v2, b2)

    state = (jnp.int32(0), V, B, v_next, beta_last)
    it, V, B, v_next, beta_last = lax.while_loop(cond, restart, state)

    w, Z, res = ritz(B, V, beta_last)
    if smallest:
        w_sel = w[:n_components]
        Z_sel = Z[:, :n_components]
        res_sel = res[:n_components]
    else:
        w_sel = w[-n_components:][::-1]
        Z_sel = Z[:, -n_components:][:, ::-1]
        res_sel = res[-n_components:][::-1]
    vecs = V.T @ Z_sel
    return w_sel, vecs, res_sel, it


def lanczos_solver(matvec: Callable, n: int, n_components: int,
                   ncv: Optional[int] = None, max_iter: int = 0,
                   tol: float = 1e-9, seed: int = 42, smallest: bool = True,
                   v0=None, dtype=jnp.float32,
                   return_info: bool = False):
    """Compute extreme eigenpairs of the symmetric operator ``matvec`` by
    thick-restart Lanczos. Returns (eigenvalues (k,), eigenvectors (n, k));
    eigenvalues ascending for ``smallest``, descending otherwise — matching
    the reference outputs (lanczos.cuh:745/:1089).

    ``tol`` controls the beta-based Ritz residual stopping test
    (relative to |lambda| with an absolute floor of ``tol`` itself, since
    Laplacian spectra reach 0); ``max_iter`` bounds total Lanczos STEPS
    across restarts (0 = 100 * ncv). ``ncv`` is the Krylov width per
    cycle. ``return_info=True`` additionally returns (residuals (k,),
    n_restarts) for convergence inspection.
    """
    if ncv is None or ncv <= 0:
        ncv = min(n, max(4 * n_components + 1, 32))
    ncv = min(ncv, n)
    if not (1 <= n_components <= n):
        raise ValueError(
            f"n_components={n_components} out of range [1, n={n}] — an "
            f"n-dimensional operator has at most n eigenpairs"
        )
    if n_components > ncv - 2:
        if n > ncv:
            raise ValueError(
                f"n_components={n_components} needs ncv >= n_components + 2 "
                f"for thick restart (got ncv={ncv})"
            )
        # full-width Krylov (ncv == n): one cycle is an exact
        # tridiagonalization, but if it does NOT converge to tol, restart
        # cycles can only retain ncv - 2 Ritz pairs — fewer than wanted —
        # and may stall against the restart budget. Not silent.
        from raft_tpu.core import logger

        logger.warn(
            "lanczos: n_components=%d exceeds ncv-2=%d at full Krylov "
            "width (n=%d <= ncv); restarts retain only %d Ritz pairs and "
            "convergence may stall — for this many pairs prefer a dense "
            "eigendecomposition (linalg.eig_dc)",
            n_components, ncv - 2, n, ncv - 2,
        )
    # keep at least every wanted pair across restarts (discarding one
    # re-derives it from scratch each cycle and stalls convergence)
    keep = min(max(n_components, min(2 * n_components, ncv - 2)),
               max(ncv - 2, 1))
    steps_per_cycle = max(ncv - keep, 1)
    max_steps = max_iter if max_iter and max_iter > 0 else 100 * ncv
    max_restarts = max(0, -(-(max_steps - ncv) // steps_per_cycle))
    if v0 is None:
        v0 = jax.random.normal(jax.random.PRNGKey(seed), (n,), dtype=dtype)
    else:
        v0 = jnp.asarray(v0, dtype=dtype)
    w, vecs, res, it = _thick_restart_lanczos(
        matvec, n, n_components, ncv, keep, max_restarts,
        jnp.asarray(tol, dtype), v0, smallest, dtype,
    )
    if return_info:
        return w, vecs, res, it
    return w, vecs


def lanczos_smallest_eigenvectors(matvec, n, n_components, **kw):
    """Reference lanczos.cuh:745 computeSmallestEigenvectors."""
    return lanczos_solver(matvec, n, n_components, smallest=True, **kw)


def lanczos_largest_eigenvectors(matvec, n, n_components, **kw):
    """Reference lanczos.cuh:1089 computeLargestEigenvectors."""
    return lanczos_solver(matvec, n, n_components, smallest=False, **kw)
