"""Clustering — analog of raft/cluster (reference cpp/include/raft/cluster/:
kmeans; single-linkage hierarchical clustering lives in
:mod:`raft_tpu.sparse.hierarchy` mirroring the reference layout).
"""

from raft_tpu.cluster.kmeans import (
    KMeans,
    KMeansOutput,
    KMeansParams,
    kmeans,
    kmeans_fit,
    kmeans_plus_plus_init,
    kmeans_predict,
    kmeans_transform,
)

__all__ = [
    "KMeans",
    "KMeansOutput",
    "KMeansParams",
    "kmeans",
    "kmeans_fit",
    "kmeans_plus_plus_init",
    "kmeans_predict",
    "kmeans_transform",
]
