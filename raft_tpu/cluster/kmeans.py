"""k-means — analog of ``raft::cluster::kmeans``
(cpp/include/raft/cluster/kmeans.cuh:49 public API; implementation
cpp/include/raft/cluster/detail/kmeans.cuh: k-means++ init
``initializeCentroids``:454 / ``chooseNewCentroid``:357, lloyd loop :780-992
with ``assignCentroids``:565 / ``updateCentroids``:637 and empty-cluster
reseeding :882-896).

TPU mapping:

* **assign** — fused distance+argmin on the MXU (:func:`fused_l2_nn`), the
  reference's ``computeDistances`` + ``minDistances`` collapsed into one
  pass with no n×k matrix in HBM;
* **update** — blocked one-hot matmul: scan over row blocks, each block's
  centroid contribution is ``onehot(labels).T @ x`` — an MXU matmul —
  instead of the reference's thrust sort + reduce_by_key (irregular scatter
  is the one pattern TPUs punish);
* **init** — k-means++ via inverse-CDF sampling on the running min-distance
  (the reference's ``chooseNewCentroid`` distribution), one fori_loop step
  per seed, only the newly chosen centroid's distances computed per step;
* the lloyd loop is a ``lax.while_loop`` on (centroids, residual) with the
  reference's convergence rule |Δresidual|/n > tol.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import errors
from raft_tpu.distance.fused_l2_nn import fused_l2_nn

__all__ = [
    "KMeansParams",
    "KMeansOutput",
    "kmeans_plus_plus_init",
    "kmeans_fit",
    "kmeans_fit_batched",
    "kmeans_predict",
    "kmeans_transform",
    "kmeans",
    "KMeans",
]


@dataclasses.dataclass(frozen=True)
class KMeansParams:
    """Solver knobs (analog of the arg list of reference kmeans.cuh:49 and
    the spectral ``kmeans_solver_t`` config, spectral/cluster_solvers.hpp:38)."""

    n_clusters: int = 8
    max_iter: int = 300
    tol: float = 1e-4
    seed: int = 0
    init: str = "k-means++"  # "k-means++" | "random" | "array"
    block_rows: int = 1 << 16
    # Operand dtype for the centroid-update matmul. None (default) keeps
    # operands at the input dtype — the reference accumulates at input
    # precision (detail/kmeans.cuh updateCentroids) and a silent bf16
    # round would perturb every caller's centroids by ~1e-3 relative.
    # "bfloat16" opts into 2x-MXU-rate updates (the IVF-PQ codebook /
    # throughput regime, where intra-cluster averaging washes the
    # rounding out).
    compute_dtype: Optional[str] = None


class KMeansOutput(NamedTuple):
    centroids: jax.Array   # (k, d)
    labels: jax.Array      # (m,) int32
    inertia: jax.Array     # scalar f32 — the reference's `residual`
    n_iter: jax.Array      # scalar int32


def _update_centroids(x, labels, k: int, block_rows: int,
                      compute_dtype=None):
    """Blocked one-hot matmul centroid update; returns (sums (k,d), counts (k,)).

    ``compute_dtype=None``: operands at the input dtype (reference
    precision, detail/kmeans.cuh updateCentroids); "bfloat16" opts into
    2x-MXU-rate updates with f32 accumulation (~0.4%-relative operand
    rounding that averages out over each cluster's members).
    """
    m, d = x.shape
    cd = jnp.dtype(compute_dtype) if compute_dtype is not None else x.dtype
    bm = min(block_rows, m)
    nb = -(-m // bm)
    pad = nb * bm - m
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    # padded rows get label k and are sliced off the one-hot
    lp = jnp.pad(labels, (0, pad), constant_values=k)

    # the XLA DEFAULT f32 matmul rounds operands to bf16 on TPU — exact
    # input-precision updates therefore need HIGHEST explicitly
    prec = (
        lax.Precision.HIGHEST if jnp.dtype(cd).itemsize >= 4 else None
    )

    def body(carry, blk):
        sums, counts = carry
        xb, lb = blk
        oh = jax.nn.one_hot(lb, k, dtype=cd)               # (bm, k)
        sums = sums + lax.dot_general(
            oh, xb.astype(cd), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        counts = counts + jnp.sum(oh, axis=0, dtype=jnp.float32)
        return (sums, counts), None

    init = (jnp.zeros((k, d), jnp.float32), jnp.zeros((k,), jnp.float32))
    (sums, counts), _ = lax.scan(
        body, init, (xp.reshape(nb, bm, d), lp.reshape(nb, bm))
    )
    return sums, counts


@functools.partial(jax.jit, static_argnames=("k",))
def kmeans_plus_plus_init(x, k: int, key):
    """k-means++ seeding (reference detail/kmeans.cuh:454 initializeCentroids:
    first seed uniform, then each next ∝ current min squared distance via
    inverse-CDF sampling — chooseNewCentroid:357)."""
    m, d = x.shape
    f32 = jnp.float32
    keys = jax.random.split(key, k)
    first = jax.random.randint(keys[0], (), 0, m)
    cents = jnp.zeros((k, d), x.dtype).at[0].set(x[first])
    d2 = jnp.sum((x - x[first]) ** 2, axis=1).astype(f32)

    def body(i, carry):
        cents, d2 = carry
        cdf = jnp.cumsum(d2)
        u = jax.random.uniform(keys[i], (), f32) * cdf[-1]
        nxt = jnp.searchsorted(cdf, u)
        nxt = jnp.minimum(nxt, m - 1)
        cents = cents.at[i].set(x[nxt])
        nd = jnp.sum((x - x[nxt]) ** 2, axis=1).astype(f32)
        return cents, jnp.minimum(d2, nd)

    cents, _ = lax.fori_loop(1, k, body, (cents, d2))
    return cents


@functools.partial(
    jax.jit,
    static_argnames=("k", "max_iter", "tol", "block_rows", "compute_dtype"),
)
def _lloyd(x, cents0, k: int, max_iter: int, tol: float, block_rows: int,
           compute_dtype=None):
    m, d = x.shape

    def assign(cents):
        # default MXU precision (bf16 passes, f32 accumulate): ~5x the
        # HIGHEST-precision gram; borderline mis-assignments are benign in
        # lloyd iterations (and vanish as centroids converge)
        minv, mini = fused_l2_nn(x, cents, precision="default")
        return mini, minv

    def reseed_empty(cents, counts, minv):
        # empty-cluster handling (reference :882-896): move empty centroids
        # onto the points currently farthest from their assigned centroid.
        # ``minv`` is REUSED from this iteration's assignment — recomputing
        # it here would cost a third full (m, k, d) pass per iteration.
        far = jnp.argsort(-minv)  # farthest points first
        empty_rank = jnp.cumsum(counts == 0) - 1  # rank among empties
        take = jnp.where(counts == 0, far[jnp.clip(empty_rank, 0, m - 1)], 0)
        return jnp.where(
            (counts == 0)[:, None], x[take].astype(cents.dtype), cents
        )

    def cond(state):
        it, _, prev_res, res = state
        return (it < max_iter) & (jnp.abs(prev_res - res) / m > tol)

    def step(state):
        # ONE assignment per iteration yields both the labels and the
        # residual of the current centroids (the reference's
        # assignCentroids + cub reduce single pass, detail/kmeans.cuh:565)
        # — an assign/update/re-assign structure would pay a third full
        # (m, k, d) pass per iteration just to refresh the residual.
        it, cents, _, res = state
        labels, minv = assign(cents)
        sums, counts = _update_centroids(x, labels, k, block_rows,
                                         compute_dtype)
        new_cents = sums / jnp.maximum(counts, 1.0)[:, None]
        new_cents = new_cents.astype(x.dtype)
        new_cents = reseed_empty(new_cents, counts, minv)
        return it + 1, new_cents, res, jnp.sum(minv)

    # prev=-inf, res=+inf: first two cond checks see an inf difference
    # (a nan from inf-inf would end the loop before it starts)
    state = (jnp.int32(0), cents0, jnp.float32(-jnp.inf), jnp.float32(jnp.inf))
    it, cents, _, _ = lax.while_loop(cond, step, state)
    labels, minv = assign(cents)
    return KMeansOutput(cents, labels.astype(jnp.int32), jnp.sum(minv), it)


def kmeans_fit(
    x,
    params: Optional[KMeansParams] = None,
    *,
    centroids=None,
    **kw,
) -> KMeansOutput:
    """Fit k-means (reference detail/kmeans.cuh:947 → :780 loop)."""
    if params is None:
        params = KMeansParams(**kw)
    x = jnp.asarray(x)
    errors.check_matrix(x, "x")
    errors.check_k(params.n_clusters, x.shape[0], "n_clusters vs n rows")
    errors.expects(params.max_iter >= 1, "max_iter must be >= 1, got %d", params.max_iter)
    errors.expects(
        centroids is None
        or tuple(jnp.shape(centroids)) == (params.n_clusters, x.shape[1]),
        "centroids: expected shape %s, got %s",
        (params.n_clusters, x.shape[1]),
        None if centroids is None else tuple(jnp.shape(centroids)),
    )
    key = jax.random.PRNGKey(params.seed)
    if centroids is not None:
        cents0 = jnp.asarray(centroids, x.dtype)
    elif params.init == "random":
        idx = jax.random.choice(
            key, x.shape[0], (params.n_clusters,), replace=False
        )
        cents0 = x[idx]
    else:
        cents0 = kmeans_plus_plus_init(x, params.n_clusters, key)
    return _lloyd(
        x, cents0, params.n_clusters, params.max_iter, params.tol,
        params.block_rows, params.compute_dtype,
    )


def kmeans_fit_batched(xs, params: Optional[KMeansParams] = None, **kw):
    """Fit B independent k-means problems of identical shape in ONE
    vmapped program — the batched entry point for callers like IVF-PQ
    codebook training (M subspaces of equal shape), where B sequential
    fits of skinny problems underfill the MXU and pay B dispatches.

    ``xs``: (B, n, d). Returns a :class:`KMeansOutput` with leading batch
    axis on every leaf. Seeds derive from ``params.seed`` per problem.
    Requires n >= n_clusters (no per-problem padding in the batched path).
    """
    if params is None:
        params = KMeansParams(**kw)
    xs = jnp.asarray(xs)
    errors.check_matrix(xs, "xs", ndim=3)
    B, n, d = xs.shape
    errors.check_k(params.n_clusters, n, "n_clusters vs n rows")
    errors.expects(
        params.max_iter >= 1, "max_iter must be >= 1, got %d", params.max_iter
    )
    keys = jax.random.split(jax.random.PRNGKey(params.seed), B)
    if params.init == "random":
        def pick(key):
            return jax.random.choice(
                key, n, (params.n_clusters,), replace=False
            )

        idxs = jax.vmap(pick)(keys)
        cents0 = jnp.take_along_axis(xs, idxs[:, :, None], axis=1)
    else:
        cents0 = jax.vmap(
            lambda x, k_: kmeans_plus_plus_init(x, params.n_clusters, k_)
        )(xs, keys)
    return jax.vmap(
        lambda x, c0: _lloyd(
            x, c0, params.n_clusters, params.max_iter, params.tol,
            params.block_rows, params.compute_dtype,
        )
    )(xs, cents0)


def kmeans_predict(x, centroids):
    """Assign each row to its nearest centroid (reference assignCentroids)."""
    _, labels = fused_l2_nn(jnp.asarray(x), jnp.asarray(centroids))
    return labels


def kmeans_transform(x, centroids, *, sqrt: bool = True):
    """Distances to every centroid (reference computeDistances:86)."""
    from raft_tpu.distance.pairwise import pairwise_distance

    metric = "l2_sqrt_expanded" if sqrt else "l2_expanded"
    return pairwise_distance(jnp.asarray(x), jnp.asarray(centroids), metric)


def kmeans(x, k: int, tol: float = 1e-4, max_iter: int = 300, seed: int = 0):
    """Signature-parity convenience matching the reference's spectral-flavor
    entry ``raft::cluster::kmeans(handle, n, d, k, tol, maxiter, obs, ...)``
    (cluster/kmeans.cuh:49). Returns (codes, residual, n_iter)."""
    out = kmeans_fit(
        x, KMeansParams(n_clusters=k, tol=tol, max_iter=max_iter, seed=seed)
    )
    return out.labels, out.inertia, out.n_iter


class KMeans:
    """Small estimator facade over the functional API."""

    def __init__(self, n_clusters: int = 8, **kw):
        self.params = KMeansParams(n_clusters=n_clusters, **kw)
        self.output: Optional[KMeansOutput] = None

    def fit(self, x):
        self.output = kmeans_fit(x, self.params)
        return self

    @property
    def cluster_centers_(self):
        return self.output.centroids

    @property
    def labels_(self):
        return self.output.labels

    @property
    def inertia_(self):
        return self.output.inertia

    def predict(self, x):
        return kmeans_predict(x, self.output.centroids)

    def transform(self, x):
        return kmeans_transform(x, self.output.centroids)
