"""Matrix utilities — analog of raft/matrix (reference
cpp/include/raft/matrix/{matrix,math,col_wise_sort}.cuh, ~2.8 kLoC).

Slicing/gather/reverse/argmax/diagonal/triangular ops as XLA compositions;
column-wise sort via ``jnp.sort``/``argsort`` (XLA's sort is the TPU-tuned
primitive the reference builds with cub segmented radix sort).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def copy_rows(x, indices):
    """Gather rows (reference matrix.cuh:copyRows)."""
    return jnp.take(jnp.asarray(x), jnp.asarray(indices), axis=0)


def slice_matrix(x, x1: int, y1: int, x2: int, y2: int):
    """out = x[x1:x2, y1:y2] (reference matrix.cuh:sliceMatrix)."""
    return jnp.asarray(x)[x1:x2, y1:y2]


def truncate_zero_origin(x, n_rows: int, n_cols: int):
    return jnp.asarray(x)[:n_rows, :n_cols]


def col_reverse(x):
    """Reverse column order (reference matrix.cuh:colReverse)."""
    return jnp.asarray(x)[:, ::-1]


def row_reverse(x):
    """Reverse row order (reference matrix.cuh:rowReverse)."""
    return jnp.asarray(x)[::-1, :]


def get_diagonal(x):
    """Extract diagonal (reference matrix.cuh:getDiagonalMatrix)."""
    return jnp.diagonal(jnp.asarray(x))


def set_diagonal(x, vec):
    x = jnp.asarray(x)
    n = min(x.shape)
    return x.at[jnp.arange(n), jnp.arange(n)].set(jnp.asarray(vec)[:n])


def invert_diagonal(x):
    """In-place 1/diag (reference matrix.cuh:invertDiagonalMatrix)."""
    x = jnp.asarray(x)
    n = min(x.shape)
    idx = jnp.arange(n)
    return x.at[idx, idx].set(1.0 / x[idx, idx])


def argmax(x, axis: int = 1):
    """Arg-max per row (axis=1) or per column (axis=0)
    (reference matrix.cuh:argmax computes one index per data row)."""
    return jnp.argmax(jnp.asarray(x), axis=axis)


def argmin(x, axis: int = 1):
    return jnp.argmin(jnp.asarray(x), axis=axis)


def copy_upper_triangular(x):
    """Copy strict upper triangle into a vector-packed form is not needed;
    the reference (matrix.cuh:copyUpperTriangular) writes U into a k x k
    matrix — here we just return triu."""
    return jnp.triu(jnp.asarray(x))


def ratio(x, axis: Optional[int] = None):
    """x / sum(x) (reference math.cuh:ratio)."""
    x = jnp.asarray(x)
    return x / jnp.sum(x, axis=axis, keepdims=axis is not None)


def seq_root(x, scalar: float = 1.0, set_neg_zero: bool = False):
    """sqrt(scalar * x), optionally clamping negatives to 0
    (reference math.cuh:seqRoot)."""
    x = jnp.asarray(x) * scalar
    if set_neg_zero:
        x = jnp.maximum(x, 0)
    return jnp.sqrt(x)


def zero_small_values(x, thres: float = 1e-15):
    """Set |x| <= thres to zero (reference math.cuh:setSmallValuesZero)."""
    x = jnp.asarray(x)
    return jnp.where(jnp.abs(x) <= thres, jnp.zeros_like(x), x)


def sort_cols_per_row(x, ascending: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Sort each row's values, returning (sorted, source-column indices)
    (reference matrix/col_wise_sort.cuh:sort_cols_per_row)."""
    x = jnp.asarray(x)
    if not ascending:
        x = -x
    idx = jnp.argsort(x, axis=1, stable=True)
    sorted_vals = jnp.take_along_axis(x, idx, axis=1)
    if not ascending:
        sorted_vals = -sorted_vals
    return sorted_vals, idx
