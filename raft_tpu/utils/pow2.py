"""Power-of-two arithmetic helpers — analog of
cpp/include/raft/pow2_utils.cuh (struct Pow2: roundUp/roundDown/mod/div)
and integer_utils.h (round_up_safe, div_rounding_up_safe)."""

from __future__ import annotations

__all__ = ["Pow2", "round_up_safe", "round_down_safe", "div_rounding_up"]


class Pow2:
    """Mirror of the reference Pow2<Value> helper (pow2_utils.cuh)."""

    def __init__(self, value: int):
        if value <= 0 or value & (value - 1):
            raise ValueError(f"{value} is not a power of two")
        self.value = value
        self.mask = value - 1
        self.log2 = value.bit_length() - 1

    def round_up(self, x: int) -> int:
        return (x + self.mask) & ~self.mask

    def round_down(self, x: int) -> int:
        return x & ~self.mask

    def div(self, x: int) -> int:
        return x >> self.log2

    def mod(self, x: int) -> int:
        return x & self.mask

    def is_aligned(self, x: int) -> bool:
        return (x & self.mask) == 0


def round_up_safe(x: int, multiple: int) -> int:
    """reference integer_utils.h round_up_safe."""
    return ((x + multiple - 1) // multiple) * multiple


def round_down_safe(x: int, multiple: int) -> int:
    return (x // multiple) * multiple


def div_rounding_up(x: int, divisor: int) -> int:
    return (x + divisor - 1) // divisor
