"""Small host utilities — analog of the reference L0 helpers that are not
CUDA-specific: raft/common/seive.hpp (prime sieve), pow2_utils.cuh,
integer_utils.h.
"""

from raft_tpu.utils.seive import Seive
from raft_tpu.utils.pow2 import Pow2, round_up_safe, round_down_safe, div_rounding_up

__all__ = [
    "Seive",
    "Pow2",
    "round_up_safe",
    "round_down_safe",
    "div_rounding_up",
]
