"""Host prime sieve — analog of cpp/include/raft/common/seive.hpp
(class Seive: Sieve of Eratosthenes over a fixed range, used by hashing
utilities)."""

from __future__ import annotations

import numpy as np

__all__ = ["Seive"]


class Seive:
    """Sieve of Eratosthenes up to ``n`` (reference seive.hpp:28)."""

    def __init__(self, n: int):
        self.n = n
        sieve = np.ones(n + 1, bool)
        sieve[:2] = False
        for p in range(2, int(n**0.5) + 1):
            if sieve[p]:
                sieve[p * p :: p] = False
        self._mask = sieve

    def is_prime(self, x: int) -> bool:
        """reference seive.hpp isPrime()."""
        return bool(self._mask[x])

    def primes(self) -> np.ndarray:
        return np.nonzero(self._mask)[0]
