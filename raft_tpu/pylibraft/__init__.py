"""pylibraft-shaped facade — signature parity with the reference Python API
(python/pylibraft/pylibraft/: common.Handle, distance.pairwise_distance;
python/raft/raft/: Handle/Stream/interruptible — SURVEY.md §2 #44-45).

Where pylibraft accepts any ``__cuda_array_interface__`` object and writes
into a preallocated output, this facade accepts anything ``jnp.asarray``
takes (numpy, jax.Array, buffers) and returns the result — functional, as
the north star specifies ("pylibraft accepts jax.Array wherever it
currently takes cupy").
"""

from raft_tpu.pylibraft.common import Handle, Stream, DeviceResources
from raft_tpu.pylibraft import distance
from raft_tpu.pylibraft import cluster
from raft_tpu.pylibraft import neighbors

__all__ = [
    "Handle",
    "Stream",
    "DeviceResources",
    "distance",
    "cluster",
    "neighbors",
]
