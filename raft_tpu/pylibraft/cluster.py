"""pylibraft.cluster facade — kmeans entry points shaped like the
reference's Python kmeans API (pylibraft 22.08 cluster.kmeans:
compute_new_centroids etc.; the 22.06 tree exposes kmeans via C++ only,
cpp/include/raft/cluster/kmeans.cuh:49).
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.cluster import KMeansParams, kmeans_fit, kmeans_predict

__all__ = ["fit", "predict", "cluster_cost", "KMeansParams"]


def fit(X, n_clusters: int, max_iter: int = 300, tol: float = 1e-4,
        seed: int = 0, handle=None):
    """Returns (centroids, labels, inertia, n_iter)."""
    out = kmeans_fit(
        jnp.asarray(X),
        KMeansParams(n_clusters=n_clusters, max_iter=max_iter, tol=tol,
                     seed=seed),
    )
    return out.centroids, out.labels, out.inertia, out.n_iter


def predict(X, centroids, handle=None):
    return kmeans_predict(jnp.asarray(X), jnp.asarray(centroids))


def cluster_cost(X, centroids, handle=None):
    """Sum of squared distances to the nearest centroid."""
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn

    minv, _ = fused_l2_nn(jnp.asarray(X), jnp.asarray(centroids))
    return jnp.sum(minv)
