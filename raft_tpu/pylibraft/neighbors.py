"""pylibraft.neighbors facade — brute-force + IVF search entry points
shaped like the reference's Python neighbors API (pylibraft 22.10+
neighbors.ivf_pq / brute_force; 22.06 exposes kNN through C++ and pyraft).
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.spatial import brute_force_knn as _bfknn
from raft_tpu.spatial.ann import (
    IVFFlatParams, ivf_flat_build, ivf_flat_search,
    IVFPQParams, ivf_pq_build, ivf_pq_search,
)

__all__ = ["brute_force", "ivf_flat", "ivf_pq"]


class brute_force:
    @staticmethod
    def knn(dataset, queries, k: int, metric: str = "l2", handle=None):
        return _bfknn(jnp.asarray(dataset), jnp.asarray(queries), k,
                      metric=metric)


class ivf_flat:
    IndexParams = IVFFlatParams

    @staticmethod
    def build(dataset, params: IVFFlatParams = IVFFlatParams(), handle=None):
        return ivf_flat_build(jnp.asarray(dataset), params)

    @staticmethod
    def search(index, queries, k: int, n_probes: int = 8, handle=None):
        return ivf_flat_search(index, jnp.asarray(queries), k,
                               n_probes=n_probes)


class ivf_pq:
    IndexParams = IVFPQParams

    @staticmethod
    def build(dataset, params: IVFPQParams = IVFPQParams(), handle=None):
        return ivf_pq_build(jnp.asarray(dataset), params)

    @staticmethod
    def search(index, queries, k: int, n_probes: int = 8, handle=None):
        return ivf_pq_search(index, jnp.asarray(queries), k,
                             n_probes=n_probes)
