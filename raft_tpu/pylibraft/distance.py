"""pylibraft.distance facade — signature parity with
python/pylibraft/pylibraft/distance/pairwise_distance.pyx:91-192
(``distance(X, Y, dists, metric)``) and fused_l2_nn_argmin.
"""

from __future__ import annotations


import jax.numpy as jnp

from raft_tpu.distance import pairwise_distance as _pairwise
from raft_tpu.distance import fused_l2_nn_argmin as _fused_argmin
from raft_tpu.distance.distance_type import DISTANCE_NAMES

#: metric names accepted by the reference pyx (pairwise_distance.pyx:35-60)
SUPPORTED_DISTANCES = sorted(DISTANCE_NAMES)


def pairwise_distance(X, Y, out=None, metric: str = "euclidean",
                      p: float = 2.0, handle=None):
    """Compute all-pairs distances (reference pairwise_distance.pyx:91).

    ``out`` is accepted for signature parity; when given, the result is
    also written into it via buffer protocol if possible (numpy arrays),
    and always returned."""
    d = _pairwise(jnp.asarray(X), jnp.asarray(Y), metric, p=p)
    if out is not None:
        import numpy as np

        view = np.asarray(out)
        if view.flags.writeable:
            view[...] = np.asarray(d)
    return d


distance = pairwise_distance  # reference exposes both spellings


def fused_l2_nn_argmin(X, Y, handle=None):
    """Nearest-row index under L2 (pylibraft 22.08 fused_l2_nn_argmin)."""
    return _fused_argmin(jnp.asarray(X), jnp.asarray(Y))
