"""Handle/Stream facade — analog of pylibraft.common
(python/pylibraft/pylibraft/common/handle.pyx Handle,
common/cuda.pyx Stream; pyraft python/raft/raft/common/handle.pyx:30-60).
"""

from __future__ import annotations


import jax

from raft_tpu.core.resources import Resources

__all__ = ["Handle", "Stream", "DeviceResources"]


class Stream:
    """API-parity stream object (reference common/cuda.pyx). On TPU, XLA
    owns scheduling; a Stream is a named token used only for interface
    compatibility. ``sync()`` issues an effects barrier."""

    def __init__(self, name: str = "default"):
        self.name = name

    def sync(self) -> None:
        jax.effects_barrier()


class Handle(Resources):
    """pyraft/pylibraft Handle (handle.pyx:30-60): a Resources subclass
    with the n_streams constructor knob mapped to dispatch lanes."""

    def __init__(self, n_streams: int = 0, device=None, mesh=None):
        super().__init__(device=device, mesh=mesh, n_lanes=max(n_streams, 1))

    def sync(self, *arrays) -> None:  # handle.sync() parity
        super().sync(*arrays)


DeviceResources = Handle
