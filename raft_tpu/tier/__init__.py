"""Popularity-tiered two-tier slab storage — cold IVF lists in host
RAM, a hot working set in HBM, promotion/demotion as zero-retrace
runtime-operand flips of the unchanged grouped serving program
(ROADMAP item 4; docs/tiering.md).

* :class:`TieredListStore` — the store: host cold authority, hot-slot
  view index, copy-publish installs, mutation-epoch invalidation, the
  measured recall guardrail;
* :class:`SlabFetcher` — the async host→device promotion worker
  (bounded queue + in-flight window, flight-recorded fetch spans);
* :class:`PromotionPolicy` — hysteresis planning over the measured
  per-list load signal.
"""

from raft_tpu.tier.fetch import SlabFetcher
from raft_tpu.tier.policy import PromotionPolicy
from raft_tpu.tier.store import TieredListStore, TierRuntime, TierStats

__all__ = [
    "PromotionPolicy",
    "SlabFetcher",
    "TierRuntime",
    "TierStats",
    "TieredListStore",
]
