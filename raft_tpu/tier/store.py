"""The two-tier slab store — cold IVF lists in host RAM, a hot working
set in HBM, membership as a RUNTIME operand of the unchanged grouped
serving program (ROADMAP item 4: "break the HBM wall").

Capacity per chip is whatever fits HBM, but PR 14 measured the Zipf
skew that made the result cache worth 3.1-3.6x — and the same skew
means list accesses are heavily skewed too: most of a shard's slab is
paid for and almost never probed. The tier splits the slab by
POPULARITY instead of truncating it:

* the COLD tier is the full list-sorted slab, held once in (pinned)
  host RAM — on CPU host-sim that is a plain numpy array; on TPU the
  same buffer is what ``jax.device_put`` DMAs from;
* the HOT tier is a fixed budget of ``n_slots`` list-sized HBM slots
  (``slot_rows = max_list`` rows each, the grouped scan's own padded
  list height), plus a parallel id slab mapping hot positions back to
  original row ids.

**The serving program is untouched.** :class:`TieredListStore` builds a
synthetic :class:`~raft_tpu.spatial.ann.ivf_flat.IVFFlatIndex` VIEW
over the hot buffer — ``data_sorted`` is the hot slab,
``storage.sorted_ids`` the hot id map, ``list_offsets``/``list_sizes``
derived from the hot-slot indirection (hot list ``l`` at slot ``s`` →
offset ``s*max_list``; a cold list gets the sentinel offset with size
0) — and calls the ONE grouped scan body
(:func:`...ivf_flat._grouped_impl`) on it. Every tier array is a
runtime operand of that compiled program, so promotion/demotion flips
are ZERO-RETRACE (pinned by the ``ivf_flat_grouped_tiered``
program-contract entry and the cache-size audit in tests/test_tier.py).
An int8 SQ index tiers its CODES (``dequant`` rides along), so bytes
halve in both tiers and on the host→device bus.

**Graceful degradation.** A probe that lands on a cold list scans an
empty slot: its ``in_list`` mask is all-false, every candidate scores
+inf, and the query is answered from the hot lists it DID hit — the
grouped scan's own sentinel discipline, no new code path. The miss is
counted, recorded into the per-list load feed
(:func:`raft_tpu.resilience.replica.record_list_load`), and handed to
the async fetcher for promotion (serve-from-hot + async fill), under
the measured recall guardrail (:meth:`TieredListStore.measure_recall`,
acceptance >= 0.95 of the hot-path recall at the bench config).

**Install = copy-publish double buffer.** A slab install is one jitted
``dynamic_update_slice`` with the slot row offset as a runtime scalar
(ONE compiled install program); it produces a NEW hot buffer and the
old one stays valid for every in-flight dispatch still holding the
previous runtime snapshot — the same no-donation rule as the executor's
hedge re-stage. Snapshots (:meth:`runtime`) are taken under the store
lock, so offsets/sizes/ids/data always describe the same membership
version.

**Mutation-epoch invalidation** (the result-cache discipline,
docs/tiering.md "Epoch invalidation"): :meth:`sync_mutations` pulls the
wrapped :class:`~raft_tpu.spatial.ann.mutation.MutableIndex`'s epoch
journal. Upsert/delete change only the tombstone ``row_mask`` (delta
rows live outside the frozen slab), so the view mask is re-gathered and
re-published — a pre-write mask can never serve after the sync.
Compaction rewrites the slab itself: the journal reports "all lists"
and the store re-snapshots its host authority and invalidates EVERY hot
slot.
"""

from __future__ import annotations

import dataclasses
import time
import typing
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.spatial.ann.common import ListStorage, static_qcap
from raft_tpu.spatial.ann.ivf_flat import IVFFlatIndex, _grouped_impl

__all__ = ["TierRuntime", "TierStats", "TieredListStore"]


@jax.jit
def _install_rows(buf, slab, row0):
    """THE slab-install program: one dynamic_update_slice with the slot
    row offset as a runtime scalar — every slot, every list compiles to
    this single program. No donation: the returned buffer is a NEW
    array and the input stays valid for in-flight dispatches holding
    the previous runtime snapshot (the copy IS the double buffer)."""
    return lax.dynamic_update_slice(buf, slab, (row0, 0))


@jax.jit
def _install_ids(buf, ids, row0):
    return lax.dynamic_update_slice(buf, ids, (row0,))


@dataclasses.dataclass(frozen=True)
class TierRuntime:
    """One consistent tier snapshot — what a dispatch closure receives
    as its ``tier=`` runtime operand (taken under the store lock, so
    the view arrays and the row mask describe the same membership
    version). All leaves are runtime operands: snapshot swaps never
    retrace."""

    view: IVFFlatIndex        # the hot-buffer view index
    row_mask: jax.Array       # (n_view + 1,) int8 hot-position live mask
    version: int              # membership version (debugging/telemetry)
    epoch: int                # mutation epoch the snapshot reflects
    # the SQ dequant pair riding the snapshot (None for flat) — part of
    # the consistent view: a host refresh that re-quantized must never
    # mix new codes with old scales
    dequant: Optional[Tuple[jax.Array, jax.Array]] = None


@dataclasses.dataclass(frozen=True)
class TierStats:
    """Host-side counters (kept live even with ``RAFT_TPU_OBS=off`` —
    the bench and the guardrail read these, not the registry)."""

    n_lists: int
    n_slots: int
    hot_lists: int
    probe_hits: int
    probe_misses: int
    fetches: int
    demotions: int
    invalidations: int
    fetch_ms_total: float
    overlapped_fetches: int
    hot_bytes: int
    epoch: int
    last_recall: Optional[float]

    @property
    def hit_rate(self) -> float:
        tot = self.probe_hits + self.probe_misses
        return self.probe_hits / tot if tot else 0.0

    @property
    def fetch_overlap_pct(self) -> float:
        return (100.0 * self.overlapped_fetches / self.fetches
                if self.fetches else 0.0)


class TieredListStore:
    """Popularity-tiered list storage over one IVF-Flat (or SQ-coded)
    index — see the module docstring for the design.

    ``index``: an :class:`IVFFlatIndex` or
    :class:`~raft_tpu.spatial.ann.ivf_sq.IVFSQIndex` (tiered through
    its flat code view; ``dequant`` rides every scan). The index's
    arrays are snapshotted to host numpy ONCE at construction — that
    host copy IS the cold tier (and the authority every promotion
    fetches from).

    ``n_slots`` / ``hbm_budget_bytes``: the hot working set, either as
    a slot count or as a byte budget for the hot data slab
    (``n_slots = budget // (max_list * d * itemsize)``, clamped to
    ``[1, n_lists]``). A budget below ``n_lists`` slots is what makes
    this a tier; the bench serves ``>= 4x`` the budget.

    ``epoch``: the mutation epoch the snapshotted state reflects (pass
    ``mindex.epoch`` when tiering an already-mutated index — e.g. the
    post-compaction rebuild — so the first :meth:`sync_mutations` is a
    no-op instead of a full invalidation).

    ``min_recall``: the measured recall guardrail —
    :meth:`measure_recall` records into the ``tier_recall`` gauge and
    counts a ``tier_recall_breaches_total`` when the measurement falls
    below it (the store never silently degrades past the guardrail
    without a metric trail).
    """

    def __init__(self, index, *, n_slots: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 name: str = "tier", shard: int = 0,
                 epoch: int = 0,
                 min_recall: Optional[float] = None,
                 touch_decay: float = 0.9,
                 registry: "obs_metrics.MetricRegistry | None" = None,
                 flight=None,
                 clock: Callable[[], float] = time.monotonic):
        base, dequant, origin = _resolve_base(index)
        self._origin = origin
        self._dequant = dequant
        self.name = str(name)
        self.shard = int(shard)
        self.min_recall = min_recall
        self.flight = flight
        self._clock = clock

        # -- the cold tier: ONE host snapshot of the list-sorted slab
        # (pinned host RAM on TPU; plain numpy on CPU host-sim) --------
        storage = base.storage
        self._data_np = np.asarray(base.data_sorted)     # (n + 1, d)
        self._sids_np = np.asarray(storage.sorted_ids)   # (n,)
        self._offs_np = np.asarray(storage.list_offsets)
        self._szs_np = np.asarray(storage.list_sizes)
        self._cents_np = np.asarray(base.centroids, np.float32)
        self._cn2_np = np.sum(self._cents_np ** 2, axis=1)
        self._n = int(storage.n)
        self._d = int(self._data_np.shape[1])
        self._L = int(storage.max_list)
        self._n_lists = int(storage.list_index.shape[0])
        self._metric = base.metric
        # the authoritative tombstone mask (refreshed by sync_mutations)
        self._mask_np = np.ones(self._n + 1, np.int8)

        n_slots = _resolve_slots(
            n_slots, hbm_budget_bytes, self._L, self._d,
            self._data_np.dtype.itemsize, self._n_lists,
        )
        self.n_slots = n_slots
        self._n_view = n_slots * self._L

        # -- host mirrors of the membership (under _install) -----------
        self._slot_of = np.full(self._n_lists, -1, np.int32)
        self._list_at = np.full(n_slots, -1, np.int32)
        # original sorted-slab position of each hot row (the mask
        # re-gather input); n = "points at the sentinel row"
        self._hot_pos = np.full(self._n_view, self._n, np.int64)
        self._offs_host = np.full(self._n_lists + 1, self._n_view,
                                  np.int32)
        self._szs_host = np.zeros(self._n_lists, np.int32)

        # -- device state (every array a runtime operand) --------------
        self._cents_dev = jnp.asarray(base.centroids)
        self._hot_data = jnp.zeros((self._n_view + 1, self._d),
                                   self._data_np.dtype)
        self._hot_ids = jnp.full((self._n_view,), -1, jnp.int32)
        # only ``.shape[0]`` of list_index is read by the grouped scan
        self._dummy_index = jnp.zeros((self._n_lists, 1), jnp.int32)
        self._offs_dev = jnp.asarray(self._offs_host)
        self._szs_dev = jnp.asarray(self._szs_host)
        self._maskv_dev = jnp.ones((self._n_view + 1,), jnp.int8)

        # -- load signal + counters ------------------------------------
        self._touch = np.zeros(self._n_lists, np.float64)
        self._touch_decay = float(touch_decay)
        self._hits = 0
        self._misses = 0
        self._fetches = 0
        self._demotions = 0
        self._invalidations = 0
        self._fetch_ms = 0.0
        self._overlapped = 0
        self._version = 0
        # the mutation epoch this snapshot reflects — seed it with the
        # source MutableIndex's CURRENT epoch when tiering mutated
        # state (e.g. a post-compaction rebuild), so the first
        # sync_mutations isn't a spurious full invalidation
        self._seen_epoch = int(epoch)
        self.last_recall: Optional[float] = None
        self._fill_sink: Optional[Callable[[Sequence[int]], None]] = None

        # ``_install`` serializes EVERY membership/data change (promote,
        # demote, mask refresh, host refresh); ``_lock`` guards only the
        # published snapshot + counters. Order: _install -> _lock.
        self._install = lockcheck.make_lock("TieredListStore._install")
        self._lock = lockcheck.make_lock("TieredListStore._lock")

        reg = (obs_metrics.default_registry()
               if registry is None else registry)
        self._c_hits = reg.counter("tier_probe_hits_total", tier=name)
        self._c_misses = reg.counter("tier_probe_misses_total", tier=name)
        self._c_fetches = reg.counter("tier_fetches_total", tier=name)
        self._c_demotions = reg.counter("tier_demotions_total", tier=name)
        self._c_invalid = reg.counter("tier_invalidations_total",
                                      tier=name)
        self._c_breach = reg.counter("tier_recall_breaches_total",
                                     tier=name)
        self._g_hot = reg.gauge("tier_hot_lists", tier=name)
        self._g_bytes = reg.gauge("tier_hot_bytes", tier=name)
        self._g_recall = reg.gauge("tier_recall", tier=name)
        self._h_fetch = reg.histogram("tier_fetch_ms", tier=name)
        self._g_bytes.set(float(self._hot_data.size
                                * self._hot_data.dtype.itemsize))

    # -- snapshots -------------------------------------------------------
    def runtime(self) -> Dict[str, TierRuntime]:
        """The runtime-operand snapshot for a serving dispatch — shaped
        for :class:`~raft_tpu.serving.ServingExecutor`'s
        ``runtime_provider`` hook (merged into every dispatch's keyword
        arguments outside the executor locks)."""
        with self._lock:
            view = IVFFlatIndex(
                centroids=self._cents_dev,
                data_sorted=self._hot_data,
                storage=ListStorage(
                    sorted_ids=self._hot_ids,
                    list_offsets=self._offs_dev,
                    list_index=self._dummy_index,
                    list_sizes=self._szs_dev,
                    n=self._n_view,
                    max_list=self._L,
                ),
                metric=self._metric,
            )
            return {"tier": TierRuntime(
                view=view, row_mask=self._maskv_dev,
                version=self._version, epoch=self._seen_epoch,
                dequant=self._dequant,
            )}

    def stats(self) -> TierStats:
        with self._lock:
            return TierStats(
                n_lists=self._n_lists, n_slots=self.n_slots,
                hot_lists=int((self._slot_of >= 0).sum()),
                probe_hits=self._hits, probe_misses=self._misses,
                fetches=self._fetches, demotions=self._demotions,
                invalidations=self._invalidations,
                fetch_ms_total=self._fetch_ms,
                overlapped_fetches=self._overlapped,
                hot_bytes=int(self._hot_data.size
                              * self._hot_data.dtype.itemsize),
                epoch=self._seen_epoch, last_recall=self.last_recall,
            )

    def hot_lists(self) -> np.ndarray:
        """List ids currently hot, ascending (a host copy)."""
        with self._lock:
            return np.nonzero(self._slot_of >= 0)[0].astype(np.int32)

    def measured_load(self) -> np.ndarray:
        """The decayed per-list touch signal the promotion policy ranks
        by — same units as :func:`...replica.measured_list_load` rows
        (a host copy)."""
        with self._lock:
            return self._touch.copy()

    # -- serving ---------------------------------------------------------
    def search(self, queries, k: int, *, n_probes: int = 8,
               qcap: typing.Union[int, str, None] = None,
               list_block: int = 32,
               stream_partials: Optional[bool] = None,
               runtime: Optional[TierRuntime] = None,
               account: bool = True,
               fill: bool = True) -> Tuple[jax.Array, jax.Array]:
        """Grouped search over the HOT tier — the unchanged
        :func:`_grouped_impl` body on the hot-slot view. Probes landing
        on cold lists contribute nothing (all-+inf — the graceful
        degraded answer); when ``account`` they are counted, fed into
        the per-list load signal, and (when ``fill`` and a fetcher is
        attached) queued for async promotion.

        ``runtime``: an explicit :class:`TierRuntime` snapshot (what an
        executor dispatch received); default takes a fresh one.
        ``qcap`` resolves SHAPE-ONLY via
        :func:`...ann.common.static_qcap` — never a host sync."""
        q = jnp.asarray(queries)
        errors.check_matrix(q, "queries")
        errors.expects(
            q.shape[1] == self._d,
            "TieredListStore.search: queries d=%d != index d=%d",
            q.shape[1], self._d,
        )
        errors.expects(
            k <= self._L and k <= n_probes * self._L,
            "TieredListStore.search: k=%d exceeds the candidate pool "
            "(max_list=%d, n_probes=%d)", k, self._L, n_probes,
        )
        nq = int(q.shape[0])
        qc = static_qcap(qcap, nq, n_probes, self._n_lists)
        if account:
            self._account(np.asarray(q, np.float32), n_probes, fill)
        snap = runtime if runtime is not None \
            else self.runtime()["tier"]
        list_block = max(1, min(list_block, self._n_lists))
        lockcheck.note_dispatch("TieredListStore.search")
        vals, ids = _grouped_impl(
            snap.view, q, k, n_probes, qc, list_block,
            stream_partials=stream_partials, row_mask=snap.row_mask,
            use_pallas=False, pallas_interpret=False,
            dequant=snap.dequant,
        )
        if self._metric == "l2":
            vals = jnp.sqrt(jnp.maximum(vals, 0.0))
        return vals, ids

    def _account(self, q_np: np.ndarray, n_probes: int,
                 fill: bool) -> None:
        """Host-side probe accounting: the coarse probe replayed in
        numpy (order-only — ties may break differently from the device
        probe, which only perturbs the LOAD signal, never an answer).
        Updates hit/miss counters, the decayed touch signal, the
        per-(shard, list) load feed, and queues cold probed lists for
        async fill.

        Exactly-zero rows are treated as executor micro-batch PADDING
        and not accounted (the staging path pads partial batches with
        zeros; counting them would pin the origin's nearest lists hot
        and inflate the hit rate at low load). Their ANSWER is
        unaffected — only the load signal skips them."""
        from raft_tpu.resilience.replica import record_list_load

        live = np.any(q_np != 0.0, axis=1)
        if not live.all():
            q_np = q_np[live]
            if q_np.shape[0] == 0:
                return
        p = min(n_probes, self._n_lists)
        # order-only distance: |c|^2 - 2 q.c (the |q|^2 term is a
        # per-row constant)
        d2 = self._cn2_np[None, :] - 2.0 * (q_np @ self._cents_np.T)
        if p < self._n_lists:
            probes = np.argpartition(d2, p - 1, axis=1)[:, :p]
        else:
            probes = np.broadcast_to(
                np.arange(self._n_lists), d2.shape).copy()
        counts = np.bincount(probes.ravel(),
                             minlength=self._n_lists).astype(np.float64)
        with self._lock:
            hot = self._slot_of[probes] >= 0
            hits = int(hot.sum())
            misses = int(hot.size - hits)
            self._hits += hits
            self._misses += misses
            self._touch *= self._touch_decay
            self._touch += counts
            miss_lists = (np.unique(probes[~hot])
                          if misses else np.empty(0, np.int64))
            sink = self._fill_sink
        self._c_hits.inc(hits)
        self._c_misses.inc(misses)
        record_list_load(counts, shard=self.shard)
        if fill and sink is not None and miss_lists.size:
            sink([int(x) for x in miss_lists])

    # -- membership ------------------------------------------------------
    def promote(self, list_ids: Sequence[int], *,
                busy=False) -> int:
        """Synchronously fetch + install the given lists into free hot
        slots (already-hot ids are no-ops). Returns the number
        installed; stops early when the hot set is full — pair with
        :meth:`demote` or let :meth:`rebalance` plan swaps. ``busy``
        (bool or callable) stamps the fetch spans compute-overlapped
        (the async fetcher passes its executor-busy probe)."""
        done = 0
        with self._install:
            for lid in list_ids:
                self._check_list(lid)
                if self._slot_of[lid] >= 0:
                    continue
                free = np.nonzero(self._list_at < 0)[0]
                if free.size == 0:
                    break
                self._install_list(int(lid), int(free[0]), busy=busy)
                done += 1
            if done:
                self._publish()
        return done

    def demote(self, list_ids: Sequence[int]) -> int:
        """Flip the given hot lists cold — membership only, nothing is
        copied back (the host slab is the authority; a hot slab is
        never dirtied). Returns the number demoted."""
        done = 0
        with self._install:
            for lid in list_ids:
                self._check_list(lid)
                slot = int(self._slot_of[lid])
                if slot < 0:
                    continue
                self._evict_slot(slot)
                done += 1
                if self.flight is not None:
                    self.flight.record("tier_demote", list=int(lid),
                                       slot=slot)
            if done:
                self._publish()
        with self._lock:
            self._demotions += done
        self._c_demotions.inc(done)
        return done

    def apply_moves(self, moves: Sequence[Tuple[int, Optional[int]]],
                    *, busy=False) -> int:
        """Apply a promotion plan — ``(promote_list, victim_list|None)``
        pairs from :class:`~raft_tpu.tier.policy.PromotionPolicy` — as
        one membership transaction (one publish, one version bump).
        Returns the number of lists promoted."""
        done = 0
        with self._install:
            for lid, victim in moves:
                self._check_list(lid)
                if self._slot_of[lid] >= 0:
                    continue
                if victim is not None and self._slot_of[victim] >= 0:
                    slot = int(self._slot_of[victim])
                    self._evict_slot(slot)
                    with self._lock:
                        self._demotions += 1
                    self._c_demotions.inc()
                else:
                    free = np.nonzero(self._list_at < 0)[0]
                    if free.size == 0:
                        continue
                    slot = int(free[0])
                self._install_list(int(lid), slot, busy=busy)
                done += 1
            if done:
                self._publish()
        return done

    def rebalance(self, policy, *, busy=False) -> int:
        """Plan against the current measured load and apply — the
        periodic promotion/demotion cycle (the fetcher and the bench
        both drive this)."""
        with self._lock:
            slot_of = self._slot_of.copy()
        moves = policy.plan(self.measured_load(), slot_of, self.n_slots)
        return self.apply_moves(moves, busy=busy) if moves else 0

    def attach_fill_sink(
            self, sink: Optional[Callable[[Sequence[int]], None]],
    ) -> None:
        """Register the async-fill callback (the
        :class:`~raft_tpu.tier.fetch.SlabFetcher` attaches itself);
        ``None`` detaches."""
        with self._lock:
            self._fill_sink = sink

    # -- mutation-epoch invalidation --------------------------------------
    def sync_mutations(self, mindex) -> Optional[set]:
        """Pull a :class:`MutableIndex`'s epoch journal forward (the
        result-cache invalidation discipline, docs/tiering.md).
        Upsert/delete change only tombstones — the view mask is
        re-gathered from the fresh ``row_mask`` and re-published.
        Compaction (journal answer ``None``) rewrites the slab: the
        host authority is re-snapshotted and EVERY hot slot is
        invalidated. Returns the changed-list set (``None`` = all,
        empty = no-op)."""
        from raft_tpu.spatial.ann.mutation import lists_changed_since

        with self._install:
            epoch = int(mindex.epoch)
            if epoch == self._seen_epoch:
                return set()
            changed = lists_changed_since(mindex, self._seen_epoch)
            if changed is None:
                # full invalidation — but the CURRENT tombstones must
                # ride along (a journal-overflow None without a
                # compaction still has live deletes in row_mask)
                self._refresh_host_locked(
                    mindex.index, row_mask=np.asarray(mindex.row_mask),
                )
            else:
                with self._lock:
                    self._mask_np = np.asarray(mindex.row_mask)
                self._publish()
            with self._lock:
                self._seen_epoch = epoch
            return changed

    def refresh_host(self, index) -> None:
        """Re-snapshot the host (cold-tier) authority from ``index``
        and invalidate every hot slot — the compaction path. The index
        must keep the tier's static geometry (n, max_list, n_lists,
        dtype); a compaction that changes it needs a NEW store (the
        same statics-change rule as any serving program swap)."""
        with self._install:
            self._refresh_host_locked(index)

    def _refresh_host_locked(self, index, row_mask=None) -> None:
        base, dequant, _ = _resolve_base(index)
        storage = base.storage
        errors.expects(
            int(storage.n) == self._n
            and int(storage.max_list) == self._L
            and int(storage.list_index.shape[0]) == self._n_lists
            and np.asarray(base.data_sorted).dtype == self._data_np.dtype,
            "refresh_host: index geometry changed "
            "(n=%d max_list=%d n_lists=%d vs store n=%d max_list=%d "
            "n_lists=%d) — build a new TieredListStore",
            int(storage.n), int(storage.max_list),
            int(storage.list_index.shape[0]),
            self._n, self._L, self._n_lists,
        )
        with self._lock:
            # swap every host-authority ref in ONE critical section so
            # a concurrent fetch_slab snapshot never mixes old offsets
            # with a new slab
            self._data_np = np.asarray(base.data_sorted)
            self._sids_np = np.asarray(storage.sorted_ids)
            self._offs_np = np.asarray(storage.list_offsets)
            self._szs_np = np.asarray(storage.list_sizes)
            self._mask_np = (np.ones(self._n + 1, np.int8)
                             if row_mask is None
                             else np.asarray(row_mask, np.int8))
            self._dequant = dequant
        n_inval = int((self._list_at >= 0).sum())
        for slot in range(self.n_slots):
            if self._list_at[slot] >= 0:
                self._evict_slot(slot)
        self._publish()
        with self._lock:
            self._invalidations += n_inval
        self._c_invalid.inc(n_inval)
        if self.flight is not None and n_inval:
            self.flight.record("tier_invalidate", reason="refresh_host",
                               n_slots=n_inval)

    # -- guardrail ---------------------------------------------------------
    def measure_recall(self, queries, k: int, *, n_probes: int = 8,
                       qcap: typing.Union[int, str, None] = None,
                       list_block: int = 32) -> float:
        """Measured id-overlap recall of the TIERED answer against the
        full (all-lists-resident) grouped search at the same probes and
        tombstones — the degraded-probe guardrail. Records the
        ``tier_recall`` gauge; a measurement below ``min_recall``
        counts a breach (and a flight event). The full-path reference
        dispatches the ORIGINAL index — run this on a sampled cadence,
        not on the serving hot path."""
        q = jnp.asarray(queries)
        nq = int(q.shape[0])
        qc = static_qcap(qcap, nq, n_probes, self._n_lists)
        _, tiered_ids = self.search(
            q, k, n_probes=n_probes, qcap=qc, list_block=list_block,
            account=False, fill=False,
        )
        base, dequant, _ = _resolve_base(self._origin)
        lb = max(1, min(list_block, self._n_lists))
        with self._lock:
            full_mask = jnp.asarray(self._mask_np)
        _, full_ids = _grouped_impl(
            base, q, k, n_probes, qc, lb, row_mask=full_mask,
            use_pallas=False, pallas_interpret=False, dequant=dequant,
        )
        r = _id_recall(np.asarray(tiered_ids), np.asarray(full_ids))
        with self._lock:
            self.last_recall = r
        self._g_recall.set(r)
        if self.min_recall is not None and r < self.min_recall:
            self._c_breach.inc()
            if self.flight is not None:
                self.flight.record(
                    "tier_recall_breach", recall=round(r, 4),
                    min_recall=self.min_recall,
                )
        return r

    @property
    def degraded(self) -> bool:
        """True when the LAST measured recall sits below the guardrail
        (never measured = not degraded — measure before trusting)."""
        with self._lock:
            lr = self.last_recall
        return (self.min_recall is not None and lr is not None
                and lr < self.min_recall)

    # -- internals (under _install) ----------------------------------------
    def _check_list(self, lid: int) -> None:
        errors.expects(
            0 <= int(lid) < self._n_lists,
            "tier: list id %d out of range [0, %d)", int(lid),
            self._n_lists,
        )

    def fetch_slab(self, lid: int) -> Tuple[np.ndarray, np.ndarray,
                                            np.ndarray]:
        """Read one list's slab from the host (cold) tier: the
        ``(max_list, d)`` zero-padded rows, the ``(max_list,)`` id map
        (-1 pad), and the ``(max_list,)`` original sorted positions
        (``n`` pad — the sentinel mask row). This is THE host read the
        ``host-fetch-in-traced-body`` lint keeps out of compiled
        programs."""
        with self._lock:
            # one consistent host-authority snapshot (refresh_host
            # swaps all four refs under this lock; arrays themselves
            # are replaced, never mutated in place)
            data, sids, offs, szs = (self._data_np, self._sids_np,
                                     self._offs_np, self._szs_np)
        off = int(offs[lid])
        sz = int(szs[lid])
        slab = np.zeros((self._L, self._d), data.dtype)
        slab[:sz] = data[off:off + sz]
        ids = np.full(self._L, -1, np.int32)
        ids[:sz] = sids[off:off + sz]
        pos = np.full(self._L, self._n, np.int64)
        pos[:sz] = np.arange(off, off + sz)
        return slab, ids, pos

    def _install_list(self, lid: int, slot: int, *,
                      busy=False) -> None:
        """Fetch ``lid``'s slab and install it into ``slot`` (caller
        holds ``_install``): host read → async device_put → ONE jitted
        dynamic_update_slice per buffer → host-mirror update. The
        device arrays are PUBLISHED by the caller's :meth:`_publish`
        (one consistent snapshot per transaction). ``busy`` — bool or
        zero-arg callable sampled around the span — stamps the fetch
        compute-overlapped (the ``fetch_overlap_pct`` numerator)."""
        t0 = self._clock()
        was_busy = bool(busy() if callable(busy) else busy)
        slab, ids, pos = self.fetch_slab(lid)
        dev_slab = jax.device_put(slab)      # async H2D — the overlap
        dev_ids = jax.device_put(ids)
        row0 = jnp.int32(slot * self._L)
        with self._lock:
            cur_data, cur_ids = self._hot_data, self._hot_ids
        new_data = _install_rows(cur_data, dev_slab, row0)
        new_ids = _install_ids(cur_ids, dev_ids, row0)
        ms = (self._clock() - t0) * 1e3
        if callable(busy):
            was_busy = was_busy or bool(busy())
        with self._lock:
            self._hot_data = new_data
            self._hot_ids = new_ids
            self._fetches += 1
            self._fetch_ms += ms
            if was_busy:
                self._overlapped += 1
        self._slot_of[lid] = slot
        self._list_at[slot] = lid
        self._hot_pos[slot * self._L:(slot + 1) * self._L] = pos
        self._offs_host[lid] = slot * self._L
        self._szs_host[lid] = self._szs_np[lid]
        self._c_fetches.inc()
        self._h_fetch.observe(ms)
        if self.flight is not None:
            self.flight.record(
                "tier_fetch", list=int(lid), slot=int(slot),
                ms=round(ms, 3), rows=int(self._szs_np[lid]),
                overlapped=was_busy,
            )

    def _evict_slot(self, slot: int) -> None:
        """Membership-only eviction (caller holds ``_install``): the
        slot's rows stay in the buffer but no offset points at them —
        the next snapshot can never scan them."""
        lid = int(self._list_at[slot])
        if lid >= 0:
            self._slot_of[lid] = -1
            self._offs_host[lid] = self._n_view
            self._szs_host[lid] = 0
        self._list_at[slot] = -1
        self._hot_pos[slot * self._L:(slot + 1) * self._L] = self._n

    def _publish(self) -> None:
        """Push the host mirrors to fresh device arrays and swap them
        into the published snapshot atomically (caller holds
        ``_install``; readers hold ``_lock`` only)."""
        offs = jnp.asarray(self._offs_host)
        szs = jnp.asarray(self._szs_host)
        maskv = np.ones(self._n_view + 1, np.int8)
        maskv[:-1] = self._mask_np[np.minimum(self._hot_pos, self._n)]
        maskv_dev = jnp.asarray(maskv)
        with self._lock:
            self._offs_dev = offs
            self._szs_dev = szs
            self._maskv_dev = maskv_dev
            self._version += 1
            hot = int((self._slot_of >= 0).sum())
        self._g_hot.set(float(hot))

    def __repr__(self) -> str:
        st = self.stats()
        return (f"TieredListStore(name={self.name!r}, "
                f"hot={st.hot_lists}/{st.n_lists} lists in "
                f"{st.n_slots} slots, hit_rate={st.hit_rate:.3f}, "
                f"fetches={st.fetches}, epoch={st.epoch})")


# -- helpers -----------------------------------------------------------------
def _resolve_base(index):
    """``(flat_view, dequant, origin)`` for an IVFFlatIndex or an
    IVFSQIndex (tiered through its int8 code view — bytes halve in both
    tiers and on the bus)."""
    from raft_tpu.spatial.ann.ivf_sq import IVFSQIndex, _flat_view

    if isinstance(index, IVFSQIndex):
        return _flat_view(index), (
            jnp.asarray(index.vmin, jnp.float32),
            jnp.asarray(index.vscale, jnp.float32),
        ), index
    errors.expects(
        isinstance(index, IVFFlatIndex),
        "TieredListStore: expected an IVFFlatIndex or IVFSQIndex, "
        "got %s", type(index).__name__,
    )
    return index, None, index


def _resolve_slots(n_slots, budget, L, d, itemsize, n_lists) -> int:
    errors.expects(
        (n_slots is None) != (budget is None),
        "TieredListStore: pass exactly one of n_slots / "
        "hbm_budget_bytes",
    )
    if n_slots is None:
        slab = L * d * itemsize
        n_slots = max(1, int(budget) // slab)
    errors.expects(int(n_slots) >= 1,
                   "TieredListStore: n_slots=%d < 1", int(n_slots))
    return min(int(n_slots), n_lists)


def _id_recall(got: np.ndarray, ref: np.ndarray) -> float:
    """Mean per-query id overlap |got ∩ ref| / |ref| (invalid -1 rows
    excluded from the reference — a reference that itself found fewer
    than k rows never penalizes the tier)."""
    n = got.shape[0]
    tot, denom = 0.0, 0
    for i in range(n):
        r = set(int(x) for x in ref[i] if int(x) >= 0)
        if not r:
            continue
        g = set(int(x) for x in got[i] if int(x) >= 0)
        tot += len(g & r) / len(r)
        denom += 1
    return tot / denom if denom else 1.0
