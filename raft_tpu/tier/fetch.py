"""The async host→device slab fetcher — promotion traffic
double-buffered against serving compute, exactly the executor's
staging discipline (PR 8): ``device_put`` OFF the serving hot loop, a
bounded in-flight window, and every fetch a span in the flight
recorder.

A serving miss (:meth:`TieredListStore.search` probing a cold list)
costs nothing on the dispatch path — the query is answered from the
hot lists it hit, and the cold list id lands in this fetcher's bounded
queue. The fetcher thread drains up to ``window`` requests per cycle,
picks a slot for each (a free one, else the policy's coldest victim —
hysteresis lives in :class:`~raft_tpu.tier.policy.PromotionPolicy`),
and runs the store's install path: host slab read → async H2D →
jitted copy-publish install. Because ``jax.device_put`` is async and
the install program is enqueued behind in-flight serving programs, the
transfer overlaps compute; ``busy_fn`` (e.g. ``lambda:
executor.stats().in_flight > 0``) stamps which fetch spans actually
overlapped serving — the bench's ``fetch_overlap_pct``.

Bounds: the queue holds at most ``max_pending`` distinct list ids
(already-hot and already-queued ids dedup; overflow is DROPPED and
counted — a miss storm must shed fill work, not grow a queue), and at
most ``window`` slabs are in flight per cycle (the double-buffer
window, the executor's ``max_in_flight`` analog).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence

from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import crash as obs_crash

__all__ = ["SlabFetcher"]


class SlabFetcher:
    """The background promotion worker of one
    :class:`~raft_tpu.tier.store.TieredListStore`.

    ``policy``: optional
    :class:`~raft_tpu.tier.policy.PromotionPolicy` consulted when the
    hot set is full — it nominates a victim only when the candidate's
    measured load beats the victim's by its hysteresis margin, so a
    cold one-off probe can never thrash a genuinely hot list. Without
    a policy, a full hot set simply drops fill requests (counted).

    ``busy_fn``: sampled at each fetch's start and end; True either
    time marks the span compute-overlapped.
    """

    def __init__(self, store, *, window: int = 2,
                 max_pending: Optional[int] = None,
                 policy=None,
                 busy_fn: Optional[Callable[[], bool]] = None,
                 name: Optional[str] = None,
                 max_restarts: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        from raft_tpu import errors

        errors.expects(window >= 1, "SlabFetcher: window=%d < 1", window)
        errors.expects(max_restarts >= 0,
                       "SlabFetcher: max_restarts=%d < 0", max_restarts)
        self.store = store
        self.window = int(window)
        self.max_pending = (4 * store.n_slots if max_pending is None
                            else int(max_pending))
        self.policy = policy
        self._busy_fn = busy_fn
        self.name = name or f"{store.name}-fetch"
        self.max_restarts = int(max_restarts)
        self._clock = clock
        self._lock = lockcheck.make_lock("SlabFetcher._lock")
        self._work = lockcheck.make_condition(self._lock)
        self._queue: list = []        # FIFO of distinct cold list ids
        self._queued: set = set()
        self._closed = False
        self._drops = 0
        self._cycles = 0
        self._restarts = 0
        self._gave_up = False
        reg = obs_metrics.default_registry()
        self._c_dropped = reg.counter("tier_fill_dropped_total",
                                      tier=store.name)
        self._c_restarts = reg.counter("tier_fetcher_restarts_total",
                                       tier=store.name)
        obs_crash.install_excepthook()
        store.attach_fill_sink(self.request)
        self._thread = threading.Thread(
            target=self._run, name=self.name, daemon=True,
        )
        self._thread.start()

    # -- producer side -------------------------------------------------------
    def request(self, list_ids: Sequence[int]) -> int:
        """Enqueue cold lists for async promotion (the store's fill
        sink). Dedups against the queue and the current hot set;
        overflow past ``max_pending`` is dropped and counted. Returns
        the number actually enqueued."""
        hot = set(int(x) for x in self.store.hot_lists())
        added = dropped = 0
        with self._work:
            if self._closed:
                return 0
            for lid in list_ids:
                lid = int(lid)
                if lid in self._queued or lid in hot:
                    continue
                if len(self._queue) >= self.max_pending:
                    dropped += 1
                    continue
                self._queue.append(lid)
                self._queued.add(lid)
                added += 1
            if added:
                self._work.notify()
            if dropped:
                self._drops += dropped
        if dropped:
            self._c_dropped.inc(dropped)
        return added

    def stats(self) -> dict:
        with self._lock:
            return {"pending": len(self._queue), "dropped": self._drops,
                    "cycles": self._cycles, "restarts": self._restarts,
                    "gave_up": self._gave_up}

    @property
    def gave_up(self) -> bool:
        """True once the bounded restart policy exhausted: the worker
        is dead, the fill sink is detached, and the store serves from
        its current hot set (degraded, recall-guardrail-watched) until
        a replacement fetcher is attached."""
        with self._lock:
            return self._gave_up

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until the queue is empty and the in-cycle batch has
        been installed (tests/bench barrier). True on success."""
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            with self._lock:
                if not self._queue and not self._queued:
                    return True
            time.sleep(0.002)
        return False

    def close(self) -> None:
        with self._work:
            if self._closed:
                return
            self._closed = True
            self._work.notify_all()
        self.store.attach_fill_sink(None)
        self._thread.join(timeout=10.0)

    def __enter__(self) -> "SlabFetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the fetcher thread ----------------------------------------------
    def _run(self) -> None:
        """The thread target: ``_loop`` under a BOUNDED restart policy
        (ISSUE 18). A promotion-batch exception used to kill the worker
        silently — the queue kept filling, nothing drained, and the
        first symptom was recall decay. Now each crash counts in
        ``tier_fetcher_restarts_total{tier=...}`` and the loop restarts
        (per-batch bookkeeping in ``_loop``'s ``finally`` keeps the
        queue consistent across the tear-down); after ``max_restarts``
        crashes the worker GIVES UP deliberately: detach the fill sink
        (the store serves from its current hot set — degraded but
        correct, the recall guardrail watches it), record a flight
        event, and re-raise so the crash excepthook chain
        (``obs/crash.py``, installed in ``__init__``) surfaces the
        final exception in ``thread_uncaught_total``."""
        while True:
            try:
                self._loop()
                return                      # clean close()
            except Exception:
                with self._lock:
                    self._restarts += 1
                    restarts = self._restarts
                    give_up = restarts > self.max_restarts
                    if give_up:
                        self._gave_up = True
                if not give_up:
                    self._c_restarts.inc()
                    continue
                # exhausted: degrade to serve-from-hot and surface
                self.store.attach_fill_sink(None)
                if getattr(self.store, "flight", None) is not None:
                    self.store.flight.record(
                        "tier_fetcher_gave_up", tier=self.store.name,
                        restarts=restarts,
                        max_restarts=self.max_restarts,
                    )
                raise

    def _loop(self) -> None:
        while True:
            with self._work:
                while not self._queue and not self._closed:
                    self._work.wait(timeout=0.05)
                if self._closed and not self._queue:
                    return
                batch = self._queue[:self.window]
                del self._queue[:len(batch)]
            try:
                self._promote_batch(batch)
            finally:
                with self._lock:
                    self._queued.difference_update(batch)
                    self._cycles += 1

    def _promote_batch(self, batch) -> None:
        """One double-buffer cycle: resolve a slot per candidate (free,
        else the policy's hysteresis victim) and run the store's
        install transaction. The window bounds how many slabs are in
        flight at once."""
        store = self.store
        slot_of = store._slot_of  # noqa: SLF001 — the fetcher is the
        # store's own worker; reads are re-validated inside apply_moves
        load = store.measured_load()
        hot_now = int((slot_of >= 0).sum())
        moves = []
        victims: list = []
        for lid in batch:
            if slot_of[lid] >= 0:
                continue
            victim = None
            if hot_now + len(moves) - len(victims) >= store.n_slots:
                if self.policy is None:
                    continue            # full and no policy: shed
                victim = self.policy.pick_victim(
                    load, slot_of,
                    exclude=[m[0] for m in moves] + victims,
                    candidate_load=float(load[lid]),
                )
                if victim is None:
                    continue            # hysteresis says don't thrash
                victims.append(victim)
            moves.append((lid, victim))
        if not moves:
            return
        store.apply_moves(moves, busy=self._busy_fn or False)
