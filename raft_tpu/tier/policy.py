"""Promotion/demotion planning — popularity with hysteresis.

The load signal is PR 14's measurement chain: every tiered search
records its probe histogram into ``serving_list_rows_total{shard,list}``
(:func:`raft_tpu.resilience.replica.record_list_load`) and into the
store's decayed in-process touch vector; the policy ranks lists by
that vector. Planning is pure host numpy — no device state, no lock —
so a control plane (ROADMAP item 2) can evaluate plans without owning
a store.

Hysteresis is the anti-thrash rule: a cold candidate displaces the
coldest hot list only when its measured load beats the victim's by
``demote_margin`` (and clears ``min_touches``). Under a Zipf mix the
hot set converges to the head and one-off tail probes bounce off the
margin instead of evicting it; ``max_moves`` bounds the install
traffic any single cycle can queue behind serving dispatches.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu import errors

__all__ = ["PromotionPolicy"]


@dataclasses.dataclass(frozen=True)
class PromotionPolicy:
    """The hysteresis planner (see the module docstring).

    ``demote_margin`` — a candidate must carry at least this multiple
    of the victim's load (> 1 damps thrash; 1.0 = pure LFU).
    ``min_touches`` — floor on a candidate's load before it is worth a
    fetch at all (a single stray probe never promotes).
    ``max_moves`` — per-cycle cap on planned moves.
    """

    demote_margin: float = 1.25
    min_touches: float = 1.0
    max_moves: int = 4

    def __post_init__(self):
        errors.expects(self.demote_margin >= 1.0,
                       "PromotionPolicy: demote_margin=%s < 1",
                       self.demote_margin)
        errors.expects(self.max_moves >= 1,
                       "PromotionPolicy: max_moves=%d < 1",
                       self.max_moves)

    def plan(self, load: np.ndarray, slot_of: np.ndarray,
             n_slots: int) -> List[Tuple[int, Optional[int]]]:
        """Plan up to ``max_moves`` ``(promote, victim|None)`` pairs:
        first fill free slots with the hottest qualifying cold lists,
        then swap while the hottest remaining cold list beats the
        coldest hot list by the margin. ``load`` is the measured
        per-list signal; ``slot_of`` the current membership (-1 =
        cold)."""
        load = np.asarray(load, np.float64)
        slot_of = np.asarray(slot_of)
        hot_mask = slot_of >= 0
        cold = np.nonzero(~hot_mask)[0]
        cold = cold[load[cold] >= self.min_touches]
        if cold.size == 0:
            return []
        cold = cold[np.argsort(-load[cold], kind="stable")]
        moves: List[Tuple[int, Optional[int]]] = []
        free = int(n_slots) - int(hot_mask.sum())
        ci = 0
        while ci < cold.size and free > 0 and len(moves) < self.max_moves:
            moves.append((int(cold[ci]), None))
            ci += 1
            free -= 1
        hot = np.nonzero(hot_mask)[0]
        hot = hot[np.argsort(load[hot], kind="stable")]    # coldest first
        hi = 0
        while (ci < cold.size and hi < hot.size
               and len(moves) < self.max_moves):
            cand, victim = int(cold[ci]), int(hot[hi])
            if load[cand] < self.demote_margin * max(load[victim], 0.0) \
                    or load[cand] <= load[victim]:
                break          # sorted both ways: no later pair can pass
            moves.append((cand, victim))
            ci += 1
            hi += 1
        return moves

    def pick_victim(self, load: np.ndarray, slot_of: np.ndarray, *,
                    candidate_load: float,
                    exclude: Sequence[int] = ()) -> Optional[int]:
        """The fetcher's single-victim query: the coldest hot list the
        candidate beats by the margin, or ``None`` (don't thrash).
        ``exclude`` removes lists mid-plan (being promoted this cycle,
        or already nominated)."""
        if candidate_load < self.min_touches:
            return None
        load = np.asarray(load, np.float64)
        slot_of = np.asarray(slot_of)
        hot = np.nonzero(slot_of >= 0)[0]
        if exclude:
            hot = hot[~np.isin(hot, np.asarray(list(exclude)))]
        if hot.size == 0:
            return None
        victim = int(hot[np.argmin(load[hot])])
        if (candidate_load >= self.demote_margin
                * max(load[victim], 0.0)
                and candidate_load > load[victim]):
            return victim
        return None
