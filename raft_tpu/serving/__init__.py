"""Open-loop serving for the warmed one-dispatch search programs.

The compiled serving programs (docs/serving.md) answer a batch in one
dispatch; this package is the executor ABOVE them that a production
front end actually needs — the layer the reference never grew past its
``raft::handle_t`` resource container (SURVEY "What RAFT is"), and the
layer that turns the measured program QPS into deliverable open-loop
throughput (ROADMAP item 3, "millions of users"):

* :class:`~raft_tpu.serving.batching.BucketSet` /
  :func:`~raft_tpu.serving.batching.pack_requests` — shape-bucketed
  micro-batching: arrivals coalesce into EXACTLY the warmed
  ``warmup(nq)`` batch shapes, so steady-state serving never retraces;
* :class:`~raft_tpu.serving.executor.ServingExecutor` — the open-loop
  executor: pipelined host→device staging, a bounded async-dispatch
  in-flight window, completion-order demux back to per-request
  futures, with :class:`~raft_tpu.resilience.AdmissionController`
  shedding at the door,
  :class:`~raft_tpu.resilience.HedgePolicy`-driven straggler hedging
  onto a backup replica, and ``shard_mask``/``FailoverPlan`` route
  arrays flowing through as runtime inputs;
* :class:`~raft_tpu.serving.result_cache.ResultCache` /
  :class:`~raft_tpu.serving.result_cache.CentroidSigner` — the
  hot-traffic shaping layer (docs/serving.md "Hot traffic"): an
  exact + semantic query-result cache over the set-associative
  :class:`~raft_tpu.cache.VectorCache`, invalidated by mutation
  epoch, feeding the executor's submit-side cache hits and request
  coalescing;
* the deterministic Poisson load generator feeding it lives in
  :mod:`raft_tpu.testing.load` (seeded open-loop arrival schedules
  — plus the Zipf repeated-query mix — the bench's offered-load
  sweep and the chaos suite replay the same traffic).
"""

from raft_tpu.serving.batching import (
    BucketSet,
    MicroBatch,
    PendingRequest,
    pack_requests,
)
from raft_tpu.serving.executor import ExecutorStats, ServingExecutor
from raft_tpu.serving.result_cache import (
    CentroidSigner,
    ResultCache,
    ResultCacheStats,
    semantic_recall,
)

__all__ = [
    "BucketSet",
    "MicroBatch",
    "PendingRequest",
    "pack_requests",
    "ExecutorStats",
    "ServingExecutor",
    "CentroidSigner",
    "ResultCache",
    "ResultCacheStats",
    "semantic_recall",
]
