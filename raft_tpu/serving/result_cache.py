"""Semantic query-result cache for the hot-traffic serving tier
(ISSUE 15; ROADMAP item 5, docs/serving.md "Hot traffic").

Real million-user traffic is Zipf-skewed: a small set of hot queries
recurs constantly, yet the serving path re-runs the full IVF pipeline
for every arrival. This module caches FINISHED ``(dists, ids)`` results
keyed on a quantized query signature, in two tiers:

* **Exact tier** — a 64-bit content hash of the query's float32 bytes:
  a hit is bitwise the same query, so serving the cached rows is
  result-identical to re-dispatching (no recall question).
* **Semantic tier** — the coarse-probe SUPER-CENTROID ids
  (:class:`CentroidSigner`, the :func:`~raft_tpu.spatial.ann.common.
  two_level_probe` key): two queries whose top super clusters agree
  land in the same cache line, so a near-duplicate of a hot query hits
  too. Semantic hits return ANOTHER query's rows, so they are gated
  behind a MEASURED recall guardrail (:meth:`ResultCache.
  calibrate_semantic`) and disabled by default.

Both tiers are backed by :class:`raft_tpu.cache.VectorCache` — the
set-associative LRU of the reference's ``cache_util.cuh`` lineage,
repurposed: one cached result is one fixed-width int32 payload vector
``[sig_lo, sig_hi, epoch, dists_bits(k), ids(k)]`` (float32 distance
bits are stored BIT-CAST so the round trip is exact; the full 64-bit
signature rides in the payload, so a 31-bit set-key collision can
never serve another query's rows — the payload verifies before a hit
counts). A bounded per-request **L1 hash front** sits above the exact
tier: the VectorCache probe is an array program (~0.2 ms even jitted),
cheap next to a big serving dispatch but NOT next to a saturated
program's per-row cost — the hot-head exact path must be a host hash
map (~µs), with the tiers underneath catching regrouped rows, L1
evictions, and everything semantic.

**Invalidation is by mutation epoch**, not by key: every entry is
stamped with the writer's epoch (:attr:`raft_tpu.spatial.ann.mutation.
MutableIndex.epoch` — bumped by every applied upsert/delete batch and
by compaction), and a lookup that presents a NEWER epoch treats the
entry as stale: counted, evicted, and re-served fresh. One integer
compare makes every pre-write result die on the first post-write
lookup — no enumeration of affected keys, no cross-thread flush. The
``stale-epoch-read`` jaxlint rule (docs/static_analysis.md) flags
lookups that do not thread a live epoch value.

Counters (``serving_result_cache_total{cache,result=hit|semantic_hit|
miss|stale}``, ``serving_result_cache_inserts_total``) land in the
:mod:`raft_tpu.obs` registry; the executor adds span events per hit
(docs/observability.md).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
from typing import Callable, Optional, Tuple

import numpy as np

from raft_tpu import errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.cache import VectorCache
from raft_tpu.obs import metrics as obs_metrics

__all__ = [
    "CentroidSigner",
    "ResultCache",
    "ResultCacheStats",
    "exact_signatures",
    "semantic_recall",
]

# payload layout (int32 words): full 64-bit signature (2 words, the
# collision guard), the writer's mutation epoch (1 word), then k
# bit-cast float32 distances and k int32 ids
_N_META = 3


def _fold_key(sig_lo: np.ndarray) -> np.ndarray:
    """The VectorCache set key for a signature: low word masked into
    [0, 2^31) — non-negative (−1 is the cache's empty sentinel), full
    64 bits still verified against the payload on every hit."""
    return (sig_lo & np.int32(0x7FFFFFFF)).astype(np.int32)


def exact_signatures(rows: np.ndarray, salt: bytes = b"") -> np.ndarray:
    """Per-row 64-bit content signatures of a ``(m, d)`` float32 batch:
    ``blake2b`` over each row's bytes (plus ``salt`` — the cache mixes
    its ``k`` in, so the same vector asked at a different k can never
    alias). Returns ``(m, 2)`` int32 — the (lo, hi) words stored in and
    verified against the payload."""
    rows = np.ascontiguousarray(rows, np.float32)
    errors.expects(rows.ndim == 2,
                   "exact_signatures: expected (m, d) rows, got %s",
                   tuple(rows.shape))
    out = np.empty((rows.shape[0], 2), np.int32)
    for i in range(rows.shape[0]):
        dig = hashlib.blake2b(rows[i].tobytes() + salt,
                              digest_size=8).digest()
        out[i] = np.frombuffer(dig, np.int32)
    return out


class CentroidSigner:
    """The semantic signature: a query row's top super-centroid ids.

    Scores rows against the ``(n_super, d)`` super-centroid set on the
    host (numpy — the set is ~sqrt(n_centroids) small, and the signer
    runs per submit, off the device hot path) and hashes the SORTED top
    ``n_probes`` super ids: two queries probing the same super clusters
    share a signature, which is exactly the granularity at which the
    IVF pipeline itself would have scanned the same lists. Coarser
    ``n_probes=1`` buckets more aggressively (higher hit rate, lower
    semantic recall); the guardrail decides whether that trade is
    servable (docs/serving.md "Hot traffic")."""

    def __init__(self, super_cents, n_probes: int = 2):
        sc = np.ascontiguousarray(super_cents, np.float32)
        errors.expects(sc.ndim == 2 and sc.shape[0] >= 1,
                       "CentroidSigner: expected (n_super, d) "
                       "super-centroids, got %s", tuple(sc.shape))
        errors.expects(n_probes >= 1,
                       "CentroidSigner: n_probes=%d < 1", n_probes)
        self.super_cents = sc
        self.n_probes = int(min(n_probes, sc.shape[0]))
        self._norms = np.einsum("sd,sd->s", sc, sc)

    @classmethod
    def from_coarse(cls, coarse, n_probes: int = 2) -> "CentroidSigner":
        """Build from a :class:`~raft_tpu.spatial.ann.common.CoarseIndex`
        (the serving index's own two-level probe geometry — the
        signature then matches what the probe would scan)."""
        return cls(np.asarray(coarse.super_cents), n_probes=n_probes)

    def super_ids(self, rows: np.ndarray) -> np.ndarray:
        """``(m, n_probes)`` SORTED top super ids per row (sorted so the
        signature is order-free — ties at equal distance cannot flip
        the key between two evaluations of the same vector)."""
        rows = np.ascontiguousarray(rows, np.float32)
        d2 = (
            self._norms[None, :]
            - 2.0 * rows @ self.super_cents.T
        )  # ||q||^2 is row-constant: drop it, argpartition is invariant
        p = self.n_probes
        if p >= d2.shape[1]:
            ids = np.tile(np.arange(d2.shape[1], dtype=np.int32),
                          (rows.shape[0], 1))
        else:
            ids = np.argpartition(d2, p - 1, axis=1)[:, :p]
        return np.sort(ids.astype(np.int32), axis=1)

    def __call__(self, rows: np.ndarray, salt: bytes = b"") -> np.ndarray:
        """Per-row 64-bit semantic signatures, ``(m, 2)`` int32."""
        ids = self.super_ids(rows)
        out = np.empty((ids.shape[0], 2), np.int32)
        for i in range(ids.shape[0]):
            dig = hashlib.blake2b(ids[i].tobytes() + b"sem" + salt,
                                  digest_size=8).digest()
            out[i] = np.frombuffer(dig, np.int32)
        return out


@dataclasses.dataclass(frozen=True)
class ResultCacheStats:
    """Point-in-time cache counters (monotonic)."""

    hits: int            # exact-tier row hits served
    semantic_hits: int   # semantic-tier row hits served
    misses: int          # rows that fell through to a real dispatch
    stale: int           # rows whose entry died on an epoch mismatch
    inserts: int         # rows written

    @property
    def hit_rate(self) -> float:
        served = self.hits + self.semantic_hits
        total = served + self.misses
        return served / total if total else 0.0


def semantic_recall(queries, search_fn, signer: CentroidSigner,
                    k: int) -> Optional[float]:
    """The MEASURED semantic-hit recall guardrail: for every pair of
    sample queries sharing a semantic signature, serve one query the
    OTHER's fresh top-k (exactly what a semantic hit does) and score
    id-overlap recall@k against its own fresh top-k. Returns the mean
    over all such ordered pairs, or None when no two sample queries
    collide (an unskewed sample cannot certify the tier — leave it
    disabled). ``search_fn(rows) -> (dists, ids)`` is the real warmed
    search; eager host work, an audit — never the serving path."""
    q = np.ascontiguousarray(queries, np.float32)
    _, ids = search_fn(q)
    ids = np.asarray(ids)[:, :k]
    sigs = signer(q)
    groups: dict = {}
    for i in range(q.shape[0]):
        groups.setdefault(tuple(sigs[i]), []).append(i)
    recalls = []
    for members in groups.values():
        for a in members:
            for b in members:
                if a == b:
                    continue
                # host numpy on an eager audit — not the serving loop
                mine = set(ids[a].tolist()) - {-1}  # jaxlint: disable=sync-in-hot-path
                if not mine:
                    continue
                served = set(ids[b].tolist())  # jaxlint: disable=sync-in-hot-path
                recalls.append(len(mine & served) / len(mine))
    return float(np.mean(recalls)) if recalls else None


class ResultCache:
    """The two-tier query-result cache (module docstring).

    ``k`` — the cached result width; lookups and inserts must use the
    same k (it is salted into every signature, so a k-8 entry can never
    answer a k-16 ask even across cache instances sharing storage).

    ``n_sets`` / ``associativity`` — the :class:`VectorCache` geometry
    of EACH tier (capacity = n_sets x associativity results; LRU within
    a set). ``signer`` — the semantic signer (None = exact tier only).

    ``semantic_min_recall`` — the guardrail floor
    :meth:`calibrate_semantic` must measure before semantic hits are
    served. The tier starts DISABLED: an uncalibrated semantic hit is
    an unbounded recall loss, and docs/serving.md lists the workloads
    where it should stay off.

    Thread-safe (one lock — submit threads look up while the drain
    thread inserts). Every lookup takes ``epoch`` as a required keyword
    so the call site visibly threads the current mutation epoch — the
    ``stale-epoch-read`` lint contract. Frozen serving threads a
    constant 0 and nothing ever goes stale.
    """

    def __init__(self, k: int, *, n_sets: int = 512,
                 associativity: int = 8,
                 signer: Optional[Callable] = None,
                 semantic_min_recall: float = 0.9,
                 name: str = "serving",
                 registry: "obs_metrics.MetricRegistry | None" = None):
        errors.expects(k >= 1, "ResultCache: k=%d < 1", k)
        self.k = int(k)
        self.dim = _N_META + 2 * self.k
        self.name = str(name)
        self.signer = signer
        self.semantic_min_recall = float(semantic_min_recall)
        self.semantic_enabled = False
        self.measured_semantic_recall: Optional[float] = None
        self._salt = b"k%d" % self.k
        self._lock = lockcheck.make_lock("ResultCache._lock")
        self._exact = VectorCache(self.dim, n_sets=n_sets,
                                  associativity=associativity,
                                  dtype=np.int32)
        self._semantic = (
            VectorCache(self.dim, n_sets=n_sets,
                        associativity=associativity, dtype=np.int32)
            if signer is not None else None
        )
        # the L1 exact front: a bounded per-REQUEST OrderedDict-LRU of
        # (epoch, dists, ids) keyed on the request's signature bytes.
        # The VectorCache tiers are array programs (~0.2 ms/probe even
        # jitted) — cheaper than a big serving dispatch, but NOT
        # cheaper than a saturated program's per-row cost, so the
        # hot-head exact path must be a host hash map (~µs). The L1
        # mirrors every insert; misses (different request grouping of
        # cached rows, L1 evictions) still fall through to the per-row
        # exact tier, and the semantic tier lives only in its
        # VectorCache. Same capacity as one tier, same lock.
        self._l1: "collections.OrderedDict[bytes, tuple]" = \
            collections.OrderedDict()
        self._l1_cap = int(n_sets) * int(associativity)
        reg = (obs_metrics.default_registry()
               if registry is None else registry)
        self._c = {
            res: reg.counter("serving_result_cache_total",
                             cache=self.name, result=res)
            for res in ("hit", "semantic_hit", "miss", "stale")
        }
        self._c_inserts = reg.counter(
            "serving_result_cache_inserts_total", cache=self.name)
        self._hits = 0
        self._semantic_hits = 0
        self._misses = 0
        self._stale = 0
        self._inserts = 0

    # -- signatures ----------------------------------------------------------
    def signatures(self, rows) -> np.ndarray:
        """The per-row exact signatures of a request — also the
        COALESCING key material (the executor keys its in-flight
        duplicate map on these, so cache and coalescer can never
        disagree about what "the same query" means)."""
        return exact_signatures(np.asarray(rows, np.float32), self._salt)

    # -- the serving surface -------------------------------------------------
    def _l1_put(self, key: bytes, epoch: int, dists: np.ndarray,
                ids: np.ndarray) -> None:
        """Under _lock: (re)front one request in the L1 LRU. Stores
        private copies — callers own what lookup hands them."""
        self._l1[key] = (int(epoch), dists.copy(), ids.copy())
        self._l1.move_to_end(key)
        while len(self._l1) > self._l1_cap:
            self._l1.popitem(last=False)

    def _probe_tier(self, cache: VectorCache, sigs: np.ndarray,
                    epoch: int):
        """One tier's batched probe: returns (dists, ids, ok, stale_keys)
        — ok rows verified sig-exact AND epoch-fresh; stale_keys are the
        set keys whose entry matched the signature at an OLD epoch."""
        keys = _fold_key(sigs[:, 0])
        vecs, found = cache.get_vecs(keys)
        vecs = np.asarray(vecs)
        found = np.asarray(found)
        m = sigs.shape[0]
        k = self.k
        sig_ok = (found
                  & (vecs[:, 0] == sigs[:, 0])
                  & (vecs[:, 1] == sigs[:, 1]))
        fresh = vecs[:, 2] == np.int32(epoch)
        ok = sig_ok & fresh
        dists = vecs[:, _N_META:_N_META + k].view(np.float32)
        ids = vecs[:, _N_META + k:].copy()
        stale_keys = keys[sig_ok & ~fresh]
        return dists, ids, ok, stale_keys

    def lookup(self, rows, *, epoch: int,
               sigs: Optional[np.ndarray] = None,
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Serve a ``(m, d)`` request from cache: ``(dists (m, k) f32,
        ids (m, k) i32)`` when EVERY row hits one tier (exact first,
        then — only when calibrated on — semantic), else None. A
        signature match stamped with an older ``epoch`` is STALE: the
        entry is evicted, the stale counter ticks, and the request
        falls through to a real dispatch — this is the invalidation
        path every mutation relies on (docs/serving.md "Hot traffic").
        ``sigs`` re-uses :meth:`signatures` the caller already computed
        (the executor computes them once for coalescing + lookup)."""
        rows = np.asarray(rows, np.float32)
        if sigs is None:
            sigs = self.signatures(rows)
        m = rows.shape[0]
        l1_key = sigs.tobytes()
        with self._lock:
            ent = self._l1.get(l1_key)
            if ent is not None:
                e_epoch, e_dists, e_ids = ent
                if e_epoch == epoch and e_dists.shape[0] == m:
                    self._l1.move_to_end(l1_key)
                    self._hits += m
                    self._c["hit"].inc(m)
                    return e_dists.copy(), e_ids.copy()
                # stale or shape-drifted: drop and fall through (the
                # exact-tier probe below does the stale accounting for
                # these same rows)
                del self._l1[l1_key]
            dists, ids, ok, stale_keys = self._probe_tier(
                self._exact, sigs, epoch)
            n_stale = int(stale_keys.size)
            if stale_keys.size:
                self._exact.evict(stale_keys)
            if bool(ok.all()):
                # a sig-matching-but-stale row has ok=False, so the
                # all-hit branch is by construction stale-free; promote
                # the request back into the L1 front
                dists = dists.copy()
                self._l1_put(l1_key, epoch, dists, ids)
                self._hits += m
                self._c["hit"].inc(m)
                return dists, ids
            want_sem = self._semantic is not None and \
                self.semantic_enabled
            if not want_sem:
                self._misses += m
                self._stale += n_stale
                self._c["miss"].inc(m)
                self._c["stale"].inc(n_stale)
                return None
        # the semantic signer is a host matmul over the super-centroid
        # set — pure in ``rows``, so it runs OUTSIDE the lock (under
        # it, every submit thread would serialize behind it; the brief
        # unlock is fine, the cache is best-effort state)
        ssigs = self.signer(rows, self._salt)
        with self._lock:
            sd, si, sok, s_stale = self._probe_tier(
                self._semantic, ssigs, epoch)
            if s_stale.size:
                self._semantic.evict(s_stale)
            n_stale += int(s_stale.size)
            served = ok | sok
            if bool(served.all()):
                dists = np.where(ok[:, None], dists, sd)
                ids = np.where(ok[:, None], ids, si)
                nex = int(ok.sum())
                self._hits += nex
                self._semantic_hits += m - nex
                self._stale += n_stale
                self._c["hit"].inc(nex)
                self._c["semantic_hit"].inc(m - nex)
                self._c["stale"].inc(n_stale)
                return dists.copy(), ids
            self._misses += m
            self._stale += n_stale
            self._c["miss"].inc(m)
            self._c["stale"].inc(n_stale)
        return None

    def insert(self, rows, dists, ids, *, epoch: int,
               sigs: Optional[np.ndarray] = None) -> None:
        """Cache one request's finished rows, stamped with the epoch the
        DISPATCH ran under (the executor captures it before dispatch —
        stamping with a later epoch would resurrect pre-write data as
        fresh; stamping earlier only costs an extra miss)."""
        rows = np.asarray(rows, np.float32)
        dists = np.asarray(dists, np.float32)
        ids = np.asarray(ids, np.int32)
        m = rows.shape[0]
        errors.expects(
            dists.shape == (m, self.k) and ids.shape == (m, self.k),
            "ResultCache.insert: expected (m=%d, k=%d) results, got "
            "dists %s ids %s", m, self.k, tuple(dists.shape),
            tuple(ids.shape),
        )
        if sigs is None:
            sigs = self.signatures(rows)
        payload = np.empty((m, self.dim), np.int32)
        payload[:, 0] = sigs[:, 0]
        payload[:, 1] = sigs[:, 1]
        payload[:, 2] = np.int32(epoch)
        payload[:, _N_META:_N_META + self.k] = dists.view(np.int32)
        payload[:, _N_META + self.k:] = ids
        spay = None
        if self._semantic is not None:
            # signer outside the lock, like lookup's semantic probe
            ssigs = self.signer(rows, self._salt)
            spay = payload.copy()
            spay[:, 0] = ssigs[:, 0]
            spay[:, 1] = ssigs[:, 1]
        with self._lock:
            self._l1_put(sigs.tobytes(), epoch, dists, ids)
            self._exact.store_vecs(_fold_key(sigs[:, 0]), payload)
            if spay is not None:
                self._semantic.store_vecs(_fold_key(spay[:, 0]), spay)
            self._inserts += m
        self._c_inserts.inc(m)

    # -- the guardrail -------------------------------------------------------
    def calibrate_semantic(self, queries, search_fn, *,
                           min_recall: Optional[float] = None) -> bool:
        """Measure :func:`semantic_recall` on a sample of the REAL
        workload and enable semantic hits iff it clears the floor.
        Returns the enable decision; the measured value lands in
        :attr:`measured_semantic_recall` (None = no colliding pair in
        the sample — the tier stays off, docs/serving.md says when to
        widen the sample vs when that answer is final)."""
        errors.expects(self.signer is not None,
                       "calibrate_semantic: this cache has no signer — "
                       "construct with signer=CentroidSigner(...)")
        floor = (self.semantic_min_recall if min_recall is None
                 else float(min_recall))
        r = semantic_recall(queries, search_fn, self.signer, self.k)
        self.measured_semantic_recall = r
        self.semantic_enabled = r is not None and r >= floor
        return self.semantic_enabled

    def stats(self) -> ResultCacheStats:
        with self._lock:
            return ResultCacheStats(
                hits=self._hits, semantic_hits=self._semantic_hits,
                misses=self._misses, stale=self._stale,
                inserts=self._inserts,
            )
