"""Shape-bucketed micro-batching for the open-loop serving executor.

The serving programs are compiled per batch shape: `index.warmup(nq)`
pre-compiles one program per query-count bucket, and steady-state
serving must dispatch ONLY those shapes — a single off-bucket batch
retraces, and a retrace on the hot path is a multi-second stall
(docs/serving.md "Open-loop serving"). This module is the host-side
arithmetic that makes that discipline automatic:

* :class:`BucketSet` — the warmed batch sizes (exactly the
  ``warmup(nq)``/``static_qcap`` set), with smallest-fitting-bucket
  selection;
* :class:`PendingRequest` — one submitted request: its query rows, its
  arrival stamp, and the future its caller is holding;
* :func:`pack_requests` — coalesce whole pending requests (arrival
  order, never splitting a request across batches) into one
  bucket-shaped :class:`MicroBatch`, zero-padding the tail rows —
  padded rows are dispatched (the program's shape demands them) but
  never demuxed into any caller's result.

Everything here is numpy on the host; device staging and dispatch live
in :mod:`raft_tpu.serving.executor`.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu import errors

__all__ = ["BucketSet", "PendingRequest", "MicroBatch", "pack_requests"]


@dataclasses.dataclass(frozen=True)
class BucketSet:
    """The warmed micro-batch sizes, ascending and distinct.

    ``select(n)`` returns the smallest bucket that fits ``n`` query
    rows — or the LARGEST bucket when ``n`` exceeds it (the caller
    packs what fits and leaves the rest pending; arrivals straddling a
    bucket boundary become two batches, never an unwarmed shape).
    """

    sizes: Tuple[int, ...]

    def __post_init__(self):
        errors.expects(len(self.sizes) >= 1, "BucketSet: no sizes")
        errors.expects(
            all(isinstance(s, int) and not isinstance(s, bool) and s >= 1
                for s in self.sizes),
            "BucketSet: sizes must be positive ints, got %r", self.sizes,
        )
        errors.expects(
            all(a < b for a, b in zip(self.sizes, self.sizes[1:])),
            "BucketSet: sizes must be strictly ascending, got %r",
            self.sizes,
        )

    @classmethod
    def of(cls, sizes: Sequence[int]) -> "BucketSet":
        return cls(tuple(sorted(int(s) for s in set(sizes))))

    @property
    def smallest(self) -> int:
        return self.sizes[0]

    @property
    def largest(self) -> int:
        return self.sizes[-1]

    def select(self, n_rows: int) -> int:
        """Smallest bucket >= ``n_rows`` (the largest when none fits)."""
        errors.expects(n_rows >= 1, "BucketSet.select: n_rows=%d < 1",
                       n_rows)
        for s in self.sizes:
            if s >= n_rows:
                return s
        return self.largest


@dataclasses.dataclass
class PendingRequest:
    """One submitted request, waiting to be packed into a micro-batch."""

    queries: np.ndarray        # (m, d) float32, m >= 1
    future: object             # concurrent.futures.Future
    t_arrival: float           # executor-clock stamp (flush deadline)
    ticket: Optional[object] = None   # opaque admission bookkeeping
    # the flight-recorder correlation id (raft_tpu.obs.flight): the
    # executor stamps a per-process sequence at submit so one request's
    # span events (submit→pack→dispatch→hedge→demux) join up
    req_id: int = -1
    # hot-traffic shaping (ISSUE 15): the request's per-row exact
    # signatures (computed once at submit — the coalescing key AND the
    # cache-fill key), the coalescing-leader map key this request
    # registered under (None = not a leader), and the futures of
    # requests COALESCED onto this one. Followers are resolved from
    # the demuxed batch result directly — NOT by mirroring this
    # request's own future, so a caller cancelling the leader can
    # never cancel an unrelated follower.
    sigs: Optional[np.ndarray] = None
    sig_key: Optional[tuple] = None
    followers: List[object] = dataclasses.field(default_factory=list)

    @property
    def n_rows(self) -> int:
        return int(self.queries.shape[0])


@dataclasses.dataclass
class MicroBatch:
    """One bucket-shaped batch: the padded host buffer plus the demux
    map back to the requests it carries."""

    queries: np.ndarray                      # (bucket, d) float32
    entries: List[Tuple[PendingRequest, int]]  # (request, start row)
    n_valid: int                             # valid rows; rest is padding
    batch_id: int = -1                       # flight-recorder correlation

    @property
    def bucket(self) -> int:
        return int(self.queries.shape[0])

    @property
    def n_requests(self) -> int:
        return len(self.entries)

    @property
    def n_padded(self) -> int:
        return self.bucket - self.n_valid


def pack_requests(pending: List[PendingRequest], buckets: BucketSet,
                  dim: int) -> Tuple[Optional[MicroBatch],
                                     List[PendingRequest]]:
    """Pack a prefix of ``pending`` (arrival order) into one micro-batch.

    Whole requests only: a request's rows always land contiguously in a
    single batch (its caller gets one result array), so a request that
    would overflow the chosen bucket stays pending for the NEXT batch —
    that is the bucket-straddling case, and it yields two warmed-shape
    dispatches instead of one unwarmed one. Returns
    ``(batch_or_None, still_pending)``; None only when ``pending`` is
    empty or its first request alone exceeds the largest bucket
    (rejected at submit, so not reachable through the executor).
    """
    if not pending:
        return None, pending
    total = sum(r.n_rows for r in pending)
    bucket = buckets.select(min(total, buckets.largest))
    taken: List[Tuple[PendingRequest, int]] = []
    used = 0
    for req in pending:
        if used + req.n_rows > bucket:
            break
        taken.append((req, used))
        used += req.n_rows
    if not taken:
        return None, pending
    # re-select on the rows that actually packed: the whole-request
    # constraint can leave `used` far below the total-row bucket guess
    # (buckets (4, 8), pending [3-row, 6-row] -> only 3 rows fit), and
    # dispatching them in the smaller warmed shape beats padding the
    # larger one
    bucket = buckets.select(used)
    out = np.zeros((bucket, dim), np.float32)
    for req, start in taken:
        out[start:start + req.n_rows] = req.queries
    batch = MicroBatch(queries=out, entries=taken, n_valid=used)
    return batch, pending[len(taken):]
