"""The open-loop serving executor: dynamic micro-batching + async
pipelined dispatch over the warmed one-dispatch programs.

The fused serving programs are fast enough that dispatch GAPS, not the
hardware, bound open-loop throughput: a loop that batches, dispatches,
and then blocks for the result leaves the device idle for the whole
host round trip of every batch. This executor closes those gaps
(docs/serving.md "Open-loop serving"):

* **Shape-bucketed coalescing** — arrivals are packed into micro-batches
  whose sizes are EXACTLY the ``index.warmup(nq)`` bucket set
  (:class:`raft_tpu.serving.batching.BucketSet`), so steady-state
  serving never retraces: health flips, failover re-routes, partial
  batches, and bursty arrivals all dispatch the same compiled programs
  (cache-size-audited in tests/test_open_loop.py — the same
  zero-retrace discipline as ``shard_mask``).
* **Pipelined staging** — the batcher thread stages the NEXT padded
  host buffer onto the device while earlier batches compute; with
  ``donate=True`` dispatch closures the staged buffer is donated, so
  steady state double-buffers host→device transfer against compute.
* **A bounded in-flight window** — up to ``max_in_flight`` dispatched
  programs ride JAX's async dispatch queue at once; the window bounds
  device-queue memory and keeps worst-case queueing delay
  ``max_in_flight × service_time``.
* **Completion-order demux** — a drain thread polls the in-flight set
  (readiness, not dispatch order), converts each finished batch to host
  once, and slices per-request rows back into the per-request futures
  callers hold. Padded rows never surface.
* **The resilience stack is wired in, not bolted on** — an
  :class:`~raft_tpu.resilience.AdmissionController` gates ``submit``
  (non-blocking ``enqueue``: open-loop arrivals are shed, never
  slowed), a :class:`~raft_tpu.resilience.HedgePolicy` +
  ``backup_dispatch`` hedges straggling batches onto the other replica
  (the batch's HOST copy is re-staged, so hedging composes with
  donation), and **runtime inputs** (``shard_mask`` /
  ``FailoverPlan`` route arrays) flow through ``set_runtime`` into
  every later dispatch — one executor serves healthy, degraded, and
  mixed-ingest traffic with the same compiled programs.

The executor is engine-agnostic: ``dispatch(staged_batch, **runtime)``
is any callable returning a pytree of device arrays whose
leading-axis-``bucket`` leaves are per-row results (a ``(dists, ids)``
tuple, a :class:`~raft_tpu.resilience.PartialSearchResult`, a mutation
-tier ``mutable_search`` output). It must be warmed for every bucket
size before ``submit`` traffic arrives.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from raft_tpu import compat, errors
from raft_tpu.core.interruptible import Interruptible
from raft_tpu.resilience.admission import AdmissionController
from raft_tpu.resilience.deadline import HedgePolicy
from raft_tpu.serving.batching import (
    BucketSet,
    MicroBatch,
    PendingRequest,
    pack_requests,
)

__all__ = ["ServingExecutor", "ExecutorStats"]


@dataclasses.dataclass(frozen=True)
class ExecutorStats:
    """Point-in-time executor counters (monotonic except the gauges)."""

    submitted: int            # requests accepted into the pending queue
    completed: int            # request futures resolved successfully
    failed: int               # request futures resolved with an error
    batches: int              # micro-batches dispatched
    flushes_full: int         # batches flushed because a bucket filled
    flushes_deadline: int     # batches flushed by the coalescing deadline
    valid_rows: int           # real query rows dispatched
    padded_rows: int          # zero rows dispatched for shape only
    hedged_batches: int       # batches that dispatched a backup
    backup_wins: int          # hedged batches the backup answered first
    pending: int              # gauge: requests waiting to be batched
    in_flight: int            # gauge: batches dispatched, not demuxed

    @property
    def pad_fraction(self) -> float:
        """Padding overhead of the bucket discipline (padded rows over
        all dispatched rows) — the knob-tuning signal for bucket sizes
        vs ``flush_age_s`` (docs/serving.md)."""
        total = self.padded_rows + self.valid_rows
        return self.padded_rows / total if total else 0.0


class _InFlight:
    """One dispatched micro-batch awaiting demux."""

    __slots__ = ("batch", "candidates", "t_dispatch", "ticket",
                 "runtime", "hedged", "t_hedge_attempt")

    def __init__(self, batch: MicroBatch, out: Any, t_dispatch: float,
                 ticket: Optional[int], runtime: Dict[str, Any]):
        self.batch = batch
        self.candidates: List[Any] = [out]   # [primary, backup?]
        self.t_dispatch = t_dispatch
        self.ticket = ticket
        self.runtime = runtime
        self.hedged = False
        self.t_hedge_attempt: Optional[float] = None


def _ready(tree: Any) -> bool:
    return all(
        leaf.is_ready()
        for leaf in jax.tree.leaves(tree) if hasattr(leaf, "is_ready")
    )


class ServingExecutor:
    """Open-loop serving front end over warmed bucket programs.

    ``dispatch(staged, **runtime)`` — the warmed serving closure; it
    receives a device-staged ``(bucket, dim)`` float32 batch and the
    current runtime-input snapshot and returns device outputs whose
    leading-axis-``bucket`` arrays are per-row results.

    ``buckets`` — the warmed batch sizes (a :class:`BucketSet` or a
    sequence of ints); ``submit`` rejects requests larger than the
    largest bucket (``RaftLogicError`` — warm a bigger bucket instead,
    an unwarmed shape would retrace on the hot path).

    ``flush_age_s`` — the coalescing deadline: a partial batch is
    flushed once its OLDEST request has waited this long (latency floor
    at light load; bigger values fill bigger buckets).

    ``max_in_flight`` — the async dispatch window, in batches.

    ``admission`` — optional :class:`AdmissionController`; its queue
    bound sheds ``submit`` callers with
    :class:`~raft_tpu.errors.RaftOverloadError` and its occupancy feeds
    ``retry_after_s`` pricing. ``max_queue`` counts REQUESTS waiting to
    be batched — size it to the queueing delay you will tolerate.

    ``hedge`` / ``backup_dispatch`` — optional straggler cover: a batch
    not ready ``hedge.hedge_delay_s()`` (or a fixed float) after
    dispatch is re-dispatched through ``backup_dispatch`` (the OTHER
    replica's warmed closure) from its retained host buffer; the first
    ready answer is demuxed, the loser is abandoned cooperatively.

    ``runtime_inputs`` — initial runtime-operand snapshot passed as
    keyword arguments to every dispatch (e.g. ``shard_mask=``,
    ``failover=``); :meth:`set_runtime` swaps values mid-stream with
    zero retraces (they are runtime operands of the compiled program).

    ``stage`` — host→device staging (default :func:`jax.device_put`);
    override to pin placement. ``donate`` is the caller's contract
    with its dispatch closure; the executor always re-stages hedged
    batches from the host copy, so donation inside ``dispatch`` is
    safe.
    """

    def __init__(
        self,
        dispatch: Callable[..., Any],
        buckets: "BucketSet | Sequence[int]",
        *,
        dim: int,
        flush_age_s: float = 0.002,
        max_in_flight: int = 4,
        admission: Optional[AdmissionController] = None,
        hedge: "HedgePolicy | float | None" = None,
        backup_dispatch: Optional[Callable[..., Any]] = None,
        runtime_inputs: Optional[Dict[str, Any]] = None,
        stage: Callable[[np.ndarray], Any] = jax.device_put,
        clock: Callable[[], float] = time.monotonic,
        name: str = "serving",
    ):
        errors.expects(dim >= 1, "ServingExecutor: dim=%d < 1", dim)
        errors.expects(
            flush_age_s >= 0.0,
            "ServingExecutor: flush_age_s=%s < 0", flush_age_s,
        )
        errors.expects(
            max_in_flight >= 1,
            "ServingExecutor: max_in_flight=%d < 1", max_in_flight,
        )
        errors.expects(
            backup_dispatch is None or hedge is not None,
            "ServingExecutor: backup_dispatch without a hedge policy "
            "would never fire; pass hedge=",
        )
        self._dispatch = dispatch
        self.buckets = (
            buckets if isinstance(buckets, BucketSet)
            else BucketSet.of(buckets)
        )
        self.dim = int(dim)
        self.flush_age_s = float(flush_age_s)
        self.max_in_flight = int(max_in_flight)
        self.admission = admission
        self.hedge = hedge
        self._backup = backup_dispatch
        self._stage = stage
        self._clock = clock
        self.name = name

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)       # batcher wake
        self._done = threading.Condition(self._lock)       # drain wake
        self._pending: List[PendingRequest] = []
        self._inflight: List[_InFlight] = []
        self._closed = False
        self._batcher_exited = False
        # counters (under _lock)
        self._submitted = 0
        self._completed = 0
        self._failed = 0
        self._batches = 0
        self._flushes_full = 0
        self._flushes_deadline = 0
        self._valid_rows = 0
        self._padded_rows = 0
        self._hedged_batches = 0
        self._backup_wins = 0
        self._runtime: Dict[str, Any] = dict(runtime_inputs or {})

        self._batcher = threading.Thread(
            target=self._batch_loop, name=f"{name}-batcher", daemon=True,
        )
        self._drainer = threading.Thread(
            target=self._drain_loop, name=f"{name}-drain", daemon=True,
        )
        self._batcher.start()
        self._drainer.start()

    # -- the request surface -------------------------------------------------
    def submit(self, queries) -> Future:
        """Queue one request (``(d,)`` or ``(m, d)`` float32 rows) and
        return its :class:`~concurrent.futures.Future`. The result is
        the dispatch output's pytree with every leading-axis-bucket
        array sliced to THIS request's ``m`` rows (host numpy).

        Never blocks on the server: a full admission queue sheds with
        :class:`~raft_tpu.errors.RaftOverloadError` immediately
        (``retry_after_s`` priced from occupancy), an oversized request
        fails loudly instead of retracing an unwarmed shape, and
        otherwise the request is pending when this returns.
        """
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        errors.expects(
            q.ndim == 2 and q.shape[1] == self.dim,
            "submit: expected (m, %d) query rows, got %s",
            self.dim, tuple(q.shape),
        )
        errors.expects(
            1 <= q.shape[0] <= self.buckets.largest,
            "submit: %d rows exceed the largest warmed bucket (%d) — "
            "warm a bigger bucket or split the request",
            q.shape[0], self.buckets.largest,
        )
        if self.admission is not None:
            self.admission.enqueue()       # may shed: RaftOverloadError
        fut: Future = Future()
        req = PendingRequest(queries=q, future=fut,
                             t_arrival=self._clock())
        with self._work:
            if self._closed:
                if self.admission is not None:
                    self.admission.cancel_queued()
                errors.fail("submit on a closed ServingExecutor")
            self._pending.append(req)
            self._submitted += 1
            self._work.notify()
        return fut

    def set_runtime(self, **updates: Any) -> None:
        """Swap runtime-operand values (``shard_mask=``, ``failover=``,
        mutation slabs, ...) for every LATER dispatch. Values are
        runtime inputs of the compiled programs, so flips never
        retrace; in-flight batches keep the snapshot they were
        dispatched with (``None`` removes a key)."""
        with self._lock:
            for key, val in updates.items():
                if val is None:
                    self._runtime.pop(key, None)
                else:
                    self._runtime[key] = val

    def stats(self) -> ExecutorStats:
        with self._lock:
            return ExecutorStats(
                submitted=self._submitted,
                completed=self._completed,
                failed=self._failed,
                batches=self._batches,
                flushes_full=self._flushes_full,
                flushes_deadline=self._flushes_deadline,
                valid_rows=self._valid_rows,
                padded_rows=self._padded_rows,
                hedged_batches=self._hedged_batches,
                backup_wins=self._backup_wins,
                pending=len(self._pending),
                in_flight=len(self._inflight),
            )

    def close(self, timeout_s: float = 30.0) -> None:
        """Flush remaining pending requests, drain in-flight batches,
        and stop both loops. Idempotent."""
        with self._work:
            self._closed = True
            self._work.notify_all()
            self._done.notify_all()
        self._batcher.join(timeout_s)
        self._drainer.join(timeout_s)

    def __enter__(self) -> "ServingExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the batcher thread --------------------------------------------------
    def _flush_wait_s(self) -> Optional[float]:
        """Under _lock: seconds until the oldest pending request's
        coalescing deadline, 0 when a flush is due NOW, None when there
        is nothing to flush."""
        if not self._pending:
            return None
        rows = sum(r.n_rows for r in self._pending)
        if rows >= self.buckets.largest or self._closed:
            return 0.0
        age = self._clock() - self._pending[0].t_arrival
        return max(0.0, self.flush_age_s - age)

    def _batch_loop(self) -> None:
        while True:
            with self._work:
                wait_s = self._flush_wait_s()
                while not (wait_s == 0.0 or (self._closed
                                             and not self._pending)):
                    self._work.wait(
                        timeout=0.05 if wait_s is None else wait_s
                    )
                    wait_s = self._flush_wait_s()
                if self._closed and not self._pending:
                    break
                rows = sum(r.n_rows for r in self._pending)
                batch, self._pending = pack_requests(
                    self._pending, self.buckets, self.dim
                )
                if batch is None:      # unreachable via submit; be safe
                    continue
                runtime = dict(self._runtime)
                full = batch.n_padded == 0 and rows >= batch.bucket
            self._dispatch_batch(batch, runtime, full)
        with self._done:
            self._batcher_exited = True
            self._done.notify_all()

    def _dispatch_batch(self, batch: MicroBatch,
                        runtime: Dict[str, Any], full: bool) -> None:
        # window check OUTSIDE the lock: the batcher blocks here (not
        # the submitters) when max_in_flight programs are queued
        while True:
            with self._done:
                if len(self._inflight) < self.max_in_flight:
                    break
                self._done.wait(0.05)
        ticket = None
        try:
            if self.admission is not None:
                ticket = self.admission.begin_service(batch.n_requests)
            # stage the padded host buffer, then dispatch: both are
            # async against earlier batches still computing — this IS
            # the double buffer (donate-friendly: hedges re-stage from
            # batch.queries, never reuse this device buffer)
            staged = self._stage(batch.queries)
            t0 = self._clock()
            out = self._dispatch(staged, **runtime)
        except Exception as exc:   # noqa: BLE001 — fail THIS batch only
            if ticket is not None:
                # abort, not finish: a crashed dispatch must not feed
                # its ~0 held-time into the service EWMA or count its
                # failed requests as completed
                self.admission.abort_service(ticket)
            elif self.admission is not None:
                self.admission.cancel_queued(batch.n_requests)
            self._fail_batch(batch, exc)
            return
        fl = _InFlight(batch, out, t0, ticket, runtime)
        with self._done:
            self._inflight.append(fl)
            self._batches += 1
            if full:
                self._flushes_full += 1
            else:
                self._flushes_deadline += 1
            self._valid_rows += batch.n_valid
            self._padded_rows += batch.n_padded
            self._done.notify_all()

    # -- the drain (demux) thread --------------------------------------------
    def _hedge_delay_s(self) -> Optional[float]:
        if self.hedge is None or self._backup is None:
            return None
        if isinstance(self.hedge, HedgePolicy):
            return self.hedge.hedge_delay_s()
        return float(self.hedge)

    def _maybe_hedge(self, fl: _InFlight, delay: float) -> None:
        now = self._clock()
        if fl.hedged or now - fl.t_dispatch < delay:
            return
        # space retries by the hedge delay: a transiently-failing
        # backup gets another shot next window, not every 0.5 ms sweep
        if (fl.t_hedge_attempt is not None
                and now - fl.t_hedge_attempt < delay):
            return
        fl.t_hedge_attempt = now
        try:
            backup = self._backup(
                self._stage(fl.batch.queries), **fl.runtime
            )
        except Exception:   # noqa: BLE001 — primary still owes the answer
            return
        # mark hedged only on a SUCCESSFUL backup dispatch: the flag
        # drives the primary_wins/backup_wins accounting in _finish,
        # and a failed attempt must leave the batch re-hedgeable
        fl.hedged = True
        fl.candidates.append(backup)
        with self._lock:
            self._hedged_batches += 1
        if isinstance(self.hedge, HedgePolicy):
            with self.hedge._lock:
                self.hedge.hedges += 1

    def _drain_loop(self) -> None:
        poll_s = 0.0005
        while True:
            with self._done:
                while not self._inflight and not (
                    self._closed and self._batcher_exited
                ):
                    self._done.wait(0.05)
                if not self._inflight:
                    if self._closed and self._batcher_exited \
                            and not self._pending:
                        return
                    continue
                snapshot = list(self._inflight)
            # hedge-delay check EVERY iteration: near saturation some
            # batch is almost always ready, and a straggler must not
            # wait for an idle poll loop to be covered. The delay is
            # batch-independent — compute it once per sweep, not per
            # batch (HedgePolicy.hedge_delay_s takes its lock and runs
            # a percentile over the sample window)
            delay = self._hedge_delay_s()
            if delay is not None:
                for fl in snapshot:
                    self._maybe_hedge(fl, delay)
            finished = None
            for fl in snapshot:                # completion order, not FIFO
                for cand in fl.candidates:
                    if _ready(cand):
                        finished = (fl, cand)
                        break
                if finished is not None:
                    break
            if finished is None:
                Interruptible.yield_now()
                time.sleep(poll_s)
                poll_s = min(poll_s * 2.0, 0.02)
                continue
            poll_s = 0.0005
            fl, winner = finished
            with self._done:
                self._inflight.remove(fl)
                self._done.notify_all()
            self._finish(fl, winner)

    def _finish(self, fl: _InFlight, winner: Any) -> None:
        if fl.ticket is not None:
            self.admission.finish_service(fl.ticket)
        held = self._clock() - fl.t_dispatch
        backup_won = fl.hedged and len(fl.candidates) > 1 \
            and winner is fl.candidates[1]
        if isinstance(self.hedge, HedgePolicy):
            self.hedge.record(held)
            with self.hedge._lock:
                if not fl.hedged:
                    self.hedge.unhedged += 1
                elif backup_won:
                    self.hedge.backup_wins += 1
                else:
                    self.hedge.primary_wins += 1
        # readiness-gating wrappers (testing.faults.DelayedReady) carry
        # the real output in .value — demux the underlying tree
        while hasattr(winner, "is_ready") and hasattr(winner, "value") \
                and not hasattr(winner, "shape"):
            winner = winner.value
        try:
            # the ONE intentional host sync of the serving path: the
            # winner is already ready, this is the demux conversion
            host = compat.tree_map(np.asarray, winner)  # jaxlint: disable=sync-in-hot-path
        except Exception as exc:   # noqa: BLE001
            self._fail_batch(fl.batch, exc)
            return
        bucket = fl.batch.bucket
        delivered = 0
        for req, start in fl.batch.entries:
            if req.future.done():     # caller cancelled while queued
                continue
            rows = slice(start, start + req.n_rows)
            result = compat.tree_map(
                lambda a, rows=rows: a[rows] if (
                    isinstance(a, np.ndarray) and a.ndim >= 1
                    and a.shape[0] == bucket
                ) else a,
                host,
            )
            try:
                req.future.set_result(result)
            except InvalidStateError:
                continue              # cancel raced the done() check
            delivered += 1
        with self._lock:
            self._completed += delivered
            self._backup_wins += int(backup_won)

    def _fail_batch(self, batch: MicroBatch, exc: BaseException) -> None:
        for req, _ in batch.entries:
            if not req.future.done():
                try:
                    req.future.set_exception(exc)
                except InvalidStateError:
                    pass              # cancel raced the done() check
        with self._lock:
            self._failed += batch.n_requests
