"""The open-loop serving executor: dynamic micro-batching + async
pipelined dispatch over the warmed one-dispatch programs.

The fused serving programs are fast enough that dispatch GAPS, not the
hardware, bound open-loop throughput: a loop that batches, dispatches,
and then blocks for the result leaves the device idle for the whole
host round trip of every batch. This executor closes those gaps
(docs/serving.md "Open-loop serving"):

* **Shape-bucketed coalescing** — arrivals are packed into micro-batches
  whose sizes are EXACTLY the ``index.warmup(nq)`` bucket set
  (:class:`raft_tpu.serving.batching.BucketSet`), so steady-state
  serving never retraces: health flips, failover re-routes, partial
  batches, and bursty arrivals all dispatch the same compiled programs
  (cache-size-audited in tests/test_open_loop.py — the same
  zero-retrace discipline as ``shard_mask``).
* **Pipelined staging** — the batcher thread stages the NEXT padded
  host buffer onto the device while earlier batches compute; with
  ``donate=True`` dispatch closures the staged buffer is donated, so
  steady state double-buffers host→device transfer against compute.
* **A bounded in-flight window** — up to ``max_in_flight`` dispatched
  programs ride JAX's async dispatch queue at once; the window bounds
  device-queue memory and keeps worst-case queueing delay
  ``max_in_flight × service_time``.
* **Completion-order demux** — a drain thread polls the in-flight set
  (readiness, not dispatch order), converts each finished batch to host
  once, and slices per-request rows back into the per-request futures
  callers hold. Padded rows never surface.
* **The resilience stack is wired in, not bolted on** — an
  :class:`~raft_tpu.resilience.AdmissionController` gates ``submit``
  (non-blocking ``enqueue``: open-loop arrivals are shed, never
  slowed), a :class:`~raft_tpu.resilience.HedgePolicy` +
  ``backup_dispatch`` hedges straggling batches onto the other replica
  (the batch's HOST copy is re-staged, so hedging composes with
  donation), and **runtime inputs** (``shard_mask`` /
  ``FailoverPlan`` route arrays) flow through ``set_runtime`` into
  every later dispatch — one executor serves healthy, degraded, and
  mixed-ingest traffic with the same compiled programs.

The executor is engine-agnostic: ``dispatch(staged_batch, **runtime)``
is any callable returning a pytree of device arrays whose
leading-axis-``bucket`` leaves are per-row results (a ``(dists, ids)``
tuple, a :class:`~raft_tpu.resilience.PartialSearchResult`, a mutation
-tier ``mutable_search`` output). It must be warmed for every bucket
size before ``submit`` traffic arrives.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from raft_tpu import compat, errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.core.interruptible import Interruptible
from raft_tpu.obs import crash as obs_crash
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs.flight import FlightRecorder
from raft_tpu.resilience.admission import AdmissionController
from raft_tpu.resilience.deadline import HedgePolicy
from raft_tpu.serving.batching import (
    BucketSet,
    MicroBatch,
    PendingRequest,
    pack_requests,
)
from raft_tpu.serving.result_cache import ResultCache, exact_signatures

__all__ = ["ServingExecutor", "ExecutorStats", "STAGES"]

# the serving pipeline's named stages, in hop order — each is a
# ``serving_stage_ms{executor,stage,bucket}`` histogram recorded from
# timestamps the executor already takes (docs/observability.md "Stage
# timing"): queue_wait (submit → packed), batch_build (pack + pad),
# staging (host→device put), dispatch_ready (dispatch → drain-loop
# readiness — the polling gives it for free, no block_until_ready),
# demux (host conversion + per-request slicing), e2e (submit → future
# resolved; the SLO-trigger input)
STAGES = ("queue_wait", "batch_build", "staging", "dispatch_ready",
          "demux", "e2e")


@dataclasses.dataclass(frozen=True)
class ExecutorStats:
    """Point-in-time executor counters (monotonic except the gauges)."""

    submitted: int            # requests accepted into the pending queue
    completed: int            # request futures resolved successfully
    failed: int               # request futures resolved with an error
    batches: int              # micro-batches dispatched
    flushes_full: int         # batches flushed because a bucket filled
    flushes_deadline: int     # batches flushed by the coalescing deadline
    valid_rows: int           # real query rows dispatched
    padded_rows: int          # zero rows dispatched for shape only
    hedged_batches: int       # batches that dispatched a backup
    backup_wins: int          # hedged batches the backup answered first
    pending: int              # gauge: requests waiting to be batched
    in_flight: int            # gauge: batches dispatched, not demuxed
    # NOTE: new fields are APPENDED with defaults (after the r13 stage
    # dicts below) so pre-r15 positional constructions stay valid
    # histogram-derived per-stage latency quantiles (ISSUE 13): stage
    # name -> milliseconds, pooled across this executor's buckets via
    # the registry's log2 histograms. Appended with defaults so every
    # pre-r13 positional construction and field read stays valid —
    # nothing deprecated, nothing moved.
    stage_p50_ms: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    stage_p99_ms: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    # hot-traffic shaping (ISSUE 15, docs/serving.md "Hot traffic"),
    # appended with byte-compatible defaults like the r13 stage dicts:
    # requests answered by subscribing to an identical in-flight
    # request's future (they never consumed micro-batch rows), requests
    # served straight from the result cache, and cached ROW entries
    # that died on an epoch mismatch (the invalidation counter)
    coalesced_requests: int = 0
    cache_hits: int = 0
    cache_stale: int = 0

    @property
    def pad_fraction(self) -> float:
        """Padding overhead of the bucket discipline (padded rows over
        all dispatched rows) — the knob-tuning signal for bucket sizes
        vs ``flush_age_s`` (docs/serving.md)."""
        total = self.padded_rows + self.valid_rows
        return self.padded_rows / total if total else 0.0


class _InFlight:
    """One dispatched micro-batch awaiting demux."""

    __slots__ = ("batch", "candidates", "t_dispatch", "ticket",
                 "runtime", "hedged", "t_hedge_attempt", "epoch")

    def __init__(self, batch: MicroBatch, out: Any, t_dispatch: float,
                 ticket: Optional[int], runtime: Dict[str, Any],
                 epoch: int = 0):
        self.batch = batch
        self.candidates: List[Any] = [out]   # [primary, backup?]
        self.t_dispatch = t_dispatch
        self.ticket = ticket
        self.runtime = runtime
        self.hedged = False
        self.t_hedge_attempt: Optional[float] = None
        # the mutation epoch the dispatch ran under — cache fills are
        # stamped with THIS value, captured with the runtime snapshot
        # (stamping the completion-time epoch would mark pre-write
        # results fresh after a mid-flight write)
        self.epoch = epoch


def _ready(tree: Any) -> bool:
    return all(
        leaf.is_ready()
        for leaf in jax.tree.leaves(tree) if hasattr(leaf, "is_ready")
    )


class ServingExecutor:
    """Open-loop serving front end over warmed bucket programs.

    ``dispatch(staged, **runtime)`` — the warmed serving closure; it
    receives a device-staged ``(bucket, dim)`` float32 batch and the
    current runtime-input snapshot and returns device outputs whose
    leading-axis-``bucket`` arrays are per-row results.

    ``buckets`` — the warmed batch sizes (a :class:`BucketSet` or a
    sequence of ints); ``submit`` rejects requests larger than the
    largest bucket (``RaftLogicError`` — warm a bigger bucket instead,
    an unwarmed shape would retrace on the hot path).

    ``flush_age_s`` — the coalescing deadline: a partial batch is
    flushed once its OLDEST request has waited this long (latency floor
    at light load; bigger values fill bigger buckets).

    ``max_in_flight`` — the async dispatch window, in batches.

    ``admission`` — optional :class:`AdmissionController`; its queue
    bound sheds ``submit`` callers with
    :class:`~raft_tpu.errors.RaftOverloadError` and its occupancy feeds
    ``retry_after_s`` pricing. ``max_queue`` counts REQUESTS waiting to
    be batched — size it to the queueing delay you will tolerate.

    ``hedge`` / ``backup_dispatch`` — optional straggler cover: a batch
    not ready ``hedge.hedge_delay_s()`` (or a fixed float) after
    dispatch is re-dispatched through ``backup_dispatch`` (the OTHER
    replica's warmed closure) from its retained host buffer; the first
    ready answer is demuxed, the loser is abandoned cooperatively.

    ``runtime_inputs`` — initial runtime-operand snapshot passed as
    keyword arguments to every dispatch (e.g. ``shard_mask=``,
    ``failover=``); :meth:`set_runtime` swaps values mid-stream with
    zero retraces (they are runtime operands of the compiled program).

    ``runtime_provider`` — optional per-dispatch runtime source
    (ISSUE 17, docs/tiering.md): a zero-arg callable returning a dict
    overlaid onto the runtime snapshot once per batch, sampled outside
    the executor's locks just before staging. This is how a
    :class:`~raft_tpu.tier.TieredListStore` hands each dispatch its
    CURRENT hot-tier view without ``set_runtime`` churn — promotions
    flip runtime operands, never statics, so no dispatch retraces. The
    sampled overlay rides in the batch's in-flight record: a hedge
    re-dispatch reuses the exact snapshot the primary saw.

    ``stage`` — host→device staging (default :func:`jax.device_put`);
    override to pin placement. ``donate`` is the caller's contract
    with its dispatch closure; the executor always re-stages hedged
    batches from the host copy, so donation inside ``dispatch`` is
    safe.

    ``registry`` — the :class:`~raft_tpu.obs.MetricRegistry` the
    per-stage latency histograms (:data:`STAGES`), hedge counters, and
    the coverage gauge record into (default: the process-wide
    registry; ``RAFT_TPU_OBS=off`` no-ops every recorder).
    ``flight`` — an optional :class:`~raft_tpu.obs.FlightRecorder`;
    when given, every request's span (submit→pack→dispatch→hedge→
    demux) is traced by id and the ring is auto-dumped as JSONL when a
    batch fails or ``close()`` finds failures outstanding
    (docs/observability.md "Flight recorder").

    ``result_cache`` / ``epoch_fn`` / ``coalesce`` — hot-traffic
    shaping (ISSUE 15, docs/serving.md "Hot traffic"): a
    :class:`~raft_tpu.serving.ResultCache` serves repeated queries
    before they reach admission or a micro-batch (fills are stamped
    with the dispatch-time mutation epoch from ``epoch_fn``, default
    constant 0 for frozen indexes; ``set_runtime`` re-samples it with
    every state swap), and coalescing (on by default whenever a cache
    is given) subscribes an identical same-epoch in-flight duplicate
    to the original's future instead of dispatching it again. Both are
    host-side only: the compiled dispatch programs are untouched, so
    cache on/off can never retrace.
    """

    def __init__(
        self,
        dispatch: Callable[..., Any],
        buckets: "BucketSet | Sequence[int]",
        *,
        dim: int,
        flush_age_s: float = 0.002,
        max_in_flight: int = 4,
        admission: Optional[AdmissionController] = None,
        hedge: "HedgePolicy | float | None" = None,
        backup_dispatch: Optional[Callable[..., Any]] = None,
        runtime_inputs: Optional[Dict[str, Any]] = None,
        runtime_provider: Optional[Callable[[], Dict[str, Any]]] = None,
        stage: Callable[[np.ndarray], Any] = jax.device_put,
        clock: Callable[[], float] = time.monotonic,
        name: str = "serving",
        registry: "obs_metrics.MetricRegistry | None" = None,
        flight: Optional[FlightRecorder] = None,
        result_cache: Optional[ResultCache] = None,
        epoch_fn: Optional[Callable[[], int]] = None,
        coalesce: Optional[bool] = None,
    ):
        errors.expects(dim >= 1, "ServingExecutor: dim=%d < 1", dim)
        errors.expects(
            flush_age_s >= 0.0,
            "ServingExecutor: flush_age_s=%s < 0", flush_age_s,
        )
        errors.expects(
            max_in_flight >= 1,
            "ServingExecutor: max_in_flight=%d < 1", max_in_flight,
        )
        errors.expects(
            backup_dispatch is None or hedge is not None,
            "ServingExecutor: backup_dispatch without a hedge policy "
            "would never fire; pass hedge=",
        )
        self._dispatch = dispatch
        self.buckets = (
            buckets if isinstance(buckets, BucketSet)
            else BucketSet.of(buckets)
        )
        self.dim = int(dim)
        self.flush_age_s = float(flush_age_s)
        self.max_in_flight = int(max_in_flight)
        self.admission = admission
        self.hedge = hedge
        self._backup = backup_dispatch
        self._stage = stage
        self._clock = clock
        self.name = name
        # observability (ISSUE 13, docs/observability.md): per-stage
        # log2 latency histograms keyed (stage, bucket) — handles are
        # cached here so the hot path never touches the registry lock —
        # plus the optional flight recorder tracing request ids through
        # every hop. All recording honors the RAFT_TPU_OBS gate.
        self._registry = (obs_metrics.default_registry()
                          if registry is None else registry)
        self.flight = flight
        self._stage_hist: Dict[tuple, obs_metrics.Histogram] = {}
        self._c_completed = self._registry.counter(
            "serving_requests_total", executor=name, outcome="completed")
        self._c_failed = self._registry.counter(
            "serving_requests_total", executor=name, outcome="failed")
        self._c_hedges = self._registry.counter(
            "serving_hedges_total", executor=name)
        self._c_backup_wins = self._registry.counter(
            "serving_backup_wins_total", executor=name)
        # created on FIRST coverage sighting: a single-chip executor
        # never demuxes a PartialSearchResult, and a coverage gauge
        # stuck at its 0.0 initial value would read as total loss
        self._g_coverage: Optional[obs_metrics.Gauge] = None
        # hot-traffic shaping (ISSUE 15, docs/serving.md "Hot
        # traffic"): the optional result cache, the mutation-epoch
        # source (default: constant 0 — a frozen index never goes
        # stale), and request coalescing (on whenever a cache supplies
        # the signature scheme, or forced with coalesce=True)
        self._rcache = result_cache
        self._epoch_fn: Callable[[], int] = (
            (lambda: 0) if epoch_fn is None else epoch_fn
        )
        self._coalesce_on = (
            result_cache is not None if coalesce is None else bool(coalesce)
        )
        self._c_coalesced = self._registry.counter(
            "serving_coalesced_total", executor=name)
        self._sig_leaders: Dict[tuple, tuple] = {}   # key -> (req, epoch)
        self._coalesced = 0
        self._cache_hits = 0
        self._req_seq = 0
        self._batch_seq = 0
        # the epoch every dispatch is stamped with: sampled at init and
        # re-sampled by set_runtime (the serialization point at which
        # mutated state becomes visible to later dispatches) — see
        # docs/serving.md "Hot traffic" for the install ordering rule
        self._rt_epoch = int(self._epoch_fn())

        self._lock = lockcheck.make_lock("ServingExecutor._lock")
        self._work = lockcheck.make_condition(self._lock)  # batcher wake
        self._done = lockcheck.make_condition(self._lock)  # drain wake
        self._pending: List[PendingRequest] = []
        self._inflight: List[_InFlight] = []
        self._closed = False
        self._batcher_exited = False
        # counters (under _lock)
        self._submitted = 0
        self._completed = 0
        self._failed = 0
        self._batches = 0
        self._flushes_full = 0
        self._flushes_deadline = 0
        self._valid_rows = 0
        self._padded_rows = 0
        self._hedged_batches = 0
        self._backup_wins = 0
        self._runtime: Dict[str, Any] = dict(runtime_inputs or {})
        # per-dispatch runtime source (ISSUE 17, docs/tiering.md): a
        # callable sampled once per batch, OUTSIDE the executor lock,
        # whose dict overlays self._runtime — how a TieredListStore
        # hands every dispatch the CURRENT hot-tier snapshot without a
        # set_runtime round-trip per promotion. The sampled snapshot is
        # pinned into the batch's _InFlight record so a hedge re-uses
        # the exact operands the primary saw.
        self._runtime_provider = runtime_provider

        # a dead batcher/drainer must not vanish silently: route
        # uncaught thread exceptions to thread_uncaught_total + a
        # flight event (docs/observability.md "Thread crashes")
        obs_crash.install_excepthook()
        if self.flight is not None:
            obs_crash.set_flight_sink(self.flight)
        self._batcher = threading.Thread(
            target=self._batch_loop, name=f"{name}-batcher", daemon=True,
        )
        self._drainer = threading.Thread(
            target=self._drain_loop, name=f"{name}-drain", daemon=True,
        )
        self._batcher.start()
        self._drainer.start()

    # -- the request surface -------------------------------------------------
    def submit(self, queries) -> Future:
        """Queue one request (``(d,)`` or ``(m, d)`` float32 rows) and
        return its :class:`~concurrent.futures.Future`. The result is
        the dispatch output's pytree with every leading-axis-bucket
        array sliced to THIS request's ``m`` rows (host numpy).

        Never blocks on the server: a full admission queue sheds with
        :class:`~raft_tpu.errors.RaftOverloadError` immediately
        (``retry_after_s`` priced from occupancy), an oversized request
        fails loudly instead of retracing an unwarmed shape, and
        otherwise the request is pending when this returns.
        """
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        errors.expects(
            q.ndim == 2 and q.shape[1] == self.dim,
            "submit: expected (m, %d) query rows, got %s",
            self.dim, tuple(q.shape),
        )
        errors.expects(
            1 <= q.shape[0] <= self.buckets.largest,
            "submit: %d rows exceed the largest warmed bucket (%d) — "
            "warm a bigger bucket or split the request",
            q.shape[0], self.buckets.largest,
        )
        # hot-traffic shaping (docs/serving.md "Hot traffic"): a cache
        # hit or a coalesce answers BEFORE admission — neither consumes
        # a queue slot or a micro-batch row
        sigs = None
        epoch_now = 0
        if self._rcache is not None or self._coalesce_on:
            epoch_now = int(self._epoch_fn())
            sigs = (self._rcache.signatures(q)
                    if self._rcache is not None
                    else exact_signatures(q))
        if self._rcache is not None:
            cached = self._rcache.lookup(q, epoch=epoch_now, sigs=sigs)
            if cached is not None:
                return self._resolve_from_cache(q, cached)
        if self._coalesce_on:
            fut = self._try_coalesce(q, sigs, epoch_now)
            if fut is not None:
                return fut
        if self.admission is not None:
            try:
                self.admission.enqueue()   # may shed: RaftOverloadError
            except errors.RaftOverloadError:
                if self.flight is not None:
                    self.flight.record("shed", rows=int(q.shape[0]))
                raise
        fut: Future = Future()
        req = PendingRequest(queries=q, future=fut,
                             t_arrival=self._clock())
        with self._work:
            if self._closed:
                if self.admission is not None:
                    self.admission.cancel_queued()
                errors.fail("submit on a closed ServingExecutor")
            req.req_id = self._req_seq
            self._req_seq += 1
            if self.flight is not None:
                # record BEFORE the batcher can see the request: a
                # 'pack' preceding its own 'submit' in the ring would
                # invert causality in the postmortem artifact (the
                # recorder lock is a leaf — no ordering hazard)
                self.flight.record("submit", request_id=req.req_id,
                                   rows=int(q.shape[0]))
            req.sigs = sigs
            self._pending.append(req)
            self._submitted += 1
            if self._coalesce_on and sigs is not None:
                # this request becomes the signature's LEADER: later
                # identical submits (same rows, same epoch) attach as
                # followers instead of consuming batch rows. The entry
                # is released (identity-checked) when the request's
                # batch demuxes or fails — a stale-epoch leader is
                # simply replaced.
                key = (int(q.shape[0]), sigs.tobytes())
                prev = self._sig_leaders.get(key)
                if prev is None or prev[1] != epoch_now:
                    self._sig_leaders[key] = (req, epoch_now)
                    req.sig_key = key
            self._work.notify()
        return fut

    def _resolve_from_cache(self, q: np.ndarray, cached: Any) -> Future:
        """Resolve a submit straight from the result cache: the future
        completes before this returns, no queue slot, no batch row."""
        fut: Future = Future()
        with self._lock:
            if self._closed:
                errors.fail("submit on a closed ServingExecutor")
            rid = self._req_seq
            self._req_seq += 1
            self._submitted += 1
            self._completed += 1
            self._cache_hits += 1
        if self.flight is not None:
            self.flight.record("submit", request_id=rid,
                               rows=int(q.shape[0]))
            self.flight.record("cache_hit", request_id=rid,
                               rows=int(q.shape[0]))
        self._c_completed.inc()
        fut.set_result(cached)
        return fut

    def _try_coalesce(self, q: np.ndarray, sigs: np.ndarray,
                      epoch_now: int) -> Optional[Future]:
        """Attach this request as a FOLLOWER of an identical in-flight
        leader (same per-row signatures, same row count, same mutation
        epoch — an epoch mismatch means a write landed since the leader
        was submitted, and its answer may be pre-write). The follower's
        future is resolved from the leader's demuxed BATCH rows, not
        from the leader's own future — a caller cancelling the leader
        cancels only itself. Returns None when there is no compatible
        leader."""
        key = (int(q.shape[0]), sigs.tobytes())
        fut: Future = Future()
        with self._work:
            if self._closed:
                return None
            leader = self._sig_leaders.get(key)
            if leader is None or leader[1] != epoch_now:
                return None
            leader[0].followers.append(fut)
            rid = self._req_seq
            self._req_seq += 1
            self._submitted += 1
            self._coalesced += 1
        if self.flight is not None:
            self.flight.record("submit", request_id=rid,
                               rows=int(q.shape[0]))
            self.flight.record("coalesce", request_id=rid,
                               rows=int(q.shape[0]))
        self._c_coalesced.inc()
        return fut

    def _release_followers(self, batch: MicroBatch) -> Dict[int, list]:
        """Atomically retire the batch's leader registrations and
        snapshot their followers (by request id). After the map entry
        is gone no new follower can attach (attachment happens under
        the same lock), so the snapshot is complete — every follower is
        resolved exactly once, by whoever demuxes or fails the batch."""
        subs: Dict[int, list] = {}
        with self._work:
            for req, _start in batch.entries:
                if req.sig_key is not None:
                    cur = self._sig_leaders.get(req.sig_key)
                    if cur is not None and cur[0] is req:
                        del self._sig_leaders[req.sig_key]
                if req.followers:
                    subs[req.req_id] = list(req.followers)
                    req.followers = []
        return subs

    def set_runtime(self, **updates: Any) -> None:
        """Swap runtime-operand values (``shard_mask=``, ``failover=``,
        mutation slabs, ...) for every LATER dispatch. Values are
        runtime inputs of the compiled programs, so flips never
        retrace; in-flight batches keep the snapshot they were
        dispatched with (``None`` removes a key)."""
        with self._lock:
            for key, val in updates.items():
                if val is None:
                    self._runtime.pop(key, None)
                else:
                    self._runtime[key] = val
            # re-sample the mutation epoch WITH the state swap: later
            # dispatches see the new values and stamp cache fills with
            # the new epoch atomically. Mutators that hand state to the
            # dispatch closure by other means call set_runtime() with
            # no updates after installing it (docs/serving.md "Hot
            # traffic")
            self._rt_epoch = int(self._epoch_fn())
        if self.flight is not None:
            # the failover-flip postmortem breadcrumb: a FailoverPlan's
            # route array is tiny and names exactly which replica copy
            # serves each shard from here on
            fields: Dict[str, Any] = {"keys": sorted(updates)}
            for key, val in updates.items():
                route = getattr(val, "route", None)
                if route is not None:
                    # a (P,) host routing array at flip time — not the
                    # per-batch hot path
                    fields[f"{key}_route"] = (
                        np.asarray(route).tolist())  # jaxlint: disable=sync-in-hot-path
            self.flight.record("runtime_update", **fields)

    def _hist(self, stage_name: str, bucket: int) -> obs_metrics.Histogram:
        """The (stage, bucket) latency histogram, registry-created once
        and cached on this executor (the hot path's one-dict-lookup)."""
        key = (stage_name, bucket)
        h = self._stage_hist.get(key)
        if h is None:
            h = self._registry.histogram(
                "serving_stage_ms", executor=self.name,
                stage=stage_name, bucket=bucket,
            )
            self._stage_hist[key] = h
        return h

    def stage_quantile(self, stage_name: str, q: float,
                       ) -> Optional[float]:
        """One stage's latency quantile in ms, pooled across buckets
        (None before any observation) — what :meth:`stats` reads."""
        # snapshot first: the batcher/drain threads insert new bucket
        # keys concurrently and dict iteration must not see the resize
        hists = [h for (s, _b), h in list(self._stage_hist.items())
                 if s == stage_name]
        return obs_metrics.merged_quantile(hists, q)

    def stats(self) -> ExecutorStats:
        p50: Dict[str, float] = {}
        p99: Dict[str, float] = {}
        for stage_name in STAGES:
            v50 = self.stage_quantile(stage_name, 50.0)
            if v50 is None:
                continue
            p50[stage_name] = v50
            p99[stage_name] = self.stage_quantile(stage_name, 99.0)
        with self._lock:
            return ExecutorStats(
                submitted=self._submitted,
                completed=self._completed,
                failed=self._failed,
                batches=self._batches,
                flushes_full=self._flushes_full,
                flushes_deadline=self._flushes_deadline,
                valid_rows=self._valid_rows,
                padded_rows=self._padded_rows,
                hedged_batches=self._hedged_batches,
                backup_wins=self._backup_wins,
                pending=len(self._pending),
                in_flight=len(self._inflight),
                stage_p50_ms=p50,
                stage_p99_ms=p99,
                coalesced_requests=self._coalesced,
                cache_hits=self._cache_hits,
                cache_stale=(self._rcache.stats().stale
                             if self._rcache is not None else 0),
            )

    def close(self, timeout_s: float = 30.0) -> None:
        """Flush remaining pending requests, drain in-flight batches,
        and stop both loops. Idempotent."""
        with self._work:
            self._closed = True
            self._work.notify_all()
            self._done.notify_all()
        self._batcher.join(timeout_s)
        self._drainer.join(timeout_s)
        if self.flight is not None:
            with self._lock:
                failed = self._failed
            if failed:
                # shutdown with failures outstanding: the third
                # automatic dump trigger (docs/observability.md)
                self.flight.record("close", failed=failed)
                try:
                    self.flight.dump("close-with-failures")
                except Exception:   # noqa: BLE001 — close() must
                    pass            # complete even when the sink can't

    def __enter__(self) -> "ServingExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the batcher thread --------------------------------------------------
    def _flush_wait_s(self) -> Optional[float]:
        """Under _lock: seconds until the oldest pending request's
        coalescing deadline, 0 when a flush is due NOW, None when there
        is nothing to flush."""
        if not self._pending:
            return None
        rows = sum(r.n_rows for r in self._pending)
        if rows >= self.buckets.largest or self._closed:
            return 0.0
        age = self._clock() - self._pending[0].t_arrival
        return max(0.0, self.flush_age_s - age)

    def _batch_loop(self) -> None:
        while True:
            with self._work:
                wait_s = self._flush_wait_s()
                while not (wait_s == 0.0 or (self._closed
                                             and not self._pending)):
                    self._work.wait(
                        timeout=0.05 if wait_s is None else wait_s
                    )
                    wait_s = self._flush_wait_s()
                if self._closed and not self._pending:
                    break
                rows = sum(r.n_rows for r in self._pending)
                t_pack0 = self._clock()
                batch, self._pending = pack_requests(
                    self._pending, self.buckets, self.dim
                )
                if batch is None:      # unreachable via submit; be safe
                    continue
                batch.batch_id = self._batch_seq
                self._batch_seq += 1
                runtime = dict(self._runtime)
                epoch = self._rt_epoch
                full = batch.n_padded == 0 and rows >= batch.bucket
            # stage metrics from stamps this loop already holds: the
            # pack wall time, and each packed request's queue wait
            now = self._clock()
            self._hist("batch_build", batch.bucket).observe(
                (now - t_pack0) * 1e3)
            qw = self._hist("queue_wait", batch.bucket)
            for req, start in batch.entries:
                qw.observe((now - req.t_arrival) * 1e3)
                if self.flight is not None:
                    self.flight.record(
                        "pack", request_id=req.req_id,
                        batch_id=batch.batch_id, bucket=batch.bucket,
                        start=start,
                    )
            self._dispatch_batch(batch, runtime, full, epoch)
        with self._done:
            self._batcher_exited = True
            self._done.notify_all()

    def _dispatch_batch(self, batch: MicroBatch,
                        runtime: Dict[str, Any], full: bool,
                        epoch: int = 0) -> None:
        # window check OUTSIDE the lock: the batcher blocks here (not
        # the submitters) when max_in_flight programs are queued
        while True:
            with self._done:
                if len(self._inflight) < self.max_in_flight:
                    break
                self._done.wait(0.05)
        ticket = None
        try:
            if self.admission is not None:
                ticket = self.admission.begin_service(batch.n_requests)
            # sample the per-dispatch runtime source (tier snapshots
            # etc.) outside every lock — a provider may itself take a
            # store lock, and must never nest under _done/_lock
            if self._runtime_provider is not None:
                runtime = {**runtime, **self._runtime_provider()}
            # stage the padded host buffer, then dispatch: both are
            # async against earlier batches still computing — this IS
            # the double buffer (donate-friendly: hedges re-stage from
            # batch.queries, never reuse this device buffer)
            t_s0 = self._clock()
            staged = self._stage(batch.queries)
            t0 = self._clock()
            lockcheck.note_dispatch("ServingExecutor._dispatch")
            out = self._dispatch(staged, **runtime)
            # staging is the host-side cost of the device_put call —
            # the transfer itself overlaps compute (that's the point);
            # a blocking stage override shows up here
            self._hist("staging", batch.bucket).observe(
                (t0 - t_s0) * 1e3)
            if self.flight is not None:
                self.flight.record(
                    "dispatch", batch_id=batch.batch_id,
                    bucket=batch.bucket, n_requests=batch.n_requests,
                    requests=[r.req_id for r, _ in batch.entries],
                )
        except Exception as exc:   # noqa: BLE001 — fail THIS batch only
            if ticket is not None:
                # abort, not finish: a crashed dispatch must not feed
                # its ~0 held-time into the service EWMA or count its
                # failed requests as completed
                self.admission.abort_service(ticket)
            elif self.admission is not None:
                self.admission.cancel_queued(batch.n_requests)
            self._fail_batch(batch, exc)
            return
        fl = _InFlight(batch, out, t0, ticket, runtime, epoch)
        with self._done:
            self._inflight.append(fl)
            self._batches += 1
            if full:
                self._flushes_full += 1
            else:
                self._flushes_deadline += 1
            self._valid_rows += batch.n_valid
            self._padded_rows += batch.n_padded
            self._done.notify_all()

    # -- the drain (demux) thread --------------------------------------------
    def _hedge_delay_s(self) -> Optional[float]:
        if self.hedge is None or self._backup is None:
            return None
        if isinstance(self.hedge, HedgePolicy):
            return self.hedge.hedge_delay_s()
        return float(self.hedge)

    def _maybe_hedge(self, fl: _InFlight, delay: float) -> None:
        now = self._clock()
        if fl.hedged or now - fl.t_dispatch < delay:
            return
        # space retries by the hedge delay: a transiently-failing
        # backup gets another shot next window, not every 0.5 ms sweep
        if (fl.t_hedge_attempt is not None
                and now - fl.t_hedge_attempt < delay):
            return
        fl.t_hedge_attempt = now
        try:
            backup = self._backup(
                self._stage(fl.batch.queries), **fl.runtime
            )
        except Exception as exc:   # noqa: BLE001 — primary still owes
            if self.flight is not None:        # the answer
                self.flight.record(
                    "hedge_fail", batch_id=fl.batch.batch_id,
                    error=type(exc).__name__,
                )
            return
        # mark hedged only on a SUCCESSFUL backup dispatch: the flag
        # drives the primary_wins/backup_wins accounting in _finish,
        # and a failed attempt must leave the batch re-hedgeable
        fl.hedged = True
        fl.candidates.append(backup)
        with self._lock:
            self._hedged_batches += 1
        self._c_hedges.inc()
        if self.flight is not None:
            # this event NAMES the straggler: the batch that sat
            # unready past the hedge delay, and for how long
            self.flight.record(
                "hedge", batch_id=fl.batch.batch_id,
                age_ms=round((now - fl.t_dispatch) * 1e3, 3),
            )
        if isinstance(self.hedge, HedgePolicy):
            with self.hedge._lock:
                self.hedge.hedges += 1

    def _drain_loop(self) -> None:
        poll_s = 0.0005
        while True:
            with self._done:
                while not self._inflight and not (
                    self._closed and self._batcher_exited
                ):
                    self._done.wait(0.05)
                if not self._inflight:
                    if self._closed and self._batcher_exited \
                            and not self._pending:
                        return
                    continue
                snapshot = list(self._inflight)
            # hedge-delay check EVERY iteration: near saturation some
            # batch is almost always ready, and a straggler must not
            # wait for an idle poll loop to be covered. The delay is
            # batch-independent — compute it once per sweep, not per
            # batch (HedgePolicy.hedge_delay_s takes its lock and runs
            # a percentile over the sample window)
            delay = self._hedge_delay_s()
            if delay is not None:
                for fl in snapshot:
                    self._maybe_hedge(fl, delay)
            finished = None
            for fl in snapshot:                # completion order, not FIFO
                for cand in fl.candidates:
                    if _ready(cand):
                        finished = (fl, cand)
                        break
                if finished is not None:
                    break
            if finished is None:
                Interruptible.yield_now()
                time.sleep(poll_s)
                poll_s = min(poll_s * 2.0, 0.02)
                continue
            poll_s = 0.0005
            fl, winner = finished
            with self._done:
                self._inflight.remove(fl)
                self._done.notify_all()
            self._finish(fl, winner)

    def _finish(self, fl: _InFlight, winner: Any) -> None:
        if fl.ticket is not None:
            self.admission.finish_service(fl.ticket)
        held = self._clock() - fl.t_dispatch
        bucket = fl.batch.bucket
        # dispatch→ready straight from the drain loop's own readiness
        # polling — the stamp pair already existed, no new sync
        self._hist("dispatch_ready", bucket).observe(held * 1e3)
        backup_won = fl.hedged and len(fl.candidates) > 1 \
            and winner is fl.candidates[1]
        if isinstance(self.hedge, HedgePolicy):
            self.hedge.record(held)
            with self.hedge._lock:
                if not fl.hedged:
                    self.hedge.unhedged += 1
                elif backup_won:
                    self.hedge.backup_wins += 1
                else:
                    self.hedge.primary_wins += 1
        # readiness-gating wrappers (testing.faults.DelayedReady) carry
        # the real output in .value — demux the underlying tree
        while hasattr(winner, "is_ready") and hasattr(winner, "value") \
                and not hasattr(winner, "shape"):
            winner = winner.value
        t_demux0 = self._clock()
        try:
            # the ONE intentional host sync of the serving path: the
            # winner is already ready, this is the demux conversion
            host = compat.tree_map(np.asarray, winner)  # jaxlint: disable=sync-in-hot-path
        except Exception as exc:   # noqa: BLE001
            self._fail_batch(fl.batch, exc)
            return
        # mnmg coverage, read off the ALREADY-converted host result (a
        # PartialSearchResult-shaped pytree carries .coverage) — the
        # degraded-serving gauge, no extra sync
        cov = getattr(host, "coverage", None)
        if cov is not None:
            try:
                cov_min = float(np.min(cov))
            except (TypeError, ValueError):
                cov_min = None
            if cov_min is not None:
                if self._g_coverage is None:
                    self._g_coverage = self._registry.gauge(
                        "serving_coverage_min", executor=self.name)
                self._g_coverage.set(cov_min)
        # retire the batch's coalescing leaders FIRST: once released,
        # no new follower can attach, so this demux resolves exactly
        # the snapshot — including followers of a leader whose own
        # caller cancelled (their rows are right here in the batch)
        subs = self._release_followers(fl.batch)
        delivered = 0
        n_followers = 0
        for req, start in fl.batch.entries:
            followers = subs.get(req.req_id, ())
            if req.future.done() and not followers:
                continue              # caller cancelled while queued
            rows = slice(start, start + req.n_rows)
            result = compat.tree_map(
                lambda a, rows=rows: a[rows] if (
                    isinstance(a, np.ndarray) and a.ndim >= 1
                    and a.shape[0] == bucket
                ) else a,
                host,
            )
            if not req.future.done():
                try:
                    req.future.set_result(result)
                    delivered += 1
                except InvalidStateError:
                    pass              # cancel raced the done() check
            for f in followers:
                try:
                    f.set_result(result)
                    n_followers += 1
                except InvalidStateError:
                    pass              # the follower's caller cancelled
            if self._rcache is not None:
                # fill AFTER resolving the callers (cache writes are
                # off the latency path), stamped with the DISPATCH
                # epoch, re-using the submit-time signatures
                self._cache_fill(req, result, fl.epoch)
        now = self._clock()
        self._hist("demux", bucket).observe((now - t_demux0) * 1e3)
        e2e = self._hist("e2e", bucket)
        for req, _start in fl.batch.entries:
            e2e.observe((now - req.t_arrival) * 1e3)
        delivered += n_followers
        self._c_completed.inc(delivered)
        if backup_won:
            self._c_backup_wins.inc()
        if self.flight is not None:
            self.flight.record(
                "demux", batch_id=fl.batch.batch_id,
                winner=("backup" if backup_won
                        else "primary" if fl.hedged else "unhedged"),
                held_ms=round(held * 1e3, 3), delivered=delivered,
            )
        with self._lock:
            self._completed += delivered
            self._backup_wins += int(backup_won)

    def _cache_fill(self, req: PendingRequest, result: Any,
                    epoch: int) -> None:
        """Insert one demuxed request into the result cache when the
        result has the standard search shape — a ``(dists, ids)`` pair
        of ``(m, k)`` arrays at the cache's k. Anything else (a
        PartialSearchResult pytree, a mutation-tier triple, a
        different k) is silently not cached: the cache accelerates the
        common search path, it never constrains the dispatch contract."""
        try:
            if not isinstance(result, (tuple, list)) or len(result) != 2:
                return
            dists, ids = result
            m = req.n_rows
            k = self._rcache.k
            if not (isinstance(dists, np.ndarray)
                    and isinstance(ids, np.ndarray)
                    and dists.shape == (m, k) and ids.shape == (m, k)
                    and np.issubdtype(dists.dtype, np.floating)
                    and np.issubdtype(ids.dtype, np.integer)):
                return
            # req.sigs was computed at submit with this cache's salt —
            # re-using it keeps the per-row hashing off the drain
            # thread (the serving path's serialization point)
            self._rcache.insert(req.queries, dists, ids, epoch=epoch,
                                sigs=req.sigs)
        except Exception:   # noqa: BLE001 — a cache-write failure must
            pass            # never fail a delivered request

    def _fail_batch(self, batch: MicroBatch, exc: BaseException) -> None:
        if self.flight is not None:
            # the postmortem path: record the failure, then dump the
            # ring BEFORE resolving futures — the file shows what the
            # doomed batch looked like when it died (deadline trips
            # arrive here too: a timed-out dispatch raises)
            self.flight.record(
                "batch_fail", batch_id=batch.batch_id,
                bucket=batch.bucket, error=type(exc).__name__,
                message=str(exc)[:200],
                requests=[r.req_id for r, _ in batch.entries],
            )
            try:
                self.flight.dump("batch-fail")
            except Exception:   # noqa: BLE001 — a failed DUMP (bad
                pass            # dir, disk full) must not escape this
                                # handler: the futures below still owe
                                # their callers the real exception, and
                                # an escape would kill the worker thread
        subs = self._release_followers(batch)
        n_failed = batch.n_requests
        for req, _ in batch.entries:
            if not req.future.done():
                try:
                    req.future.set_exception(exc)
                except InvalidStateError:
                    pass              # cancel raced the done() check
            for f in subs.get(req.req_id, ()):
                n_failed += 1
                try:
                    f.set_exception(exc)
                except InvalidStateError:
                    pass              # the follower's caller cancelled
        self._c_failed.inc(n_failed)
        with self._lock:
            self._failed += n_failed
