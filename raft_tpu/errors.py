"""Error/validation layer — TPU-native analog of the reference exception
machinery (cpp/include/raft/error.hpp:38-177: ``raft::exception`` with a
collected backtrace, ``raft::logic_error``, and the ``RAFT_EXPECTS`` /
``RAFT_FAIL`` macros).

Design notes (Python/JAX, not a translation):

* Python exceptions already carry tracebacks, so the reference's manual
  ``backtrace(3)`` collection (error.hpp:57-103) maps to the interpreter's
  native traceback; :class:`RaftException` adds the reference's
  "RAFT failure at file:line" message framing by capturing the caller's
  frame at raise time.
* ``expects``/``fail`` are plain functions, usable inside jit-traced code
  as long as the condition is a static Python bool (shape/dtype checks —
  the overwhelming majority of ``RAFT_EXPECTS`` uses in the reference).
  Value-dependent checks on traced arrays cannot raise at trace time; for
  those, hosts call :func:`expect_finite` on concrete (numpy) inputs only.
* Shared validators (:func:`check_matrix`, :func:`check_same_cols`,
  :func:`check_k`) concentrate the shape/dtype contracts the reference
  spreads across per-API ``RAFT_EXPECTS`` calls (e.g.
  distance.cuh:417-426, knn.cuh:195-213).
"""

from __future__ import annotations

import sys
from typing import Any

import numpy as np

__all__ = [
    "RaftException",
    "RaftLogicError",
    "RaftTimeoutError",
    "RaftOverloadError",
    "CorruptIndexError",
    "expects",
    "fail",
    "check_matrix",
    "check_same_cols",
    "check_k",
    "expect_finite",
]


class RaftException(RuntimeError):
    """Analog of ``raft::exception`` (error.hpp:38-55): message prefixed
    with the raise site, native traceback in place of the reference's
    collected backtrace."""

    def __init__(self, msg: str, *, _stacklevel: int = 1):
        # sys._getframe, not inspect.stack(): the latter materializes
        # (and reads source context for) EVERY frame — ~100s of ms on a
        # cold linecache, paid per raise. Timeouts/hedges/sheds raise on
        # the serving hot path, so frame capture must be O(1).
        try:
            frame = sys._getframe(_stacklevel)
            where = f"{frame.f_code.co_filename}:{frame.f_lineno}"
        except ValueError:  # stack shallower than _stacklevel
            where = "<unknown>"
        super().__init__(f"RAFT failure at {where}: {msg}")


class RaftLogicError(RaftException, ValueError):
    """Analog of ``raft::logic_error`` (error.hpp:107): a precondition on
    caller-supplied arguments failed. Subclasses ValueError so existing
    ``except ValueError`` callers (and tests) keep working."""


class RaftTimeoutError(RaftException, TimeoutError):
    """A bounded wait expired before the dispatched work became ready
    (``Interruptible.synchronize(timeout_s=...)``,
    ``resilience.dispatch_with_deadline``).

    Deliberately NOT a :class:`ValueError`: a timeout is an operational
    failure, not a bad argument, so existing ``except ValueError``
    handlers never swallow it. Subclasses the builtin ``TimeoutError``
    so generic deadline plumbing (``except TimeoutError``) also works."""


class RaftOverloadError(RaftException):
    """Admission control shed this request: the serving queue is at its
    configured depth bound (or the token limiter is empty), so accepting
    the request would grow latency without bound instead of answering
    anyone on time (``raft_tpu.resilience.admission``; docs/serving.md
    "Overload and shedding").

    Deliberately NOT a :class:`ValueError` (see
    :class:`RaftTimeoutError`): overload is an operational condition the
    CLIENT must back off from, not a malformed argument, so existing
    ``except ValueError`` bad-request handlers never absorb it.

    ``retry_after_s``: the server's suggested client backoff (None when
    it has no estimate) — the HTTP ``Retry-After`` analog.
    """

    def __init__(self, msg: str, *, retry_after_s: "float | None" = None,
                 _stacklevel: int = 1):
        super().__init__(msg, _stacklevel=_stacklevel + 1)
        self.retry_after_s = retry_after_s


class CorruptIndexError(RaftException):
    """A serialized index failed integrity verification at load
    (``spatial.ann.serialize.load_index``: per-array CRC32 manifest, the
    format-v2 header contract). ``field`` names the damaged entry —
    ``"__header__"`` when the archive/header itself is unreadable.

    Deliberately NOT a :class:`ValueError` (see
    :class:`RaftTimeoutError`): corruption must surface loudly rather
    than be absorbed by a bad-argument handler."""

    def __init__(self, msg: str, *, field: "str | None" = None,
                 _stacklevel: int = 1):
        super().__init__(msg, _stacklevel=_stacklevel + 1)
        self.field = field


def expects(cond: Any, msg: str, *args: Any) -> None:
    """``RAFT_EXPECTS(cond, fmt, ...)`` (error.hpp:151-158): raise
    :class:`RaftLogicError` unless ``cond`` is truthy.

    ``cond`` must be a static Python bool (shape/dtype predicates) — a
    traced jax array is rejected, because a data-dependent branch cannot
    raise at trace time.
    """
    if cond is True:
        return
    try:
        ok = bool(cond)
    except Exception as e:  # jax TracerBoolConversionError and kin
        raise TypeError(
            "expects() condition is a traced value; trace-time validation "
            "must be shape/dtype-static (see expect_finite for "
            "concrete-value checks)"
        ) from e
    if not ok:
        raise RaftLogicError(msg % args if args else msg, _stacklevel=2)


def fail(msg: str, *args: Any) -> None:
    """``RAFT_FAIL(fmt, ...)`` (error.hpp:167-173): unconditional raise."""
    raise RaftLogicError(msg % args if args else msg, _stacklevel=2)


# ---------------------------------------------------------------------------
# Shared validators for public entry points
# ---------------------------------------------------------------------------

_REAL_KINDS = ("f", "i", "u", "b")


def check_matrix(x: Any, name: str, *, ndim: int = 2,
                 min_rows: int = 1) -> None:
    """Validate an array argument's rank, dtype kind, and non-degeneracy
    (the per-API ``RAFT_EXPECTS`` shape block, e.g. distance.cuh:417-426)."""
    shape = getattr(x, "shape", None)
    expects(shape is not None, "%s: expected an array, got %s", name, type(x).__name__)
    expects(
        len(shape) == ndim,
        "%s: expected a %dD array, got shape %s", name, ndim, shape,
    )
    dt = np.dtype(x.dtype)
    # ml_dtypes extension floats (bfloat16, float8_*) have numpy kind 'V';
    # ask jax's dtype lattice about those
    import jax.numpy as jnp

    expects(
        dt.kind in _REAL_KINDS or jnp.issubdtype(dt, jnp.floating),
        "%s: expected a real numeric dtype, got %s", name, dt,
    )
    expects(
        shape[0] >= min_rows,
        "%s: needs at least %d row(s), got shape %s", name, min_rows, shape,
    )


def check_same_cols(x: Any, y: Any, xname: str = "x", yname: str = "y") -> None:
    """Both operands share the feature dimension (distance.cuh:420)."""
    expects(
        x.shape[-1] == y.shape[-1],
        "%s/%s: feature dims differ (%d vs %d)",
        xname, yname, x.shape[-1], y.shape[-1],
    )


def check_k(k: int, n: int, what: str = "index rows") -> None:
    """1 <= k <= n (knn.cuh select_k/brute_force_knn contracts)."""
    expects(isinstance(k, (int, np.integer)), "k must be an int, got %s", type(k).__name__)
    expects(1 <= k <= n, "k=%d out of range [1, %d] (%s)", k, n, what)


def expect_finite(x: Any, name: str = "input") -> None:
    """All-finite check for CONCRETE (host) inputs; silently skipped for
    traced values, where a value check cannot raise. Cheap relative to any
    kernel that follows (one pass over host memory)."""
    try:
        arr = np.asarray(x)
    except Exception:
        return  # traced value: cannot inspect at trace time
    if arr.dtype.kind == "f" and not np.isfinite(arr).all():
        fail("%s contains non-finite values (NaN/Inf)", name)
