"""Durability tier — crash-consistent ingest for the mutation path.

The mutation tier's checkpoints (v4 delta + full v4/v5) bound what a
crash loses to "everything since the last flush"; this package closes
the window to "nothing acked" with a write-ahead log: CRC32-framed
segments, host-side group commit (acks resolve only after fsync),
rotation with retention pinned to the delta-checkpoint LSN watermark,
torn-tail repair, and idempotent monotone-LSN replay. Recovery = load
the latest checkpoint + replay the WAL tail (docs/robustness.md
"Durability"). The sharded tier (per-rank WAL, quorum acks, frontier
reconciliation) lives in :mod:`raft_tpu.comms.mnmg_mutation`.
"""

from raft_tpu.durability.wal import (
    OP_DELETE,
    OP_UPSERT,
    WAL_VERSION,
    DurableIngest,
    WalAck,
    WalRecord,
    WalWriter,
    decode_delete,
    decode_upsert,
    encode_delete,
    encode_frame,
    encode_upsert,
    read_records,
    recover_mutable,
    repair_wal,
    replay_into,
    scan_segment,
    segment_paths,
    wal_frontier,
)

__all__ = [
    "OP_DELETE",
    "OP_UPSERT",
    "WAL_VERSION",
    "DurableIngest",
    "WalAck",
    "WalRecord",
    "WalWriter",
    "decode_delete",
    "decode_upsert",
    "encode_delete",
    "encode_frame",
    "encode_upsert",
    "read_records",
    "recover_mutable",
    "repair_wal",
    "replay_into",
    "scan_segment",
    "segment_paths",
    "wal_frontier",
]
