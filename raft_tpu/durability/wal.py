"""Durable mutation WAL — crash-consistent ingest for the mutation tier
(ROADMAP item 5's durability floor: checkpoint CRC manifests bound what
a crash can lose to "everything since the last flush"; the write-ahead
log closes that window to "nothing acked").

The log is an append-only directory of CRC32-framed segments:

* **Frame** — ``crc32 · payload-length · lsn · mutation-epoch · op ·
  payload`` (little-endian; the CRC covers everything after itself).
  Payload codecs for the two mutation ops live here too
  (:func:`encode_upsert` / :func:`encode_delete`).
* **Group commit** — :class:`WalWriter` buffers frames under one
  ``lockcheck`` lock and a dedicated flusher thread batches the
  ``write → flush → fsync`` by bytes OR interval. ALL file IO happens
  OUTSIDE the lock (the ``blocking-call-under-lock`` rule gates exactly
  this), and an ack (:class:`WalAck`) resolves only after its frame's
  fsync returned — the durability promise is the fsync, never the
  buffer.
* **Rotation + retention** — segments are named by their first LSN
  (``wal-<lsn>.log``) and rotate past ``segment_bytes``;
  :meth:`WalWriter.prune` deletes segments made wholly redundant by a
  delta checkpoint's LSN watermark
  (:func:`raft_tpu.spatial.ann.mutation.save_delta_checkpoint`'s
  ``wal_lsn`` stamp), never the active segment.
* **Torn-tail recovery** — :func:`repair_wal` truncates at the first
  damaged frame. fsync ordering means a physical crash can only tear
  the tail, so the truncation can never reach a durably-acked frame;
  damage found MID-log is treated as the tail too (later segments are
  dropped — replaying past an LSN gap would fabricate state).
* **Idempotent replay** — :func:`replay_into` applies records in LSN
  order with a monotone-LSN dedupe, so duplicated segments (a copied
  directory, a doubled flush) replay once. Recovery =
  :func:`recover_mutable`: latest delta checkpoint + WAL tail.

Entirely host-side: nothing here traces or compiles — replay calls the
mutation ops' already-jitted programs (zero retraces, cache-audited in
tests/test_wal.py). Metrics (``wal_fsync_ms``, ``wal_bytes_total``,
``wal_replay_records_total``, ``wal_torn_tail_total`` + the
``wal_torn_tail`` flight event) ride the process registry and no-op
under ``RAFT_TPU_OBS=off``. The sharded (per-rank WAL, quorum-ack)
tier lives in :mod:`raft_tpu.comms.mnmg_mutation`; docs/robustness.md
"Durability" states the full contract.
"""

from __future__ import annotations

import dataclasses
import os
import struct
import threading
import time
import typing
import zlib

import numpy as np

from raft_tpu import errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.obs import crash as obs_crash
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.spatial.ann import mutation

__all__ = [
    "OP_DELETE",
    "OP_UPSERT",
    "WAL_VERSION",
    "DurableIngest",
    "WalAck",
    "WalRecord",
    "WalWriter",
    "decode_delete",
    "decode_upsert",
    "encode_delete",
    "encode_frame",
    "encode_upsert",
    "read_records",
    "recover_mutable",
    "repair_wal",
    "replay_into",
    "scan_segment",
    "segment_paths",
    "series",
    "wal_frontier",
]


# ------------------------------------------------------------ telemetry
# WAL telemetry (docs/observability.md "Metric catalog"): fsync batch
# latency (the group-commit knob's direct readout), bytes appended,
# records replayed at recovery, and torn-tail repairs. Labeled
# ``wal=<name>`` — a process may run one WAL per rank — and cached per
# name like mutation's ``_mseries``. RAFT_TPU_OBS=off no-ops them all.
_series_cache: dict = {}
_series_lock = lockcheck.make_lock("wal._series_lock")


def series(name: str) -> dict:
    """The cached ``wal=<name>``-labeled instrument handles (public so
    the MNMG tier's recovery stamps the same replay counter)."""
    s = _series_cache.get(name)
    if s is not None:
        return s
    reg = obs_metrics.default_registry()
    with _series_lock:
        if name not in _series_cache:
            _series_cache[name] = {
                "fsync_ms": reg.histogram("wal_fsync_ms", wal=name),
                "bytes": reg.counter("wal_bytes_total", wal=name),
                "replayed": reg.counter("wal_replay_records_total",
                                        wal=name),
                "torn": reg.counter("wal_torn_tail_total", wal=name),
            }
        return _series_cache[name]


# ---------------------------------------------------------- frame codec
_MAGIC = b"RWAL"
WAL_VERSION = 1
_FILE_HEADER = _MAGIC + struct.pack("<HH", WAL_VERSION, 0)
_HEADER_LEN = len(_FILE_HEADER)                     # 8
_CRC = struct.Struct("<I")
_BODY_HEAD = struct.Struct("<IQQB")                 # len, lsn, epoch, op
_FRAME_OVERHEAD = _CRC.size + _BODY_HEAD.size       # 25
_MAX_PAYLOAD = 1 << 28

OP_UPSERT = 1
OP_DELETE = 2


@dataclasses.dataclass(frozen=True)
class WalRecord:
    """One decoded log record: the mutation-epoch the writer stamped,
    the op, and its opaque payload, totally ordered by ``lsn``."""

    lsn: int
    epoch: int
    op: int
    payload: bytes


def encode_frame(lsn: int, epoch: int, op: int, payload: bytes) -> bytes:
    """One on-disk frame: ``crc32(body) · body`` where ``body`` =
    payload-length · lsn · epoch · op · payload (all little-endian)."""
    errors.expects(
        0 <= len(payload) <= _MAX_PAYLOAD,
        "encode_frame: payload of %d bytes exceeds the %d frame cap",
        len(payload), _MAX_PAYLOAD,
    )
    body = _BODY_HEAD.pack(len(payload), lsn, epoch, op) + payload
    return _CRC.pack(zlib.crc32(body)) + body


def encode_upsert(vectors, ids) -> bytes:
    """Payload for an upsert batch: ``B · d · ids(int32) · vecs(f32)``."""
    v = np.ascontiguousarray(np.asarray(vectors, np.float32))
    i = np.ascontiguousarray(np.asarray(ids, np.int32)).reshape(-1)
    errors.expects(
        v.ndim == 2 and v.shape[0] == i.shape[0],
        "encode_upsert: vectors (%s) and ids (%s) disagree",
        tuple(v.shape), tuple(i.shape),
    )
    return (struct.pack("<II", v.shape[0], v.shape[1])
            + i.tobytes() + v.tobytes())


def decode_upsert(payload: bytes):
    """Inverse of :func:`encode_upsert` → ``(vectors, ids)``."""
    b, d = struct.unpack_from("<II", payload, 0)
    want = 8 + 4 * b + 4 * b * d
    errors.expects(
        len(payload) == want,
        "decode_upsert: payload is %d bytes, header says %d",
        len(payload), want,
    )
    i = np.frombuffer(payload, np.int32, b, 8)
    v = np.frombuffer(payload, np.float32, b * d, 8 + 4 * b)
    return v.reshape(b, d), i


def encode_delete(ids) -> bytes:
    """Payload for a delete batch: ``B · ids(int32)``."""
    i = np.ascontiguousarray(np.asarray(ids, np.int32)).reshape(-1)
    return struct.pack("<I", i.shape[0]) + i.tobytes()


def decode_delete(payload: bytes):
    """Inverse of :func:`encode_delete` → ``ids``."""
    (b,) = struct.unpack_from("<I", payload, 0)
    errors.expects(
        len(payload) == 4 + 4 * b,
        "decode_delete: payload is %d bytes, header says %d",
        len(payload), 4 + 4 * b,
    )
    return np.frombuffer(payload, np.int32, b, 4)


# ------------------------------------------------------------- segments
def _segment_name(first_lsn: int) -> str:
    return f"wal-{first_lsn:020d}.log"


def _segment_first_lsn(path) -> int:
    return int(os.path.basename(path)[4:-4])


def segment_paths(path) -> list:
    """The directory's segment files, sorted — zero-padded first-LSN
    names make name order equal LSN order."""
    if not os.path.isdir(path):
        return []
    return [os.path.join(path, n) for n in sorted(os.listdir(path))
            if n.startswith("wal-") and n.endswith(".log")]


def _fsync_dir(path, fsync) -> None:
    # directory fsync makes segment creation itself durable (a rotated
    # frame is not recoverable if its segment's dirent is lost)
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        fsync(fd)
    finally:
        os.close(fd)


def scan_segment(path):
    """Decode one segment → ``(records, good_end, damage)`` where
    ``good_end`` is the byte offset after the last intact frame and
    ``damage`` is None or why decoding stopped (``bad-header`` /
    ``short-frame`` / ``short-payload`` / ``crc-mismatch``). Never
    modifies the file; a FUTURE format version raises
    :class:`~raft_tpu.errors.CorruptIndexError` instead of being
    mistaken for damage and truncated away."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < _HEADER_LEN or data[:4] != _MAGIC:
        return [], 0, "bad-header"
    (version,) = struct.unpack_from("<H", data, 4)
    if version > WAL_VERSION:
        raise errors.CorruptIndexError(
            f"scan_segment: {os.path.basename(path)} is WAL format "
            f"v{version}, this release reads up to v{WAL_VERSION}; "
            "upgrade before recovering", field="__header__",
        )
    records: list = []
    off = _HEADER_LEN
    n = len(data)
    while off < n:
        if off + _FRAME_OVERHEAD > n:
            return records, off, "short-frame"
        (crc,) = _CRC.unpack_from(data, off)
        length, lsn, epoch, op = _BODY_HEAD.unpack_from(
            data, off + _CRC.size)
        end = off + _FRAME_OVERHEAD + length
        if length > _MAX_PAYLOAD or end > n:
            return records, off, "short-payload"
        if zlib.crc32(data[off + _CRC.size:end]) != crc:
            return records, off, "crc-mismatch"
        records.append(WalRecord(lsn=lsn, epoch=epoch, op=op,
                                 payload=data[end - length:end]))
        off = end
    return records, off, None


def _scan_wal(path):
    """All segments → ``(records, frontier, repairs)``: records in LSN
    order with the monotone dedupe applied, the highest LSN seen, and
    the repair plan (``(segment, action, good_end, reason)`` tuples —
    ``repair_wal`` executes it, ``read_records`` ignores it)."""
    records: list = []
    last = 0
    repairs: list = []
    damaged = False
    for seg in segment_paths(path):
        if damaged:
            # frames past a tear are not replayable (an LSN gap would
            # fabricate state) — later segments go with the tail
            repairs.append((seg, "remove", 0, "past-tear"))
            continue
        recs, good_end, damage = scan_segment(seg)
        for r in recs:
            if r.lsn > last:
                records.append(r)
                last = r.lsn
        if damage is not None:
            damaged = True
            if damage == "bad-header":
                repairs.append((seg, "remove", 0, damage))
            else:
                repairs.append((seg, "truncate", good_end, damage))
    return records, last, repairs


def read_records(path):
    """Read-only scan of a WAL directory → ``(records, frontier)``;
    stops at the first damaged frame without repairing anything."""
    records, last, _ = _scan_wal(path)
    return records, last


def wal_frontier(path) -> int:
    """The highest intact LSN in the directory (0 = empty log)."""
    return read_records(path)[1]


def repair_wal(path, *, name: str = "wal", flight=None):
    """Scan + REPAIR a WAL directory after a crash: truncate the torn
    segment at its last intact frame (a header-torn segment is removed
    whole — rotation fsyncs the header before any frame, so one can
    hold nothing durable) and drop segments past the tear. Returns
    ``(records, frontier)``. fsync ordering guarantees the truncation
    never reaches a durably-acked frame. Counted in
    ``wal_torn_tail_total`` plus a ``wal_torn_tail`` flight event."""
    records, last, repairs = _scan_wal(path)
    for seg, action, good_end, _reason in repairs:
        if action == "remove":
            os.remove(seg)
        else:
            with open(seg, "rb+") as f:
                f.truncate(good_end)
    if repairs:
        series(name)["torn"].inc()
        if flight is not None:
            seg, _action, good_end, reason = repairs[0]
            flight.record(
                "wal_torn_tail", wal=name,
                segment=os.path.basename(seg), reason=reason,
                offset=good_end, dropped=len(repairs) - 1,
                frontier=last,
            )
    return records, last


# --------------------------------------------------------- group commit
class WalAck:
    """The durability future :meth:`WalWriter.append` returns: the
    frame is on the buffer when the handle exists, DURABLE only once
    :meth:`wait` returns True (strictly after its batch's fsync)."""

    __slots__ = ("lsn", "_writer")

    def __init__(self, lsn: int, writer: "WalWriter"):
        self.lsn = lsn
        self._writer = writer

    def wait(self, timeout: typing.Optional[float] = None) -> bool:
        """Block until the frame is fsync-durable (True) or ``timeout``
        elapses (False). Re-raises the writer's IO error if the flusher
        died — a lost disk must fail the ack, not hang it."""
        return self._writer.wait_durable(self.lsn, timeout)

    @property
    def durable(self) -> bool:
        return self._writer.durable_lsn >= self.lsn


class WalWriter:
    """Append-only segment writer with host-side group commit.

    ``append`` assigns the LSN and buffers the frame under the lock; a
    dedicated flusher thread swaps the buffer out and runs the
    ``write → flush → fsync`` OUTSIDE the lock, batching by
    ``flush_bytes`` or ``flush_interval_s`` (whichever trips first —
    the interval bounds ack latency, the byte cap bounds batch size).
    ``clock`` and ``fsync`` are injectable so tests can prove the
    ordering contract (an ack NEVER resolves before its fsync
    returned) without a real disk.

    Reopening a directory never appends into an existing segment: the
    constructor runs :func:`repair_wal` first (appending past an
    unrepaired tear would put durably-acked frames into segments a
    later repair classifies as past-tear and deletes), then starts a
    fresh segment at the repaired ``frontier + 1``.
    A flusher IO failure latches: every later ``append``/``wait``
    re-raises it (durability can not be silently downgraded).
    """

    def __init__(self, path, *,
                 segment_bytes: int = 4 << 20,
                 flush_bytes: int = 256 << 10,
                 flush_interval_s: float = 0.002,
                 name: str = "wal",
                 flight=None,
                 clock=time.monotonic,
                 fsync=os.fsync):
        errors.expects(
            segment_bytes > 0 and flush_bytes > 0
            and flush_interval_s >= 0,
            "WalWriter: segment_bytes=%d flush_bytes=%d "
            "flush_interval_s=%s must be positive",
            segment_bytes, flush_bytes, flush_interval_s,
        )
        self.path = path
        self.name = name
        self.segment_bytes = int(segment_bytes)
        self.flush_bytes = int(flush_bytes)
        self.flush_interval_s = float(flush_interval_s)
        self._clock = clock
        self._fsync = fsync
        self._flight = flight
        self._series = series(name)
        os.makedirs(path, exist_ok=True)
        # Repair BEFORE computing the frontier: the scan stops at a
        # tear, so appending at scan-frontier + 1 would land acked
        # frames in a segment that sorts after the damaged one — a
        # later repair_wal would call it past-tear and delete it.
        _, frontier = repair_wal(path, name=name, flight=flight)
        self._lock = lockcheck.make_lock("WalWriter._lock")
        self._cv = lockcheck.make_condition(self._lock)
        self._buf: list = []
        self._buf_bytes = 0
        self._buf_t0 = 0.0
        self._last_buffered = frontier
        self._durable_lsn = frontier
        self._next_lsn = frontier + 1
        self._closed = False
        self._io_error: typing.Optional[BaseException] = None
        # the file handle is flusher-owned: only the flusher thread
        # touches it after construction, so it needs no lock at all
        self._active_seg = os.path.join(
            path, _segment_name(frontier + 1))
        if os.path.exists(self._active_seg):
            # post-repair this can only be a record-free shell (a
            # header-only segment left by a no-append open); records
            # here mean LSNs the scan missed — refuse to truncate them
            if scan_segment(self._active_seg)[0]:
                raise errors.CorruptIndexError(
                    f"WalWriter({name}): segment "
                    f"{os.path.basename(self._active_seg)} holds "
                    f"records although the repaired frontier is "
                    f"{frontier}; refusing to overwrite it",
                    field="__frontier__",
                )
        self._file = open(self._active_seg, "wb")
        self._file.write(_FILE_HEADER)
        self._file.flush()
        self._fsync(self._file.fileno())
        _fsync_dir(path, self._fsync)
        obs_crash.install_excepthook()
        self._thread = threading.Thread(
            target=self._run, name=f"wal-flusher-{name}", daemon=True)
        self._thread.start()

    # -- write side ----------------------------------------------------
    def append(self, op: int, payload: bytes, *,
               epoch: int = 0,
               lsn: typing.Optional[int] = None) -> WalAck:
        """Frame + buffer one record; returns its :class:`WalAck`.
        ``lsn`` (optional) lets a coordinator drive a global LSN stream
        across several per-rank writers (gaps are fine — replay is
        monotone, not contiguous); it must exceed every LSN this writer
        already assigned."""
        data = bytes(payload)
        with self._lock:
            errors.expects(
                not self._closed, "WalWriter(%s): append after close",
                self.name,
            )
            if self._io_error is not None:
                raise self._io_error
            if lsn is None:
                lsn = self._next_lsn
            errors.expects(
                lsn >= self._next_lsn,
                "WalWriter(%s): lsn %d not monotone (next is %d)",
                self.name, lsn, self._next_lsn,
            )
            self._next_lsn = lsn + 1
            frame = encode_frame(lsn, int(epoch), int(op), data)
            if self._buf_bytes == 0:
                self._buf_t0 = self._clock()
            self._buf.append(frame)
            self._buf_bytes += len(frame)
            self._last_buffered = lsn
            self._cv.notify_all()
        self._series["bytes"].inc(len(frame))
        return WalAck(lsn, self)

    def wait_durable(self, lsn: int,
                     timeout: typing.Optional[float] = None) -> bool:
        """Block until ``durable_lsn >= lsn`` (True) or ``timeout``
        elapses (False); re-raises a latched flusher IO error."""
        deadline = (None if timeout is None
                    else self._clock() + float(timeout))
        with self._lock:
            while self._durable_lsn < lsn:
                if self._io_error is not None:
                    raise self._io_error
                if deadline is None:
                    self._cv.wait(0.05)
                    continue
                left = deadline - self._clock()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.05))
            return True

    @property
    def durable_lsn(self) -> int:
        """The highest LSN whose fsync has returned."""
        with self._lock:
            return self._durable_lsn

    @property
    def last_lsn(self) -> int:
        """The highest LSN assigned (durable or still buffered)."""
        with self._lock:
            return self._last_buffered

    def close(self, timeout_s: float = 30.0) -> None:
        """Drain the buffer (one final fsync), stop the flusher, close
        the segment. Idempotent; appends after close raise."""
        with self._lock:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout_s)
        errors.expects(
            not self._thread.is_alive(),
            "WalWriter(%s): flusher still running after %.1fs",
            self.name, timeout_s,
        )

    # -- retention -----------------------------------------------------
    def prune(self, watermark_lsn: int) -> list:
        """Delete segments made wholly redundant by a checkpoint at
        ``watermark_lsn``: a segment goes only when the NEXT segment's
        first LSN is ≤ ``watermark + 1`` (so every record it holds is
        ≤ the watermark), and the active segment never goes. Returns
        the removed paths."""
        with self._lock:
            active = self._active_seg
        segs = segment_paths(self.path)
        removed = []
        for i, seg in enumerate(segs[:-1]):
            if seg == active:
                continue
            if _segment_first_lsn(segs[i + 1]) <= int(watermark_lsn) + 1:
                os.remove(seg)
                removed.append(seg)
        return removed

    # -- flusher (owns the file handle) --------------------------------
    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._buf and not self._closed:
                    self._cv.wait(0.05)
                if not self._buf and self._closed:
                    break
                # group window: linger for more frames until the byte
                # or interval trigger trips (close flushes immediately)
                while (self._buf_bytes < self.flush_bytes
                       and not self._closed):
                    left = self.flush_interval_s - (
                        self._clock() - self._buf_t0)
                    if left <= 0:
                        break
                    self._cv.wait(min(left, 0.05))
                batch = b"".join(self._buf)
                last = self._last_buffered
                self._buf.clear()
                self._buf_bytes = 0
            # ALL file IO outside the lock: appenders keep enqueueing
            # while the disk syncs (blocking-call-under-lock gates this)
            try:
                t0 = time.perf_counter()
                self._file.write(batch)
                self._file.flush()
                self._fsync(self._file.fileno())
                dt_ms = (time.perf_counter() - t0) * 1e3
            except BaseException as e:
                with self._lock:
                    self._io_error = e
                    self._closed = True
                    self._cv.notify_all()
                break
            self._series["fsync_ms"].observe(dt_ms)
            with self._lock:
                self._durable_lsn = last
                self._cv.notify_all()
            if self._file.tell() >= self.segment_bytes:
                self._rotate(last + 1)
        self._file.close()

    def _rotate(self, next_lsn: int) -> None:
        # flusher-only; the new segment's header AND its dirent are
        # durable before any frame lands in it
        self._file.close()
        path = os.path.join(self.path, _segment_name(next_lsn))
        f = open(path, "wb")
        f.write(_FILE_HEADER)
        f.flush()
        self._fsync(f.fileno())
        _fsync_dir(self.path, self._fsync)
        self._file = f
        with self._lock:
            self._active_seg = path


# --------------------------------------------------------------- replay
def replay_into(mindex, records, *, start_lsn: int = 0,
                name: typing.Optional[str] = None):
    """Idempotently replay decoded records into a
    :class:`~raft_tpu.spatial.ann.mutation.MutableIndex`: records at or
    below ``start_lsn`` (the checkpoint watermark) and non-monotone
    LSNs are skipped, so duplicated segments replay once. Returns
    ``(mindex, last_lsn, n_applied)``. Replay re-runs the SAME
    acceptance logic the live path ran from the same state, so the
    reconstruction is exact — including the rejections."""
    last = int(start_lsn)
    n = 0
    for rec in records:
        if rec.lsn <= last:
            continue
        if rec.op == OP_UPSERT:
            vecs, ids = decode_upsert(rec.payload)
            mindex, _ = mutation.upsert(mindex, vecs, ids)
        elif rec.op == OP_DELETE:
            mindex, _ = mutation.delete(
                mindex, decode_delete(rec.payload))
        else:
            raise errors.CorruptIndexError(
                f"replay_into: unknown op {rec.op} at lsn {rec.lsn}",
                field="op",
            )
        last = rec.lsn
        n += 1
    series(name or mindex.name)["replayed"].inc(n)
    return mindex, last, n


def recover_mutable(mindex, wal_dir, *,
                    checkpoint_path=None,
                    name: typing.Optional[str] = None,
                    flight=None):
    """Crash recovery = latest delta checkpoint + WAL tail replay.

    ``mindex`` is the BASE state (a fresh wrap of the last FULL
    checkpoint); ``checkpoint_path`` (optional) is the newest delta
    checkpoint, whose ``wal_lsn`` watermark tells replay where to
    start. Repairs the WAL's torn tail first, then replays every
    record past the watermark. Pure upsert/delete streams keep the
    main slabs and ``id_to_pos`` constant, so the reconstruction is
    exact up to the last durable frame. Returns
    ``(mindex, frontier_lsn, n_replayed)``."""
    watermark = 0
    if checkpoint_path is not None and os.path.exists(checkpoint_path):
        mindex = mutation.apply_delta_checkpoint(mindex, checkpoint_path)
        wm = mutation.delta_checkpoint_watermark(checkpoint_path)
        watermark = 0 if wm is None else int(wm)
    nm = name or mindex.name
    records, frontier = repair_wal(wal_dir, name=nm, flight=flight)
    mindex, last, n = replay_into(
        mindex, records, start_lsn=watermark, name=nm)
    return mindex, max(last, frontier), n


# ------------------------------------------------------- durable ingest
class DurableIngest:
    """The single-chip crash-consistent ingest front end: WAL-first
    apply with durable acks.

    Each op journals the batch, applies it to the in-memory
    :class:`MutableIndex` (journal and apply are atomic under one
    lock, so LSN order IS apply order), then waits for durability
    OUTSIDE the lock before returning — the return value is the ack.
    A crash loses the in-memory state wholesale, so apply-before-fsync
    is safe: recovery (:func:`recover_mutable`) rebuilds exactly the
    durable prefix, which covers every acked batch and never a torn
    one. :meth:`checkpoint` stamps the applied LSN into the delta
    checkpoint and prunes the WAL behind it.

    A durability failure (the writer latched an IO error, or an ack
    timed out) latches HERE too: the in-memory state is now ahead of
    the durable log, so :attr:`mindex` and every later op raise —
    serving it would expose rows that were never durable and vanish on
    restart. Discard the front end and re-run
    :func:`recover_mutable`."""

    def __init__(self, mindex, wal: WalWriter, *,
                 applied_lsn: typing.Optional[int] = None):
        self._lock = lockcheck.make_lock("DurableIngest._lock")
        self._mindex = mindex
        self._wal = wal
        self._applied_lsn = int(
            wal.durable_lsn if applied_lsn is None else applied_lsn)
        self._failed: typing.Optional[BaseException] = None

    def _require_live(self) -> None:
        # under self._lock
        if self._failed is not None:
            raise errors.CorruptIndexError(
                "DurableIngest: a durability ack failed "
                f"({self._failed!r}); the in-memory state is ahead of "
                "the durable log — discard this front end and re-run "
                "recover_mutable", field="__wal__",
            ) from self._failed

    def _await_durable(self, ack: WalAck):
        # outside self._lock: parks behind the disk
        try:
            ok = ack.wait()
            errors.expects(
                ok, "DurableIngest: ack for lsn %d timed out", ack.lsn)
        except BaseException as e:
            with self._lock:
                if self._failed is None:
                    self._failed = e
            raise

    @property
    def mindex(self):
        """The current (search-servable) index state; raises once a
        durability ack has failed (the state is no longer durable)."""
        with self._lock:
            self._require_live()
            return self._mindex

    @property
    def applied_lsn(self) -> int:
        with self._lock:
            return self._applied_lsn

    @property
    def wal(self) -> WalWriter:
        with self._lock:
            return self._wal

    def upsert(self, vectors, ids):
        """Journal + apply one upsert batch; returns the accepted mask
        only after the batch is fsync-durable."""
        v = np.asarray(vectors, np.float32)
        i = np.asarray(ids, np.int32)
        payload = encode_upsert(v, i)
        with self._lock:
            self._require_live()
            ack = self._wal.append(
                OP_UPSERT, payload, epoch=self._mindex.epoch)
            self._mindex, accepted = mutation.upsert(self._mindex, v, i)
            self._applied_lsn = ack.lsn
        self._await_durable(ack)
        return accepted

    def delete(self, ids):
        """Journal + apply one delete batch; returns the found mask
        only after the batch is fsync-durable."""
        i = np.asarray(ids, np.int32)
        payload = encode_delete(i)
        with self._lock:
            self._require_live()
            ack = self._wal.append(
                OP_DELETE, payload, epoch=self._mindex.epoch)
            self._mindex, found = mutation.delete(self._mindex, i)
            self._applied_lsn = ack.lsn
        self._await_durable(ack)
        return found

    def checkpoint(self, path, *, prune: bool = True) -> int:
        """Write a delta checkpoint stamped with the applied LSN (the
        retention watermark) and prune segments behind it. Returns the
        watermark.

        The recovery contract is "LATEST checkpoint + WAL tail", so
        this writes every list with delta content (not just the
        incremental dirty set — an earlier checkpoint to the same path
        would have cleared it and the overwrite would lose those
        lists)."""
        with self._lock:
            self._require_live()
            m = self._mindex
            lsn = self._applied_lsn
            w = self._wal
        lists = np.nonzero(np.asarray(m.delta.counts))[0].tolist()
        mutation.save_delta_checkpoint(m, path, lists=lists,
                                       wal_lsn=lsn)
        if prune:
            w.prune(lsn)
        return lsn

    def close(self) -> None:
        with self._lock:
            w = self._wal
        w.close()
