"""Native host runtime — ctypes bindings over the C++ host algorithms
(src/host_algos.cpp), the analog of the reference's precompiled runtime
libraries (libraft_distance/libraft_nn, cpp/src/, SURVEY.md §2 #42-43):
non-templated native entry points the Python layer calls directly.

The shared library is compiled lazily with g++ on first import and cached
next to the package; importing this module raises ImportError when no
binary can be produced, and callers fall back to numpy paths.
"""

from __future__ import annotations

import ctypes
import glob
import hashlib
import os
import subprocess
import tempfile

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "host_algos.cpp")


def _lib_path() -> str:
    # Cache keyed by source hash: a binary built from different source never
    # loads (mtimes are unreliable after git checkout), and the binary itself
    # is never version-controlled.
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_HERE, f"libraft_tpu_host-{digest}.so")


def _build(lib: str) -> None:
    # Build to a temp file then atomically rename, so a crashed/concurrent
    # build never leaves a half-written .so at the cache path.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
        )
        os.chmod(tmp, 0o755)  # mkstemp's 0600 would break shared installs
        os.replace(tmp, lib)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    for stale in glob.glob(os.path.join(_HERE, "libraft_tpu_host-*.so")):
        if stale != lib:
            try:
                os.unlink(stale)
            except OSError:
                pass


def _load() -> ctypes.CDLL:
    lib = _lib_path()
    if not os.path.exists(lib):
        _build(lib)
    return ctypes.CDLL(lib)


try:
    _lib = _load()
except Exception as e:  # no toolchain / build failure -> numpy fallbacks
    raise ImportError(f"raft_tpu.native unavailable: {e}") from e

_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")

_lib.rt_build_dendrogram.restype = ctypes.c_int64
_lib.rt_build_dendrogram.argtypes = [
    _i32p, _i32p, _f32p, ctypes.c_int64, ctypes.c_int32, _i64p, _f64p, _i64p,
]
_lib.rt_extract_flat.restype = None
_lib.rt_extract_flat.argtypes = [
    _i64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, _i32p,
]
_lib.rt_make_monotonic.restype = ctypes.c_int32
_lib.rt_make_monotonic.argtypes = [_i32p, _i32p, ctypes.c_int64, ctypes.c_int32]
_lib.rt_merge_topk.restype = None
_lib.rt_merge_topk.argtypes = [
    _f32p, _i32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, _f32p, _i32p,
]


def dendrogram(src, dst, weights, n: int):
    """Agglomerative merge of weight-sorted edges (native
    build_dendrogram_host). Returns (children (n_merges, 2), deltas, sizes)."""
    src = np.ascontiguousarray(src, np.int32)
    dst = np.ascontiguousarray(dst, np.int32)
    weights = np.ascontiguousarray(weights, np.float32)
    children = np.zeros((max(n - 1, 1), 2), np.int64)
    deltas = np.zeros(max(n - 1, 1), np.float64)
    sizes = np.zeros(max(n - 1, 1), np.int64)
    n_merges = _lib.rt_build_dendrogram(
        src, dst, weights, len(src), n, children.reshape(-1), deltas, sizes
    )
    return children[:n_merges], deltas[:n_merges], sizes[:n_merges]


def extract_flat(children, n: int, n_clusters: int) -> np.ndarray:
    """Native dendrogram cut + monotonic relabel."""
    children = np.ascontiguousarray(children, np.int64)
    labels = np.zeros(n, np.int32)
    _lib.rt_extract_flat(
        children.reshape(-1), len(children), n, n_clusters, labels
    )
    return labels


def make_monotonic(labels, n_max: int = None) -> np.ndarray:
    """Native first-occurrence monotonic relabel (label/classlabels.cuh)."""
    labels = np.ascontiguousarray(labels, np.int32)
    if n_max is None:
        n_max = int(labels.max()) + 1 if len(labels) else 1
    out = np.zeros_like(labels)
    _lib.rt_make_monotonic(labels, out, len(labels), n_max)
    return out


def merge_topk(part_dists, part_indices):
    """Native P-way sorted merge of (P, m, k) top-k lists."""
    d = np.ascontiguousarray(part_dists, np.float32)
    i = np.ascontiguousarray(part_indices, np.int32)
    P, m, k = d.shape
    out_d = np.zeros((m, k), np.float32)
    out_i = np.zeros((m, k), np.int32)
    _lib.rt_merge_topk(d.reshape(-1), i.reshape(-1), P, m, k,
                       out_d.reshape(-1), out_i.reshape(-1))
    return out_d, out_i
