// Native host-side algorithms — the TPU build's analog of the reference's
// precompiled native runtime entry points (cpp/src/distance/pairwise_distance.cu:24
// runtime API pattern): sequential, latency-sensitive host loops that sit at
// the device->host boundary of the pipelines (the same boundary where the
// reference runs build_dendrogram_host, sparse/hierarchy/detail/agglomerative.cuh).
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).
//
// Build: raft_tpu/native/__init__.py compiles this lazily with g++ -O3 into
// libraft_tpu_host.so next to the package, falling back to numpy
// implementations when no toolchain is present.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Union-find with path halving (shared by the dendrogram + flatten + label
// merge entry points; the reference's host union-find in agglomerative.cuh).
// ---------------------------------------------------------------------------
static inline int64_t uf_find(int64_t* parent, int64_t a) {
  while (parent[a] != a) {
    parent[a] = parent[parent[a]];
    a = parent[a];
  }
  return a;
}

// Agglomerative merge of weight-sorted MST edges into a dendrogram
// (reference sparse/hierarchy/detail/agglomerative.cuh build_dendrogram_host).
// children: (n-1, 2) int64, deltas: (n-1) double, sizes: (n-1) int64.
// Returns the number of merges performed.
int64_t rt_build_dendrogram(const int32_t* src, const int32_t* dst,
                            const float* weights, int64_t n_edges, int32_t n,
                            int64_t* children, double* deltas,
                            int64_t* sizes) {
  const int64_t total = 2 * static_cast<int64_t>(n) - 1;
  std::vector<int64_t> parent(total);
  std::vector<int64_t> csize(total, 1);
  for (int64_t i = 0; i < total; ++i) parent[i] = i;

  int64_t nxt = n;
  for (int64_t e = 0; e < n_edges && nxt < total; ++e) {
    const int64_t a = uf_find(parent.data(), src[e]);
    const int64_t b = uf_find(parent.data(), dst[e]);
    if (a == b) continue;
    const int64_t m = nxt - n;
    children[2 * m] = a;
    children[2 * m + 1] = b;
    deltas[m] = static_cast<double>(weights[e]);
    csize[nxt] = csize[a] + csize[b];
    sizes[m] = csize[nxt];
    parent[a] = nxt;
    parent[b] = nxt;
    ++nxt;
  }
  return nxt - n;
}

// Cut a dendrogram into n_clusters flat, first-occurrence-monotonic labels
// (reference detail/agglomerative.cuh extract_flattened_clusters +
// label/classlabels.cuh make_monotonic).
void rt_extract_flat(const int64_t* children, int64_t n_merges, int32_t n,
                     int32_t n_clusters, int32_t* labels) {
  const int64_t total = 2 * static_cast<int64_t>(n) - 1;
  std::vector<int64_t> parent(total);
  for (int64_t i = 0; i < total; ++i) parent[i] = i;

  const int64_t keep = n_merges - (n_clusters - 1);
  for (int64_t e = 0; e < keep; ++e) {
    const int64_t a = uf_find(parent.data(), children[2 * e]);
    const int64_t b = uf_find(parent.data(), children[2 * e + 1]);
    const int64_t m = uf_find(parent.data(), n + e);
    parent[a] = m;
    parent[b] = m;
  }
  std::vector<int32_t> remap(total, -1);
  int32_t nxt = 0;
  for (int32_t i = 0; i < n; ++i) {
    const int64_t r = uf_find(parent.data(), i);
    if (remap[r] < 0) remap[r] = nxt++;
    labels[i] = remap[r];
  }
}

// Relabel arbitrary non-negative labels to consecutive first-occurrence ids
// (reference label/classlabels.cuh make_monotonic). Returns #unique.
int32_t rt_make_monotonic(const int32_t* in, int32_t* out, int64_t n,
                          int32_t n_max) {
  std::vector<int32_t> remap(n_max, -1);
  int32_t nxt = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int32_t v = in[i];
    if (v < 0 || v >= n_max) { out[i] = -1; continue; }
    if (remap[v] < 0) remap[v] = nxt++;
    out[i] = remap[v];
  }
  return nxt;
}

// Merge P sorted k-lists per query on host (reference knn_merge_parts
// fallback for host-resident results). parts_d: (P, m, k), parts_i idem.
void rt_merge_topk(const float* parts_d, const int32_t* parts_i, int32_t P,
                   int32_t m, int32_t k, float* out_d, int32_t* out_i) {
  std::vector<int32_t> cursor(P);
  for (int32_t q = 0; q < m; ++q) {
    std::fill(cursor.begin(), cursor.end(), 0);
    for (int32_t j = 0; j < k; ++j) {
      int32_t best_p = -1;
      float best = 0.f;
      for (int32_t p = 0; p < P; ++p) {
        if (cursor[p] >= k) continue;
        const float v =
            parts_d[(static_cast<int64_t>(p) * m + q) * k + cursor[p]];
        if (best_p < 0 || v < best) {
          best = v;
          best_p = p;
        }
      }
      const int64_t off =
          (static_cast<int64_t>(best_p) * m + q) * k + cursor[best_p];
      out_d[static_cast<int64_t>(q) * k + j] = parts_d[off];
      out_i[static_cast<int64_t>(q) * k + j] = parts_i[off];
      ++cursor[best_p];
    }
  }
}

}  // extern "C"
