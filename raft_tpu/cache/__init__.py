"""Vector cache — analog of raft/cache
(cpp/include/raft/cache/cache_util.cuh:45-334).
"""

from raft_tpu.cache.cache import VectorCache

__all__ = ["VectorCache"]
