"""Set-associative vector cache — analog of
cpp/include/raft/cache/cache_util.cuh:45-334 (``get_vecs``, ``store_vecs``,
``assign_cache_idx``, ``rank_set_entries``): an LRU-ish cache of feature
vectors keyed by integer id, used to avoid recomputing expensive per-vector
work (the reference's use case is SVM kernel columns; the serving tier's is
the hot-traffic result cache, raft_tpu/serving/result_cache.py).

Functional JAX state: (keys, time, store) arrays updated out-of-place; the
class wraps them with an imperative facade like the reference's
``cache::Cache``. Lookup and placement are dense gathers/scatters over the
associativity dimension — no host branching — and each operation runs as
ONE jitted program (the result cache calls these per serving submit, where
an eager ~10-op dispatch chain per lookup was the whole cache cost).

Thread safety: this class holds NO lock on purpose. State is functional
(every mutation returns out-of-place arrays rebound to the fields), so
concurrent callers must serialize externally — the result cache does it
under ``ResultCache._lock``, which is exactly how the concurrency
auditor's census sees it (docs/static_analysis.md "Three tiers": the
lock-order graph tracks ``ResultCache._lock``; an unlocked VectorCache
shared across threads would lose updates, not corrupt memory).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["VectorCache"]


@jax.jit
def _get_impl(keys, time, store, q, clock):
    """(vecs, found, new_time): gather hits + LRU touch, one program."""
    n_sets = keys.shape[0]
    sets = q % n_sets
    lane_keys = keys[sets]                           # (q, assoc)
    hit = lane_keys == q[:, None]
    found = jnp.any(hit, axis=1)
    lane = jnp.argmax(hit, axis=1)
    vecs = store[sets, lane]
    vecs = jnp.where(found[:, None], vecs, 0)
    new_time = time.at[sets, lane].set(
        jnp.where(found, clock, time[sets, lane])
    )
    return vecs, found, new_time


@jax.jit
def _store_impl(keys, time, store, k, v, clock):
    """(new_keys, new_time, new_store): ranked placement, one program."""
    n_sets, assoc = keys.shape
    B = k.shape[0]
    sets = k % n_sets
    lane_keys = keys[sets]
    hit = lane_keys == k[:, None]
    found = jnp.any(hit, axis=1)
    hit_lane = jnp.argmax(hit, axis=1)
    # lanes being UPDATED by this batch are not victims: a key-update
    # and a new-key insert in the same set must never scatter to one
    # slot (duplicate-index scatters apply per array in unspecified
    # order — keys/time/store could disagree and a later get would
    # serve the wrong vector)
    safe_lane = jnp.where(found, hit_lane, assoc)       # OOB drops
    hit_mask = jnp.zeros(keys.shape, jnp.bool_).at[
        sets, safe_lane
    ].set(True, mode="drop")
    time_rank = jnp.where(hit_mask, jnp.iinfo(jnp.int32).max, time)
    # within-batch rank among NEW keys targeting the same set (the
    # two-pass stable-sort idiom; update rows sort into a sentinel
    # group so they consume no victim rank) -> the rank-th LRU lane,
    # so two colliding inserts can never overwrite each other's slot
    sets_rank = jnp.where(found, n_sets, sets)
    order = jnp.argsort(sets_rank, stable=True)
    ss = sets_rank[order]
    starts = jnp.searchsorted(
        ss, jnp.arange(n_sets, dtype=ss.dtype)
    ).astype(jnp.int32)
    within = jnp.zeros((B,), jnp.int32).at[order].set(
        jnp.arange(B, dtype=jnp.int32)
        - starts[jnp.clip(ss, 0, n_sets - 1)]
    )
    lru = jnp.argsort(time_rank[sets], axis=1, stable=True)
    victim = jnp.take_along_axis(
        lru, (within % assoc)[:, None], axis=1
    )[:, 0]
    lane = jnp.where(found, hit_lane, victim)
    # duplicate keys collapse: later occurrences write the FIRST
    # occurrence's lane (last write wins there)
    first_idx = jnp.argmax(k[None, :] == k[:, None], axis=1)
    lane = lane[first_idx]              # (duplicates share a set too)
    return (
        keys.at[sets, lane].set(k),
        time.at[sets, lane].set(clock),
        store.at[sets, lane].set(v),
    )


@jax.jit
def _evict_impl(keys, k):
    n_sets = keys.shape[0]
    sets = k % n_sets
    hit = keys[sets] == k[:, None]
    lane = jnp.argmax(hit, axis=1)
    found = jnp.any(hit, axis=1)
    return keys.at[sets, lane].set(
        jnp.where(found, -1, keys[sets, lane])
    )


class VectorCache:
    """n_sets × associativity cache of (dim,) vectors.

    Keys map to set ``key % n_sets``; victims are chosen by least-recent
    use within the set (reference rank_set_entries ranks by time).
    """

    def __init__(self, dim: int, n_sets: int = 256, associativity: int = 8,
                 dtype=jnp.float32):
        self.dim = dim
        self.n_sets = n_sets
        self.assoc = associativity
        self.keys = jnp.full((n_sets, associativity), -1, jnp.int32)
        self.time = jnp.zeros((n_sets, associativity), jnp.int32)
        self.store = jnp.zeros((n_sets, associativity, dim), dtype)
        self.clock = 0

    @property
    def n_cached(self) -> int:
        return int(jnp.sum(self.keys >= 0))

    def get_vecs(self, query_keys) -> Tuple[jax.Array, jax.Array]:
        """Fetch vectors for ``query_keys``; returns (vecs (q, dim), found
        (q,) bool) (reference get_vecs: gathers hits, reports misses)."""
        q = jnp.asarray(query_keys, jnp.int32)
        self.clock += 1
        vecs, found, self.time = _get_impl(
            self.keys, self.time, self.store, q, jnp.int32(self.clock)
        )
        return vecs, found

    def store_vecs(self, store_keys, vecs) -> None:
        """Insert vectors, evicting least-recently-used entries of each
        target set (reference store_vecs + assign_cache_idx). DISTINCT
        keys mapping to the same set within one call claim DISTINCT
        victim lanes (their within-batch rank indexes the set's LRU
        order — the reference's rank_set_entries/assign_cache_idx
        contract; beyond the associativity they wrap and overwrite).
        Duplicate keys within one call collapse to a single slot (last
        write wins per scatter semantics)."""
        k = jnp.asarray(store_keys, jnp.int32)
        v = jnp.asarray(vecs, self.store.dtype)
        self.clock += 1
        self.keys, self.time, self.store = _store_impl(
            self.keys, self.time, self.store, k, v,
            jnp.int32(self.clock),
        )

    def evict(self, keys) -> None:
        """Invalidate entries (no direct reference analog; utility)."""
        k = jnp.asarray(keys, jnp.int32)
        self.keys = _evict_impl(self.keys, k)
