"""Set-associative vector cache — analog of
cpp/include/raft/cache/cache_util.cuh:45-334 (``get_vecs``, ``store_vecs``,
``assign_cache_idx``, ``rank_set_entries``): an LRU-ish cache of feature
vectors keyed by integer id, used to avoid recomputing expensive per-vector
work (the reference's use case is SVM kernel columns).

Functional JAX state: (keys, time, store) arrays updated out-of-place; the
class wraps them with an imperative facade like the reference's
``cache::Cache``. Lookup and placement are dense gathers/scatters over the
associativity dimension — no host branching.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["VectorCache"]


class VectorCache:
    """n_sets × associativity cache of (dim,) vectors.

    Keys map to set ``key % n_sets``; victims are chosen by least-recent
    use within the set (reference rank_set_entries ranks by time).
    """

    def __init__(self, dim: int, n_sets: int = 256, associativity: int = 8,
                 dtype=jnp.float32):
        self.dim = dim
        self.n_sets = n_sets
        self.assoc = associativity
        self.keys = jnp.full((n_sets, associativity), -1, jnp.int32)
        self.time = jnp.zeros((n_sets, associativity), jnp.int32)
        self.store = jnp.zeros((n_sets, associativity, dim), dtype)
        self.clock = 0

    @property
    def n_cached(self) -> int:
        return int(jnp.sum(self.keys >= 0))

    def get_vecs(self, query_keys) -> Tuple[jax.Array, jax.Array]:
        """Fetch vectors for ``query_keys``; returns (vecs (q, dim), found
        (q,) bool) (reference get_vecs: gathers hits, reports misses)."""
        q = jnp.asarray(query_keys, jnp.int32)
        sets = q % self.n_sets
        lane_keys = self.keys[sets]                      # (q, assoc)
        hit = lane_keys == q[:, None]
        found = jnp.any(hit, axis=1)
        lane = jnp.argmax(hit, axis=1)
        vecs = self.store[sets, lane]
        vecs = jnp.where(found[:, None], vecs, 0)
        # touch hit entries (LRU time update)
        self.clock += 1
        self.time = self.time.at[sets, lane].set(
            jnp.where(found, self.clock, self.time[sets, lane])
        )
        return vecs, found

    def store_vecs(self, store_keys, vecs) -> None:
        """Insert vectors, evicting the LRU entry of each target set
        (reference store_vecs + assign_cache_idx). Duplicate keys within
        one call collapse to a single slot (last write wins per scatter
        semantics)."""
        k = jnp.asarray(store_keys, jnp.int32)
        v = jnp.asarray(vecs)
        sets = k % self.n_sets
        lane_keys = self.keys[sets]
        hit = lane_keys == k[:, None]
        found = jnp.any(hit, axis=1)
        hit_lane = jnp.argmax(hit, axis=1)
        # victim: least-recently-used lane of the set (empty lanes have
        # time 0 and lose ties -> filled first)
        victim = jnp.argmin(self.time[sets], axis=1)
        lane = jnp.where(found, hit_lane, victim)
        self.clock += 1
        self.keys = self.keys.at[sets, lane].set(k)
        self.time = self.time.at[sets, lane].set(self.clock)
        self.store = self.store.at[sets, lane].set(v)

    def evict(self, keys) -> None:
        """Invalidate entries (no direct reference analog; utility)."""
        k = jnp.asarray(keys, jnp.int32)
        sets = k % self.n_sets
        hit = self.keys[sets] == k[:, None]
        lane = jnp.argmax(hit, axis=1)
        found = jnp.any(hit, axis=1)
        self.keys = self.keys.at[sets, lane].set(
            jnp.where(found, -1, self.keys[sets, lane])
        )
