"""R-way shard replication placement + runtime failover routing.

PR 3's ``shard_mask`` degrades gracefully when a rank dies — but the
dead rank's lists are simply GONE from every answer (``coverage`` drops
below 1.0 and stays there until a rebuild). At the ROADMAP's serving
scale a single-chip failure must not cost recall, so the sharded
engines support **R-way replication**: every logical shard's lists are
stored on R ranks (striped — logical shard ``s`` lives on ranks
``{(s + j*offset) % P}``), and a runtime routing input selects WHICH
replica copy serves each shard. With at most R-1 failures per replica
group, coverage stays 1.0 and results are identical to the healthy
mesh; only a whole dead replica group degrades to the PR 3 partial
path.

This module carries the host-side placement/routing logic — pure numpy,
no mesh required, so a control plane can plan failovers without
touching a device:

* :class:`ReplicaPlacement` — the striped shard→ranks map, mirroring
  the slab layout :func:`raft_tpu.comms.mnmg_ivf.replicate_index`
  builds (``place_index(..., replication=R)``);
* :class:`FailoverPlan` — maps a :class:`ShardHealth` (or mask) +
  placement to the ``(P,)`` int32 ``route`` array the degraded search
  programs take as a RUNTIME input (``route[s]`` = the replica copy
  index currently serving logical shard ``s``; -1 = whole group dead).
  Health flips change route VALUES only — the compiled program never
  retraces (trace-audited in tests/test_resilience.py).

The memory cost is exactly R× the slab footprint (lists, rows, codes);
quantizers and ownership maps were already replicated. docs/robustness.md
"Replication & failover" states the full contract.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import numpy as np

from raft_tpu import errors
from raft_tpu.obs import metrics as obs_metrics

__all__ = [
    "ReplicaPlacement",
    "FailoverPlan",
    "resolve_route",
    "record_shard_load",
    "measured_shard_load",
    "record_list_load",
    "measured_list_load",
    "popularity_replication",
]

# failover-routing telemetry (ISSUE 13, docs/observability.md): every
# plan built counts, and the two gauges show the CURRENT routing
# posture — shards served off-primary (a flip in effect) and shards
# with no live holder (coverage loss). Paired with
# ``health_transitions_total`` these narrate a failure end to end.
_reg = obs_metrics.default_registry()
_M_PLANS = _reg.counter("failover_plans_total")
_G_REROUTED = _reg.gauge("failover_rerouted_shards")
_G_UNSERVED = _reg.gauge("failover_unserved_shards")
del _reg


@dataclasses.dataclass(frozen=True)
class ReplicaPlacement:
    """The striped shard→ranks map of an R-way replicated sharded index.

    Logical shard ``s`` (one per mesh rank; the unit of LPT ownership)
    is stored on ranks ``{(s + j*offset) % n_ranks for j in range(R)}``
    — copy 0 is the PRIMARY (the rank that serves it on a healthy
    mesh), copies 1..R-1 are standbys. Rank ``r`` therefore stores the
    segments of shards ``{(r - j*offset) % n_ranks}``, primary first —
    exactly the slab order :func:`raft_tpu.comms.mnmg_ivf.replicate_index`
    lays out.
    """

    n_ranks: int
    replication: int
    offset: int
    # chips per host: >1 records (and enforces) HOST-AWARE placement —
    # rank r lives on host r // inner_size, and every shard's R copies
    # must land on R distinct hosts (docs/multihost.md "Host-aware
    # placement"). 1 = the single-host/rank-only contract of PR 5.
    inner_size: int = 1

    @classmethod
    def striped(cls, n_ranks: int, replication: int,
                offset: "int | None" = None, *,
                inner_size: "int | None" = None) -> "ReplicaPlacement":
        """The standard placement. ``offset`` defaults to
        ``max(1, n_ranks // replication)`` — for R=2 that pairs rank
        ``r`` with ``r + P/2``, so a correlated failure of ADJACENT
        ranks (one host's chips) never takes out both copies of a
        shard. Any offset is accepted as long as every shard's R
        holders are distinct ranks.

        ``inner_size`` (chips per host) engages the HOST axis: the
        default offset becomes the host-aware stripe
        ``inner_size * max(1, n_hosts // R)`` — copies step WHOLE
        hosts, so a whole dead host (all its chips at once, the
        realistic multi-host failure unit) still leaves every shard a
        live copy — and ANY offset (default or explicit) is validated
        to land each shard's R copies on R distinct hosts. Requires
        R ≤ n_hosts: more copies than hosts cannot be host-disjoint
        (docs/multihost.md "Host-aware placement";
        :func:`raft_tpu.comms.multihost.host_aware_offset` is the
        comms-level sibling of the same stripe)."""
        inner = 1 if inner_size is None else int(inner_size)
        errors.expects(
            inner >= 1 and (inner == 1 or n_ranks % inner == 0),
            "inner_size=%d: n_ranks=%d is not a whole number of hosts",
            inner, n_ranks,
        )
        if offset is None:
            if inner > 1:
                n_hosts = n_ranks // inner
                errors.expects(
                    replication <= n_hosts,
                    "replication=%d copies cannot land on distinct "
                    "hosts (%d hosts of %d chips) — pass an explicit "
                    "offset to accept same-host copies",
                    replication, n_hosts, inner,
                )
                offset = inner * max(1, n_hosts // max(replication, 1))
            else:
                offset = max(1, n_ranks // max(replication, 1))
        errors.expects(
            1 <= replication <= n_ranks,
            "replication=%d out of range [1, n_ranks=%d] — a rank "
            "cannot hold two copies of the same shard",
            replication, n_ranks,
        )
        errors.expects(offset >= 1, "offset=%d < 1", offset)
        for delta in range(1, replication):
            errors.expects(
                (delta * offset) % n_ranks != 0,
                "offset=%d collides copies %d apart on a %d-rank mesh "
                "(two copies of one shard would land on the same rank)",
                offset, delta, n_ranks,
            )
        p = cls(n_ranks=n_ranks, replication=replication, offset=offset,
                inner_size=inner)
        if inner > 1:
            # the stripe validation above is necessary but not
            # sufficient (offsets near a host boundary can wrap two
            # copies onto one host) — check the actual holder sets
            for s in range(n_ranks):
                hosts = [r // inner for r in p.holders(s)]
                errors.expects(
                    len(set(hosts)) == replication,
                    "offset=%d places shard %d's copies on hosts %s — "
                    "not host-disjoint (inner_size=%d)",
                    offset, s, hosts, inner,
                )
        return p

    @classmethod
    def of_index(cls, index) -> "ReplicaPlacement":
        """The placement a replicated sharded index was built with
        (``place_index(..., replication=R)`` stamps the statics)."""
        return cls(
            n_ranks=int(index.sorted_ids.shape[0]),
            replication=int(getattr(index, "replication", 1) or 1),
            offset=int(getattr(index, "replica_offset", 1) or 1),
        )

    def holders(self, shard: int) -> Tuple[int, ...]:
        """The ranks storing ``shard``'s lists, primary (copy 0) first."""
        errors.expects(
            0 <= shard < self.n_ranks,
            "shard %d out of range [0, %d)", shard, self.n_ranks,
        )
        return tuple(
            (shard + j * self.offset) % self.n_ranks
            for j in range(self.replication)
        )

    def segments(self, rank: int) -> Tuple[int, ...]:
        """The logical shards stored on ``rank``, in slab-segment order
        (segment 0 = the rank's own primary shard)."""
        errors.expects(
            0 <= rank < self.n_ranks,
            "rank %d out of range [0, %d)", rank, self.n_ranks,
        )
        return tuple(
            (rank - j * self.offset) % self.n_ranks
            for j in range(self.replication)
        )

    def holder_hosts(self, shard: int) -> Tuple[int, ...]:
        """The hosts storing ``shard``'s copies, primary first (host =
        rank // inner_size; all zeros when the placement carries no
        host axis)."""
        return tuple(
            r // max(self.inner_size, 1) for r in self.holders(shard)
        )

    @property
    def host_disjoint(self) -> bool:
        """True iff every shard's R copies land on R distinct hosts —
        the whole-host-failure survival contract (a host-aware
        ``striped(..., inner_size=)`` placement guarantees it at
        construction; docs/multihost.md)."""
        if self.inner_size <= 1:
            return self.replication == 1
        return all(
            len(set(self.holder_hosts(s))) == self.replication
            for s in range(self.n_ranks)
        )

    @property
    def memory_factor(self) -> int:
        """Slab-memory multiplier vs the unreplicated index (exactly R:
        lists, rows, and codes are stored R times; quantizers and
        ownership maps were already replicated)."""
        return self.replication


# -- popularity-aware replication (ISSUE 15, docs/serving.md "Hot
# traffic"): Zipf-skewed traffic concentrates load on a few HOT shards,
# so a uniform per-shard replication factor either under-covers the hot
# shards or wastes memory on the cold ones. The measured per-shard
# dispatch load (counters the serving tier records per probe-routed
# row) drives two host-side decisions — a NON-UNIFORM replication
# vector (how many copies each shard deserves within a fixed copy
# budget) and a LOAD-WEIGHTED failover route
# (:meth:`FailoverPlan.load_balanced`). Both are planning VALUES only:
# the route stays a runtime operand of the same compiled programs, so
# a popularity-driven re-route can never retrace (trace-audited in
# tests/test_result_cache.py).

_SHARD_LOAD_METRIC = "serving_shard_rows_total"


def record_shard_load(shard_rows, *, registry=None,
                      name: str = _SHARD_LOAD_METRIC) -> None:
    """Accumulate a per-shard dispatched-row count vector into the
    ``{name}{shard=s}`` counters — the measurement side of
    popularity-aware replication. Callers hand in whatever granularity
    they have (per-batch probe→owner histograms, a bench's offered
    template mix, :meth:`FailoverPlan.serving_load`); the counters sum
    it process-wide. ``RAFT_TPU_OBS=off`` no-ops it like every
    recorder."""
    rows = np.asarray(shard_rows)
    errors.expects(rows.ndim == 1,
                   "record_shard_load: expected a (P,) vector, got %s",
                   tuple(rows.shape))
    reg = obs_metrics.default_registry() if registry is None else registry
    for s in range(rows.shape[0]):
        n = int(rows[s])
        if n:
            reg.counter(name, shard=s).inc(n)


def measured_shard_load(n_shards: int, *, registry=None,
                        name: str = _SHARD_LOAD_METRIC) -> np.ndarray:
    """The accumulated per-shard load, ``(P,)`` float64 (zeros where no
    traffic was recorded) — the input of
    :func:`popularity_replication` and
    :meth:`FailoverPlan.load_balanced`."""
    errors.expects(n_shards >= 1,
                   "measured_shard_load: n_shards=%d < 1", n_shards)
    reg = obs_metrics.default_registry() if registry is None else registry
    load = np.zeros(n_shards, np.float64)
    for inst in reg.series(name):
        s = inst.labels.get("shard")
        if s is None:
            continue
        s = int(s)
        if 0 <= s < n_shards:
            load[s] += float(inst.value)
    return load


# per-LIST granularity (ISSUE 17, docs/tiering.md): the tier's
# promotion policy needs to know which LISTS are hot, not just which
# shards — but an index has thousands of lists and a counter per list
# is a cardinality bomb. The rule: a shard mints at most
# ``max_series`` per-list series (first-come under Zipf traffic ≈ the
# head, which is exactly the set the tier can act on); everything else
# folds into the ``list="other"`` bucket, so total traffic is still
# conserved and the catalog stays bounded.

_LIST_LOAD_METRIC = "serving_list_rows_total"
_LIST_SERIES_CAP = 64


def record_list_load(list_rows, *, shard: int = 0, registry=None,
                     name: str = _LIST_LOAD_METRIC,
                     max_series: int = _LIST_SERIES_CAP) -> None:
    """Accumulate a per-list dispatched-row vector into the
    bounded-cardinality ``{name}{shard=s,list=l}`` counters — the
    measurement side of tier promotion
    (:class:`raft_tpu.tier.PromotionPolicy`). ``list_rows`` is a
    ``(n_lists,)`` count vector (a probe histogram, a touch decay
    snapshot — whatever granularity the caller has). Lists that
    already own a series always record to it; new series are minted
    only while the shard holds fewer than ``max_series``, after which
    the remainder lands in ``list="other"``. ``RAFT_TPU_OBS=off``
    no-ops it like every recorder."""
    rows = np.asarray(list_rows)
    errors.expects(rows.ndim == 1,
                   "record_list_load: expected a (n_lists,) vector, "
                   "got %s", tuple(rows.shape))
    reg = obs_metrics.default_registry() if registry is None else registry
    shard_l = str(int(shard))
    minted = set()
    for inst in reg.series(name):
        if (inst.labels.get("shard") == shard_l
                and inst.labels.get("list") not in (None, "other")):
            minted.add(inst.labels["list"])
    other = 0
    for lid in np.nonzero(rows)[0]:
        n = int(rows[lid])
        key = str(int(lid))
        if key in minted or len(minted) < max_series:
            minted.add(key)
            reg.counter(name, shard=shard_l, list=key).inc(n)
        else:
            other += n
    if other:
        reg.counter(name, shard=shard_l, list="other").inc(other)


def measured_list_load(n_lists: int, *, shard: "int | None" = None,
                       registry=None,
                       name: str = _LIST_LOAD_METRIC) -> np.ndarray:
    """The accumulated per-list load, ``(n_lists,)`` float64 — the
    promotion policy's ranking signal. ``shard=None`` sums every
    shard's series; the ``list="other"`` residual bucket is excluded
    (it names no actionable list)."""
    errors.expects(n_lists >= 1,
                   "measured_list_load: n_lists=%d < 1", n_lists)
    reg = obs_metrics.default_registry() if registry is None else registry
    load = np.zeros(n_lists, np.float64)
    want = None if shard is None else str(int(shard))
    for inst in reg.series(name):
        lid = inst.labels.get("list")
        if lid in (None, "other"):
            continue
        if want is not None and inst.labels.get("shard") != want:
            continue
        lid = int(lid)
        if 0 <= lid < n_lists:
            load[lid] += float(inst.value)
    return load


def popularity_replication(load, *, budget: int, r_min: int = 1,
                           r_max: "int | None" = None) -> np.ndarray:
    """Distribute a fixed copy ``budget`` over shards proportionally to
    measured load (largest-remainder apportionment): every shard keeps
    at least ``r_min`` copies (availability floor — a cold shard must
    still survive a failure), hot shards absorb the surplus up to
    ``r_max`` (default: the shard count, i.e. uncapped). Returns the
    ``(P,)`` int replication vector, summing exactly to ``budget``.

    This is a PLANNING output: the slab layout stays the uniform-R
    :class:`ReplicaPlacement` (the compiled programs depend on its
    statics), and the vector says where the NEXT capacity decision —
    which R to rebuild with, which shards to pin an extra standby for,
    which copies a load-weighted route should prefer — pays off.
    With uniform load it degenerates to uniform replication."""
    load = np.asarray(load, np.float64)
    p = load.shape[0]
    errors.expects(load.ndim == 1 and p >= 1,
                   "popularity_replication: expected a (P,) load "
                   "vector, got %s", tuple(load.shape))
    r_max = p if r_max is None else int(r_max)
    errors.expects(
        1 <= r_min <= r_max,
        "popularity_replication: need 1 <= r_min=%d <= r_max=%d",
        r_min, r_max,
    )
    errors.expects(
        p * r_min <= budget <= p * r_max,
        "popularity_replication: budget=%d cannot satisfy %d shards "
        "with copies in [%d, %d]", budget, p, r_min, r_max,
    )
    copies = np.full(p, r_min, np.int64)
    spare = budget - p * r_min
    total = float(load.sum())
    share = (load / total if total > 0
             else np.full(p, 1.0 / p)) * spare
    grant = np.minimum(np.floor(share).astype(np.int64),
                       r_max - r_min)
    copies += grant
    left = budget - int(copies.sum())
    # largest remainders first (ties: lower shard id — deterministic)
    rem = np.where(copies < r_max, share - np.floor(share), -1.0)
    for s in np.lexsort((np.arange(p), -rem)):
        if left == 0:
            break
        if copies[s] < r_max:
            copies[s] += 1
            left -= 1
    # r_max clamping can strand budget; spread it over the coldest
    # shards that still have headroom
    while left > 0:
        open_s = np.nonzero(copies < r_max)[0]
        take = open_s[np.argsort(load[open_s], kind="stable")]
        for s in take[:left]:
            copies[s] += 1
        left = budget - int(copies.sum())
    return copies.astype(np.int32)


def _alive_mask(health: Any, n_ranks: int) -> np.ndarray:
    # local import: degraded.py is jax-importing; keep this module
    # usable from a mesh-free control plane unless a mask must resolve
    from raft_tpu.resilience.degraded import resolve_shard_mask

    return resolve_shard_mask(health, n_ranks)


@dataclasses.dataclass(frozen=True)
class FailoverPlan:
    """A routing decision: which replica copy serves each logical shard.

    ``route`` is the ``(P,)`` int32 RUNTIME input of the degraded
    sharded search programs: ``route[s]`` is the copy index ``j`` such
    that rank ``(s + j*offset) % P`` serves shard ``s``'s lists; ``-1``
    means every holder is down and the shard goes unserved (the search
    degrades to the PR 3 partial path for exactly those probes). A
    healthy mesh routes everything to copy 0 — the all-zeros route is
    the default when no plan is passed.

    Each shard is served by EXACTLY ONE rank under any plan, so merged
    results carry no duplicates and — whenever ``fully_covered`` — are
    identical to the healthy mesh's (every list is scored by the same
    kernel over an identical replica of its rows; only which allgather
    part carries the contribution changes).
    """

    placement: ReplicaPlacement
    route: np.ndarray

    @classmethod
    def from_health(cls, placement: ReplicaPlacement,
                    health: Any) -> "FailoverPlan":
        """Route every shard to its FIRST live holder (primary wins when
        up, so a healthy mesh yields the all-zeros route and flipping a
        rank back up restores primary serving). ``health`` is anything
        :func:`raft_tpu.resilience.resolve_shard_mask` accepts — a
        :class:`ShardHealth`, a :class:`HealthReport`, a ``(P,)``
        array-like, or ``True``."""
        alive = _alive_mask(health, placement.n_ranks)
        route = np.full(placement.n_ranks, -1, np.int32)
        for s in range(placement.n_ranks):
            for j, r in enumerate(placement.holders(s)):
                if alive[r]:
                    route[s] = j
                    break
        _M_PLANS.inc()
        _G_REROUTED.set(int((route > 0).sum()))
        _G_UNSERVED.set(int((route < 0).sum()))
        return cls(placement=placement, route=route)

    @classmethod
    def from_host_health(cls, placement: ReplicaPlacement,
                         host_alive: Any,
                         inner_size: "int | None" = None) -> "FailoverPlan":
        """The HOST-failure form of :meth:`from_health`: ``host_alive``
        is a per-HOST mask (host h covers ranks
        ``[h*inner_size, (h+1)*inner_size)`` — the row-major rank order
        of the 2-level mesh), expanded to the flat rank mask and routed
        exactly as rank failures are. With a host-aware placement
        (``striped(..., inner_size=)``) and R=2, any single whole dead
        host keeps every shard served (``fully_covered``) — the
        multi-host failure contract (docs/multihost.md "Host failure
        semantics"). ``inner_size`` defaults to the placement's own."""
        inner = placement.inner_size if inner_size is None else int(inner_size)
        errors.expects(
            inner >= 1 and placement.n_ranks % inner == 0,
            "from_host_health: inner_size=%d does not tile n_ranks=%d",
            inner, placement.n_ranks,
        )
        host_alive = np.asarray(host_alive)
        errors.expects(
            host_alive.shape == (placement.n_ranks // inner,),
            "from_host_health: expected a (%d,) per-host mask, got "
            "shape %s", placement.n_ranks // inner,
            tuple(host_alive.shape),
        )
        alive = np.repeat((host_alive != 0).astype(np.int32), inner)
        return cls.from_health(placement, alive)

    @classmethod
    def load_balanced(cls, placement: ReplicaPlacement, health: Any,
                      load=None, *, registry=None) -> "FailoverPlan":
        """The LOAD-WEIGHTED route (ISSUE 15): among each shard's live
        holders, pick the copy that keeps the per-rank served load most
        even — hot shards claim their least-loaded live holder FIRST
        (descending measured load, so the ranks that must also absorb
        their hedged re-dispatches stay coolest), cold shards fill in
        around them. ``load`` is the ``(P,)`` measured per-shard load
        (default: :func:`measured_shard_load` from the registry's
        dispatch counters). Ties prefer the lower copy index, so a
        healthy mesh under uniform load yields exactly
        :meth:`from_health`'s all-zeros route.

        Route VALUES only: the result is an ordinary
        :class:`FailoverPlan` over the same placement, consumed by the
        same ``(P,)`` runtime route input — a popularity-driven
        re-route never retraces the serving program."""
        alive = _alive_mask(health, placement.n_ranks)
        p = placement.n_ranks
        if load is None:
            load = measured_shard_load(p, registry=registry)
        load = np.asarray(load, np.float64)
        errors.expects(
            load.shape == (p,),
            "load_balanced: expected a (%d,) load vector, got %s",
            p, tuple(load.shape),
        )
        route = np.full(p, -1, np.int32)
        rank_load = np.zeros(p, np.float64)
        # hottest shards pick first (stable ties by shard id)
        for s in np.lexsort((np.arange(p), -load)):
            best_j, best_r = -1, -1
            for j, r in enumerate(placement.holders(int(s))):
                if not alive[r]:
                    continue
                if best_j < 0 or rank_load[r] < rank_load[best_r]:
                    best_j, best_r = j, r
            if best_j >= 0:
                route[s] = best_j
                rank_load[best_r] += load[s]
        _M_PLANS.inc()
        _G_REROUTED.set(int((route > 0).sum()))
        _G_UNSERVED.set(int((route < 0).sum()))
        return cls(placement=placement, route=route)

    @property
    def fully_covered(self) -> bool:
        """True iff every logical shard has a live serving rank — the
        zero-coverage-loss regime (≤ R-1 failures per replica group)."""
        return bool((self.route >= 0).all())

    @property
    def unserved_shards(self) -> list:
        """Logical shards with no live holder (whole group dead)."""
        return np.nonzero(self.route < 0)[0].tolist()

    def serving_rank(self, shard: int) -> int:
        """The rank currently serving ``shard`` (-1 = unserved)."""
        j = int(self.route[shard])
        if j < 0:
            return -1
        return self.placement.holders(shard)[j]

    def serving_load(self) -> np.ndarray:
        """Shards served per rank, ``(P,)`` int — 1 everywhere on a
        healthy mesh; a failover rank carries 2+ (its grouped search
        scans more non-empty lists, so size ``qcap``/latency budgets
        for the failover load, not the healthy one)."""
        load = np.zeros(self.placement.n_ranks, np.int64)
        for s in range(self.placement.n_ranks):
            r = self.serving_rank(s)
            if r >= 0:
                load[r] += 1
        return load

    def __repr__(self) -> str:  # compact operator-facing summary
        moved = np.nonzero(self.route > 0)[0].tolist()
        dead = self.unserved_shards
        return (
            f"FailoverPlan(P={self.placement.n_ranks}, "
            f"R={self.placement.replication}, failed_over={moved}, "
            f"unserved={dead})"
        )


def resolve_route(failover: Any, n_ranks: int, replication: int,
                  offset: int) -> np.ndarray:
    """Normalize a search's ``failover=`` argument to the ``(P,)`` int32
    route array the compiled degraded program consumes. Accepts ``None``
    (healthy: all copy 0), a :class:`FailoverPlan` (its placement must
    match the index's replication geometry — a plan built for a
    different stripe would route probes into the wrong slab segments),
    or an explicit ``(P,)`` array of copy indices in ``[-1, R)``."""
    if failover is None:
        return np.zeros(n_ranks, np.int32)
    if isinstance(failover, FailoverPlan):
        p = failover.placement
        errors.expects(
            p.n_ranks == n_ranks and p.replication == replication
            and (replication == 1 or p.offset == offset),
            "failover plan placement (P=%d, R=%d, offset=%d) does not "
            "match the index layout (P=%d, R=%d, offset=%d)",
            p.n_ranks, p.replication, p.offset,
            n_ranks, replication, offset,
        )
        arr = failover.route
    else:
        arr = np.asarray(failover)
    errors.expects(
        arr.shape == (n_ranks,),
        "failover route: expected shape (%d,), got %s",
        n_ranks, tuple(arr.shape),
    )
    arr = arr.astype(np.int32)
    errors.expects(
        bool(((arr >= -1) & (arr < replication)).all()),
        "failover route entries must be replica copy indices in "
        "[-1, %d)", replication,
    )
    return arr
