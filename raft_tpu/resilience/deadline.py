"""Deadlines and retries for serving dispatches.

The reference treats cancellable waits as a core primitive
(``raft::interruptible`` polls a stream wait, interruptible.hpp:66-120)
but leaves deadlines and retry policy to callers. At serving scale
(ROADMAP north star) a slow chip, a preempted host, or a hung collective
must turn into a bounded, classified error the caller can retry — not an
indefinite block. This module provides that failure model:

* :class:`Deadline` — a monotonic-clock budget shared across attempts;
* :class:`RetryPolicy` — max attempts, exponential backoff with
  DETERMINISTIC jitter (seeded; two replicas retrying the same failure
  de-synchronize identically run-to-run, so chaos tests replay exactly),
  and retryable-error classification;
* :func:`dispatch_with_deadline` — dispatch + bounded wait + retry,
  built on ``Interruptible.synchronize(timeout_s=...)``. Retries call
  the SAME function object, so a jitted program is re-dispatched from
  jax's compile cache: a retry costs dispatch, not compile
  (tests/test_resilience.py audits trace and dispatch counts).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Callable, Optional, Tuple

import numpy as np

from raft_tpu import errors
from raft_tpu.core.interruptible import Interruptible

__all__ = ["Deadline", "RetryPolicy", "dispatch_with_deadline"]


@dataclasses.dataclass(frozen=True)
class Deadline:
    """A wall-clock budget on the monotonic clock.

    ``Deadline.after(0.5)`` expires 500 ms from construction; every
    attempt of a retried dispatch draws from the SAME budget, so retries
    can never extend the caller's latency bound. ``Deadline.unbounded()``
    never expires (remaining() is +inf).
    """

    expires_at: float  # time.monotonic() timestamp; +inf = unbounded

    @classmethod
    def after(cls, timeout_s: Optional[float]) -> "Deadline":
        """A deadline ``timeout_s`` seconds from now (None = unbounded)."""
        if timeout_s is None:
            return cls.unbounded()
        errors.expects(
            timeout_s > 0, "Deadline.after: timeout_s=%s must be > 0",
            timeout_s,
        )
        return cls(time.monotonic() + float(timeout_s))

    @classmethod
    def unbounded(cls) -> "Deadline":
        return cls(math.inf)

    @property
    def bounded(self) -> bool:
        return math.isfinite(self.expires_at)

    def remaining(self) -> float:
        """Seconds left (never negative; +inf when unbounded)."""
        return max(0.0, self.expires_at - time.monotonic())

    def expired(self) -> bool:
        return self.remaining() <= 0.0


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry classification + exponential backoff with deterministic
    jitter.

    ``retryable_types`` classifies errors worth re-dispatching: by
    default timeouts (:class:`raft_tpu.errors.RaftTimeoutError`) and
    cancellations are retryable, while logic errors
    (:class:`raft_tpu.errors.RaftLogicError` — a bad argument retried is
    a bad argument again) and everything else are not. The backoff for
    attempt ``a`` (1-based) is
    ``min(max_delay_s, base_delay_s * multiplier**(a-1))`` scaled by a
    jitter factor drawn from a counter-based PRNG seeded on
    ``(seed, a)`` — deterministic across runs and replicas, so fault
    injection replays exactly and two replicas with different seeds
    de-synchronize their retry storms.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    jitter_frac: float = 0.25
    seed: int = 0
    retryable_types: Tuple[type, ...] = (
        errors.RaftTimeoutError,
        TimeoutError,
    )

    def is_retryable(self, exc: BaseException) -> bool:
        """Classification: may this failure be re-dispatched?"""
        return isinstance(exc, self.retryable_types)

    def backoff_s(self, attempt: int) -> float:
        """Delay before attempt ``attempt + 1`` (after failure number
        ``attempt``, 1-based), with deterministic jitter in
        ``[1 - jitter_frac, 1 + jitter_frac]``."""
        errors.expects(attempt >= 1, "backoff_s: attempt=%d < 1", attempt)
        base = min(
            self.max_delay_s,
            self.base_delay_s * self.multiplier ** (attempt - 1),
        )
        u = float(
            np.random.default_rng((self.seed, attempt)).uniform(-1.0, 1.0)
        )
        return max(0.0, base * (1.0 + self.jitter_frac * u))


def dispatch_with_deadline(
    fn: Callable[..., Any], *args: Any,
    timeout_s: Optional[float] = None,
    deadline: Optional[Deadline] = None,
    retry: Optional[RetryPolicy] = None,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    **kwargs: Any,
) -> Any:
    """Dispatch ``fn(*args, **kwargs)`` and wait for its outputs under a
    deadline, retrying classified failures.

    * ``timeout_s`` bounds EACH attempt's wait
      (``Interruptible.synchronize(timeout_s=...)`` →
      :class:`raft_tpu.errors.RaftTimeoutError` on expiry);
    * ``deadline`` (optional) is an overall budget across all attempts —
      each attempt's wait is clipped to the tighter of the two, and no
      retry starts once it has expired;
    * ``retry`` governs how many attempts and which errors qualify
      (default: a single attempt, i.e. no retries);
    * ``on_retry(attempt, exc, sleep_s)`` is called before each backoff
      sleep — the observability hook (log/metric the failure).

    ``fn`` is called again on retry, NOT re-traced: a jitted ``fn``
    re-dispatches the already-compiled program (jax's jit cache keys on
    the same shapes/statics), so a retry costs dispatch latency only.
    The abandoned attempt's device work still completes in the
    background (cooperative semantics, exactly like
    ``Interruptible.cancel``) — on a mesh this means a retry may briefly
    queue behind the straggler it is retrying past; the per-attempt
    timeout covers that window.

    Retries and BUFFER DONATION do not mix: a dispatch that donates its
    inputs (``donate_queries=True`` on the sharded searches,
    ``jax.jit(donate_argnums=...)``) consumes the argument buffers on
    the FIRST attempt, so a retry would re-dispatch deleted arrays and
    die on a non-retryable RuntimeError. Under a retry policy keep
    donation off, or have ``fn`` materialize a fresh batch per call.

    Cancellation composes: a ``cancel()`` aimed at this thread raises
    ``InterruptedException`` out of the wait, which is NOT retryable
    under the default policy and propagates immediately.
    """
    retry = RetryPolicy(max_attempts=1) if retry is None else retry
    errors.expects(
        retry.max_attempts >= 1,
        "dispatch_with_deadline: max_attempts=%d < 1", retry.max_attempts,
    )
    overall = Deadline.unbounded() if deadline is None else deadline
    attempt = 0
    while True:
        attempt += 1
        wait_s: Optional[float] = timeout_s
        if overall.bounded:
            rem = overall.remaining()
            wait_s = rem if wait_s is None else min(wait_s, rem)
        try:
            out = fn(*args, **kwargs)
            Interruptible.synchronize(out, timeout_s=wait_s)
            return out
        except Exception as exc:
            exhausted = (
                attempt >= retry.max_attempts
                or not retry.is_retryable(exc)
                or overall.expired()
            )
            if exhausted:
                raise
            sleep_s = min(retry.backoff_s(attempt), overall.remaining())
            if on_retry is not None:
                on_retry(attempt, exc, sleep_s)
            if sleep_s > 0:
                time.sleep(sleep_s)
