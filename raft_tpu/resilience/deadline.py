"""Deadlines and retries for serving dispatches.

The reference treats cancellable waits as a core primitive
(``raft::interruptible`` polls a stream wait, interruptible.hpp:66-120)
but leaves deadlines and retry policy to callers. At serving scale
(ROADMAP north star) a slow chip, a preempted host, or a hung collective
must turn into a bounded, classified error the caller can retry — not an
indefinite block. This module provides that failure model:

* :class:`Deadline` — a monotonic-clock budget shared across attempts;
* :class:`RetryPolicy` — max attempts, exponential backoff with
  DETERMINISTIC jitter (seeded; two replicas retrying the same failure
  de-synchronize identically run-to-run, so chaos tests replay exactly),
  and retryable-error classification;
* :func:`dispatch_with_deadline` — dispatch + bounded wait + retry,
  built on ``Interruptible.synchronize(timeout_s=...)``. Retries call
  the SAME function object, so a jitted program is re-dispatched from
  jax's compile cache: a retry costs dispatch, not compile
  (tests/test_resilience.py audits trace and dispatch counts);
* :class:`HedgePolicy` + :func:`dispatch_hedged` — tail-latency
  hedging: when the primary dispatch is still not ready after a
  percentile-derived hedge delay, a backup is dispatched and the FIRST
  ready answer wins (the "tied requests" pattern; the loser's device
  work completes in the background — cooperative semantics, exactly
  like an abandoned retry). Deterministic under
  ``raft_tpu.testing.faults`` stragglers, so the chaos suite replays.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Callable, List, Optional, Tuple

import jax
import numpy as np

from raft_tpu import errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.core.interruptible import Interruptible

__all__ = [
    "Deadline", "RetryPolicy", "dispatch_with_deadline",
    "HedgePolicy", "dispatch_hedged", "wait_first",
]


@dataclasses.dataclass(frozen=True)
class Deadline:
    """A wall-clock budget on the monotonic clock.

    ``Deadline.after(0.5)`` expires 500 ms from construction; every
    attempt of a retried dispatch draws from the SAME budget, so retries
    can never extend the caller's latency bound. ``Deadline.unbounded()``
    never expires (remaining() is +inf).
    """

    expires_at: float  # time.monotonic() timestamp; +inf = unbounded

    @classmethod
    def after(cls, timeout_s: Optional[float]) -> "Deadline":
        """A deadline ``timeout_s`` seconds from now (None = unbounded)."""
        if timeout_s is None:
            return cls.unbounded()
        errors.expects(
            timeout_s > 0, "Deadline.after: timeout_s=%s must be > 0",
            timeout_s,
        )
        return cls(time.monotonic() + float(timeout_s))

    @classmethod
    def unbounded(cls) -> "Deadline":
        return cls(math.inf)

    @property
    def bounded(self) -> bool:
        return math.isfinite(self.expires_at)

    def remaining(self) -> float:
        """Seconds left (never negative; +inf when unbounded)."""
        return max(0.0, self.expires_at - time.monotonic())

    def expired(self) -> bool:
        return self.remaining() <= 0.0


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry classification + exponential backoff with deterministic
    jitter.

    ``retryable_types`` classifies errors worth re-dispatching: by
    default timeouts (:class:`raft_tpu.errors.RaftTimeoutError`) and
    cancellations are retryable, while logic errors
    (:class:`raft_tpu.errors.RaftLogicError` — a bad argument retried is
    a bad argument again) and everything else are not. The backoff for
    attempt ``a`` (1-based) is
    ``min(max_delay_s, base_delay_s * multiplier**(a-1))`` scaled by a
    jitter factor drawn from a counter-based PRNG seeded on
    ``(seed, a)`` — deterministic across runs and replicas, so fault
    injection replays exactly and two replicas with different seeds
    de-synchronize their retry storms.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    jitter_frac: float = 0.25
    seed: int = 0
    retryable_types: Tuple[type, ...] = (
        errors.RaftTimeoutError,
        TimeoutError,
    )

    def is_retryable(self, exc: BaseException) -> bool:
        """Classification: may this failure be re-dispatched?"""
        return isinstance(exc, self.retryable_types)

    def backoff_s(self, attempt: int) -> float:
        """Delay before attempt ``attempt + 1`` (after failure number
        ``attempt``, 1-based), with deterministic jitter in
        ``[1 - jitter_frac, 1 + jitter_frac]``."""
        errors.expects(attempt >= 1, "backoff_s: attempt=%d < 1", attempt)
        base = min(
            self.max_delay_s,
            self.base_delay_s * self.multiplier ** (attempt - 1),
        )
        u = float(
            np.random.default_rng((self.seed, attempt)).uniform(-1.0, 1.0)
        )
        return max(0.0, base * (1.0 + self.jitter_frac * u))


def dispatch_with_deadline(
    fn: Callable[..., Any], *args: Any,
    timeout_s: Optional[float] = None,
    deadline: Optional[Deadline] = None,
    retry: Optional[RetryPolicy] = None,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    **kwargs: Any,
) -> Any:
    """Dispatch ``fn(*args, **kwargs)`` and wait for its outputs under a
    deadline, retrying classified failures.

    * ``timeout_s`` bounds EACH attempt's wait
      (``Interruptible.synchronize(timeout_s=...)`` →
      :class:`raft_tpu.errors.RaftTimeoutError` on expiry);
    * ``deadline`` (optional) is an overall budget across all attempts —
      each attempt's wait is clipped to the tighter of the two, and no
      retry starts once it has expired;
    * ``retry`` governs how many attempts and which errors qualify
      (default: a single attempt, i.e. no retries);
    * ``on_retry(attempt, exc, sleep_s)`` is called before each backoff
      sleep — the observability hook (log/metric the failure).

    ``fn`` is called again on retry, NOT re-traced: a jitted ``fn``
    re-dispatches the already-compiled program (jax's jit cache keys on
    the same shapes/statics), so a retry costs dispatch latency only.
    The abandoned attempt's device work still completes in the
    background (cooperative semantics, exactly like
    ``Interruptible.cancel``) — on a mesh this means a retry may briefly
    queue behind the straggler it is retrying past; the per-attempt
    timeout covers that window.

    Retries and BUFFER DONATION do not mix: a dispatch that donates its
    inputs (``donate_queries=True`` on the sharded searches,
    ``jax.jit(donate_argnums=...)``) consumes the argument buffers on
    the FIRST attempt, so a retry would re-dispatch deleted arrays and
    die on a non-retryable RuntimeError. Under a retry policy keep
    donation off, or have ``fn`` materialize a fresh batch per call.

    Cancellation composes: a ``cancel()`` aimed at this thread raises
    ``InterruptedException`` out of the wait, which is NOT retryable
    under the default policy and propagates immediately.
    """
    retry = RetryPolicy(max_attempts=1) if retry is None else retry
    errors.expects(
        retry.max_attempts >= 1,
        "dispatch_with_deadline: max_attempts=%d < 1", retry.max_attempts,
    )
    overall = Deadline.unbounded() if deadline is None else deadline
    attempt = 0
    while True:
        attempt += 1
        wait_s: Optional[float] = timeout_s
        if overall.bounded:
            rem = overall.remaining()
            wait_s = rem if wait_s is None else min(wait_s, rem)
        try:
            out = fn(*args, **kwargs)
            Interruptible.synchronize(out, timeout_s=wait_s)
            return out
        except Exception as exc:
            exhausted = (
                attempt >= retry.max_attempts
                or not retry.is_retryable(exc)
                or overall.expired()
            )
            if exhausted:
                raise
            sleep_s = min(retry.backoff_s(attempt), overall.remaining())
            if on_retry is not None:
                on_retry(attempt, exc, sleep_s)
            if sleep_s > 0:
                time.sleep(sleep_s)


class HedgePolicy:
    """Percentile-based hedge-delay tuning + hedge outcome counters
    (thread-safe).

    The hedge delay is the latency percentile at which a dispatch is
    declared "probably straggling" and worth backing up: hedging at p95
    bounds the extra dispatch load to ~5% of traffic while cutting the
    tail above it to roughly ``delay + p50`` (the classic tied-requests
    tradeoff — docs/robustness.md "hedge-delay tuning").
    :func:`dispatch_hedged` records every completed dispatch's latency
    here, so the delay adapts to the measured distribution;
    ``default_delay_s`` serves until ``min_samples`` have been seen, and
    ``min_delay_s``/``max_delay_s`` clamp the estimate (a hedge delay
    below the dispatch cost would double EVERY request's load).

    Counters: ``primary_wins`` / ``backup_wins`` count hedged races by
    winner; ``hedges`` counts backup dispatches (the added-load
    metric); ``unhedged`` counts dispatches the primary won before the
    delay expired.
    """

    def __init__(self, *, percentile: float = 95.0,
                 default_delay_s: float = 0.05,
                 min_delay_s: float = 0.0,
                 max_delay_s: float = 10.0,
                 window: int = 1024, min_samples: int = 16):
        errors.expects(
            0.0 < percentile <= 100.0,
            "HedgePolicy: percentile=%s out of range (0, 100]", percentile,
        )
        errors.expects(
            min_delay_s <= max_delay_s,
            "HedgePolicy: min_delay_s=%s > max_delay_s=%s",
            min_delay_s, max_delay_s,
        )
        errors.expects(
            window >= 1 and min_samples >= 1,
            "HedgePolicy: window=%d / min_samples=%d must be >= 1",
            window, min_samples,
        )
        self.percentile = float(percentile)
        self.default_delay_s = float(default_delay_s)
        self.min_delay_s = float(min_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self._lock = lockcheck.make_lock("HedgePolicy._lock")
        self._samples: List[float] = []
        self.hedges = 0
        self.unhedged = 0
        self.primary_wins = 0
        self.backup_wins = 0

    def record(self, seconds: float) -> None:
        """Record one completed dispatch's latency (a bounded sliding
        window of the most recent ``window`` samples)."""
        with self._lock:
            self._samples.append(float(seconds))
            if len(self._samples) > self.window:
                del self._samples[: len(self._samples) - self.window]

    @property
    def n_samples(self) -> int:
        with self._lock:
            return len(self._samples)

    def hedge_delay_s(self) -> float:
        """The current hedge delay: the configured latency percentile of
        the recorded window, clamped to [min_delay_s, max_delay_s];
        ``default_delay_s`` (clamped) until ``min_samples`` samples."""
        with self._lock:
            if len(self._samples) < self.min_samples:
                est = self.default_delay_s
            else:
                est = float(
                    np.percentile(np.asarray(self._samples),
                                  self.percentile)
                )
        return min(self.max_delay_s, max(self.min_delay_s, est))


def _ready_leaves(x) -> list:
    return [
        leaf for leaf in jax.tree.leaves(x) if hasattr(leaf, "is_ready")
    ]


def wait_first(candidates, *, timeout_s: Optional[float],
               poll_interval_s: float = 0.0005,
               max_poll_interval_s: float = 0.02) -> int:
    """Index of the FIRST fully-ready candidate (every ``is_ready`` leaf
    ready), polling with the same cancellable backoff loop as
    ``Interruptible.synchronize``; :class:`raft_tpu.errors.RaftTimeoutError`
    if none is ready within ``timeout_s``. Public for custom dispatch
    layers racing replica candidates the way :func:`dispatch_hedged`
    does. (The open-loop executor's drain loop implements the same
    readiness idiom NON-blocking — it sweeps many in-flight batches per
    poll instead of parking on one candidate set, so it cannot call
    this helper.)"""
    pending = [_ready_leaves(c) for c in candidates]
    deadline = (
        None if timeout_s is None else time.monotonic() + timeout_s
    )
    interval = poll_interval_s
    while True:
        Interruptible.yield_now()
        for i, leaves in enumerate(pending):
            pending[i] = [leaf for leaf in leaves if not leaf.is_ready()]
            if not pending[i]:
                return i
        if deadline is not None and time.monotonic() >= deadline:
            raise errors.RaftTimeoutError(
                "dispatch_hedged: neither primary nor backup ready "
                f"within {timeout_s:.3g}s"
            )
        time.sleep(interval)
        interval = min(interval * 2.0, max_poll_interval_s)


def dispatch_hedged(
    fn: Callable[..., Any], *args: Any,
    hedge: "HedgePolicy | float" = 0.05,
    backup_fn: Optional[Callable[..., Any]] = None,
    timeout_s: Optional[float] = None,
    deadline: Optional[Deadline] = None,
    on_hedge: Optional[Callable[[float], None]] = None,
    **kwargs: Any,
) -> Any:
    """Dispatch ``fn(*args, **kwargs)`` and, if it is still not ready
    after the hedge delay, dispatch a backup — the first ready answer
    wins (Dean & Barroso's "tied requests": the p99 of a hedged
    dispatch collapses toward ``hedge_delay + p50``, because a
    straggling chip no longer holds the answer hostage).

    * ``hedge``: a :class:`HedgePolicy` (percentile-adaptive, records
      every completed latency and counts outcomes) or a fixed delay in
      seconds;
    * ``backup_fn``: the backup dispatch (default: ``fn`` again — on a
      replicated deployment pass the OTHER replica's entry point, so
      the backup cannot land on the same straggling chip);
    * ``timeout_s`` / ``deadline``: overall wait bound across both
      dispatches (the tighter wins), raising
      :class:`raft_tpu.errors.RaftTimeoutError` — measured from entry,
      so the hedge delay spends the same budget;
    * ``on_hedge(delay_s)``: observability hook, called once when the
      backup is actually dispatched.

    The LOSER is abandoned, not preempted: its device work completes in
    the background (cooperative semantics, exactly like a
    ``dispatch_with_deadline`` retry past a straggler), and its output
    buffers are dropped with the reference. Hedging therefore costs up
    to one duplicate dispatch per hedge — bound it by hedging at a high
    percentile. Like retries, hedging and BUFFER DONATION do not mix:
    a donated batch is consumed by the primary dispatch, so the backup
    would re-dispatch deleted arrays; keep donation off or have ``fn``
    materialize a fresh batch per call.

    Deterministic under injected faults: with
    ``raft_tpu.testing.faults.inject_delay``/``inject_straggler``
    gating readiness on the host clock, the same fault schedule yields
    the same winner every run (the chaos suite replays bit-for-bit).
    """
    policy = hedge if isinstance(hedge, HedgePolicy) else None
    delay_s = (
        policy.hedge_delay_s() if policy is not None else float(hedge)
    )
    errors.expects(
        delay_s >= 0, "dispatch_hedged: hedge delay %s < 0", delay_s
    )
    overall = Deadline.unbounded() if deadline is None else deadline
    if timeout_s is not None:
        overall = Deadline(
            min(overall.expires_at, time.monotonic() + timeout_s)
        )
    t0 = time.monotonic()
    primary = fn(*args, **kwargs)
    first_wait = delay_s
    if overall.bounded:
        first_wait = min(first_wait, overall.remaining())
    try:
        Interruptible.synchronize(primary, timeout_s=first_wait)
        if policy is not None:
            policy.record(time.monotonic() - t0)
            with policy._lock:
                policy.unhedged += 1
        return primary
    except errors.RaftTimeoutError:
        if overall.bounded and overall.expired():
            raise  # the budget, not the hedge delay, ended the wait
    if policy is not None:
        with policy._lock:
            policy.hedges += 1
    if on_hedge is not None:
        on_hedge(delay_s)
    backup = (backup_fn if backup_fn is not None else fn)(
        *args, **kwargs
    )
    winner = wait_first(
        (primary, backup),
        timeout_s=overall.remaining() if overall.bounded else None,
    )
    if policy is not None:
        policy.record(time.monotonic() - t0)
        with policy._lock:
            if winner == 0:
                policy.primary_wins += 1
            else:
                policy.backup_wins += 1
    return primary if winner == 0 else backup
