"""Admission control for the serving path — shed early, never collapse.

An overloaded queue is the quiet killer of tail latency: past the
sustainable rate, every admitted request makes EVERY later request
slower, latency grows without bound, and by the time clients time out
the server has burned its capacity computing answers nobody is waiting
for. The cure is ancient and boring: bound the queue, and REJECT at the
door once it is full — a shed request costs microseconds and tells the
client exactly when to retry, while an admitted-then-late request costs
a full dispatch and tells nobody anything (docs/serving.md "Overload
and shedding").

:class:`AdmissionController` is that door for the jitted serving
dispatches:

* a **concurrency bound** (``max_concurrent``) — how many dispatches
  may be in flight at once (usually 1 per mesh: the device serializes
  them anyway, and queueing host-side keeps the deadline machinery in
  charge);
* a **queue bound** (``max_queue``) — how many requests may WAIT for a
  slot; arrivals beyond it are shed immediately with
  :class:`raft_tpu.errors.RaftOverloadError` carrying ``retry_after_s``
  (estimated from the measured service time — the queue ahead of the
  client, priced);
* an optional **token limiter** (``rate`` tokens/s, ``burst`` bucket
  depth) — an absolute request-rate ceiling independent of measured
  service time, for capping a tenant or protecting a cold cache;
* **counters** (:meth:`AdmissionController.stats`): admitted / shed /
  completed / queue depth / peak depth — the shed-rate observability
  the overload bench row reports.

Two entry styles share the same bounds, counters, and pricing:

* the **blocking** context manager (``with ctrl.admit(): dispatch()``)
  — one thread per request, the queue wait happens inside ``admit``;
* the **asynchronous** triple :meth:`AdmissionController.enqueue` /
  :meth:`~AdmissionController.begin_service` /
  :meth:`~AdmissionController.finish_service` — the open-loop serving
  executor's path (:mod:`raft_tpu.serving`), where a request is queued
  by the arrival thread, dispatched inside a micro-batch by the batcher
  thread, and completed by the demux thread. ``enqueue`` NEVER blocks:
  an open-loop arrival stream must be answered shed-or-queued
  immediately, not slowed to the service rate. Its bound is the
  blocking path's TOTAL capacity — queued + in-service requests vs
  ``max_queue + max_concurrent`` — because a request the blocking
  world would have handed a free slot immediately sits in the async
  queue until the batcher picks it up. ``max_concurrent`` is not
  re-checked at :meth:`~AdmissionController.begin_service`: the
  executor coalesces queued requests into micro-batches (its
  ``max_in_flight`` window bounds device concurrency), so in-service
  REQUEST count legitimately exceeds concurrent program count.

``retry_after_s`` pricing is occupancy-aware: the per-request service
estimate is the completion-measured EWMA *or the age of the oldest
request still in service, whichever is larger*. The EWMA alone is
updated only on completions, so a burst landing after an idle stretch
(or a service-time regression) would price retries from stale history
while the evidence of the true current service time — how long the
in-flight work has already been running — sits unread in the occupancy
(regression-tested with an injected clock).

Everything is host-side and thread-safe; the injected ``clock`` makes
the limiter and the pricing deterministic under test. Timeouts while
QUEUED raise :class:`raft_tpu.errors.RaftTimeoutError` (the caller's
deadline expired — same classification as a slow dispatch), never an
overload.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import time
from typing import Callable, Optional

from raft_tpu import errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.resilience.deadline import Deadline

__all__ = ["AdmissionController", "AdmissionStats"]


@dataclasses.dataclass(frozen=True)
class AdmissionStats:
    """A point-in-time snapshot of an :class:`AdmissionController`'s
    counters (all monotonic except the two depth gauges)."""

    admitted: int
    completed: int
    shed_queue: int
    shed_rate: int
    timed_out: int
    in_flight: int
    queue_depth: int
    peak_queue_depth: int

    @property
    def shed(self) -> int:
        """Total requests rejected at the door (queue + rate)."""
        return self.shed_queue + self.shed_rate

    @property
    def offered(self) -> int:
        """Total requests that reached the controller."""
        return self.admitted + self.shed + self.timed_out

    @property
    def shed_fraction(self) -> float:
        off = self.offered
        return self.shed / off if off else 0.0


class AdmissionController:
    """Bounded-depth admission for serving dispatches (thread-safe).

    ``with ctrl.admit(timeout_s=...):`` brackets one request: it either
    acquires an in-flight slot (waiting in the bounded queue if
    necessary), sheds immediately with
    :class:`~raft_tpu.errors.RaftOverloadError` (queue full or token
    limiter empty), or raises
    :class:`~raft_tpu.errors.RaftTimeoutError` when the caller's wait
    budget expires while queued. The body runs the dispatch; slot
    release and the service-time EWMA (which prices ``retry_after_s``
    for later sheds) happen on exit, success or failure.

    ``retry_after_s``: fallback retry-after for sheds before any
    service time has been measured (None = omit the estimate).
    ``clock``: monotonic-seconds source, injectable for deterministic
    token-limiter tests.
    ``registry`` / ``name``: where the live shed/occupancy series
    (``admission_shed_total{controller=name, reason}``, the
    queue/in-flight/service-EWMA gauges) record — default the
    process-wide :func:`raft_tpu.obs.default_registry`
    (docs/observability.md; ``RAFT_TPU_OBS=off`` no-ops them).
    """

    def __init__(self, *, max_concurrent: int = 1, max_queue: int = 0,
                 rate: Optional[float] = None,
                 burst: Optional[int] = None,
                 retry_after_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: "obs_metrics.MetricRegistry | None" = None,
                 name: str = "admission"):
        errors.expects(
            max_concurrent >= 1,
            "AdmissionController: max_concurrent=%d < 1", max_concurrent,
        )
        errors.expects(
            max_queue >= 0,
            "AdmissionController: max_queue=%d < 0", max_queue,
        )
        errors.expects(
            rate is None or rate > 0,
            "AdmissionController: rate=%s must be > 0 (or None)", rate,
        )
        self.max_concurrent = int(max_concurrent)
        self.max_queue = int(max_queue)
        self.rate = None if rate is None else float(rate)
        self.burst = (
            None if self.rate is None
            else max(1, int(burst if burst is not None else 1))
        )
        self.retry_after_s = retry_after_s
        self._clock = clock
        self._lock = lockcheck.make_lock("AdmissionController._lock")
        self._slot_free = lockcheck.make_condition(self._lock)
        self._in_flight = 0
        self._queue_depth = 0
        self._peak_queue = 0
        self._admitted = 0
        self._completed = 0
        self._shed_queue = 0
        self._shed_rate = 0
        self._timed_out = 0
        self._service_ewma_s: Optional[float] = None
        # requests currently IN SERVICE: ticket -> (start stamp, n).
        # The stamps feed occupancy-aware retry_after pricing (the age
        # of the oldest in-flight work bounds the estimate from below)
        self._inflight_started: dict = {}
        self._next_ticket = 0
        # token bucket state (continuous refill at `rate`/s up to burst)
        self._tokens = float(self.burst or 0)
        self._token_stamp = clock()
        # live shed/occupancy metrics (ISSUE 13, docs/observability.md):
        # the same counters stats() snapshots, but readable by a scrape
        # while the overload is HAPPENING. Handles cached here; every
        # recorder honors the RAFT_TPU_OBS gate.
        reg = (obs_metrics.default_registry() if registry is None
               else registry)
        self.name = name
        self._m_shed = {
            "queue": reg.counter("admission_shed_total",
                                 controller=name, reason="queue"),
            "rate": reg.counter("admission_shed_total",
                                controller=name, reason="rate"),
        }
        self._m_timeout = reg.counter("admission_timeouts_total",
                                      controller=name)
        self._g_queue = reg.gauge("admission_queue_depth",
                                  controller=name)
        self._g_inflight = reg.gauge("admission_in_flight",
                                     controller=name)
        self._g_service = reg.gauge("admission_service_ewma_ms",
                                    controller=name)

    # -- observability -------------------------------------------------------
    def stats(self) -> AdmissionStats:
        with self._lock:
            return AdmissionStats(
                admitted=self._admitted,
                completed=self._completed,
                shed_queue=self._shed_queue,
                shed_rate=self._shed_rate,
                timed_out=self._timed_out,
                in_flight=self._in_flight,
                queue_depth=self._queue_depth,
                peak_queue_depth=self._peak_queue,
            )

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return self._queue_depth

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    def _service_estimate(self) -> Optional[float]:
        """Per-request service-time estimate for pricing: the
        completion-measured EWMA, floored by the AGE of the oldest
        request still in service. The EWMA only moves on completions, so
        after an idle stretch (or a service-time regression) it is stale
        exactly when a burst arrives — but the in-flight occupancy
        already shows the truth: work that has been running for 80 ms is
        evidence the next slot will not free in the 2 ms the old EWMA
        remembers."""
        est = self._service_ewma_s
        if self._inflight_started:
            # PER-REQUEST age: a ticket is a whole micro-batch, and
            # pricing a 64-request batch's 80 ms age as 80 ms/request
            # would overprice retries by the batch size — amortize
            # exactly like finish_service does for the EWMA
            now = self._clock()
            floor = max(
                (now - t0) / max(n, 1)
                for t0, n in self._inflight_started.values()
            )
            est = max(est or 0.0, floor)
        return est

    def _retry_after(self, waiters: int) -> Optional[float]:
        """Price the queue ahead of a shed client: (queued + in-flight)
        service times at the occupancy-floored EWMA
        (:meth:`_service_estimate`); the configured fallback before any
        service evidence exists. Before the FIRST completion the
        fallback also floors the occupancy price — a request that
        started microseconds ago is not evidence service is fast."""
        est = self._service_estimate()
        if est is None:
            return self.retry_after_s
        priced = (waiters + self._in_flight) * est
        if self._service_ewma_s is None and self.retry_after_s is not None:
            priced = max(priced, self.retry_after_s)
        return priced

    def _sync_gauges(self) -> None:
        """Mirror the two depth gauges into the registry (lock held by
        the caller; gauge locks are leaves, no ordering hazard)."""
        self._g_queue.set(self._queue_depth)
        self._g_inflight.set(self._in_flight)

    def _refill_tokens(self, now: float) -> None:
        self._tokens = min(
            float(self.burst),
            self._tokens + (now - self._token_stamp) * self.rate,
        )
        self._token_stamp = now

    # -- the admission gate --------------------------------------------------
    @contextlib.contextmanager
    def admit(self, *, timeout_s: Optional[float] = None,
              deadline: Optional[Deadline] = None):
        """Admit one request (context manager). Sheds with
        :class:`RaftOverloadError` when the queue is at ``max_queue`` or
        the token limiter is empty; raises :class:`RaftTimeoutError` if
        no in-flight slot frees within ``timeout_s`` /
        ``deadline.remaining()`` (the tighter) while queued."""
        if deadline is not None:
            rem = deadline.remaining()
            timeout_s = rem if timeout_s is None else min(timeout_s, rem)
        if timeout_s is not None and not math.isfinite(timeout_s):
            # an unbounded Deadline (remaining() = +inf) means wait
            # forever — Condition.wait(inf) would OverflowError
            timeout_s = None
        with self._lock:
            # queue bound first (stateless check), then the token bucket
            # (which consumes): a queue-shed request must not burn a token
            if (
                self._in_flight >= self.max_concurrent
                and self._queue_depth >= self.max_queue
            ):
                self._shed_queue += 1
                self._m_shed["queue"].inc()
                raise errors.RaftOverloadError(
                    f"admission queue full ({self._queue_depth} waiting, "
                    f"{self._in_flight} in flight; max_queue="
                    f"{self.max_queue})",
                    retry_after_s=self._retry_after(self._queue_depth),
                )
            if self.rate is not None:
                self._refill_tokens(self._clock())
                if self._tokens < 1.0:
                    self._shed_rate += 1
                    self._m_shed["rate"].inc()
                    raise errors.RaftOverloadError(
                        f"rate limit exhausted ({self.rate}/s, burst "
                        f"{self.burst})",
                        retry_after_s=(1.0 - self._tokens) / self.rate,
                    )
                self._tokens -= 1.0
            self._queue_depth += 1
            self._peak_queue = max(self._peak_queue, self._queue_depth)
            self._sync_gauges()
            wait_until = (
                None if timeout_s is None
                else time.monotonic() + timeout_s
            )
            try:
                while self._in_flight >= self.max_concurrent:
                    wait = (
                        None if wait_until is None
                        else wait_until - time.monotonic()
                    )
                    if wait is not None and wait <= 0:
                        self._timed_out += 1
                        self._m_timeout.inc()
                        raise errors.RaftTimeoutError(
                            "admission wait expired after "
                            f"{timeout_s:.3g}s ({self._queue_depth - 1} "
                            "still queued ahead)"
                        )
                    self._slot_free.wait(wait)
            finally:
                self._queue_depth -= 1
                # re-sync HERE, not only in _begin_locked: the timeout
                # path leaves through the exception, and a stale depth
                # gauge during sustained overload is exactly when the
                # gauge matters (review-caught r13)
                self._sync_gauges()
            ticket = self._begin_locked(1)
        try:
            yield self
        finally:
            self.finish_service(ticket)

    # -- the asynchronous (executor) path ------------------------------------
    def _begin_locked(self, n: int) -> int:
        """Move ``n`` requests into service (lock held): counters, and
        the in-flight start stamp that feeds occupancy pricing."""
        self._in_flight += n
        self._admitted += n
        ticket = self._next_ticket
        self._next_ticket += 1
        self._inflight_started[ticket] = (self._clock(), n)
        self._sync_gauges()
        return ticket

    def enqueue(self, n: int = 1) -> None:
        """NON-BLOCKING admission into the bounded queue — the open-loop
        arrival path (:class:`raft_tpu.serving.ServingExecutor` calls
        this from ``submit``). Sheds immediately with
        :class:`~raft_tpu.errors.RaftOverloadError` (occupancy-priced
        ``retry_after_s``) when total outstanding work (queued + in
        service) is at ``max_queue + max_concurrent`` or the token
        limiter is empty; otherwise the request is QUEUED and the
        caller returns to the arrival stream without waiting for a
        slot. Dispatch/completion are reported later via
        :meth:`begin_service` / :meth:`finish_service`;
        :meth:`cancel_queued` gives up a queued spot (shutdown, caller
        timeout)."""
        errors.expects(n >= 1, "enqueue: n=%d < 1", n)
        with self._lock:
            # the async bound is TOTAL OUTSTANDING (queued + in service)
            # vs max_queue + max_concurrent — the blocking path's total
            # capacity. A pure queue-depth check would shed a default
            # (1, 0) controller's every request on an IDLE server: the
            # async queue holds requests a free slot would have absorbed
            # immediately in the blocking world.
            cap = self.max_queue + self.max_concurrent
            if self._queue_depth + self._in_flight + n > cap:
                self._shed_queue += n
                self._m_shed["queue"].inc(n)
                raise errors.RaftOverloadError(
                    f"admission capacity full ({self._queue_depth} "
                    f"waiting + {self._in_flight} in flight >= "
                    f"max_queue={self.max_queue} + max_concurrent="
                    f"{self.max_concurrent})",
                    retry_after_s=self._retry_after(self._queue_depth),
                )
            if self.rate is not None:
                self._refill_tokens(self._clock())
                if self._tokens < float(n):
                    self._shed_rate += n
                    self._m_shed["rate"].inc(n)
                    raise errors.RaftOverloadError(
                        f"rate limit exhausted ({self.rate}/s, burst "
                        f"{self.burst})",
                        retry_after_s=(float(n) - self._tokens) / self.rate,
                    )
                self._tokens -= float(n)
            self._queue_depth += n
            self._peak_queue = max(self._peak_queue, self._queue_depth)
            self._sync_gauges()

    def begin_service(self, n: int = 1) -> int:
        """Report ``n`` queued requests dispatched (queue → in service).
        Returns the service ticket to pass to :meth:`finish_service`.
        The executor calls this when a micro-batch leaves the batcher;
        from this stamp on, the batch's age floors the retry-after
        pricing (:meth:`_service_estimate`)."""
        errors.expects(n >= 1, "begin_service: n=%d < 1", n)
        with self._lock:
            errors.expects(
                self._queue_depth >= n,
                "begin_service: %d requested but only %d queued",
                n, self._queue_depth,
            )
            self._queue_depth -= n
            return self._begin_locked(n)

    def finish_service(self, ticket: int) -> None:
        """Report a service ticket complete: counters, slot release, and
        the per-request service-time EWMA (batch held-time amortized
        over its ``n`` requests) that prices later sheds."""
        with self._lock:
            t0, n = self._inflight_started.pop(ticket)
            held = max(0.0, self._clock() - t0) / max(n, 1)
            self._in_flight -= n
            self._completed += n
            self._service_ewma_s = (
                held if self._service_ewma_s is None
                else 0.8 * self._service_ewma_s + 0.2 * held
            )
            self._g_service.set(self._service_ewma_s * 1e3)
            self._sync_gauges()
            self._slot_free.notify(n)

    def abort_service(self, ticket: int) -> None:
        """Release a service ticket whose dispatch FAILED: the slot
        frees (waiters wake), but neither the service-time EWMA nor
        ``completed`` moves — a crashed dispatch is not service-time
        evidence (a near-zero ``held`` would drag the EWMA toward 0 and
        underprice every later shed), and its requests were answered
        with an exception, not served."""
        with self._lock:
            _t0, n = self._inflight_started.pop(ticket)
            self._in_flight -= n
            self._sync_gauges()
            self._slot_free.notify(n)

    def cancel_queued(self, n: int = 1) -> None:
        """Give back ``n`` queued spots without serving them (executor
        shutdown, a caller abandoning its queued request)."""
        with self._lock:
            self._queue_depth -= min(n, self._queue_depth)
            self._sync_gauges()

    def __repr__(self) -> str:
        s = self.stats()
        return (
            f"AdmissionController(max_concurrent={self.max_concurrent}, "
            f"max_queue={self.max_queue}, in_flight={s.in_flight}, "
            f"queued={s.queue_depth}, admitted={s.admitted}, "
            f"shed={s.shed})"
        )
