"""Shard health tracking + communicator health checks.

The reference polls NCCL's async error state inside ``sync_stream``
(std_comms.hpp) and ships collective round-trip self-tests
(``test_collective_*``); on TPU the XLA runtime surfaces fabric errors
through the computation itself, so the serving layer tracks health
HOST-SIDE: :class:`ShardHealth` is the per-rank validity mask the
degraded sharded searches consume (``mnmg_ivf_pq_search`` /
``mnmg_ivf_flat_search`` ``shard_mask=``), and :func:`health_check`
wraps the communicator self-test suite
(:func:`raft_tpu.comms.self_test.run_all_self_tests`) with
per-collective timings — the liveness probe a serving loop runs between
batches (docs/robustness.md).

Rank-level downs come from EXTERNAL signals (a dead worker process, a
missed heartbeat, an operator action) via ``mark_down``; the self-test
probe validates the surviving fabric as a whole. The mask feeds the
compiled search program as a RUNTIME argument, so flipping a rank's
health never recompiles the serving program.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional, Tuple

import numpy as np

from raft_tpu import errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.obs import metrics as obs_metrics

__all__ = [
    "ShardHealth",
    "HealthMonitor",
    "HealthProbe",
    "HealthReport",
    "health_check",
]

# health-transition telemetry (ISSUE 13, docs/observability.md): every
# ACTUAL up/down flip counts (idempotent re-marks do not), and the
# up-rank gauge tracks the most recently flipped tracker — the
# failover-flip signal an alert watches next to
# ``failover_rerouted_shards`` (resilience/replica.py)
_reg = obs_metrics.default_registry()
_M_FLIPS = {
    "down": _reg.counter("health_transitions_total", direction="down"),
    "up": _reg.counter("health_transitions_total", direction="up"),
}
_G_RANKS_UP = _reg.gauge("health_ranks_up")
del _reg


class ShardHealth:
    """Host-side per-rank up/down tracker (thread-safe).

    ``mask()`` snapshots the per-rank validity as an int32 ``(P,)``
    array — 1 = up, 0 = down — in exactly the form the degraded sharded
    searches take as their ``shard_mask`` runtime input.
    """

    def __init__(self, n_ranks: int, *, telemetry: bool = True):
        errors.expects(n_ranks >= 1, "ShardHealth: n_ranks=%d < 1", n_ranks)
        self._lock = lockcheck.make_lock("ShardHealth._lock")
        self._up = np.ones(n_ranks, dtype=bool)
        # `telemetry=False` is for THROWAWAY trackers (the
        # resolve_shard_mask HealthReport normalization builds one per
        # search call): only a long-lived tracker may drive the global
        # flip counters/gauge, or steady degraded traffic would count
        # one fake "flip" per call and whipsaw the gauge
        # (review-caught r13)
        self._telemetry = bool(telemetry)
        if self._telemetry:
            # seed the gauge at construction: a fresh tracker is
            # all-up, and a scrape before the first flip must not read
            # the gauge's 0.0 initial value as a total outage
            _G_RANKS_UP.set(n_ranks)

    @property
    def n_ranks(self) -> int:
        # .shape is immutable metadata of an array that is only ever
        # mutated in place, never rebound — safe to read unlocked
        return self._up.shape[0]  # jaxlint: disable=unguarded-shared-state

    def _check_rank(self, rank: int) -> None:
        errors.expects(   # .shape reads: immutable metadata, see n_ranks
            0 <= rank < self._up.shape[0],  # jaxlint: disable=unguarded-shared-state
            "ShardHealth: rank %d out of range [0, %d)",
            rank, self._up.shape[0],  # jaxlint: disable=unguarded-shared-state
        )

    def mark_down(self, rank: int) -> None:
        """Record an external down signal for ``rank`` (idempotent)."""
        self._check_rank(rank)
        with self._lock:
            flipped = bool(self._up[rank])
            self._up[rank] = False
            if flipped and self._telemetry:
                # gauge write INSIDE the lock: two concurrent flips
                # must apply their counts in flip order, or the gauge
                # holds the stale value until the next transition
                # (gauge locks are leaves — no ordering hazard)
                _M_FLIPS["down"].inc()
                _G_RANKS_UP.set(int(self._up.sum()))

    def mark_up(self, rank: int) -> None:
        """Record recovery of ``rank`` (idempotent)."""
        self._check_rank(rank)
        with self._lock:
            flipped = not bool(self._up[rank])
            self._up[rank] = True
            if flipped and self._telemetry:
                _M_FLIPS["up"].inc()
                _G_RANKS_UP.set(int(self._up.sum()))

    def is_up(self, rank: int) -> bool:
        self._check_rank(rank)
        with self._lock:
            return bool(self._up[rank])

    @property
    def n_up(self) -> int:
        with self._lock:
            return int(self._up.sum())

    @property
    def all_up(self) -> bool:
        with self._lock:
            return bool(self._up.all())

    def apply_report(self, report: "HealthReport") -> "ShardHealth":
        """Fold a :class:`HealthReport` into the tracker: every rank
        implicated by a FAILED probe is marked down — a failed probe
        carrying rank attribution (``HealthProbe.ranks``, e.g. a
        per-rank heartbeat sweep) downs exactly those ranks; one with
        no attribution downs EVERY rank, because a collective that
        cannot round-trip means no mesh program can run at all. Passing
        probes mark nothing up (recovery of an externally-downed rank
        is the external system's call — flip it back with ``mark_up``
        after :func:`raft_tpu.comms.mnmg_ivf.recover_rank`). Returns
        ``self``, so the health-check → mask pipeline is one
        expression: ``health.apply_report(report).mask()``."""
        for probe in report.probes.values():
            if probe.ok:
                continue
            ranks = probe.ranks or tuple(range(self.n_ranks))
            for r in ranks:
                self.mark_down(r)
        return self

    def mask(self) -> np.ndarray:
        """Snapshot the validity mask as int32 ``(P,)`` (1 = up)."""
        with self._lock:
            return self._up.astype(np.int32)

    def __repr__(self) -> str:  # compact operator-facing summary
        with self._lock:
            down = np.nonzero(~self._up)[0].tolist()
        return (
            f"ShardHealth(n_ranks={self.n_ranks}, "
            f"down={down if down else 'none'})"
        )


class HealthMonitor:
    """Flap suppression for raw per-rank health observations
    (thread-safe): the ONE debounce spelling shared by the
    :class:`~raft_tpu.resilience.supervisor.ServingSupervisor` and
    manual health loops, with the same discipline as the SLO profile
    trigger (``obs/capture.py``): ``consecutive`` contradicting
    observations confirm a transition, and ``cooldown_s`` of hysteresis
    after each confirmed flip bounds how often a rank may change state
    no matter how hard the probe oscillates.

    ``observe(rank, up)`` folds one raw observation and returns
    ``"down"`` / ``"up"`` exactly when it CONFIRMS a transition (else
    ``None``) — the caller acts only on that edge, so an oscillating
    probe produces at most one action per cooldown window. The clock is
    injectable for deterministic tests. Confirmed flips count in
    ``health_transitions_total{rank,direction}`` (the rank-attributed
    companion of the :class:`ShardHealth` direction-only series).
    """

    def __init__(self, n_ranks: int, *, consecutive: int = 3,
                 cooldown_s: float = 1.0, clock=time.monotonic,
                 telemetry: bool = True):
        errors.expects(n_ranks >= 1,
                       "HealthMonitor: n_ranks=%d < 1", n_ranks)
        errors.expects(consecutive >= 1,
                       "HealthMonitor: consecutive=%d < 1", consecutive)
        self.consecutive = int(consecutive)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._telemetry = bool(telemetry)
        self._lock = lockcheck.make_lock("HealthMonitor._lock")
        self._confirmed = np.ones(n_ranks, dtype=bool)
        self._streak = np.zeros(n_ranks, dtype=np.int64)
        # last confirmed flip per rank; -inf so the first transition is
        # never cooldown-suppressed
        self._last_flip = np.full(n_ranks, -np.inf, dtype=np.float64)
        self._transitions = 0
        self._counters: Dict[Tuple[int, str], object] = {}

    @property
    def n_ranks(self) -> int:
        # immutable array metadata, see ShardHealth.n_ranks
        return self._confirmed.shape[0]  # jaxlint: disable=unguarded-shared-state

    def _check_rank(self, rank: int) -> None:
        errors.expects(   # .shape reads: immutable metadata
            0 <= rank < self.n_ranks,
            "HealthMonitor: rank %d out of range [0, %d)",
            rank, self.n_ranks,
        )

    def _count_flip(self, rank: int, direction: str) -> None:
        key = (rank, direction)
        c = self._counters.get(key)
        if c is None:
            reg = obs_metrics.default_registry()
            c = reg.counter("health_transitions_total",
                            rank=rank, direction=direction)
            self._counters[key] = c
        c.inc()

    def observe(self, rank: int, up: bool) -> Optional[str]:
        """Fold one raw observation; return ``"down"``/``"up"`` iff it
        confirms a transition, else ``None``.

        A transition confirms when ``consecutive`` back-to-back
        observations contradict the confirmed state AND ``cooldown_s``
        has elapsed since that rank's last confirmed flip. A
        cooldown-suppressed streak is KEPT (not reset), so a contradiction
        that persists through the window flips on the first observation
        after it expires."""
        self._check_rank(rank)
        up = bool(up)
        with self._lock:
            if up == bool(self._confirmed[rank]):
                self._streak[rank] = 0
                return None
            self._streak[rank] += 1
            if self._streak[rank] < self.consecutive:
                return None
            now = float(self._clock())
            if now - float(self._last_flip[rank]) < self.cooldown_s:
                return None  # hysteresis: streak kept, flip deferred
            self._confirmed[rank] = up
            self._streak[rank] = 0
            self._last_flip[rank] = now
            self._transitions += 1
            direction = "up" if up else "down"
            if self._telemetry:
                # counter write inside the lock, same rationale as
                # ShardHealth.mark_down (flip-ordered counts)
                self._count_flip(rank, direction)
        return direction

    def observe_report(self, report: "HealthReport") -> Dict[int, str]:
        """Fold a :class:`HealthReport` sweep as DOWN observations for
        every implicated rank, mirroring ``ShardHealth.apply_report``
        (failed attributed probes down their ranks; an unattributed
        failure implicates every rank; passing probes observe nothing —
        up-observations need a positive per-rank signal via
        :meth:`observe`). Returns ``{rank: direction}`` for the
        transitions this sweep confirmed."""
        implicated: set = set()
        for probe in report.probes.values():
            if probe.ok:
                continue
            implicated.update(probe.ranks or range(self.n_ranks))
        out: Dict[int, str] = {}
        for r in sorted(implicated):
            d = self.observe(r, False)
            if d is not None:
                out[r] = d
        return out

    def is_up(self, rank: int) -> bool:
        """The CONFIRMED (debounced) state of ``rank``."""
        self._check_rank(rank)
        with self._lock:
            return bool(self._confirmed[rank])

    def force(self, rank: int, up: bool) -> None:
        """Pin the confirmed state WITHOUT counting a transition — the
        supervisor's rollback hook: after a failed heal it forces the
        rank back to confirmed-down so only a fresh sustained up-streak
        (post-cooldown) re-triggers reintegration."""
        self._check_rank(rank)
        with self._lock:
            self._confirmed[rank] = bool(up)
            self._streak[rank] = 0
            self._last_flip[rank] = float(self._clock())

    @property
    def transition_count(self) -> int:
        """Total confirmed transitions — the flap-invariant bound
        (route pushes per supervisor must never exceed it)."""
        with self._lock:
            return int(self._transitions)

    def __repr__(self) -> str:
        with self._lock:
            down = np.nonzero(~self._confirmed)[0].tolist()
        return (
            f"HealthMonitor(n_ranks={self.n_ranks}, "
            f"consecutive={self.consecutive}, "
            f"cooldown_s={self.cooldown_s}, "
            f"down={down if down else 'none'})"
        )


@dataclasses.dataclass(frozen=True)
class HealthProbe:
    """One probe's result: pass/fail + wall time.

    ``ranks`` optionally attributes a FAILURE to specific ranks (a
    per-rank heartbeat/liveness probe); empty means the probe speaks
    for the whole mesh — :meth:`ShardHealth.apply_report` downs every
    rank on an unattributed failure. The collective self-test sweep
    (:func:`health_check`) emits unattributed probes."""

    ok: bool
    seconds: float
    ranks: Tuple[int, ...] = ()


@dataclasses.dataclass(frozen=True)
class HealthReport:
    """The full self-test sweep with per-collective timings.

    ``probes`` maps collective name → :class:`HealthProbe`; ``ok`` is
    the conjunction. Timings include trace+compile on a cold program —
    run one warm-up sweep at bring-up if you alert on latency.
    """

    probes: Dict[str, HealthProbe]

    @property
    def ok(self) -> bool:
        return all(p.ok for p in self.probes.values())

    @property
    def failed(self) -> list:
        return sorted(n for n, p in self.probes.items() if not p.ok)

    @property
    def total_seconds(self) -> float:
        return sum(p.seconds for p in self.probes.values())


def health_check(comms, *, health: Optional[ShardHealth] = None,
                 raise_on_failure: bool = False) -> HealthReport:
    """Run the communicator round-trip self-tests with per-collective
    timings — the serving loop's fabric liveness probe.

    Wraps :data:`raft_tpu.comms.self_test.SELF_TESTS` (the registry
    behind ``run_all_self_tests``), timing each collective's round trip
    individually. A probe that RAISES (an XLA runtime error from a torn
    mesh) is recorded as failed, not propagated — the report is the
    failure signal.

    ``health``: when a sweep fails, every rank is marked down on the
    tracker — a collective that cannot round-trip means the mesh program
    cannot run at all, so no shard is servable until the mesh is rebuilt
    (rank-granular downs come from external signals via ``mark_down``).
    A PASSING sweep does NOT mark anything up: recovery of an
    externally-downed rank is the external system's call.

    ``raise_on_failure=True`` raises :class:`raft_tpu.errors.RaftException`
    listing the failed collectives instead of returning the report.
    """
    from raft_tpu.comms.self_test import SELF_TESTS

    probes: Dict[str, HealthProbe] = {}
    for name, fn in SELF_TESTS.items():
        t0 = time.perf_counter()
        try:
            ok = bool(fn(comms))
        except Exception:  # torn mesh: the failure IS the signal
            ok = False
        probes[name] = HealthProbe(ok=ok, seconds=time.perf_counter() - t0)
    report = HealthReport(probes=probes)
    if health is not None:
        # unattributed collective failures down every rank (see
        # ShardHealth.apply_report); a passing sweep marks nothing up
        health.apply_report(report)
    if raise_on_failure and not report.ok:
        raise errors.RaftException(
            f"health_check: collectives failed round-trip: {report.failed}"
        )
    return report
