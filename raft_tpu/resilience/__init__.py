"""Resilience layer for the serving path — the explicit failure model.

The reference builds robustness into its primitives
(``raft::interruptible`` cancellable stream waits, NCCL async-error
polling in ``sync_stream``, communicator round-trip self-tests); at the
ROADMAP's serving scale (heavy traffic, millions of users) preemption,
slow chips, dead shards, corrupt checkpoints, and poisoned inputs are
ROUTINE, so every failure mode needs a bounded, classified, testable
answer (docs/robustness.md):

* deadlines + retries: :class:`Deadline`, :class:`RetryPolicy`,
  :func:`dispatch_with_deadline` — bounded waits over
  ``Interruptible.synchronize(timeout_s=)``; retries re-dispatch the
  already-compiled program;
* shard health: :class:`ShardHealth` (the per-rank validity mask the
  degraded sharded searches consume), :func:`health_check` (the
  communicator self-test sweep with per-collective timings);
* degraded results: :class:`PartialSearchResult` — the
  ``coverage``/``partial`` contract returned by the sharded searches
  under ``shard_mask=``;
* fault injection lives in :mod:`raft_tpu.testing.faults` so the chaos
  suite (tests/test_resilience.py) proves each behavior on CPU in CI.
"""

from raft_tpu.resilience.deadline import (
    Deadline,
    RetryPolicy,
    dispatch_with_deadline,
)
from raft_tpu.resilience.degraded import (
    PartialSearchResult,
    resolve_shard_mask,
)
from raft_tpu.resilience.health import (
    HealthProbe,
    HealthReport,
    ShardHealth,
    health_check,
)

__all__ = [
    "Deadline",
    "RetryPolicy",
    "dispatch_with_deadline",
    "PartialSearchResult",
    "resolve_shard_mask",
    "ShardHealth",
    "HealthProbe",
    "HealthReport",
    "health_check",
]
