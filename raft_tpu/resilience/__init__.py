"""Resilience layer for the serving path — the explicit failure model.

The reference builds robustness into its primitives
(``raft::interruptible`` cancellable stream waits, NCCL async-error
polling in ``sync_stream``, communicator round-trip self-tests); at the
ROADMAP's serving scale (heavy traffic, millions of users) preemption,
slow chips, dead shards, corrupt checkpoints, poisoned inputs, and
overload are ROUTINE, so every failure mode needs a bounded,
classified, testable answer (docs/robustness.md):

* deadlines + retries: :class:`Deadline`, :class:`RetryPolicy`,
  :func:`dispatch_with_deadline` — bounded waits over
  ``Interruptible.synchronize(timeout_s=)``; retries re-dispatch the
  already-compiled program;
* tail-latency hedging: :class:`HedgePolicy`, :func:`dispatch_hedged`
  — a backup dispatch after a percentile-derived delay, first ready
  answer wins, loser abandoned (cooperative);
* shard health: :class:`ShardHealth` (the per-rank validity mask the
  degraded sharded searches consume; ``apply_report`` folds a
  :func:`health_check` sweep straight into it), :func:`health_check`
  (the communicator self-test sweep with per-collective timings);
* replication + failover: :class:`ReplicaPlacement`,
  :class:`FailoverPlan` — R-way striped shard replicas
  (``place_index(..., replication=R)``) and the runtime route that
  serves a dead rank's lists from a live replica with ZERO coverage
  loss;
* degraded results: :class:`PartialSearchResult` — the
  ``coverage``/``partial`` contract returned by the sharded searches
  under ``shard_mask=``;
* admission control: :class:`AdmissionController` — bounded queue +
  concurrency + token limiter, shedding with
  :class:`raft_tpu.errors.RaftOverloadError` instead of collapsing;
* self-healing: :class:`ServingSupervisor` + :class:`HealActions` +
  :class:`HealthMonitor` — the background control loop that debounces
  raw health observations (N-consecutive + cooldown), pushes
  load-balanced failover routes into every registered executor on a
  confirmed down (zero-retrace), and drives the resumable
  QUARANTINED→RESYNCING→WARMING→SERVING reintegration pipeline on a
  confirmed heal (docs/robustness.md "Self-healing");
* fault injection lives in :mod:`raft_tpu.testing.faults`, and the
  scripted chaos-schedule harness (:mod:`raft_tpu.testing.chaos`)
  proves the composed loop under timed fault scripts with declarative
  invariant checkers, so the chaos suites (tests/test_resilience.py,
  tests/test_chaos.py) prove each behavior on CPU in CI.
"""

from raft_tpu.resilience.admission import (
    AdmissionController,
    AdmissionStats,
)
from raft_tpu.resilience.deadline import (
    Deadline,
    HedgePolicy,
    RetryPolicy,
    dispatch_hedged,
    dispatch_with_deadline,
)
from raft_tpu.resilience.degraded import (
    PartialSearchResult,
    resolve_shard_mask,
)
from raft_tpu.resilience.health import (
    HealthMonitor,
    HealthProbe,
    HealthReport,
    ShardHealth,
    health_check,
)
from raft_tpu.resilience.replica import (
    FailoverPlan,
    ReplicaPlacement,
    measured_list_load,
    measured_shard_load,
    popularity_replication,
    record_list_load,
    record_shard_load,
    resolve_route,
)
from raft_tpu.resilience.supervisor import (
    STATE_QUARANTINED,
    STATE_RECOVERING,
    STATE_RESYNCING,
    STATE_SERVING,
    STATE_WARMING,
    HealActions,
    ServingSupervisor,
    SupervisorStats,
)

__all__ = [
    "AdmissionController",
    "AdmissionStats",
    "Deadline",
    "HedgePolicy",
    "RetryPolicy",
    "dispatch_hedged",
    "dispatch_with_deadline",
    "PartialSearchResult",
    "resolve_shard_mask",
    "ShardHealth",
    "HealthMonitor",
    "HealthProbe",
    "HealthReport",
    "health_check",
    "ServingSupervisor",
    "SupervisorStats",
    "HealActions",
    "STATE_SERVING",
    "STATE_QUARANTINED",
    "STATE_RECOVERING",
    "STATE_RESYNCING",
    "STATE_WARMING",
    "FailoverPlan",
    "ReplicaPlacement",
    "resolve_route",
    "record_shard_load",
    "measured_shard_load",
    "record_list_load",
    "measured_list_load",
    "popularity_replication",
]
