"""Self-healing serving supervisor: detect → reroute → resync →
reintegrate, with no operator in the loop.

Seventeen PRs built every recovery primitive as a hand-callable —
``health_check`` probes the fabric, :class:`ShardHealth` masks a dead
rank out of the compiled programs, ``FailoverPlan.load_balanced``
reroutes its shards to live replicas, ``recover_rank`` splices its main
slabs back from a checkpoint, ``resync_rank`` catches its mutation
state up from a donor replica, ``TieredListStore.sync_mutations``
re-syncs the cold tier — and the chaos tests choreographed them BY
HAND. :class:`ServingSupervisor` is the background control loop that
runs the choreography itself (ROADMAP item 2's robustness half; the
reference lineage has no analog — ``raft::comms`` exposes health state
but nothing watches it):

- **Detect.** Each tick runs the injected ``probe`` (a
  :func:`~raft_tpu.resilience.health.health_check` sweep, a heartbeat
  table, or a scripted truth in tests) and folds the raw per-rank
  observations through a :class:`~raft_tpu.resilience.health.HealthMonitor`
  — N-consecutive confirm + cooldown hysteresis, the same debounce
  discipline as the SLO profile trigger — so a flapping probe cannot
  whipsaw the route.
- **Reroute.** A confirmed DOWN marks the rank on the shared
  :class:`ShardHealth`, recomputes a load-balanced
  :class:`~raft_tpu.resilience.replica.FailoverPlan`, and atomically
  pushes ``shard_mask`` + ``failover`` into every registered
  :class:`~raft_tpu.serving.executor.ServingExecutor` via
  ``set_runtime``. Both are RUNTIME operands of the warmed programs
  (pinned by the program contracts), so a push never recompiles —
  zero-retrace is audited in the chaos suite by compiled-cache size.
- **Reintegrate.** A confirmed UP drives the heal pipeline as a
  RESUMABLE per-rank state machine — QUARANTINED → RECOVERING (WAL
  tail replay, docs/robustness.md "Durability") → RESYNCING (recover +
  resync) → WARMING (tier sync + program warm) → SERVING — each step
  under its own deadline with :class:`~raft_tpu.resilience.deadline.RetryPolicy`
  backoff; a step that exhausts its budget rolls the rank back to
  QUARANTINED (optional ``rollback`` hook first), keeps the
  routed-around plan serving, and re-arms the monitor so only a fresh
  confirmed up-streak retries. Completed steps are remembered, so a
  supervisor restart (or a crash surfaced through
  ``thread_uncaught_total``) resumes mid-pipeline instead of replaying
  side-effectful steps.

Every transition emits metrics (``supervisor_state{rank}``,
``supervisor_route_pushes_total``, ``supervisor_heals_total{outcome}``)
and flight events. What the supervisor will NOT do: change topology
(grow/shrink the mesh is the elastic checkpoint path), rebuild indexes,
or tune serving knobs (that is ROADMAP item 2's autopilot) — it only
actuates routes and the heal pipeline over a FIXED placement
(docs/robustness.md "Self-healing").
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from raft_tpu import errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.obs import crash as obs_crash
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.resilience.deadline import Deadline, RetryPolicy
from raft_tpu.resilience.health import (
    HealthMonitor,
    HealthReport,
    ShardHealth,
)
from raft_tpu.resilience.replica import FailoverPlan, ReplicaPlacement

__all__ = [
    "HealActions",
    "ServingSupervisor",
    "SupervisorStats",
    "STATE_SERVING",
    "STATE_QUARANTINED",
    "STATE_RECOVERING",
    "STATE_RESYNCING",
    "STATE_WARMING",
]

# the per-rank reintegration state machine: QUARANTINED is the routed-
# around steady state of a down rank; RECOVERING covers durable-state
# replay (WAL tail past the checkpoint watermark — docs/robustness.md
# "Durability"); RESYNCING covers the data-plane splice (checkpoint
# recover + mutation-delta resync); WARMING covers bring-back
# validation (tier journal sync + program warm); SERVING is healthy.
# Encoded in the supervisor_state gauge as 0/1/2/3/4.
STATE_SERVING = "serving"
STATE_QUARANTINED = "quarantined"
STATE_RESYNCING = "resyncing"
STATE_WARMING = "warming"
STATE_RECOVERING = "recovering"
_STATE_CODE = {
    STATE_SERVING: 0,
    STATE_QUARANTINED: 1,
    STATE_RESYNCING: 2,
    STATE_WARMING: 3,
    STATE_RECOVERING: 4,
}

# the ordered heal pipeline; each step maps to the state the rank shows
# while it runs. Steps with no configured action are skipped (and still
# recorded as done, so resume semantics stay simple). WAL replay runs
# FIRST: the rank's durable mutation state must be current before the
# peer resync diffs against it (and before tier sync reads its epochs).
_HEAL_STEPS: Tuple[Tuple[str, str], ...] = (
    ("replay_wal", STATE_RECOVERING),
    ("recover", STATE_RESYNCING),
    ("resync", STATE_RESYNCING),
    ("sync_tier", STATE_WARMING),
    ("warm", STATE_WARMING),
)


@dataclasses.dataclass
class HealActions:
    """The reintegration actuators, injected so the supervisor stays
    decoupled from index specifics. Each is ``fn(rank) -> None`` (or
    ``None`` to skip the step): ``replay_wal`` replays the rank's
    durable WAL tail past the checkpoint watermark
    (:func:`~raft_tpu.durability.wal.recover_mutable` /
    :func:`~raft_tpu.comms.mnmg_mutation.mnmg_recover` — runs FIRST,
    under the RECOVERING state), ``recover`` splices the rank's main
    slabs back (:func:`~raft_tpu.comms.mnmg_ivf.recover_rank` from the
    latest checkpoint), ``resync`` catches its mutation state up from a
    donor replica (:func:`~raft_tpu.comms.mnmg_mutation.resync_rank`),
    ``sync_tier`` replays the tier journal
    (``TieredListStore.sync_mutations``), ``warm`` runs any bring-back
    validation (a healthy-mask probe search). ``rollback`` runs once
    when a step exhausts its retry/deadline budget, BEFORE the rank
    drops back to QUARANTINED — undo partial effects there (e.g. restore
    the pre-splice index cell)."""

    recover: Optional[Callable[[int], None]] = None
    resync: Optional[Callable[[int], None]] = None
    sync_tier: Optional[Callable[[int], None]] = None
    warm: Optional[Callable[[int], None]] = None
    rollback: Optional[Callable[[int], None]] = None
    replay_wal: Optional[Callable[[int], None]] = None


@dataclasses.dataclass(frozen=True)
class SupervisorStats:
    """Snapshot of the control loop's lifetime counts + per-rank
    states (strings from the STATE_* constants)."""

    ticks: int
    route_pushes: int
    heals_ok: int
    heals_rolled_back: int
    states: Dict[int, str]
    last_push_t: Optional[float]


class ServingSupervisor:
    """Background detect→reroute→resync→reintegrate control loop.

    ``probe`` is called once per tick and returns either a
    ``{rank: up}`` mapping (a heartbeat sweep; in tests a scripted
    truth) or a :class:`HealthReport` (down-attribution only — see
    :meth:`HealthMonitor.observe_report`). Confirmed transitions
    actuate the shared ``health`` tracker, push a fresh load-balanced
    route into every registered executor, and (on up) drive the heal
    pipeline. ``step()`` runs ONE tick synchronously — deterministic
    tests drive it directly with an injectable clock; ``start()`` runs
    it on a daemon thread routed through the crash excepthook, so an
    uncaught supervisor bug surfaces in ``thread_uncaught_total`` and
    ``start()`` can simply be called again (state, including mid-heal
    progress, lives on the object, not the thread).

    Lock discipline: ``ServingSupervisor._lock`` guards only the
    supervisor's own bookkeeping (states, heal progress, counters).
    Probes, health/monitor updates, route pushes, and heal actions all
    run OUTSIDE it — they take their own locks (``ShardHealth._lock``,
    ``ServingExecutor._lock``, ...), keeping the lock-order graph a
    tree rooted here.
    """

    def __init__(self, health: ShardHealth, placement: ReplicaPlacement,
                 probe: Callable[[], Any], *,
                 heal: Optional[HealActions] = None,
                 monitor: Optional[HealthMonitor] = None,
                 interval_s: float = 0.25,
                 step_deadline_s: float = 30.0,
                 retry: Optional[RetryPolicy] = None,
                 load: Optional[Callable[[], Any]] = None,
                 registry=None, flight=None, name: str = "supervisor",
                 clock=time.monotonic, sleep=time.sleep):
        errors.expects(interval_s > 0.0,
                       "ServingSupervisor: interval_s=%s <= 0", interval_s)
        self.health = health
        self.placement = placement
        self.heal = heal or HealActions()
        self.monitor = monitor or HealthMonitor(
            health.n_ranks, clock=clock
        )
        errors.expects(
            self.monitor.n_ranks == health.n_ranks,
            "ServingSupervisor: monitor ranks %d != health ranks %d",
            self.monitor.n_ranks, health.n_ranks,
        )
        self.interval_s = float(interval_s)
        self.step_deadline_s = float(step_deadline_s)
        self.retry = retry or RetryPolicy()
        self.name = name
        self._probe = probe
        self._load = load
        self._registry = registry or obs_metrics.default_registry()
        self._flight = flight
        self._clock = clock
        self._sleep = sleep

        self._lock = lockcheck.make_lock("ServingSupervisor._lock")
        self._executors: List[Any] = []
        self._state: Dict[int, str] = {
            r: STATE_SERVING for r in range(health.n_ranks)
        }
        # index into _HEAL_STEPS of the next step to run per healing
        # rank — the resume cursor; absent = not healing
        self._heal_cursor: Dict[int, int] = {}
        self._timeline: List[Tuple[float, str, int]] = []
        self._ticks = 0
        self._route_pushes = 0
        self._heals_ok = 0
        self._heals_rolled_back = 0
        self._last_push_t: Optional[float] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None

        reg = self._registry
        self._c_pushes = reg.counter("supervisor_route_pushes_total")
        self._c_heals = {
            "ok": reg.counter("supervisor_heals_total", outcome="ok"),
            "rolled_back": reg.counter("supervisor_heals_total",
                                       outcome="rolled_back"),
        }
        self._g_state = {
            r: reg.gauge("supervisor_state", rank=r)
            for r in range(health.n_ranks)
        }
        # a crash in the loop thread must surface, not vanish
        obs_crash.install_excepthook()

    # ------------------------------------------------------------------
    # registration + introspection

    def register(self, executor) -> None:
        """Add an executor to the route-push fanout. Its runtime is
        synced to the CURRENT plan immediately, so an executor that
        joins after a failover serves the degraded route at once."""
        with self._lock:
            if executor not in self._executors:
                self._executors.append(executor)
        self._push_route(reason="register")

    def unregister(self, executor) -> None:
        with self._lock:
            if executor in self._executors:
                self._executors.remove(executor)

    def state(self, rank: int) -> str:
        with self._lock:
            return self._state[rank]

    def stats(self) -> SupervisorStats:
        with self._lock:
            return SupervisorStats(
                ticks=self._ticks,
                route_pushes=self._route_pushes,
                heals_ok=self._heals_ok,
                heals_rolled_back=self._heals_rolled_back,
                states=dict(self._state),
                last_push_t=self._last_push_t,
            )

    def timeline(self) -> List[Tuple[float, str, int]]:
        """Chronological ``(t, event, rank)`` records (supervisor clock;
        rank -1 for rank-less events) — what the self-heal bench row
        reads detection/convergence/reintegration stamps from."""
        with self._lock:
            return list(self._timeline)

    def _mark(self, event: str, rank: int = -1) -> None:
        t = float(self._clock())
        with self._lock:
            self._timeline.append((t, event, rank))
        if self._flight is not None:
            self._flight.record(f"supervisor_{event}", rank=rank)

    # ------------------------------------------------------------------
    # the control loop

    def step(self) -> Dict[int, str]:
        """Run ONE tick synchronously: probe → debounce → actuate.
        Returns the transitions this tick confirmed ({rank: dir}) —
        the deterministic-test entry point (no thread needed)."""
        with self._lock:
            self._ticks += 1
        observations = self._observations(self._probe())
        transitions: Dict[int, str] = {}
        for rank, up in sorted(observations.items()):
            d = self.monitor.observe(rank, up)
            if d is not None:
                transitions[rank] = d
        for rank, d in transitions.items():
            if d == "down":
                self._on_confirmed_down(rank)
            else:
                self._on_confirmed_up(rank)
        self._advance_heals()
        return transitions

    def _observations(self, raw) -> Dict[int, bool]:
        if isinstance(raw, HealthReport):
            implicated: set = set()
            for probe in raw.probes.values():
                if not probe.ok:
                    implicated.update(
                        probe.ranks or range(self.health.n_ranks)
                    )
            return {r: False for r in implicated}
        if isinstance(raw, Mapping):
            return {int(r): bool(u) for r, u in raw.items()}
        raise errors.RaftException(
            "ServingSupervisor: probe must return a {rank: up} mapping "
            f"or a HealthReport, got {type(raw).__name__}"
        )

    def _set_state(self, rank: int, state: str) -> None:
        with self._lock:
            prev = self._state[rank]
            self._state[rank] = state
        self._g_state[rank].set(_STATE_CODE[state])
        if self._flight is not None and prev != state:
            self._flight.record("supervisor_transition", rank=rank,
                                prev=prev, state=state)

    def _on_confirmed_down(self, rank: int) -> None:
        self._mark("confirmed_down", rank)
        self.health.mark_down(rank)
        # a rank that dies mid-heal abandons the pipeline: the next
        # confirmed up restarts it from the top (completed splices are
        # stale once the rank went down again)
        with self._lock:
            self._heal_cursor.pop(rank, None)
        self._set_state(rank, STATE_QUARANTINED)
        self._push_route(reason="confirmed_down", rank=rank)

    def _on_confirmed_up(self, rank: int) -> None:
        self._mark("confirmed_up", rank)
        with self._lock:
            if self._state[rank] == STATE_SERVING:
                return  # spurious: already serving
            # resume cursor survives a supervisor restart/crash; a
            # fresh heal starts at step 0
            self._heal_cursor.setdefault(rank, 0)

    def _advance_heals(self) -> None:
        with self._lock:
            healing = sorted(self._heal_cursor)
        for rank in healing:
            self._heal(rank)

    def _heal(self, rank: int) -> None:
        self._mark("heal_started", rank)
        while True:
            with self._lock:
                cursor = self._heal_cursor.get(rank)
            if cursor is None:  # rank went down again mid-pipeline
                return
            if cursor >= len(_HEAL_STEPS):
                break
            step_name, state = _HEAL_STEPS[cursor]
            self._set_state(rank, state)
            fn = getattr(self.heal, step_name)
            if fn is not None and not self._run_heal_step(
                rank, step_name, fn
            ):
                self._rollback(rank, step_name)
                return
            with self._lock:
                # re-check: a concurrent confirmed_down may have
                # aborted the pipeline while the step ran
                if rank in self._heal_cursor:
                    self._heal_cursor[rank] = cursor + 1
        with self._lock:
            self._heal_cursor.pop(rank, None)
        self.health.mark_up(rank)
        self._set_state(rank, STATE_SERVING)
        with self._lock:
            self._heals_ok += 1
        self._c_heals["ok"].inc()
        self._mark("heal_done", rank)
        self._push_route(reason="heal_done", rank=rank)

    def _run_heal_step(self, rank: int, step_name: str, fn) -> bool:
        """One pipeline step under its deadline + retry budget. The
        deadline is COOPERATIVE: it bounds whether another attempt may
        start (and clips backoff sleeps), it cannot preempt a hung host
        call — size step_deadline_s for the slowest legitimate splice."""
        deadline = Deadline.after(self.step_deadline_s)
        attempt = 0
        while True:
            attempt += 1
            try:
                fn(rank)
                if self._flight is not None:
                    self._flight.record("supervisor_heal_step", rank=rank,
                                        step=step_name, attempt=attempt,
                                        ok=True)
                return True
            except Exception as exc:
                if self._flight is not None:
                    self._flight.record(
                        "supervisor_heal_step", rank=rank, step=step_name,
                        attempt=attempt, ok=False,
                        error=f"{type(exc).__name__}: {exc}"[:160],
                    )
                if (attempt >= self.retry.max_attempts
                        or not self.retry.is_retryable(exc)
                        or deadline.expired()):
                    return False
                self._sleep(min(self.retry.backoff_s(attempt),
                                deadline.remaining()))

    def _rollback(self, rank: int, failed_step: str) -> None:
        """Partial-failure path: undo hook, back to QUARANTINED (the
        routed-around plan keeps serving), re-arm the monitor so only a
        fresh confirmed up-streak retries — from step 0, because a
        failed splice invalidates its predecessors."""
        if self.heal.rollback is not None:
            try:
                self.heal.rollback(rank)
            except Exception as exc:  # rollback must never kill the loop
                if self._flight is not None:
                    self._flight.record(
                        "supervisor_rollback_error", rank=rank,
                        error=f"{type(exc).__name__}: {exc}"[:160],
                    )
        with self._lock:
            self._heal_cursor.pop(rank, None)
            self._heals_rolled_back += 1
        self._c_heals["rolled_back"].inc()
        self.monitor.force(rank, up=False)
        self._set_state(rank, STATE_QUARANTINED)
        self._mark("heal_rolled_back", rank)
        if self._flight is not None:
            self._flight.record("supervisor_heal_rolled_back", rank=rank,
                                step=failed_step)

    def _push_route(self, *, reason: str, rank: int = -1) -> None:
        """Recompute the load-balanced plan and atomically swap it into
        every registered executor. shard_mask + failover are runtime
        operands of the warmed programs, so this NEVER retraces."""
        plan = FailoverPlan.load_balanced(
            self.placement, self.health,
            self._load() if self._load is not None else None,
            registry=self._registry,
        )
        mask = self.health.mask()
        with self._lock:
            executors = list(self._executors)
        for ex in executors:
            ex.set_runtime(shard_mask=mask, failover=plan)
        with self._lock:
            self._route_pushes += 1
            self._last_push_t = float(self._clock())
        self._c_pushes.inc()
        self._mark("route_pushed", rank)
        if self._flight is not None:
            self._flight.record(
                "supervisor_route_push", rank=rank, reason=reason,
                route=[int(r) for r in plan.route],
                n_executors=len(executors),
            )

    # ------------------------------------------------------------------
    # the thread

    def start(self) -> None:
        """Start (or RESTART) the background loop. Idempotent while the
        thread is alive; after a crash — surfaced through the installed
        excepthook as ``thread_uncaught_total{thread=<name>}`` — calling
        ``start()`` again spawns a fresh thread that resumes from the
        object's state, including any mid-heal cursor."""
        with self._lock:
            self._closed = False
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._watch, name=self.name, daemon=True
            )
            thread = self._thread
        thread.start()

    def _watch(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
            # deliberately NOT wrapped in try/except: an uncaught bug
            # here must hit the crash excepthook (counted + flight-
            # recorded), not be silently swallowed into a zombie loop
            self.step()
            self._sleep(self.interval_s)

    def close(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._closed = True
            thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout)

    def __repr__(self) -> str:
        s = self.stats()
        down = sorted(r for r, st in s.states.items()
                      if st != STATE_SERVING)
        return (
            f"ServingSupervisor(name={self.name!r}, ticks={s.ticks}, "
            f"pushes={s.route_pushes}, heals_ok={s.heals_ok}, "
            f"rolled_back={s.heals_rolled_back}, "
            f"not_serving={down if down else 'none'})"
        )
