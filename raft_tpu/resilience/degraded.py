"""Degraded-result contract for the sharded searches.

When a shard is down, the sharded ANN searches
(:func:`raft_tpu.comms.mnmg_ivf.mnmg_ivf_pq_search`,
:func:`raft_tpu.comms.mnmg_ivf_flat.mnmg_ivf_flat_search` with
``shard_mask=``) answer from the surviving shards instead of failing the
whole query: a down shard contributes +inf distances to the merge, and
the result reports HOW MUCH of the index was actually consulted —
``coverage`` per query (fraction of probed lists owned by a live rank)
and a ``partial`` flag. Non-finite query rows are neutralized in-graph
at the serving entry (zeroed for compute, reported via ``row_valid``,
outputs forced to +inf/-1) so one poisoned row cannot contaminate the
merged top-k of its batchmates. docs/robustness.md states the full
contract.

This module carries the pieces shared by both engines: the result type,
the mask resolution (accepts a :class:`~raft_tpu.resilience.health.ShardHealth`,
an explicit array, or ``True`` for all-up), and the in-graph helpers the
compiled shard_map bodies call.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import compat, errors
from raft_tpu.resilience.health import HealthReport, ShardHealth

__all__ = ["PartialSearchResult", "resolve_shard_mask"]


@compat.register_dataclass
@dataclasses.dataclass(frozen=True)
class PartialSearchResult:
    """A sharded search answer that may cover only part of the index.

    Attributes
    ----------
    distances : (nq, k) merged distances; +inf where no live candidate
        filled the slot (and everywhere for an invalid query row).
    ids : (nq, k) GLOBAL row ids; -1 wherever ``distances`` is +inf.
    coverage : (nq,) float32 — fraction of this query's probed lists
        owned by a LIVE rank (1.0 = fully served; 0.0 for an invalid
        row). Lists owned by no rank (``expand_probe_set`` owner=-1
        extras) count as not covered: they genuinely were not searched
        here. The contract is probe-agnostic — identical under the flat
        centroid scan and the two-level ``CoarseIndex`` probe
        (tests/test_resilience.py parametrizes the suite over both).
    row_valid : (nq,) bool — False for query rows neutralized at entry
        (non-finite input).

    ``partial`` is the host-side verdict (syncs the small coverage /
    validity arrays): True iff any row was invalid or any query's
    coverage fell short of full.
    """

    distances: jax.Array
    ids: jax.Array
    coverage: jax.Array
    row_valid: jax.Array

    @property
    def partial(self) -> bool:
        cov = np.asarray(self.coverage)
        valid = np.asarray(self.row_valid)
        return bool((cov < 1.0).any() or (~valid).any())

    @property
    def min_coverage(self) -> float:
        """The worst-served query's coverage (host sync)."""
        return float(np.asarray(self.coverage).min())


def resolve_shard_mask(shard_mask: Any, n_ranks: int) -> np.ndarray:
    """Normalize a ``shard_mask=`` argument to an int32 ``(P,)`` validity
    array (1 = up). Accepts ``True`` (all ranks up — the degraded result
    type without any masking), a :class:`ShardHealth`, a
    :class:`HealthReport` (folded through a fresh tracker via
    :meth:`ShardHealth.apply_report`, so the health-check → mask
    pipeline is one call), or any array-like of per-rank truth.
    All-down is allowed: every slot merges to +inf and coverage is 0 —
    the caller sees a fully partial result, not an exception (degrade,
    don't fail)."""
    if shard_mask is True:
        return np.ones(n_ranks, np.int32)
    if isinstance(shard_mask, HealthReport):
        # telemetry=False: this tracker lives for one normalization —
        # it must not reset the global ranks-up gauge or count fake
        # flip transitions on every search call
        shard_mask = ShardHealth(
            n_ranks, telemetry=False).apply_report(shard_mask)
    if isinstance(shard_mask, ShardHealth):
        arr = shard_mask.mask()
    else:
        arr = np.asarray(shard_mask)
    errors.expects(
        arr.shape == (n_ranks,),
        "shard_mask: expected shape (%d,) to match the mesh, got %s",
        n_ranks, tuple(arr.shape),
    )
    return (np.asarray(arr) != 0).astype(np.int32)


# ---------------------------------------------------------------------------
# In-graph helpers (traced inside the shard_map search bodies)
# ---------------------------------------------------------------------------


def sanitize_query_rows(qf: jax.Array):
    """Neutralize non-finite query rows: returns ``(q_clean, row_valid)``
    where poisoned rows are zeroed (they still flow through the compiled
    program — static shapes — but a zero row cannot produce NaN/Inf
    distances that would poison the shared merge) and ``row_valid`` marks
    them for output masking."""
    row_valid = jnp.all(jnp.isfinite(qf), axis=-1)
    return jnp.where(row_valid[:, None], qf, 0.0), row_valid


def probe_coverage(owner_of_probe: jax.Array, alive: jax.Array,
                   row_valid: jax.Array) -> jax.Array:
    """Per-query served fraction: of the probed lists (``owner_of_probe``
    (nq, p) holding each probe's owning rank, -1 = unowned), the fraction
    owned by a live rank per ``alive`` (P,). Invalid rows report 0."""
    n_ranks = alive.shape[0]
    live = (owner_of_probe >= 0) & (
        alive[jnp.clip(owner_of_probe, 0, n_ranks - 1)] > 0
    )
    cov = jnp.mean(live.astype(jnp.float32), axis=-1)
    return jnp.where(row_valid, cov, 0.0)


def mask_invalid_rows(md: jax.Array, mi: jax.Array, row_valid: jax.Array):
    """Force the outputs of neutralized rows to the empty answer
    (+inf distances, -1 ids)."""
    md = jnp.where(row_valid[:, None], md, jnp.inf)
    mi = jnp.where(row_valid[:, None], mi, -1)
    return md, mi
