"""Spectral partitioning & modularity maximization — analog of
cpp/include/raft/spectral/detail/partition.hpp:64-133 (``partition`` +
``analyzePartition``), detail/modularity_maximization.hpp, matrix wrappers
detail/matrix_wrappers.hpp:130-305 (sparse/laplacian/modularity matvecs),
solver configs eigen_solvers.hpp:35-51 / cluster_solvers.hpp:38-49.

Pipeline (reference partition.hpp:64): wrap the CSR graph in a Laplacian
operator → Lanczos smallest eigenvectors → scale/normalize the embedding →
k-means on the n × k embedding → labels. Modularity maximization runs the
same with the modularity operator's LARGEST eigenvectors.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.linalg.lanczos import lanczos_solver
from raft_tpu.sparse.coo import CSR
from raft_tpu.sparse.linalg import spmv

__all__ = [
    "EigenSolverConfig",
    "ClusterSolverConfig",
    "LaplacianMatrix",
    "ModularityMatrix",
    "partition",
    "analyze_partition",
    "modularity_maximization",
    "analyze_modularity",
]


@dataclasses.dataclass(frozen=True)
class EigenSolverConfig:
    """Analog of eigen_solver_config_t (spectral/eigen_solvers.hpp:35)."""

    n_eig_vecs: int
    max_iter: int = 4000
    restart_iter: int = 0   # ncv; 0 -> auto
    tol: float = 1e-6
    seed: int = 1234567


@dataclasses.dataclass(frozen=True)
class ClusterSolverConfig:
    """Analog of cluster_solver_config_t (spectral/cluster_solvers.hpp:38)."""

    n_clusters: int
    max_iter: int = 100
    tol: float = 1e-4
    seed: int = 123456


class LaplacianMatrix:
    """L = D - A matvec wrapper (reference matrix_wrappers.hpp:305
    laplacian_matrix_t: spmv + diagonal scaling)."""

    def __init__(self, csr: CSR):
        self.csr = csr
        rows = csr.row_ids()
        contrib = jnp.where(csr.valid_mask(), csr.data, 0)
        self.degree = (
            jnp.zeros((csr.shape[0],), csr.data.dtype).at[rows].add(contrib)
        )

    def matvec(self, v):
        return self.degree * v - spmv(self.csr, v)


class ModularityMatrix:
    """B = A - d dᵀ / (2m) matvec wrapper (reference
    matrix_wrappers.hpp modularity_matrix_t)."""

    def __init__(self, csr: CSR):
        self.csr = csr
        rows = csr.row_ids()
        contrib = jnp.where(csr.valid_mask(), csr.data, 0)
        self.degree = (
            jnp.zeros((csr.shape[0],), csr.data.dtype).at[rows].add(contrib)
        )
        self.edge_sum = jnp.sum(contrib)  # = 2m for symmetric A

    def matvec(self, v):
        return spmv(self.csr, v) - self.degree * (
            jnp.dot(self.degree, v) / self.edge_sum
        )


def _normalize_rows(e):
    """transform_eigen_matrix analog (reference
    detail/spectral_util.cuh transform_eigen_matrix: scale the embedding
    before clustering)."""
    nrm = jnp.linalg.norm(e, axis=1, keepdims=True)
    return e / jnp.where(nrm == 0, 1.0, nrm)


class SpectralResult(NamedTuple):
    labels: jax.Array
    eigenvalues: jax.Array
    eigenvectors: jax.Array
    kmeans_iters: jax.Array


def partition(
    csr: CSR,
    eig_cfg: EigenSolverConfig,
    cluster_cfg: ClusterSolverConfig,
) -> SpectralResult:
    """Balanced-cut spectral partition (reference partition.hpp:64-112):
    smallest Laplacian eigenvectors (dropping the trivial constant one is
    NOT done — parity with the reference which keeps all n_eig_vecs),
    row-normalized embedding, k-means."""
    lap = LaplacianMatrix(csr)
    n = csr.shape[0]
    vals, vecs = lanczos_solver(
        lap.matvec, n, eig_cfg.n_eig_vecs,
        ncv=eig_cfg.restart_iter or None,
        seed=eig_cfg.seed, smallest=True,
    )
    emb = _normalize_rows(vecs)
    out = kmeans_fit(
        emb,
        KMeansParams(
            n_clusters=cluster_cfg.n_clusters,
            max_iter=cluster_cfg.max_iter,
            tol=cluster_cfg.tol,
            seed=cluster_cfg.seed,
        ),
    )
    return SpectralResult(out.labels, vals, vecs, out.n_iter)


def analyze_partition(csr: CSR, labels, n_clusters: int):
    """Edge cut + cluster-size balance (reference partition.hpp:133
    analyzePartition returns edgeCut and cost)."""
    labels = jnp.asarray(labels)
    valid = csr.valid_mask()
    rows = csr.row_ids()
    cross = valid & (labels[rows] != labels[csr.indices])
    edge_cut = jnp.sum(jnp.where(cross, csr.data, 0)) / 2.0  # symmetric A
    sizes = jnp.zeros((n_clusters,), jnp.float32).at[labels].add(1.0)
    cost = jnp.sum(jnp.where(sizes > 0, 1.0 / jnp.maximum(sizes, 1.0), 0.0))
    return edge_cut, cost


def modularity_maximization(
    csr: CSR,
    eig_cfg: EigenSolverConfig,
    cluster_cfg: ClusterSolverConfig,
) -> SpectralResult:
    """Cluster by the LARGEST eigenvectors of the modularity matrix
    (reference detail/modularity_maximization.hpp:spectral_modularity_maximization)."""
    mod = ModularityMatrix(csr)
    n = csr.shape[0]
    vals, vecs = lanczos_solver(
        mod.matvec, n, eig_cfg.n_eig_vecs,
        ncv=eig_cfg.restart_iter or None,
        seed=eig_cfg.seed, smallest=False,
    )
    emb = _normalize_rows(vecs)
    out = kmeans_fit(
        emb,
        KMeansParams(
            n_clusters=cluster_cfg.n_clusters,
            max_iter=cluster_cfg.max_iter,
            tol=cluster_cfg.tol,
            seed=cluster_cfg.seed,
        ),
    )
    return SpectralResult(out.labels, vals, vecs, out.n_iter)


def analyze_modularity(csr: CSR, labels) -> jax.Array:
    """Modularity Q = Σ_c (e_c/2m - (d_c/2m)²) (reference
    detail/modularity_maximization.hpp analyzeModularity)."""
    labels = jnp.asarray(labels)
    valid = csr.valid_mask()
    rows = csr.row_ids()
    w = jnp.where(valid, csr.data, 0)
    two_m = jnp.sum(w)
    intra = jnp.sum(jnp.where(labels[rows] == labels[csr.indices], w, 0))
    n = csr.shape[0]
    deg = jnp.zeros((n,), w.dtype).at[rows].add(w)
    dc = jnp.zeros((n,), w.dtype).at[labels].add(deg)
    return intra / two_m - jnp.sum((dc / two_m) ** 2)
