"""Spectral graph methods — analog of raft/spectral
(cpp/include/raft/spectral/: partition.hpp, modularity_maximization.hpp,
matrix_wrappers.hpp, eigen_solvers.hpp, cluster_solvers.hpp;
SURVEY.md §2 #24).
"""

from raft_tpu.spectral.partition import (
    EigenSolverConfig,
    ClusterSolverConfig,
    LaplacianMatrix,
    ModularityMatrix,
    partition,
    analyze_partition,
    modularity_maximization,
    analyze_modularity,
)

__all__ = [
    "EigenSolverConfig",
    "ClusterSolverConfig",
    "LaplacianMatrix",
    "ModularityMatrix",
    "partition",
    "analyze_partition",
    "modularity_maximization",
    "analyze_modularity",
]
