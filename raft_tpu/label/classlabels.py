"""Class-label utilities — analog of
cpp/include/raft/label/classlabels.cuh (getUniquelabels:65,
make_monotonic:103, getOvrlabels:86) and merge_labels.cuh:57.

All jittable with a static capacity on the unique-label count (the usual
static-shape trade: the reference returns a dynamically sized unique array;
here the capacity is an argument and the true count a returned scalar).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "get_unique_labels",
    "make_monotonic",
    "get_ovr_labels",
    "merge_labels",
]


@functools.partial(jax.jit, static_argnames=("capacity",))
def get_unique_labels(labels, capacity: Optional[int] = None):
    """Sorted unique labels (reference getUniquelabels:65).

    Returns (unique (capacity,), n_unique); slots past n_unique are padded
    with the max label.
    """
    labels = jnp.asarray(labels)
    cap = capacity or labels.shape[0]
    s = jnp.sort(labels)
    head = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    n_unique = jnp.sum(head).astype(jnp.int32)
    order = jnp.argsort(~head, stable=True)  # heads first, still sorted
    uniq = s[order][:cap]
    pad = jnp.max(labels)
    uniq = jnp.where(jnp.arange(cap) < n_unique, uniq, pad)
    return uniq, n_unique


@jax.jit
def make_monotonic(labels):
    """Map labels to consecutive ids ordered by label value
    (reference make_monotonic:103: each label becomes its rank in the
    sorted unique array)."""
    labels = jnp.asarray(labels)
    s = jnp.sort(labels)
    head = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    # rank of each sorted position's value = #heads before it
    ranks_sorted = jnp.cumsum(head) - 1
    # value -> rank lookup via searchsorted on the sorted array: first
    # occurrence index, then its rank
    first_pos = jnp.searchsorted(s, labels, side="left")
    return ranks_sorted[first_pos].astype(jnp.int32)


def get_ovr_labels(labels, target, *, dtype=jnp.float32):
    """One-vs-rest ±1 labels for a target class
    (reference getOvrlabels:86)."""
    labels = jnp.asarray(labels)
    return jnp.where(labels == target, 1, -1).astype(dtype)


@jax.jit
def merge_labels(labels_a, labels_b, mask=None):
    """Union-merge two labelings of the same points (reference
    merge_labels.cuh:57, used to stitch partial clusterings in MNMG
    DBSCAN-style flows): points sharing a label in EITHER input end up with
    one common label — the min initial label of their merged group.

    ``mask`` optionally limits which points participate in b-induced merges
    (the reference's core-point mask); masked-out points keep their
    a-labels unless pulled in via an a-group.
    """
    a = jnp.asarray(labels_a).astype(jnp.int32)
    b = jnp.asarray(labels_b).astype(jnp.int32)
    n = a.shape[0]
    if mask is None:
        mask = jnp.ones((n,), bool)
    else:
        mask = jnp.asarray(mask)

    def propagate(cur, group, active):
        """One min-propagation through a labeling: group members share min."""
        big = jnp.int32(n + 1)
        gmin = jnp.full((n,), big, jnp.int32).at[group].min(
            jnp.where(active, cur, big)
        )
        return jnp.where(active, jnp.minimum(cur, gmin[group]), cur)

    def body(state):
        cur, _ = state
        nxt = propagate(cur, a, jnp.ones((n,), bool))
        nxt = propagate(nxt, b, mask)
        return nxt, jnp.any(nxt != cur)

    def cond(state):
        return state[1]

    out, _ = lax.while_loop(
        cond, body, (jnp.arange(n, dtype=jnp.int32), jnp.bool_(True))
    )
    return out
