"""Label utilities — analog of raft/label
(cpp/include/raft/label/classlabels.cuh:65-114 getUniquelabels /
make_monotonic / getOvrlabels; merge_labels.cuh:57 merge_labels).
"""

from raft_tpu.label.classlabels import (
    get_unique_labels,
    make_monotonic,
    get_ovr_labels,
    merge_labels,
)

__all__ = [
    "get_unique_labels",
    "make_monotonic",
    "get_ovr_labels",
    "merge_labels",
]
