"""jaxlint engine: file walking, rule dispatch, suppressions, baseline, CLI.

The pipeline per file is two passes: parse + fact gathering
(:class:`~raft_tpu.analysis.facts.ModuleFacts`), then every rule runs over
the facts and emits :class:`Finding`\\ s. Findings are filtered through

* **per-line suppressions** — ``# jaxlint: disable=rule-a,rule-b`` on the
  flagged physical line;
* **the baseline** — a checked-in JSON file of grandfathered finding keys
  (``path::rule::message`` → count), so a new rule can land as a hard CI
  gate while its existing debt is burned down incrementally (the same
  contract as the reference's include_checker grandfather list).

Exit status is 0 iff no *new* findings survive both filters.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import re
import sys
from pathlib import Path
from typing import TYPE_CHECKING, Dict, Iterable, Iterator, List, Optional

from raft_tpu.analysis.facts import ModuleFacts

if TYPE_CHECKING:
    from raft_tpu.analysis.rules import Rule

DEFAULT_BASELINE = Path("ci/checks/jaxlint_baseline.json")

_SUPPRESS_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: where, which rule, and what."""

    path: str      # repo-relative posix path
    line: int
    col: int
    rule: str
    message: str

    @property
    def baseline_key(self) -> str:
        # line numbers are deliberately absent: unrelated edits above a
        # grandfathered finding must not invalidate the baseline
        return f"{self.path}::{self.rule}::{self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


class FileContext:
    """Everything a rule sees for one file."""

    def __init__(self, path: Path, rel: str, text: str, tree: ast.Module):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        self.facts = ModuleFacts(tree)

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            path=self.rel,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            rule=rule,
            message=message,
        )

    def suppressed_rules(self, line: int) -> frozenset:
        """Rules disabled on a given 1-based physical line."""
        if not (1 <= line <= len(self.lines)):
            return frozenset()
        m = _SUPPRESS_RE.search(self.lines[line - 1])
        if not m:
            return frozenset()
        return frozenset(p.strip() for p in m.group(1).split(",") if p.strip())


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: int
    baselined: int
    checked_files: int
    parse_errors: List[Finding]

    @property
    def clean(self) -> bool:
        return not self.findings and not self.parse_errors


def iter_py_files(paths: Iterable[Path]) -> Iterator[Path]:
    for p in paths:
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" not in f.parts:
                    yield f
        elif p.suffix == ".py":
            yield p


def _relpath(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


class Baseline:
    """Grandfathered findings: baseline_key -> allowed count."""

    def __init__(self, counts: Optional[Dict[str, int]] = None):
        self.counts: Dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        data = json.loads(path.read_text())
        return cls(data.get("findings", {}))

    def save(self, path: Path, findings: Iterable[Finding]) -> None:
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.baseline_key] = counts.get(f.baseline_key, 0) + 1
        payload = {
            "comment": "jaxlint grandfathered findings — burn down, never add",
            "version": 1,
            "findings": dict(sorted(counts.items())),
        }
        path.write_text(json.dumps(payload, indent=2) + "\n")

    def filter(self, findings: List[Finding]):
        """Split into (new, grandfathered), honoring per-key counts."""
        budget = dict(self.counts)
        new: List[Finding] = []
        old: List[Finding] = []
        for f in findings:
            k = f.baseline_key
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                old.append(f)
            else:
                new.append(f)
        return new, old


def lint_file(path: Path, root: Path, rules: List["Rule"]):
    """Returns (kept_findings, n_suppressed, parse_error_or_None)."""
    rel = _relpath(path, root)
    text = path.read_text()
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        err = Finding(rel, e.lineno or 1, (e.offset or 0) + 1, "parse",
                      f"syntax error: {e.msg}")
        return [], 0, err
    ctx = FileContext(path, rel, text, tree)
    kept: List[Finding] = []
    suppressed = 0
    for rule in rules:
        for f in rule.check(ctx):
            if rule.name in ctx.suppressed_rules(f.line):
                suppressed += 1
            else:
                kept.append(f)
    kept.sort(key=lambda f: (f.line, f.col, f.rule))
    return kept, suppressed, None


def lint_paths(
    paths: Iterable[Path],
    root: Optional[Path] = None,
    rules: Optional[List["Rule"]] = None,
    baseline: Optional[Baseline] = None,
) -> LintResult:
    from raft_tpu.analysis.rules import ALL_RULES

    root = root or Path.cwd()
    rules = ALL_RULES if rules is None else rules
    findings: List[Finding] = []
    parse_errors: List[Finding] = []
    suppressed = 0
    n_files = 0
    for f in iter_py_files(paths):
        n_files += 1
        kept, n_sup, err = lint_file(f, root, rules)
        findings.extend(kept)
        suppressed += n_sup
        if err is not None:
            parse_errors.append(err)
    baselined: List[Finding] = []
    if baseline is not None:
        findings, baselined = baseline.filter(findings)
    return LintResult(
        findings=findings,
        suppressed=suppressed,
        baselined=len(baselined),
        checked_files=n_files,
        parse_errors=parse_errors,
    )


def lint_source(source: str, rules: Optional[List["Rule"]] = None,
                rel: str = "<string>") -> List[Finding]:
    """Lint a source snippet in memory — the test-fixture entry point."""
    from raft_tpu.analysis.rules import ALL_RULES

    tree = ast.parse(source)
    ctx = FileContext(Path(rel), rel, source, tree)
    kept: List[Finding] = []
    for rule in (ALL_RULES if rules is None else rules):
        for f in rule.check(ctx):
            if rule.name not in ctx.suppressed_rules(f.line):
                kept.append(f)
    kept.sort(key=lambda f: (f.line, f.col, f.rule))
    return kept


def main(argv: Optional[List[str]] = None) -> int:
    from raft_tpu.analysis.rules import ALL_RULES

    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.analysis",
        description="jaxlint — JAX/TPU-aware static analysis for raft_tpu",
    )
    ap.add_argument("paths", nargs="*", default=["."],
                    help="files or directories to lint (default: .)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", type=Path, default=None,
                    help=f"baseline JSON (default: {DEFAULT_BASELINE} "
                         "if it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset to run")
    ap.add_argument("--list-rules", action="store_true")
    # -- the program tier (jaxpr-level audit; docs/static_analysis.md
    # "Two tiers"). Implemented in raft_tpu.analysis.program and only
    # imported when requested: the AST tier must keep running without
    # paying (or requiring) a jax import.
    ap.add_argument("--programs", action="store_true",
                    help="audit traced serving programs against "
                         "ci/checks/program_contracts.json instead of "
                         "linting source files")
    ap.add_argument("--contracts", type=Path, default=None,
                    help="program contracts JSON (default: "
                         "ci/checks/program_contracts.json)")
    ap.add_argument("--write-contracts", action="store_true",
                    help="re-snapshot the program contracts (pass "
                         "findings still gate the exit code)")
    ap.add_argument("--list-programs", action="store_true")
    # -- the thread tier (concurrency auditor; docs/static_analysis.md
    # "Three tiers"). Same lazy-import discipline as --programs: the
    # census/graph code only loads when asked for.
    ap.add_argument("--threads", action="store_true",
                    help="run the concurrency auditor (thread rules + "
                         "lock-order graph against "
                         "ci/checks/lock_order.json) instead of the "
                         "tier-1 rules")
    ap.add_argument("--lock-order", type=Path, default=None,
                    help="lock-order JSON (default: "
                         "ci/checks/lock_order.json)")
    ap.add_argument("--write-lock-order", action="store_true",
                    help="re-bless the observed lock-order edges and "
                         "grandfather current thread findings (cycles "
                         "still fail)")
    args = ap.parse_args(argv)

    if args.threads:
        from raft_tpu.analysis.threads.lock_order import main_threads

        return main_threads(args)
    if args.write_lock_order:
        print("jaxlint: --write-lock-order requires --threads",
              file=sys.stderr)
        return 2

    if args.programs or args.list_programs:
        from raft_tpu.analysis.program.contracts import main_programs

        return main_programs(args)
    if args.write_contracts:
        print("jaxlint: --write-contracts requires --programs",
              file=sys.stderr)
        return 2

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.name}: {r.description}")
        return 0

    rules = ALL_RULES
    if args.rules:
        wanted = {s.strip() for s in args.rules.split(",")}
        unknown = wanted - {r.name for r in ALL_RULES}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in ALL_RULES if r.name in wanted]

    if args.write_baseline and args.rules:
        print("jaxlint: --write-baseline with --rules would rewrite the "
              "baseline from a rule subset, dropping every other rule's "
              "grandfathered findings; run --write-baseline with the "
              "full rule set", file=sys.stderr)
        return 2

    baseline_path = args.baseline or DEFAULT_BASELINE
    baseline: Optional[Baseline] = None
    if not args.no_baseline and not args.write_baseline \
            and baseline_path.exists():
        baseline = Baseline.load(baseline_path)

    paths = [Path(p) for p in args.paths]
    result = lint_paths(paths, rules=rules, baseline=baseline)

    if args.write_baseline:
        Baseline().save(baseline_path, result.findings)
        print(f"jaxlint: wrote {len(result.findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    all_out = result.parse_errors + result.findings
    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in all_out],
            "suppressed": result.suppressed,
            "baselined": result.baselined,
            "checked_files": result.checked_files,
            "rules": [r.name for r in rules],
        }, indent=2))
    else:
        for f in all_out:
            print(f.render())
        print(
            f"jaxlint: checked {result.checked_files} files — "
            f"{len(all_out)} finding(s), {result.suppressed} suppressed, "
            f"{result.baselined} baselined"
        )
    return 0 if result.clean else 1
