"""Pass 1 of the analyzer: per-module fact gathering.

Rules never re-derive module structure themselves; this pass walks the AST
once and exposes:

* import alias resolution (``import jax.numpy as jnp`` → ``jnp`` means
  ``jax.numpy``; ``from jax import lax`` → ``lax`` means ``jax.lax``), so
  rules match *dotted origin paths*, not surface spellings;
* the set of function bodies that execute under a JAX trace (decorated with
  ``jit``-family transforms, or passed as the callable to ``jit`` /
  ``shard_map`` / ``vmap`` / ``lax.scan`` / ... calls), including lambdas;
* per traced function, which parameters are declared static
  (``static_argnums`` / ``static_argnames``) and therefore safe to branch on;
* a parent map for ancestor queries.

This is deliberately lexical, not a type system: a method invoked *from* a
traced region in another module is not seen. The rules it feeds are linters
— suppressions and the baseline absorb the residue.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

# Transforms whose callable argument runs under a tracer. Matched against
# the LAST segment of the resolved dotted callee (``jax.jit``, ``lax.scan``,
# ``comms.shard_map`` and the compat spelling all normalize to their tail).
TRACING_TRANSFORMS = frozenset({
    "jit", "pjit", "shard_map", "pmap", "vmap", "xmap",
    "grad", "value_and_grad", "jacfwd", "jacrev", "hessian",
    "remat", "checkpoint", "custom_jvp", "custom_vjp",
    "scan", "while_loop", "fori_loop", "cond", "switch",
    "associative_scan", "map",
})

# tails that collide with Python builtins: only a jax-rooted dotted path
# (lax.map -> "jax.lax.map") counts — the builtin `map(f, xs)` must not
# mark `f` as traced
_AMBIGUOUS_TAILS = frozenset({"map"})


def dotted_chain(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` Attribute/Name chain as ["a","b","c"], or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


class ModuleFacts:
    """Everything pass-1 knows about one parsed module."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        # local name -> dotted origin ("jnp" -> "jax.numpy")
        self.aliases: Dict[str, str] = {}
        # name -> the partial(<transform>, ...) call it was assigned
        # from: `grouped_jit = partial(jax.jit, static_argnames=...)`
        # used as `@grouped_jit` (or call-form) later — the
        # decorator-factory idiom. The factory call carries the statics.
        self.transform_factories: Dict[str, ast.Call] = {}
        self.parent: Dict[ast.AST, ast.AST] = {}
        self.functions_by_name: Dict[str, List[ast.FunctionDef]] = {}
        self.traced: List[FunctionNode] = []
        self.static_params: Dict[FunctionNode, Set[str]] = {}
        self._collect()

    # -- name resolution -----------------------------------------------------

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Resolve an Attribute/Name chain to its dotted origin path,
        expanding import aliases on the root segment."""
        chain = dotted_chain(node)
        if not chain:
            return None
        root = self.aliases.get(chain[0], chain[0])
        return ".".join([root] + chain[1:])

    def callee(self, call: ast.Call) -> Optional[str]:
        return self.dotted(call.func)

    def aliases_of(self, dotted_prefix: str) -> Set[str]:
        """Local names whose origin is exactly ``dotted_prefix``."""
        return {
            local for local, origin in self.aliases.items()
            if origin == dotted_prefix
        }

    # -- collection ----------------------------------------------------------

    def _collect(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.aliases[alias.asname] = alias.name
                    else:
                        # `import jax.numpy` binds only the root name `jax`
                        root = alias.name.split(".")[0]
                        self.aliases[root] = root
            elif isinstance(node, ast.ImportFrom):
                if node.level or not node.module:
                    continue  # relative imports stay local-package
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.aliases[local] = f"{node.module}.{alias.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions_by_name.setdefault(node.name, []).append(node)

        # pass 1.5 — transforms bound to names by ASSIGNMENT, before use:
        # `jit_k = partial(jax.jit, static_argnames=("k",))` (a decorator
        # factory carrying statics) and `jit2 = jax.jit` (a plain
        # rebinding, folded into the alias map so dotted() resolves it).
        # Runs after the import pass so alias resolution is complete, and
        # before the decorator/call pass so `@jit_k` marks its function.
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            tgt = node.targets[0].id
            val = node.value
            if isinstance(val, ast.Call):
                ct = self.dotted(val.func)
                if ct and ct.split(".")[-1] == "partial" and val.args \
                        and self._transform_tail(val.args[0]):
                    self.transform_factories[tgt] = val
            else:
                d = self.dotted(val)
                if d is not None and self._transform_tail(val):
                    self.aliases[tgt] = d

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_decorators(node)
            elif isinstance(node, ast.Call):
                self._check_transform_call(node)

    def _mark_traced(self, fn: FunctionNode, static: Set[str]) -> None:
        if fn not in self.traced:
            self.traced.append(fn)
        self.static_params.setdefault(fn, set()).update(static)

    def _param_names(self, fn: FunctionNode) -> List[str]:
        a = fn.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]

    def _static_from_call(self, call: ast.Call, fn: FunctionNode) -> Set[str]:
        """Parameter names declared static via static_argnums/argnames."""
        names = self._param_names(fn)
        static: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and isinstance(el.value, str):
                        static.add(el.value)
            elif kw.arg == "static_argnums":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and isinstance(el.value, int):
                        if 0 <= el.value < len(names):
                            static.add(names[el.value])
        return static

    def _factory_call(self, node: ast.AST) -> Optional[ast.Call]:
        """The ``partial(<transform>, ...)`` call a plain Name was
        assigned from, when this node is such a name (the decorator-
        factory idiom — its statics live on the factory call)."""
        if isinstance(node, ast.Name):
            return self.transform_factories.get(node.id)
        return None

    def _transform_tail(self, node: ast.AST,
                        _depth: int = 0) -> Optional[str]:
        fac = self._factory_call(node)
        if fac is not None:
            # depth-bounded: `j = partial(j, ...)` rebinding would
            # otherwise cycle through its own factory entry
            if _depth > 8:
                return None
            return self._transform_tail(fac.args[0], _depth + 1)
        d = self.dotted(node)
        if d is None:
            return None
        tail = d.split(".")[-1]
        if tail not in TRACING_TRANSFORMS:
            return None
        if tail in _AMBIGUOUS_TAILS and not d.startswith("jax."):
            return None
        return tail

    def _check_decorators(self, fn: ast.FunctionDef) -> None:
        for dec in fn.decorator_list:
            call = dec if isinstance(dec, ast.Call) else None
            target = call.func if call else dec
            tail = self._transform_tail(target)
            if tail is None and call is not None:
                # @partial(jax.jit, static_argnums=...)
                ct = self.dotted(call.func)
                if ct and ct.split(".")[-1] == "partial" and call.args:
                    inner_tail = self._transform_tail(call.args[0])
                    if inner_tail:
                        self._mark_traced(fn, self._static_from_call(call, fn))
                        continue
            if tail is not None:
                static = self._static_from_call(call, fn) if call else set()
                # statics declared on the assigned factory — @jit_k with
                # jit_k = partial(jax.jit, static_argnames=...) — carry
                # to every function the factory decorates
                fac = self._factory_call(target)
                if fac is not None:
                    static |= self._static_from_call(fac, fn)
                self._mark_traced(fn, static)

    def _check_transform_call(self, call: ast.Call) -> None:
        tail = self._transform_tail(call.func)
        is_partial_jit = False
        if tail is None:
            ct = self.dotted(call.func)
            if ct and ct.split(".")[-1] == "partial" and call.args:
                if self._transform_tail(call.args[0]):
                    is_partial_jit = True
        if tail is None and not is_partial_jit:
            return
        fac = self._factory_call(call.func)
        args = call.args[1:] if is_partial_jit else call.args
        static: Set[str] = set()
        for arg in args:
            fn: Optional[FunctionNode] = None
            if isinstance(arg, ast.Lambda):
                fn = arg
            elif isinstance(arg, ast.Name):
                defs = self.functions_by_name.get(arg.id)
                if defs:
                    fn = defs[-1]
            if fn is not None:
                static = self._static_from_call(call, fn)
                if fac is not None:
                    # jit_k(body): the factory's statics apply too
                    static |= self._static_from_call(fac, fn)
                self._mark_traced(fn, static)

    # -- traced-body queries -------------------------------------------------

    def traced_body_nodes(self, fn: FunctionNode):
        """All AST nodes inside a traced callable's body (including nested
        defs — they trace too when the outer one does)."""
        bodies = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in bodies:
            yield from ast.walk(stmt)

    def nonstatic_params(self, fn: FunctionNode) -> Set[str]:
        return set(self._param_names(fn)) - self.static_params.get(fn, set())
