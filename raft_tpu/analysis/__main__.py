"""Entry point: ``python -m raft_tpu.analysis [paths...]``.

The ``__name__`` guard matters: tooling that walks packages (docs/gen_api)
imports this module as ``raft_tpu.analysis.__main__``, which must not run
the CLI.
"""

import sys

from raft_tpu.analysis.engine import main

if __name__ == "__main__":
    sys.exit(main())
