"""The program auditor — jaxlint's second tier, over traced jaxprs.

The AST tier (:mod:`raft_tpu.analysis.rules`) lints source text; this
package lints the PROGRAMS the source traces into: a jaxpr walker
(:mod:`~raft_tpu.analysis.program.walker`) recursing through
pjit/shard_map/scan/cond sub-jaxprs feeds five passes
(:mod:`~raft_tpu.analysis.program.passes`) —

* ``collective-census`` — axes + payload bytes of every collective; no
  inner×outer wide collective, the DCN stage stays on the compressed
  wire;
* ``materialization-model`` — peak intermediate bytes; no
  (qcap, max_list) f32 tile materialized in a scan path;
* ``dtype-flow`` — convert_element_type census; no 64-bit dtypes,
  bf16→f32 upcasts within the sanctioned tails;
* ``donation-check`` — serving dispatches actually donate their query
  buffers in the lowering;
* ``program-count`` — the zero-retrace contract as a cached-program
  census across health/failover/mutation value flips

— over a registry of audited entry points
(:mod:`~raft_tpu.analysis.program.registry`), with each program's
measured contract snapshotted into ``ci/checks/program_contracts.json``
and drift-checked by CI
(:mod:`~raft_tpu.analysis.program.contracts`). CLI:
``python -m raft_tpu.analysis --programs``; per-index:
``index.warmup(..., audit=True)``. Docs: docs/static_analysis.md
"Two tiers".

Everything here traces abstractly on CPU (``JAX_PLATFORMS=cpu``) — no
TPU, no device dispatch; jax imports stay inside functions so the AST
tier never pays them.
"""

from raft_tpu.analysis.program.passes import (
    ALL_PASSES,
    ProgramRecord,
    run_passes,
)
from raft_tpu.analysis.program.walker import (
    EqnSite,
    aval_bytes,
    collective_axes,
    out_bytes,
    sub_jaxprs,
    walk_jaxpr,
)

__all__ = [
    "ALL_PASSES",
    "EqnSite",
    "ProgramRecord",
    "audit_warmed",
    "aval_bytes",
    "collective_axes",
    "out_bytes",
    "run_passes",
    "sub_jaxprs",
    "walk_jaxpr",
]


def audit_warmed(record: "ProgramRecord") -> None:
    """The ``warmup(audit=True)`` hook: run the jaxpr passes over one
    freshly-traced serving program and raise
    :class:`~raft_tpu.errors.RaftError` listing the findings when the
    program violates its tier's invariants (wide collectives, scan-path
    f32 tiles, 64-bit dtypes, missing donation). Contract drift is CI's
    job (``--programs``); this hook is the in-process spot check a
    serving deployment runs once at warmup."""
    from raft_tpu import errors

    _, findings = run_passes(record)
    errors.expects(
        not findings,
        "program audit failed for %s:\n%s",
        record.name, "\n".join(f.render() for f in findings),
    )
