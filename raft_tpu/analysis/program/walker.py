"""The jaxpr walker — pass 0 of the program auditor.

Where :mod:`raft_tpu.analysis.facts` gathers facts from SOURCE TEXT, this
module walks a **traced jaxpr**: the program XLA will actually compile,
after jit inlining, shard_map staging, and scan batching have happened.
Every equation is visited exactly once, recursing through any parameter
that holds a sub-jaxpr — ``pjit``'s ``jaxpr``, ``shard_map``'s body,
``scan``/``while``'s carried bodies, ``cond``/``switch``'s branch tuple,
``custom_jvp/vjp`` call jaxprs — so a hazard cannot hide one staging
level down.

Each visit yields an :class:`EqnSite` carrying the equation plus the
*context* the passes key on:

* ``path`` — the chain of enclosing primitives (``("pjit", "shard_map",
  "scan")``), for human-readable findings;
* ``in_scan`` — true inside any ``scan``/``while`` body (including the
  ``lax.map`` lowering), where a materialized intermediate is paid once
  per iteration and a wide tile is the
  ``wide-distance-materialize`` hazard's program-level twin;
* ``in_kernel`` — true inside a ``pallas_call`` kernel jaxpr, whose
  values live in VMEM refs: *not* HBM materialization, so the
  materialization model skips them (that is the entire point of the
  kernels).

The walker is deliberately schema-free: sub-jaxprs are discovered by
*type* (any ``Jaxpr``/``ClosedJaxpr`` parameter value, directly or inside
a tuple/list), not by a hand-maintained primitive table, so a new JAX
release's staging primitives are walked without a code change here.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Tuple

import numpy as np

# the only jax import the walker needs; kept narrow so the AST tier never
# pays a jax import through this package's import chain
from jax._src import core as _jcore

# primitives whose body runs once per iteration: an f32 intermediate here
# is re-materialized every step (lax.map lowers to scan, so it is covered)
_LOOP_PRIMS = frozenset({"scan", "while"})

# Pallas kernels: the sub-jaxpr operates on VMEM refs — its intermediates
# are the kernel's working set, not HBM materialization
_KERNEL_PRIMS = frozenset({"pallas_call"})


@dataclasses.dataclass(frozen=True)
class EqnSite:
    """One visited equation with its staging context."""

    eqn: object                 # jax.core.JaxprEqn
    path: Tuple[str, ...]       # enclosing primitive names, outermost first
    in_scan: bool               # inside a scan/while body (incl. lax.map)
    in_kernel: bool             # inside a pallas_call kernel jaxpr

    @property
    def prim(self) -> str:
        return self.eqn.primitive.name


def _as_jaxpr(v) -> Optional[object]:
    if isinstance(v, _jcore.ClosedJaxpr):
        return v.jaxpr
    if isinstance(v, _jcore.Jaxpr):
        return v
    return None


def sub_jaxprs(eqn) -> List[object]:
    """Every sub-jaxpr reachable from an equation's params, discovered by
    type (scalar param, or inside a tuple/list like ``cond`` branches)."""
    out: List[object] = []
    for v in eqn.params.values():
        j = _as_jaxpr(v)
        if j is not None:
            out.append(j)
        elif isinstance(v, (tuple, list)):
            for e in v:
                j = _as_jaxpr(e)
                if j is not None:
                    out.append(j)
    return out


def walk_jaxpr(jaxpr, *, into_kernels: bool = True) -> Iterator[EqnSite]:
    """Yield every equation of ``jaxpr`` (a ``Jaxpr`` or ``ClosedJaxpr``)
    and, recursively, of every sub-jaxpr. ``into_kernels=False`` stops at
    ``pallas_call`` boundaries entirely; the default walks them but marks
    the sites ``in_kernel`` so passes can choose."""
    root = jaxpr.jaxpr if isinstance(jaxpr, _jcore.ClosedJaxpr) else jaxpr
    stack: List[Tuple[object, Tuple[str, ...], bool, bool]] = [
        (root, (), False, False)
    ]
    while stack:
        j, path, in_scan, in_kernel = stack.pop()
        for eqn in j.eqns:
            name = eqn.primitive.name
            yield EqnSite(eqn, path, in_scan, in_kernel)
            subs = sub_jaxprs(eqn)
            if not subs:
                continue
            k = in_kernel or name in _KERNEL_PRIMS
            if name in _KERNEL_PRIMS and not into_kernels:
                continue
            s = in_scan or name in _LOOP_PRIMS
            for sub in subs:
                stack.append((sub, path + (name,), s, k))


def aval_bytes(aval) -> int:
    """HBM bytes of one abstract value (0 for non-array avals)."""
    dtype = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    if dtype is None or shape is None:
        return 0
    try:
        return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    except TypeError:       # symbolic dims — out of the byte model's scope
        return 0


def out_bytes(eqn) -> int:
    """Total bytes of an equation's outputs — the materialization model's
    unit of account (one equation == one XLA-visible intermediate)."""
    return sum(aval_bytes(v.aval) for v in eqn.outvars)


def collective_axes(eqn) -> Tuple[str, ...]:
    """The mesh axis names a collective runs over, normalized to a tuple
    of strings (``axes`` on psum-family, ``axis_name`` on gather-family);
    empty when the equation is not a named-axis collective."""
    ax = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if ax is None:
        return ()
    if isinstance(ax, (tuple, list, frozenset, set)):
        return tuple(str(a) for a in ax)
    return (str(ax),)
