"""The audited entry points — one :class:`ProgramRecord` per fused
serving program whose contract CI pins.

Every record is produced by tracing the REAL entry point (the serving
wrappers' own ``_prepare_*`` front halves, or the jitted engine bodies
with the wrappers' own resolved statics) over a deterministic toy world
built here: 768×16 blobs-free Gaussian data, seeded builds, an 8-device
CPU mesh — small enough that the whole registry traces in well under a
minute with ``JAX_PLATFORMS=cpu``, large enough that every staging level
(pjit → shard_map → scan → pallas_call) appears in the jaxprs. Tracing is
abstract: nothing here needs a TPU, and only the cached-program census
executes host-side Python (it compares PREPARED programs, never runs
them).

The contract snapshot pins the audit at THIS geometry. That is the
point: the hazards the passes catch — a wide collective, a materialized
(qcap, max_list) tile, a dropped donation, a value-derived static — are
*shape-pattern* regressions visible at any scale, so a toy-geometry trace
catches them at CI speed while the bench rounds keep measuring the real
ones.

Kernel-mode entries trace with ``pallas_interpret=True`` — the identical
program modulo the interpret flag, which changes how the ``pallas_call``
executes, not what the surrounding jaxpr materializes, ships, or donates.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from raft_tpu.analysis.program.passes import ProgramRecord

_NQ, _D, _N, _K, _P, _QCAP, _LISTS = 16, 16, 768, 4, 4, 8, 16


def _leaf_key(args) -> tuple:
    """Shape/dtype signature of a prepared operand pytree — together
    with the prepared function's identity this keys the compiled
    program, so equal keys == zero retraces."""
    import jax

    return tuple(
        (tuple(a.shape), str(a.dtype))
        for a in jax.tree_util.tree_leaves(args)
        if hasattr(a, "shape")
    )


def flip_census(prepare: Callable[..., tuple], flips: List[dict]) -> int:
    """The ``program-count`` census: prepare (never dispatch) the serving
    program under every runtime-value flip and count distinct
    (program identity, operand avals) pairs. The zero-retrace contract
    says this is 1 — a 2 means some static was derived from a runtime
    value and a health/failover/mutation flip would recompile."""
    keys = set()
    for kw in flips:
        fn, args, _ = prepare(**kw)
        keys.add((id(fn), _leaf_key(args)))
    return len(keys)


def donated_leaves(traced) -> List[int]:
    """Flat indices of donated input leaves from a ``jax.stages.Traced``
    (what the runtime will actually alias, not what the caller asked)."""
    import jax

    info = traced.lower().args_info
    return [
        i for i, a in enumerate(jax.tree_util.tree_leaves(info))
        if getattr(a, "donated", False)
    ]


def record_from_traced(name: str, traced, meta: dict, *,
                       program_count: Optional[int] = None,
                       donation: bool = True) -> ProgramRecord:
    return ProgramRecord(
        name=name,
        jaxpr=traced.jaxpr,
        meta=meta,
        donated=donated_leaves(traced) if donation else None,
        program_count=program_count,
    )


# -- the toy world -----------------------------------------------------------


class _World:
    """Deterministic toy indexes + meshes, built lazily and cached for
    the process (the audit runs once per CI invocation)."""

    _inst = None

    @classmethod
    def get(cls) -> "_World":
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst

    def __init__(self):
        rng = np.random.default_rng(7)
        self.x = rng.standard_normal((_N, _D)).astype(np.float32)
        self.q = rng.standard_normal((_NQ, _D)).astype(np.float32)
        self._cache: Dict[str, object] = {}

    def _memo(self, key: str, make):
        if key not in self._cache:
            self._cache[key] = make()
        return self._cache[key]

    @property
    def flat_index(self):
        from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build

        return self._memo("flat", lambda: ivf_flat_build(
            self.x, IVFFlatParams(n_lists=_LISTS, kmeans_n_iters=3, seed=0)
        ))

    @property
    def pq_index(self):
        from raft_tpu.spatial.ann import IVFPQParams, ivf_pq_build

        return self._memo("pq", lambda: ivf_pq_build(
            self.x, IVFPQParams(
                n_lists=_LISTS, pq_dim=4, pq_bits=4, kmeans_n_iters=3,
                pq_kmeans_n_iters=3, seed=0,
            )
        ))

    @property
    def sq_index(self):
        from raft_tpu.spatial.ann import IVFSQParams, ivf_sq_build

        return self._memo("sq", lambda: ivf_sq_build(
            self.x, IVFSQParams(n_lists=_LISTS, kmeans_n_iters=3, seed=0)
        ))

    @property
    def graph_index(self):
        from raft_tpu.spatial.ann import GraphParams, graph_build

        return self._memo("graph", lambda: graph_build(
            self.x, GraphParams(degree=8, seed=0)
        ))

    @property
    def comms(self):
        import jax

        from raft_tpu.comms import build_comms

        return self._memo("comms", lambda: build_comms(jax.devices()[:8]))

    @property
    def hier_comms(self):
        import jax

        from raft_tpu.comms import build_comms_hierarchical

        return self._memo("hier", lambda: build_comms_hierarchical(
            jax.devices()[:8], mesh_shape=(2, 4)
        ))

    @property
    def mnmg_pq(self):
        from raft_tpu.comms import mnmg_ivf_pq_build
        from raft_tpu.spatial.ann import IVFPQParams

        return self._memo("mnmg_pq", lambda: mnmg_ivf_pq_build(
            self.comms, self.x, IVFPQParams(
                n_lists=_LISTS, pq_dim=4, pq_bits=4, kmeans_n_iters=3,
                pq_kmeans_n_iters=3, seed=0,
            )
        ))

    @property
    def mnmg_flat(self):
        from raft_tpu.comms import mnmg_ivf_flat_build
        from raft_tpu.spatial.ann import IVFFlatParams

        return self._memo("mnmg_flat", lambda: mnmg_ivf_flat_build(
            self.comms, self.x,
            IVFFlatParams(n_lists=_LISTS, kmeans_n_iters=3, seed=0),
            metric="sqeuclidean",
        ))

    def mutation_state(self, index, salt: int = 0):
        """A placed mutation state for ``index``; ``salt`` perturbs
        VALUES only (one tombstone flipped) — the flip-census input."""
        from raft_tpu.comms.mnmg_mutation import wrap_mnmg_mutable

        m = self._memo(
            f"mut{id(index)}",
            lambda: wrap_mnmg_mutable(self.comms, index, delta_cap=2),
        )
        if not salt:
            return m.state
        import dataclasses as dc

        import jax.numpy as jnp

        rm = np.asarray(m.state.row_mask).copy()
        rm[0, salt % rm.shape[1]] = 0
        return dc.replace(m.state, row_mask=jnp.asarray(rm))


# -- single-chip engine traces (shared with warmup(audit=True)) --------------


def trace_flat_grouped(index, nq: int, k: int, n_probes: int, qcap: int,
                       *, list_block: int = 8, use_pallas: bool = False,
                       rerank_ratio: float = 4.0, dequant=None,
                       name: str = "ivf_flat_grouped",
                       extra_meta: Optional[dict] = None) -> ProgramRecord:
    """Trace the ONE grouped scan body (flat / SQ mode) with the serving
    wrapper's statics — the audit twin of ``ivf_flat_search_grouped`` /
    ``ivf_sq_search_grouped`` at an explicit serving qcap."""
    import jax.numpy as jnp

    from raft_tpu.spatial.ann.ivf_flat import _grouped_impl

    q0 = jnp.zeros((nq, index.centroids.shape[1]), jnp.float32)
    # the wrapper's own clamp — audited statics == served statics
    list_block = max(1, min(list_block, index.storage.list_index.shape[0]))
    traced = _grouped_impl.trace(
        index, q0, k, n_probes, qcap, list_block,
        use_pallas=use_pallas, pallas_interpret=True,
        rerank_ratio=float(rerank_ratio), dequant=dequant,
    )
    meta = {
        "nq": nq, "k": k, "n_probes": n_probes, "qcap": qcap,
        "max_list": int(index.storage.max_list),
        "engine": "pallas" if use_pallas else "xla",
        "allow_wide_tile": not use_pallas,
    }
    meta.update(extra_meta or {})
    return record_from_traced(name, traced, meta)


def trace_pq_grouped(index, nq: int, k: int, n_probes: int, qcap: int,
                     *, list_block: int = 8, refine_ratio: float = 2.0,
                     exact_selection: bool = True,
                     approx_recall_target: float = 0.95,
                     use_pallas: bool = False,
                     name: str = "ivf_pq_grouped",
                     extra_meta: Optional[dict] = None) -> ProgramRecord:
    """Trace the grouped ADC body with the serving wrapper's statics —
    the audit twin of ``ivf_pq_search_grouped``."""
    import jax.numpy as jnp

    from raft_tpu.spatial.ann.ivf_pq import _pq_grouped_impl

    q0 = jnp.zeros((nq, index.centroids.shape[1]), jnp.float32)
    # the wrapper's own clamp — audited statics == served statics
    list_block = max(1, min(list_block, index.centroids.shape[0]))
    traced = _pq_grouped_impl.trace(
        index, q0, k, n_probes, qcap, list_block, float(refine_ratio),
        None, None, exact_selection, approx_recall_target,
        use_pallas=use_pallas, pallas_interpret=True,
    )
    meta = {
        "nq": nq, "k": k, "n_probes": n_probes, "qcap": qcap,
        "max_list": int(index.storage.max_list),
        "engine": "pallas" if use_pallas else "xla",
        "allow_wide_tile": not use_pallas,
    }
    meta.update(extra_meta or {})
    return record_from_traced(name, traced, meta)


def trace_graph_beam(index, nq: int, k: int, beam: int, iters: int,
                     hash_bits: int, *, with_mask: bool = False,
                     use_pallas: bool = False,
                     pallas_interpret: bool = True,
                     name: str = "graph_beam",
                     extra_meta: Optional[dict] = None) -> ProgramRecord:
    """Trace the ONE beam-search body with the serving wrapper's statics
    — the audit twin of ``graph_search`` / ``GraphIndex.warmup``
    (spatial/ann/graph.py). ``with_mask`` traces the tombstone variant
    (the ``row_mask`` runtime operand in the signature)."""
    import jax.numpy as jnp

    from raft_tpu.spatial.ann.graph import _beam_impl, graph_live_mask

    q0 = jnp.zeros((nq, index.data_padded.shape[1]), jnp.float32)
    mask = graph_live_mask(index) if with_mask else None
    traced = _beam_impl.trace(
        index, q0, k, beam, iters, hash_bits, mask,
        use_pallas=use_pallas, pallas_interpret=pallas_interpret,
    )
    meta = {
        "nq": nq, "k": k, "beam": beam, "iters": iters,
        "hash_bits": hash_bits, "degree": int(index.storage.degree),
        "engine": "pallas" if use_pallas else "xla", "graph": True,
    }
    meta.update(extra_meta or {})
    return record_from_traced(name, traced, meta)


def _trace_fn(fn, *args, **kw):
    """``Traced`` for jitted fns (their own ``.trace``) or a make_jaxpr
    shim for plain functions (donation then unavailable). The shim
    traces over the FIRST argument only (the query batch) and closes
    over the rest, so Python-int statics stay concrete — exactly how
    the fused bodies call these helpers."""
    import jax

    if hasattr(fn, "trace"):
        return fn.trace(*args, **kw)

    class _Shim:
        jaxpr = jax.make_jaxpr(
            lambda q: fn(q, *args[1:], **kw)
        )(args[0])

    return _Shim()


# -- the registry ------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    name: str
    description: str
    build: Callable[[_World, bool], ProgramRecord]


def _spec(name, description):
    def deco(f):
        SPECS.append(ProgramSpec(name, description, f))
        return f
    return deco


SPECS: List[ProgramSpec] = []


@_spec("ivf_flat_grouped_pallas",
       "single-chip grouped flat scan, Pallas sub-chunk-min engine")
def _flat_pallas(w: _World, count: bool) -> ProgramRecord:
    return trace_flat_grouped(
        w.flat_index, _NQ, _K, _P, _QCAP, use_pallas=True,
        name="ivf_flat_grouped_pallas",
    )


@_spec("ivf_flat_grouped_xla",
       "single-chip grouped flat scan, legacy XLA engine (bit-stable "
       "fallback; its wide tile is intentional and pinned)")
def _flat_xla(w: _World, count: bool) -> ProgramRecord:
    return trace_flat_grouped(
        w.flat_index, _NQ, _K, _P, _QCAP, use_pallas=False,
        name="ivf_flat_grouped_xla",
    )


@_spec("ivf_pq_grouped_pallas",
       "single-chip grouped ADC scan + exact refine, Pallas engine")
def _pq_pallas(w: _World, count: bool) -> ProgramRecord:
    return trace_pq_grouped(
        w.pq_index, _NQ, _K, _P, _QCAP, use_pallas=True,
        name="ivf_pq_grouped_pallas",
    )


@_spec("ivf_pq_grouped_onehot",
       "single-chip grouped ADC scan, legacy one-hot XLA engine — the "
       "program-level pin of the AST-suppressed adc-gather site")
def _pq_onehot(w: _World, count: bool) -> ProgramRecord:
    return trace_pq_grouped(
        w.pq_index, _NQ, _K, _P, _QCAP, use_pallas=False,
        name="ivf_pq_grouped_onehot",
    )


@_spec("ivf_pq_per_query",
       "per-query ADC path (block_q-bounded LUT gather) — the "
       "program-level pin of the AST-suppressed adc-gather site")
def _pq_per_query(w: _World, count: bool) -> ProgramRecord:
    import jax.numpy as jnp

    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search

    q0 = jnp.zeros((_NQ, _D), jnp.float32)
    traced = _trace_fn(
        ivf_pq_search, w.pq_index, q0, _K,
        n_probes=_P, refine_ratio=2.0, block_q=8,
    )
    return record_from_traced(
        "ivf_pq_per_query", traced,
        {"nq": _NQ, "k": _K, "n_probes": _P, "block_q": 8,
         "max_list": int(w.pq_index.storage.max_list),
         "engine": "xla", "allow_wide_tile": True},
    )


@_spec("ivf_sq_grouped_pallas",
       "single-chip grouped SQ scan, int8 in-kernel dequant engine")
def _sq_pallas(w: _World, count: bool) -> ProgramRecord:
    import jax.numpy as jnp

    from raft_tpu.spatial.ann.ivf_sq import _flat_view

    sq = w.sq_index
    return trace_flat_grouped(
        _flat_view(sq), _NQ, _K, _P, _QCAP, use_pallas=True,
        dequant=(jnp.asarray(sq.vmin, jnp.float32),
                 jnp.asarray(sq.vscale, jnp.float32)),
        name="ivf_sq_grouped_pallas",
        extra_meta={"int8_slab": True},
    )


@_spec("ivf_flat_grouped_tiered",
       "single-chip grouped flat scan over the hot-tier slot view "
       "(raft_tpu/tier, docs/tiering.md) — tier membership as runtime "
       "operands; the promotion/demotion/tombstone flip census runs "
       "here")
def _flat_tiered(w: _World, count: bool) -> ProgramRecord:
    import jax.numpy as jnp

    from raft_tpu.obs.metrics import MetricRegistry
    from raft_tpu.spatial.ann.ivf_flat import _grouped_impl
    from raft_tpu.tier import TieredListStore

    store = w._memo("tier", lambda: TieredListStore(
        w.flat_index, n_slots=4, name="audit-tier",
        registry=MetricRegistry(),
    ))
    q0 = jnp.zeros((_NQ, _D), jnp.float32)
    lb = max(1, min(8, _LISTS))

    def prep(hot, dead=None):
        # membership flips are HOST transactions on the store; the
        # census asks whether the program each published snapshot
        # prepares is always the same one (offsets/sizes/ids/data/mask
        # are runtime operands — promote/demote/tombstone must never
        # retrace)
        store.demote(list(range(_LISTS)))
        if hot:
            store.promote(hot)
        if dead is not None:
            with store._install:    # a tombstone VALUE flip
                store._mask_np = store._mask_np.copy()
                store._mask_np[dead] = 0
                store._publish()
        snap = store.runtime()["tier"]
        args = (snap.view, q0, _K, _P, _QCAP, lb, None, None,
                snap.row_mask)
        return _grouped_impl, args, None

    flips = [dict(hot=(0, 1, 2, 3)), dict(hot=(4, 5)), dict(hot=()),
             dict(hot=(0, 1, 2, 3), dead=5)]
    fn, args, _ = prep(**flips[0])
    traced = fn.trace(*args, use_pallas=False, pallas_interpret=False)
    return record_from_traced(
        "ivf_flat_grouped_tiered", traced,
        {"nq": _NQ, "k": _K, "n_probes": _P, "qcap": _QCAP,
         "n_slots": 4,
         "max_list": int(w.flat_index.storage.max_list),
         "tiered": True, "engine": "xla", "allow_wide_tile": True},
        program_count=flip_census(prep, flips) if count else None,
    )


@_spec("graph_beam",
       "graph-ANN one-dispatch beam search (fixed-degree adjacency, "
       "bounded-hash visited set, exact f32 rerank tail) — the "
       "tombstone delete/restore (upsert-by-restore) flip census runs "
       "here; health/route flips never reach this program's operands, "
       "so the census covers every runtime input it has")
def _graph_beam(w: _World, count: bool) -> ProgramRecord:
    import jax.numpy as jnp

    from raft_tpu.spatial.ann.graph import _beam_impl

    gi = w.graph_index
    q0 = jnp.zeros((_NQ, _D), jnp.float32)
    beam, iters, hb = 8, 8, 12

    def prep(dead=(), restored=()):
        # tombstone VALUE flips only — delete a row, delete another,
        # restore the first (the upsert-by-restore mutation cycle);
        # every entry must prepare the SAME program
        rm = np.ones((_N,), np.int8)
        rm[list(dead)] = 0
        rm[list(restored)] = 1
        args = (gi, q0, _K, beam, iters, hb, jnp.asarray(rm))
        return _beam_impl, args, None

    flips = [dict(), dict(dead=(5,)), dict(dead=(5, 11)),
             dict(dead=(5, 11), restored=(5,))]
    fn, args, _ = prep(**flips[0])
    traced = fn.trace(*args, use_pallas=False, pallas_interpret=False)
    return record_from_traced(
        "graph_beam", traced,
        {"nq": _NQ, "k": _K, "beam": beam, "iters": iters,
         "hash_bits": hb, "degree": int(gi.storage.degree),
         "engine": "xla", "graph": True, "mutation": True},
        program_count=flip_census(prep, flips) if count else None,
    )


@_spec("two_level_probe_kernel",
       "fused two-level coarse probe, kernelized through the shared "
       "scan core")
def _two_level(w: _World, count: bool) -> ProgramRecord:
    import jax.numpy as jnp

    from raft_tpu.spatial.ann.common import (
        build_coarse_index, two_level_probe,
    )

    coarse = w._memo("coarse", lambda: build_coarse_index(
        w.flat_index.centroids, n_super=4, kmeans_n_iters=3, seed=0
    ))
    q0 = jnp.zeros((_NQ, _D), jnp.float32)
    traced = _trace_fn(
        two_level_probe, q0, coarse.super_cents, coarse.member_ids,
        coarse.cents_padded, coarse.n_cents, _P, 2,
        use_pallas=True, pallas_interpret=True,
    )
    return record_from_traced(
        "two_level_probe_kernel", traced,
        {"nq": _NQ, "n_probes": _P, "n_super": int(coarse.n_super),
         "max_members": int(coarse.max_members), "engine": "pallas"},
        donation=False,
    )


def _mnmg_flips(w: _World, index, mutation: bool):
    """The zero-retrace flip matrix: health up / one rank down /
    failover route VALUE flipped (rank 3's shard routed to the -1
    "unserved" sentinel — a real degraded state on an unreplicated
    index, and crucially a different VALUE so a static derived from the
    route would prepare a different program) / healed, and (mutation
    tier) a tombstone value flipped — every entry must prepare the SAME
    program."""
    down = np.ones((8,), np.int32)
    down[3] = 0
    route = np.zeros((8,), np.int32)
    route_flip = np.zeros((8,), np.int32)
    route_flip[3] = -1
    base = dict(shard_mask=np.ones((8,), np.int32), failover=route)
    if mutation:
        base["mutation"] = w.mutation_state(index, 0)
    flips = [dict(base)]
    flips.append({**base, "shard_mask": down})
    flips.append({**base, "shard_mask": down, "failover": route_flip})
    if mutation:
        flips.append({**base, "mutation": w.mutation_state(index, 5)})
    return flips


@_spec("mnmg_pq_fused",
       "sharded IVF-PQ fused one-dispatch program (flat 8-chip mesh, "
       "Pallas shard-local engine, donated serving queries)")
def _mnmg_pq(w: _World, count: bool) -> ProgramRecord:
    from raft_tpu.comms.mnmg_ivf import _prepare_pq_search

    kw = dict(n_probes=_P, qcap=_QCAP, refine_ratio=2.0,
              use_pallas=True, donate_queries=True)
    fn, args, _ = _prepare_pq_search(w.comms, w.mnmg_pq, w.q, _K, **kw)
    traced = fn.trace(*args)
    return record_from_traced(
        "mnmg_pq_fused", traced,
        {"nq": _NQ, "k": _K, "n_probes": _P, "qcap": _QCAP,
         "max_list": int(w.mnmg_pq.max_list), "engine": "pallas",
         "expect_donated_queries": True},
    )


@_spec("mnmg_pq_fused_failover_mutation",
       "sharded IVF-PQ resilient+mutation variant — health, failover "
       "route, tombstones and delta slabs as runtime inputs; the "
       "zero-retrace census runs its flip matrix here")
def _mnmg_pq_failover(w: _World, count: bool) -> ProgramRecord:
    from raft_tpu.comms.mnmg_ivf import _prepare_pq_search

    def prep(shard_mask, failover, mutation):
        return _prepare_pq_search(
            w.comms, w.mnmg_pq, w.q, _K, n_probes=_P, qcap=_QCAP,
            refine_ratio=2.0, use_pallas=True, shard_mask=shard_mask,
            failover=failover, mutation=mutation,
        )

    flips = _mnmg_flips(w, w.mnmg_pq, mutation=True)
    fn, args, _ = prep(**flips[0])
    traced = fn.trace(*args)
    return record_from_traced(
        "mnmg_pq_fused_failover_mutation", traced,
        {"nq": _NQ, "k": _K, "n_probes": _P, "qcap": _QCAP,
         "max_list": int(w.mnmg_pq.max_list), "engine": "pallas",
         "degraded": True, "mutation": True},
        program_count=flip_census(prep, flips) if count else None,
    )


@_spec("mnmg_flat_fused",
       "sharded IVF-Flat fused one-dispatch program (flat 8-chip mesh, "
       "Pallas shard-local engine, donated serving queries)")
def _mnmg_flat(w: _World, count: bool) -> ProgramRecord:
    from raft_tpu.comms.mnmg_ivf_flat import _prepare_flat_family

    fn, args, _ = _prepare_flat_family(
        w.comms, w.mnmg_flat, w.q, _K, sq=False, n_probes=_P,
        qcap=_QCAP, list_block=8, qcap_max_drop_frac=None,
        donate_queries=True, shard_mask=None, failover=None,
        overprobe=2.0, merge_ways=None, mutation=None, wire="bf16",
        use_pallas=True, rerank_ratio=4.0,
    )
    traced = fn.trace(*args)
    return record_from_traced(
        "mnmg_flat_fused", traced,
        {"nq": _NQ, "k": _K, "n_probes": _P, "qcap": _QCAP,
         "max_list": int(w.mnmg_flat.max_list), "engine": "pallas",
         "expect_donated_queries": True},
    )


@_spec("mnmg_flat_fused_failover_mutation",
       "sharded IVF-Flat resilient+mutation variant with its "
       "zero-retrace flip census")
def _mnmg_flat_failover(w: _World, count: bool) -> ProgramRecord:
    from raft_tpu.comms.mnmg_ivf_flat import _prepare_flat_family

    def prep(shard_mask, failover, mutation):
        return _prepare_flat_family(
            w.comms, w.mnmg_flat, w.q, _K, sq=False, n_probes=_P,
            qcap=_QCAP, list_block=8, qcap_max_drop_frac=None,
            donate_queries=False, shard_mask=shard_mask,
            failover=failover, overprobe=2.0, merge_ways=None,
            mutation=mutation, wire="bf16", use_pallas=True,
            rerank_ratio=4.0,
        )

    flips = _mnmg_flips(w, w.mnmg_flat, mutation=True)
    fn, args, _ = prep(**flips[0])
    traced = fn.trace(*args)
    return record_from_traced(
        "mnmg_flat_fused_failover_mutation", traced,
        {"nq": _NQ, "k": _K, "n_probes": _P, "qcap": _QCAP,
         "max_list": int(w.mnmg_flat.max_list), "engine": "pallas",
         "degraded": True, "mutation": True},
        program_count=flip_census(prep, flips) if count else None,
    )


@_spec("mnmg_pq_hier_merge",
       "sharded IVF-PQ on the 2x4 host-sim mesh — the hierarchical "
       "ICI x DCN merge tail with the compressed bf16+id wire")
def _mnmg_hier(w: _World, count: bool) -> ProgramRecord:
    from raft_tpu.comms.mnmg_ivf import _prepare_pq_search
    from raft_tpu.comms.multihost import hier_axes

    comms = w.hier_comms
    h = hier_axes(comms.mesh, comms.axis)
    fn, args, _ = _prepare_pq_search(
        comms, w.mnmg_pq, w.q, _K, n_probes=_P, qcap=_QCAP,
        refine_ratio=2.0, use_pallas=True, wire="bf16",
    )
    traced = fn.trace(*args)
    return record_from_traced(
        "mnmg_pq_hier_merge", traced,
        {"nq": _NQ, "k": _K, "n_probes": _P, "qcap": _QCAP,
         "max_list": int(w.mnmg_pq.max_list), "engine": "pallas",
         "dcn_axes": (h[0],), "dcn_wire": "bf16", "n_hosts": h[2]},
    )


def audit_all(*, count: bool = True, names=None) -> Dict[str, ProgramRecord]:
    """Build every (or the named subset of) registry record. Tracing
    only — nothing dispatches to devices."""
    w = _World.get()
    out: Dict[str, ProgramRecord] = {}
    for spec in SPECS:
        if names is not None and spec.name not in names:
            continue
        out[spec.name] = spec.build(w, count)
    return out
