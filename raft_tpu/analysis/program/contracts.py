"""Contract snapshot + drift check — the program tier's baseline file.

``ci/checks/program_contracts.json`` pins one contract per audited entry
point (:mod:`registry`): its collective census, materialization model,
dtype-cast census, donated buffers, and cached-program count. The
discipline is ``jaxlint_baseline.json``'s, applied to programs:

* CI audits the LIVE programs and fails on any pass finding — the hard
  gate, no snapshot consulted;
* then it diffs the live contracts against the committed snapshot and
  fails on ANY drift, in either direction: a changed field is a silent
  behavior change (e.g. the DCN merge regressed to an f32 wire — the
  bytes and dtypes move, the results do not), a missing live program is
  a stale snapshot entry, and an unsnapshotted live program is a new
  serving op landing unpinned;
* an INTENTIONAL change is re-snapshotted with
  ``python -m raft_tpu.analysis --programs --write-contracts`` and the
  diff reviewed like any baseline shrink.

``--format json`` emits the same schema as the jaxlint CLI (findings /
suppressed / baselined / checked_files / rules), so the one consumer
script parses both tiers.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from raft_tpu.analysis.engine import Finding

DEFAULT_CONTRACTS = Path("ci/checks/program_contracts.json")

_COMMENT = (
    "program contracts — jaxpr-level audit snapshots per fused serving "
    "program (raft_tpu.analysis.program); re-snapshot intentional "
    "changes with: python -m raft_tpu.analysis --programs "
    "--write-contracts"
)


def audit_programs(*, count: bool = True, names=None
                   ) -> Tuple[Dict[str, dict], List[Finding]]:
    """Trace every registry entry and run the passes: returns
    ``(live contracts by name, pass findings)``."""
    from raft_tpu.analysis.program.passes import run_passes
    from raft_tpu.analysis.program.registry import audit_all

    contracts: Dict[str, dict] = {}
    findings: List[Finding] = []
    for name, rec in audit_all(count=count, names=names).items():
        contract, fs = run_passes(rec)
        contracts[name] = contract
        findings.extend(fs)
    return contracts, findings


def load_contracts(path: Path) -> Dict[str, dict]:
    data = json.loads(Path(path).read_text())
    return data.get("programs", {})


def write_contracts(path: Path, contracts: Dict[str, dict]) -> None:
    payload = {
        "comment": _COMMENT,
        "version": 1,
        "programs": {k: contracts[k] for k in sorted(contracts)},
    }
    Path(path).write_text(json.dumps(payload, indent=2, sort_keys=True)
                          + "\n")


def _diff_lines(committed, live, prefix="") -> List[str]:
    """Human-readable leaf diffs between two contract fragments."""
    out: List[str] = []
    if isinstance(committed, dict) and isinstance(live, dict):
        for k in sorted(set(committed) | set(live)):
            out.extend(_diff_lines(
                committed.get(k, "<absent>"), live.get(k, "<absent>"),
                f"{prefix}{k}.",
            ))
        return out
    if committed != live:
        out.append(f"{prefix.rstrip('.')}: snapshot {committed!r} "
                   f"!= live {live!r}")
    return out


def check_drift(live: Dict[str, dict], committed: Dict[str, dict]
                ) -> List[Finding]:
    """Bidirectional drift findings (rule ``program-contract``)."""
    findings: List[Finding] = []

    def f(name: str, message: str) -> Finding:
        return Finding(
            path=f"<program:{name}>", line=0, col=0,
            rule="program-contract", message=message,
        )

    for name in sorted(set(committed) - set(live)):
        findings.append(f(
            name,
            "snapshotted program no longer exists in the registry — a "
            "stale contract entry silently narrows the gate; remove it "
            "(--write-contracts) or restore the entry point",
        ))
    for name in sorted(set(live) - set(committed)):
        findings.append(f(
            name,
            "live program has no committed contract — a new serving op "
            "must land pinned; snapshot it with --write-contracts and "
            "review the diff",
        ))
    for name in sorted(set(live) & set(committed)):
        diffs = _diff_lines(committed[name], live[name])
        if diffs:
            findings.append(f(
                name,
                "contract drift vs the committed snapshot ("
                + "; ".join(diffs[:6])
                + (f"; +{len(diffs) - 6} more" if len(diffs) > 6 else "")
                + ") — if intentional, re-snapshot with "
                "--write-contracts and review the diff",
            ))
    return findings


def run_program_audit(contracts_path: Optional[Path] = None, *,
                      write: bool = False, count: bool = True):
    """The CLI core: audit, then drift-check (or re-snapshot).
    Returns ``(findings, checked_count, live_contracts)``. Re-snapshot
    (``write=True``) rewrites the file but still RETURNS the pass
    findings: the hard gate holds regardless of any snapshot, so a
    violating program cannot be laundered into a green baseline by
    re-snapshotting it."""
    path = Path(contracts_path or DEFAULT_CONTRACTS)
    live, findings = audit_programs(count=count)
    if write:
        write_contracts(path, live)
        return findings, len(live), live
    committed = load_contracts(path) if path.exists() else {}
    findings = findings + check_drift(live, committed)
    return findings, len(live), live


def main_programs(args) -> int:
    """``python -m raft_tpu.analysis --programs`` — dispatched from
    :func:`raft_tpu.analysis.engine.main` after flag parsing."""
    import sys

    from raft_tpu.analysis.program.passes import ALL_PASSES
    from raft_tpu.analysis.program.registry import SPECS

    if args.list_programs:
        for s in SPECS:
            print(f"{s.name}: {s.description}")
        return 0

    findings, checked, _ = run_program_audit(
        args.contracts, write=args.write_contracts,
    )
    rule_names = [p.name for p in ALL_PASSES] + ["program-contract"]
    if args.write_contracts:
        # the snapshot is written either way, but pass findings are the
        # hard gate: a violating program must fail its own re-snapshot
        # run, not hide inside a freshly-green baseline
        for f in findings:
            print(f.render())
        print(f"program-audit: wrote {checked} contract(s) to "
              f"{args.contracts or DEFAULT_CONTRACTS}"
              + (f" — {len(findings)} pass finding(s) still gate"
                 if findings else ""))
        return 1 if findings else 0
    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "suppressed": 0,
            "baselined": 0,
            "checked_files": checked,
            "rules": rule_names,
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        print(
            f"program-audit: checked {checked} programs — "
            f"{len(findings)} finding(s)"
        )
    if findings:
        print("program-audit: FAIL", file=sys.stderr)
    return 1 if findings else 0
