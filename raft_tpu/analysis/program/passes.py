"""The program-auditor passes — jaxpr-level twins of the source rules.

Each pass consumes a :class:`ProgramRecord` (one traced serving program
plus the audit metadata its registry entry declared) and contributes

* **contract fields** — the measured facts that get snapshotted into
  ``ci/checks/program_contracts.json`` and drift-checked by CI (the
  baseline discipline of ``jaxlint_baseline.json``, applied to programs);
* **findings** — hard failures that gate CI regardless of any snapshot
  (:class:`raft_tpu.analysis.engine.Finding`, rendered like a lint hit
  with the pseudo-path ``<program:NAME>``).

The five passes (ISSUE 12):

``collective-census``
    Every named-axis collective with its axis names and per-chip payload
    bytes. Findings: a collective naming a DCN axis *together with* an
    inner axis (the program-level twin of the AST
    ``dcn-wide-collective`` rule — an inner pre-reduction exists by
    construction), and an f32 ``all_gather`` over the DCN axis in a
    program whose contract declares the compressed bf16 wire (the
    rerank-tail ``psum`` stays sanctioned: exact-recovery is f32 by
    design, docs/multihost.md).

``materialization-model``
    Peak single-equation output bytes (the largest XLA-visible
    intermediate) and a census of wide f32 distance tiles inside
    scan/while bodies — an f32 output whose trailing dims are exactly
    ``(qcap, max_list)`` is the materialized grouped-scan tile both
    Pallas engines exist to avoid (twin of
    ``wide-distance-materialize``). Pallas kernel jaxprs are skipped:
    their intermediates are VMEM refs, which is the point.

``dtype-flow``
    A census of ``convert_element_type`` edges (``"bfloat16->float32"``
    counts and friends). Findings: any 64-bit dtype in the program
    (serving programs are <= 32-bit by contract; the x64 harness runs in
    its own process), and — when the registry entry budgets it — more
    bf16→f32 upcasts than the sanctioned rerank/psum tails account for.

``donation-check``
    The donated input buffers of the LOWERED program (from
    ``Lowered.args_info``, i.e. what the runtime will actually alias) —
    a serving entry prepared with ``donate_queries=True`` whose lowering
    donates nothing silently doubles the query batch's HBM residency.

``program-count``
    The cached-program census across a runtime-value flip matrix
    (health up/down, failover routes, mutation states): the zero-retrace
    contract says every flip must resolve to the SAME prepared program
    (same compiled-function identity, same operand avals). A census > 1
    means some static was derived from a runtime value — the
    ``mutation-retrace`` hazard, observed at the program level.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from raft_tpu.analysis.engine import Finding
from raft_tpu.analysis.program.walker import (
    aval_bytes,
    collective_axes,
    out_bytes,
    walk_jaxpr,
)

# named-axis collectives that move payload bytes (axis_index moves none)
_COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "psum_scatter", "reduce_scatter", "pgather",
})

_64BIT = frozenset({"float64", "int64", "uint64", "complex128"})


@dataclasses.dataclass
class ProgramRecord:
    """One audited serving program.

    ``meta`` keys the passes read (all optional unless noted):

    * ``qcap`` / ``max_list`` — the grouped-scan tile dims the
      materialization model matches against;
    * ``allow_wide_tile`` — the entry intentionally materializes the
      tile (the legacy XLA engines, kept as bit-stable fallbacks): the
      census still counts it into the contract, but no finding fires;
    * ``dcn_axes`` — mesh axis names that cross host boundaries
      (from :func:`raft_tpu.comms.multihost.hier_axes`);
    * ``dcn_wire`` — ``"bf16"`` pins the compressed wire: an f32
      all_gather over a DCN axis becomes a finding;
    * ``expect_donated_queries`` — the entry was prepared as a serving
      dispatch (``donate_queries=True``): a lowering that donates no
      buffer becomes a finding;
    * ``max_bf16_to_f32`` — optional upcast budget for dtype-flow.
    """

    name: str
    jaxpr: object                              # ClosedJaxpr
    meta: Dict = dataclasses.field(default_factory=dict)
    donated: Optional[List[int]] = None        # flat donated leaf indices
    program_count: Optional[int] = None        # flip-matrix census

    def finding(self, rule: str, message: str) -> Finding:
        return Finding(
            path=f"<program:{self.name}>", line=0, col=0,
            rule=rule, message=message,
        )


# -- passes ------------------------------------------------------------------


def collective_census(rec: ProgramRecord):
    census: Dict[Tuple, int] = {}
    findings: List[Finding] = []
    dcn_axes = set(rec.meta.get("dcn_axes", ()))
    dcn_wire_dtypes = set()
    for site in walk_jaxpr(rec.jaxpr):
        if site.prim not in _COLLECTIVE_PRIMS:
            continue
        axes = collective_axes(site.eqn)
        payload = sum(aval_bytes(v.aval) for v in site.eqn.invars)
        dtypes = sorted({
            str(getattr(v.aval, "dtype", "?")) for v in site.eqn.invars
        })
        key = (site.prim, axes, payload, tuple(dtypes))
        census[key] = census.get(key, 0) + 1
        hits_dcn = dcn_axes and (set(axes) & dcn_axes)
        if hits_dcn and len(axes) > 1:
            findings.append(rec.finding(
                "collective-census",
                f"{site.prim} over axes {list(axes)} ships full per-chip "
                f"payloads ({payload} B) across the host boundary at "
                "deployment width — pre-reduce over the inner axis first "
                "(hierarchical_merge_select_k / hierarchical_allreduce, "
                "docs/multihost.md); the AST twin is "
                "dcn-wide-collective",
            ))
        if hits_dcn and site.prim == "all_gather":
            dcn_wire_dtypes.update(dtypes)
            if rec.meta.get("dcn_wire") == "bf16" and "float32" in dtypes:
                findings.append(rec.finding(
                    "collective-census",
                    f"all_gather over DCN axes {list(axes)} carries "
                    "float32 payload but this program's contract pins "
                    "the compressed bf16+id wire (6 B/candidate) — the "
                    "DCN stage regressed to the uncompressed format "
                    "(docs/multihost.md \"Byte accounting\")",
                ))
    contract = {
        "collectives": sorted(
            (
                {
                    "prim": prim, "axes": list(axes), "bytes": payload,
                    "dtypes": list(dtypes), "count": n,
                }
                for (prim, axes, payload, dtypes), n in census.items()
            ),
            key=lambda e: (e["prim"], e["axes"], e["bytes"], e["dtypes"]),
        ),
        "dcn_wire_dtypes": sorted(dcn_wire_dtypes),
    }
    return contract, findings


def materialization_model(rec: ProgramRecord):
    peak = 0
    wide = 0
    findings: List[Finding] = []
    qcap = rec.meta.get("qcap")
    max_list = rec.meta.get("max_list")
    for site in walk_jaxpr(rec.jaxpr):
        if site.in_kernel:
            continue                 # VMEM refs, not HBM materialization
        b = out_bytes(site.eqn)
        peak = max(peak, b)
        if not site.in_scan or qcap is None or max_list is None:
            continue
        for v in site.eqn.outvars:
            aval = v.aval
            shape = getattr(aval, "shape", ())
            dtype = str(getattr(aval, "dtype", ""))
            if (
                dtype == "float32" and len(shape) >= 3
                and tuple(shape[-2:]) == (qcap, max_list)
            ):
                wide += 1
                if not rec.meta.get("allow_wide_tile"):
                    findings.append(rec.finding(
                        "materialization-model",
                        f"{site.prim} materializes a "
                        f"{tuple(shape)} float32 tile inside a "
                        f"{'/'.join(site.path) or 'top-level'} scan body "
                        f"— the (qcap={qcap}, max_list={max_list}) "
                        "grouped distance tile round-trips HBM every "
                        "iteration; route the scan through the Pallas "
                        "sub-chunk-min engines (docs/ivf_scale.md); the "
                        "AST twin is wide-distance-materialize",
                    ))
    return {"peak_eqn_bytes": peak, "scan_wide_f32_tiles": wide}, findings


def dtype_flow(rec: ProgramRecord):
    # kernels ARE walked here: an in-kernel 64-bit dtype or cast is as
    # real as one outside (the kernel's working set), unlike the
    # materialization model where VMEM refs are not HBM intermediates
    casts: Dict[str, int] = {}
    wide64 = set()
    findings: List[Finding] = []
    for site in walk_jaxpr(rec.jaxpr):
        for v in list(site.eqn.invars) + list(site.eqn.outvars):
            d = str(getattr(getattr(v, "aval", None), "dtype", ""))
            if d in _64BIT:
                wide64.add(d)
        if site.prim != "convert_element_type":
            continue
        src = str(getattr(site.eqn.invars[0].aval, "dtype", "?"))
        dst = str(site.eqn.params.get("new_dtype", "?"))
        key = f"{src}->{dst}"
        casts[key] = casts.get(key, 0) + 1
    for d in sorted(wide64):
        findings.append(rec.finding(
            "dtype-flow",
            f"{d} value inside a serving program — serving programs are "
            "<= 32-bit by contract (the x64 pass runs in its own "
            "process, ci/run.sh x64); an unguarded wide dtype doubles "
            "operand bytes on every path it touches",
        ))
    budget = rec.meta.get("max_bf16_to_f32")
    up = casts.get("bfloat16->float32", 0)
    if budget is not None and up > budget:
        findings.append(rec.finding(
            "dtype-flow",
            f"{up} bfloat16->float32 upcasts but the contract sanctions "
            f"at most {budget} (the exact rerank / psum tails) — a bf16 "
            "intermediate is being widened outside the sanctioned tails",
        ))
    return {
        "casts": dict(sorted(casts.items())),
        "dtypes_64bit": sorted(wide64),
    }, findings


def donation_check(rec: ProgramRecord):
    findings: List[Finding] = []
    donated = rec.donated
    if rec.meta.get("expect_donated_queries") and not donated:
        findings.append(rec.finding(
            "donation-check",
            "prepared as a serving dispatch (donate_queries=True) but "
            "the lowered program donates NO input buffer — the query "
            "batch's memory is never aliased to the outputs, doubling "
            "its HBM residency per in-flight dispatch (docs/serving.md)",
        ))
    return {"donated": donated}, findings


def program_count(rec: ProgramRecord):
    findings: List[Finding] = []
    n = rec.program_count
    if n is not None and n > 1:
        findings.append(rec.finding(
            "program-count",
            f"{n} distinct programs across the runtime-value flip matrix "
            "(health / failover / mutation) — the zero-retrace contract "
            "requires ONE: some static is derived from a runtime value "
            "(the mutation-retrace hazard at program level, "
            "docs/robustness.md)",
        ))
    return {"program_count": n}, findings


@dataclasses.dataclass(frozen=True)
class AuditPass:
    name: str
    description: str
    run: Callable


ALL_PASSES: List[AuditPass] = [
    AuditPass(
        "collective-census",
        "every named-axis collective with axes + payload bytes; flags "
        "inner×outer wide collectives and an uncompressed DCN wire",
        collective_census,
    ),
    AuditPass(
        "materialization-model",
        "peak per-equation intermediate bytes; flags (qcap, max_list) "
        "f32 distance tiles materialized inside scan bodies",
        materialization_model,
    ),
    AuditPass(
        "dtype-flow",
        "convert_element_type census; flags 64-bit dtypes and "
        "over-budget bf16→f32 upcasts",
        dtype_flow,
    ),
    AuditPass(
        "donation-check",
        "donated input buffers of the lowered program; flags serving "
        "dispatches whose queries are not actually donated",
        donation_check,
    ),
    AuditPass(
        "program-count",
        "cached-program census across health/failover/mutation value "
        "flips; flags any retrace (> 1 program)",
        program_count,
    ),
]


def run_passes(rec: ProgramRecord):
    """Run every pass over one record; returns (contract, findings)."""
    contract: Dict = {"meta": {
        k: rec.meta[k]
        for k in sorted(rec.meta)
        if isinstance(rec.meta[k], (int, float, str, bool, type(None)))
    }}
    findings: List[Finding] = []
    for p in ALL_PASSES:
        frag, fs = p.run(rec)
        contract.update(frag)
        findings.extend(fs)
    return contract, findings
