"""Tier 3 of the analysis stack: the concurrency auditor.

Three cooperating parts (docs/static_analysis.md "Three tiers"):

* :mod:`~raft_tpu.analysis.threads.census` +
  :mod:`~raft_tpu.analysis.threads.rules` — the AST **static pass**: a
  per-class shared-state census feeding four lock-discipline rules
  (``unguarded-shared-state``, ``lock-in-traced-body``,
  ``blocking-call-under-lock``, ``sleep-under-lock``);
* :mod:`~raft_tpu.analysis.threads.lock_order` — the cross-module
  **acquired-while-held graph**: cycle detection plus drift discipline
  against the blessed partial order in ``ci/checks/lock_order.json``
  (CLI: ``python -m raft_tpu.analysis --threads
  [--write-lock-order]``);
* :mod:`~raft_tpu.analysis.threads.runtime` — the **runtime tracer**:
  :class:`~raft_tpu.analysis.threads.runtime.TracedLock` records
  per-thread held-lock stacks and asserts acquisitions against the
  same pinned order under real interleavings. Enabled via
  ``RAFT_TPU_LOCKCHECK=1``; zero-cost when off (production lock sites
  call :func:`~raft_tpu.analysis.threads.runtime.make_lock`, which
  hands back a plain ``threading.Lock`` unless tracing is on).

Everything here is stdlib-only — production modules (serving, obs,
resilience, spatial) import :mod:`.runtime` at module import time, so
this package must never pull in jax or the rest of the analysis
engine's rule registry eagerly.
"""

from raft_tpu.analysis.threads.runtime import (   # noqa: F401
    HoldOutlier,
    LockOrderViolation,
    TracedLock,
    assert_clean,
    clear,
    enabled,
    held_locks,
    hold_outliers,
    load_pinned_order,
    make_condition,
    make_lock,
    note_dispatch,
    observed_edges,
    pin_order,
    pinned_order,
    set_enabled,
    violations,
)

__all__ = [
    "HoldOutlier",
    "LockOrderViolation",
    "TracedLock",
    "assert_clean",
    "clear",
    "enabled",
    "held_locks",
    "hold_outliers",
    "load_pinned_order",
    "make_condition",
    "make_lock",
    "note_dispatch",
    "observed_edges",
    "pin_order",
    "pinned_order",
    "set_enabled",
    "violations",
]
