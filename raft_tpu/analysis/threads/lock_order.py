"""Cross-module lock-order graph + the ``--threads`` CLI entry point.

Builds the static **acquired-while-held** graph over every analyzed
file: an edge ``A -> B`` means some code path acquires lock ``B`` while
already holding lock ``A``. Edges come from four lexical shapes (all
resolved through the per-class census):

* nested ``with`` statements (``with self._lock: ... with
  self.hedge._lock:``);
* a call under a lock into an intra-class method that acquires an own
  lock;
* a call under a lock into a method of an attribute whose class is
  known (``self.admission.cancel_queued()`` under the executor lock
  -> ``AdmissionController._lock``), one level deep;
* metric traffic under a lock: ``.inc/.set/.add/.observe`` on a cached
  instrument handle -> the ``_Instrument._lock`` leaf, and registry
  factory calls (``reg.counter(...)``) -> ``MetricRegistry._lock``.

Graph nodes are ``ClassName.<attr>`` (constructing class, so the
``Counter``/``_Instrument`` subclass idiom maps to one node) or
``<module>.<var>`` for module-level locks.

The blessed partial order lives in ``ci/checks/lock_order.json``
(``"order"`` section) with jaxlint-baseline drift discipline: an
observed edge not implied by the blessed order, or a blessed edge no
longer backed by an observed path, is a finding until re-blessed with
``--write-lock-order``. Cycles ALWAYS fail — including during
``--write-lock-order``; a cyclic order must never be pinned. The
``"findings"`` section grandfathers thread-rule findings exactly like
``ci/checks/jaxlint_baseline.json`` does for tier 1.

The runtime tracer (:mod:`raft_tpu.analysis.threads.runtime`) loads the
same ``"order"`` section and asserts it under real interleavings.
"""

from __future__ import annotations

import ast
import json
import sys
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from raft_tpu.analysis.engine import (
    Baseline,
    FileContext,
    Finding,
    iter_py_files,
    lint_paths,
    _relpath,
)
from raft_tpu.analysis.threads.census import (
    INSTRUMENT_FACTORY_TAILS,
    ClassCensus,
    ModuleCensus,
    _self_attr,
    get_census,
)
from raft_tpu.analysis.threads.rules import THREAD_RULES

__all__ = [
    "DEFAULT_LOCK_ORDER",
    "LockGraph",
    "build_graph",
    "main_threads",
]

DEFAULT_LOCK_ORDER = Path("ci/checks/lock_order.json")

# method tails on a cached instrument handle that take the instrument's
# own lock (obs/metrics.py: every mutator is `with self._lock:`)
INSTRUMENT_METHOD_TAILS = frozenset({"inc", "set", "add", "observe"})
INSTRUMENT_NODE = "_Instrument._lock"
REGISTRY_NODE = "MetricRegistry._lock"


class LockGraph:
    """Directed acquired-while-held graph with edge provenance."""

    def __init__(self) -> None:
        self.edges: Dict[str, Set[str]] = {}
        # (src, dst) -> first "path:line" seen
        self.sites: Dict[Tuple[str, str], str] = {}

    def add(self, src: str, dst: str, site: str) -> None:
        if src == dst:
            return   # re-acquisition is the self-deadlock rule's turf
        self.edges.setdefault(src, set()).add(dst)
        self.sites.setdefault((src, dst), site)

    def edge_list(self) -> List[Tuple[str, str]]:
        return sorted((s, d) for s, dsts in self.edges.items()
                      for d in dsts)

    def to_order(self) -> Dict[str, List[str]]:
        return {s: sorted(d) for s, d in sorted(self.edges.items())}

    def cycles(self) -> List[List[str]]:
        """Every elementary cycle reachable by DFS (first-found per
        back edge — enough to name the offenders)."""
        out: List[List[str]] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        color: Dict[str, int] = {}   # 0/absent=white, 1=grey, 2=black
        stack: List[str] = []

        def visit(n: str) -> None:
            color[n] = 1
            stack.append(n)
            for m in sorted(self.edges.get(n, ())):
                c = color.get(m, 0)
                if c == 0:
                    visit(m)
                elif c == 1:
                    cyc = stack[stack.index(m):] + [m]
                    # canonicalize rotation so one cycle reports once
                    body = cyc[:-1]
                    i = body.index(min(body))
                    canon = tuple(body[i:] + body[:i])
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(list(canon) + [canon[0]])
            stack.pop()
            color[n] = 2

        for n in sorted(self.edges):
            if color.get(n, 0) == 0:
                visit(n)
        return out


def _has_path(order: Dict[str, List[str]], src: str, dst: str) -> bool:
    seen = {src}
    frontier = [src]
    while frontier:
        n = frontier.pop()
        for m in order.get(n, ()):
            if m == dst:
                return True
            if m not in seen:
                seen.add(m)
                frontier.append(m)
    return False


def _receiver_base(expr: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """Peel subscripts off a call receiver: ``self._m["q"].inc`` ->
    ``("attr", "_m")``; ``_M_FLIPS["down"].inc`` -> ``("name",
    "_M_FLIPS")``."""
    e = expr
    while isinstance(e, ast.Subscript):
        e = e.value
    attr = _self_attr(e)
    if attr is not None:
        return "attr", attr
    if isinstance(e, ast.Name):
        return "name", e.id
    return None, None


def _module_instruments(mc: ModuleCensus) -> Set[str]:
    """Module-level names whose assigned value contains a registry
    factory call (health.py's ``_M_FLIPS = {... reg.counter(...) ...}``
    idiom)."""
    out: Set[str] = set()
    for node in mc.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Call):
                c = mc.facts.callee(sub)
                if c and c.rsplit(".", 1)[-1] in INSTRUMENT_FACTORY_TAILS:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out.add(tgt.id)
                    break
    return out


def _own_acquired_nodes(census: ClassCensus) -> Dict[str, Set[str]]:
    """method -> graph node names of own locks it acquires."""
    out: Dict[str, Set[str]] = {}
    for method, _node, key in census.acquisitions:
        if key.startswith("self:"):
            name = census.module.lock_node_name(census, key)
            out.setdefault(method, set()).add(name)
    return out


def _census_edges(graph: LockGraph, mc: ModuleCensus, rel: str,
                  registry: Dict[str, ClassCensus]) -> None:
    mod_instruments = _module_instruments(mc)
    for census in list(mc.classes.values()) + [mc.toplevel]:
        own_acquired = _own_acquired_nodes(census)

        def node_name(key: str) -> str:
            return mc.lock_node_name(census, key)

        def site(node: ast.AST) -> str:
            return f"{rel}:{getattr(node, 'lineno', 1)}"

        # 1. nested with
        for _method, with_node, key in census.acquisitions:
            held = census.effective_held(with_node)
            if held:
                graph.add(node_name(held[-1]), node_name(key),
                          site(with_node))
        # 2-4. calls under a lock
        for node, _method in census.method_of.items():
            if not isinstance(node, ast.Call):
                continue
            held = census.effective_held(node)
            if not held:
                continue
            src = node_name(held[-1])
            f = node.func
            if not isinstance(f, ast.Attribute):
                # registry factory through a bare alias is handled below
                continue
            tail = f.attr
            kind, base = _receiver_base(f.value)
            # intra-class helper that acquires an own lock
            callee_attr = _self_attr(f)
            if callee_attr is not None and callee_attr in census.methods:
                for dst in own_acquired.get(callee_attr, ()):
                    # only an edge if the callee acquires a DIFFERENT
                    # lock than what is already held (nested-with case
                    # 1 covers the body; this covers the call site)
                    graph.add(src, dst, site(node))
                continue
            # instrument-handle mutator -> the instrument leaf lock
            if tail in INSTRUMENT_METHOD_TAILS and (
                    (kind == "attr" and base in census.instrument_attrs)
                    or (kind == "name" and base in mod_instruments)):
                graph.add(src, INSTRUMENT_NODE, site(node))
                continue
            # registry factory under a lock -> the registry lock
            callee = census.facts.callee(node)
            ctail = callee.rsplit(".", 1)[-1] if callee else None
            if ctail in INSTRUMENT_FACTORY_TAILS:
                graph.add(src, REGISTRY_NODE, site(node))
                continue
            # cross-object: self.<attr>.<method>() where the attribute's
            # class is known and the method acquires its own lock
            if kind == "attr" and base in census.attr_classes:
                target = registry.get(census.attr_classes[base])
                if target is not None:
                    tacq = _own_acquired_nodes(target)
                    for dst in tacq.get(tail, ()):
                        graph.add(src, dst, site(node))


def build_graph(paths, root: Optional[Path] = None) -> LockGraph:
    """The acquired-while-held graph over every ``.py`` under *paths*."""
    root = root or Path.cwd()
    graph = LockGraph()
    censuses: List[Tuple[str, ModuleCensus]] = []
    registry: Dict[str, ClassCensus] = {}
    for f in iter_py_files(paths):
        rel = _relpath(f, root)
        try:
            tree = ast.parse(f.read_text())
        except SyntaxError:
            continue   # the lint pass reports parse errors
        ctx = FileContext(f, rel, "", tree)
        mc = get_census(ctx)
        censuses.append((rel, mc))
        for name, census in mc.classes.items():
            # first definition wins; duplicate class names across
            # modules are resolved by whoever parsed first (lexical
            # analysis — good enough for edge discovery)
            registry.setdefault(name, census)
    for rel, mc in censuses:
        _census_edges(graph, mc, rel, registry)
    return graph


# -- blessed order I/O --------------------------------------------------------


def load_order_file(path: Path) -> Tuple[Dict[str, List[str]], Baseline]:
    if not path.exists():
        return {}, Baseline()
    data = json.loads(path.read_text())
    return data.get("order", {}), Baseline(data.get("findings", {}))


def save_order_file(path: Path, graph: LockGraph,
                    findings: List[Finding]) -> None:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.baseline_key] = counts.get(f.baseline_key, 0) + 1
    payload = {
        "comment": ("blessed lock partial order + grandfathered thread "
                    "findings — regenerate with `python -m "
                    "raft_tpu.analysis --threads --write-lock-order`"),
        "version": 1,
        "order": graph.to_order(),
        "findings": dict(sorted(counts.items())),
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")


def drift_findings(graph: LockGraph, order: Dict[str, List[str]],
                   order_path: Path) -> List[Finding]:
    """New edges not implied by the blessed order, and blessed edges no
    longer backed by any observed path (transitive closure on both
    sides, matching the runtime tracer's semantics)."""
    out: List[Finding] = []
    observed = graph.to_order()
    for src, dst in graph.edge_list():
        if dst in order.get(src, ()) or _has_path(order, src, dst):
            continue
        site = graph.sites.get((src, dst), "?")
        out.append(Finding(
            path=order_path.as_posix(), line=1, col=1,
            rule="lock-order-drift",
            message=(f"new acquired-while-held edge {src} -> {dst} "
                     f"(at {site}); re-bless with --write-lock-order"),
        ))
    for src, dsts in sorted(order.items()):
        for dst in dsts:
            if _has_path(observed, src, dst):
                continue
            out.append(Finding(
                path=order_path.as_posix(), line=1, col=1,
                rule="lock-order-drift",
                message=(f"stale blessed edge {src} -> {dst} no longer "
                         "observed; re-bless with --write-lock-order"),
            ))
    return out


def cycle_findings(graph: LockGraph, order_path: Path) -> List[Finding]:
    out: List[Finding] = []
    for cyc in graph.cycles():
        first = graph.sites.get((cyc[0], cyc[1]), "?")
        out.append(Finding(
            path=order_path.as_posix(), line=1, col=1,
            rule="lock-order-cycle",
            message=(f"lock-order cycle: {' -> '.join(cyc)} "
                     f"(first edge at {first}) — a cyclic order can "
                     "deadlock and is never blessed"),
        ))
    return out


# -- CLI ----------------------------------------------------------------------


def main_threads(args) -> int:
    """The ``--threads`` tier of ``python -m raft_tpu.analysis``."""
    if args.list_rules:
        for r in THREAD_RULES:
            print(f"{r.name}: {r.description}")
        print("lock-order-drift: observed acquired-while-held edge "
              "diverges from the blessed order in lock_order.json")
        print("lock-order-cycle: the acquired-while-held graph has a "
              "cycle")
        return 0

    rules = THREAD_RULES
    if args.rules:
        wanted = {s.strip() for s in args.rules.split(",")}
        unknown = wanted - {r.name for r in THREAD_RULES}
        if unknown:
            print(f"unknown thread rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in THREAD_RULES if r.name in wanted]

    order_path = args.lock_order or DEFAULT_LOCK_ORDER
    order, baseline = load_order_file(order_path)

    paths = [Path(p) for p in args.paths]
    use_baseline = not args.no_baseline and not args.write_lock_order
    result = lint_paths(paths, rules=rules,
                        baseline=baseline if use_baseline else None)

    graph = build_graph(paths)
    cycles = cycle_findings(graph, order_path)

    if args.write_lock_order:
        if cycles:
            for f in cycles:
                print(f.render(), file=sys.stderr)
            print("jaxlint --threads: refusing to bless a cyclic order",
                  file=sys.stderr)
            return 1
        save_order_file(order_path, graph, result.findings)
        print(f"jaxlint --threads: wrote {len(graph.edge_list())} "
              f"edge(s) and {len(result.findings)} grandfathered "
              f"finding(s) to {order_path}")
        return 0

    drift = drift_findings(graph, order, order_path)
    all_out = result.parse_errors + result.findings + cycles + drift
    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in all_out],
            "suppressed": result.suppressed,
            "baselined": result.baselined,
            "checked_files": result.checked_files,
            "edges": [f"{s} -> {d}" for s, d in graph.edge_list()],
            "rules": [r.name for r in rules],
        }, indent=2))
    else:
        for f in all_out:
            print(f.render())
        print(
            f"jaxlint --threads: checked {result.checked_files} files, "
            f"{len(graph.edge_list())} lock-order edge(s) — "
            f"{len(all_out)} finding(s), {result.suppressed} suppressed, "
            f"{result.baselined} baselined"
        )
    return 0 if not all_out else 1
