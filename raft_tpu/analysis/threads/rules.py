"""Tier-3 thread rules (the static pass of the concurrency auditor).

Four checks over the per-class census
(:mod:`raft_tpu.analysis.threads.census`), registered in
``THREAD_RULES`` (their own registry: ``ci/run.sh threads`` gates them,
the tier-1 ``style`` stage is unchanged):

* ``unguarded-shared-state`` — an attribute the class demonstrably
  guards (assigned in ``__init__``, written under an own lock
  elsewhere) is read or written WITHOUT the lock;
* ``lock-in-traced-body`` — a lock acquisition inside a jit/shard_map
  traced body (it would acquire once at trace time and never guard the
  compiled program);
* ``blocking-call-under-lock`` — ``Condition.wait`` on a condition
  whose lock is NOT the one held (or while holding additional locks:
  ``wait`` parks the thread but releases only its own lock),
  ``Event.wait``, ``Future.result``, and ``Thread.join`` while holding
  a lock — each parks a thread that other threads may need the held
  lock to wake; plus the durable-IO calls — ``os.fsync`` /
  ``os.fdatasync``, and ``.flush()`` on a receiver the census knows is
  a FILE handle (any object may grow a cheap ``flush()``) — which park
  the holder behind the DISK (the WAL group-commit contract: acks are
  taken under the lock, the fsync batch runs outside it,
  docs/robustness.md "Durability");
* ``sleep-under-lock`` — ``time.sleep`` while holding a lock
  serializes every contender behind a timer.

Suppression and baselining follow jaxlint: ``# jaxlint:
disable=<rule>`` inline, counts grandfathered in the ``findings``
section of ``ci/checks/lock_order.json``
(docs/static_analysis.md "Three tiers").
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from raft_tpu.analysis.rules import Rule
from raft_tpu.analysis.threads.census import (
    ClassCensus,
    get_census,
    _self_attr,
)

__all__ = ["THREAD_RULES"]


def _censuses(ctx) -> List[ClassCensus]:
    mc = get_census(ctx)
    return list(mc.classes.values()) + [mc.toplevel]


class UnguardedSharedState(Rule):
    name = "unguarded-shared-state"
    description = (
        "an attribute the class guards with a lock elsewhere is "
        "read/written without holding it"
    )

    def check(self, ctx) -> Iterator:
        for census in _censuses(ctx):
            if not census.guarded:
                continue
            for method, node, attr, kind in census.accesses:
                if attr not in census.guarded:
                    continue
                if census.own_locks_held(node):
                    continue
                lock = next(iter(census.locks.values()), "_lock")
                yield ctx.finding(
                    self.name, node,
                    f"{census.name}.{attr} is guarded (written under "
                    f"self.{lock} elsewhere) but {kind} in "
                    f"{method}() without the lock",
                )


class LockInTracedBody(Rule):
    name = "lock-in-traced-body"
    description = (
        "lock acquired inside a jit/shard_map traced body (locks once "
        "at trace time, guards nothing at run time)"
    )

    def check(self, ctx) -> Iterator:
        traced: Set[ast.AST] = set()
        for fn in ctx.facts.traced:
            traced.update(ctx.facts.traced_body_nodes(fn))
        if not traced:
            return
        for census in _censuses(ctx):
            for _method, with_node, key in census.acquisitions:
                if with_node in traced:
                    yield ctx.finding(
                        self.name, with_node,
                        f"lock {key.split(':', 1)[1]} acquired inside "
                        "a traced body",
                    )
            for node in census.method_of:
                if node not in traced or not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "acquire" \
                        and census.lock_key(f.value) is not None:
                    yield ctx.finding(
                        self.name, node,
                        "lock .acquire() inside a traced body",
                    )


class BlockingCallUnderLock(Rule):
    name = "blocking-call-under-lock"
    description = (
        "Condition.wait on a foreign lock, Event.wait, Future.result, "
        "Thread.join, or durable IO (os.fsync/os.fdatasync, file "
        ".flush) while holding a lock"
    )

    def check(self, ctx) -> Iterator:
        for census in _censuses(ctx):
            aliases = self._thread_aliases(census)
            file_aliases = self._file_aliases(ctx, census)
            for node, method in census.method_of.items():
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if not isinstance(f, ast.Attribute):
                    continue
                held = census.effective_held(node)
                if not held:
                    continue
                tail = f.attr
                recv_attr = _self_attr(f.value)
                if tail in ("wait", "wait_for"):
                    yield from self._check_wait(
                        ctx, census, node, method, recv_attr, held)
                elif tail == "result":
                    yield ctx.finding(
                        self.name, node,
                        f"Future.result() while holding "
                        f"{self._chain(held)} in {method}()",
                    )
                elif tail == "join":
                    is_thread = recv_attr in census.thread_attrs
                    if not is_thread and isinstance(f.value, ast.Name):
                        is_thread = f.value.id in aliases.get(method,
                                                              set())
                    if is_thread:
                        yield ctx.finding(
                            self.name, node,
                            f"Thread.join() while holding "
                            f"{self._chain(held)} in {method}()",
                        )
                elif tail in ("fsync", "fdatasync"):
                    callee = ctx.facts.callee(node)
                    if callee in ("os.fsync", "os.fdatasync"):
                        yield ctx.finding(
                            self.name, node,
                            f"{callee}() while holding "
                            f"{self._chain(held)} in {method}() — "
                            "fsync outside the lock, publish the "
                            "durable LSN under it",
                        )
                elif tail == "flush":
                    # file receivers ONLY (mirroring the fsync callee
                    # check): any object may grow a cheap .flush() —
                    # buffers, queues, loggers — and flagging those
                    # would fail the gate on non-IO code
                    is_file = recv_attr in census.file_attrs
                    if not is_file and isinstance(f.value, ast.Name):
                        is_file = f.value.id in file_aliases.get(
                            method, set())
                    if is_file:
                        yield ctx.finding(
                            self.name, node,
                            f".flush() while holding "
                            f"{self._chain(held)} in {method}() — "
                            "the holder parks behind the disk",
                        )

    def _check_wait(self, ctx, census, node, method, recv_attr, held):
        if recv_attr in census.event_attrs:
            yield ctx.finding(
                self.name, node,
                f"Event.wait() while holding {self._chain(held)} "
                f"in {method}()",
            )
            return
        if recv_attr not in census.locks:
            return   # unknown receiver: lexical limits
        underlying = f"self:{census.locks[recv_attr]}"
        others = [k for k in held if k != underlying]
        if underlying not in held:
            yield ctx.finding(
                self.name, node,
                f"Condition self.{recv_attr}.wait() without holding "
                f"its own lock in {method}()",
            )
        elif others:
            yield ctx.finding(
                self.name, node,
                f"Condition self.{recv_attr}.wait() releases only its "
                f"own lock; {self._chain(tuple(others))} stays held "
                f"while parked in {method}()",
            )

    @staticmethod
    def _chain(held) -> str:
        return " -> ".join(k.split(":", 1)[1] for k in held)

    @staticmethod
    def _file_aliases(ctx, census) -> Dict[str, Set[str]]:
        """Per method: local names bound to a file handle
        (``f = open(...)`` / ``f = self._file``)."""
        out: Dict[str, Set[str]] = {}
        for node, method in census.method_of.items():
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            is_file = _self_attr(node.value) in census.file_attrs
            if not is_file and isinstance(node.value, ast.Call):
                callee = ctx.facts.callee(node.value)
                tail = callee.rsplit(".", 1)[-1] if callee else None
                is_file = tail in ("open", "fdopen")
            if is_file:
                out.setdefault(method, set()).add(node.targets[0].id)
        return out

    @staticmethod
    def _thread_aliases(census) -> Dict[str, Set[str]]:
        """Per method: local names assigned from a thread attr
        (``t = self._thread``)."""
        out: Dict[str, Set[str]] = {}
        for node, method in census.method_of.items():
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _self_attr(node.value) in census.thread_attrs:
                out.setdefault(method, set()).add(node.targets[0].id)
        return out


class SleepUnderLock(Rule):
    name = "sleep-under-lock"
    description = "time.sleep while holding a lock"

    def check(self, ctx) -> Iterator:
        for census in _censuses(ctx):
            for node, method in census.method_of.items():
                if not isinstance(node, ast.Call):
                    continue
                if ctx.facts.callee(node) != "time.sleep":
                    continue
                held = census.effective_held(node)
                if held:
                    chain = BlockingCallUnderLock._chain(held)
                    yield ctx.finding(
                        self.name, node,
                        f"time.sleep() while holding {chain} in "
                        f"{method}()",
                    )


THREAD_RULES: List[Rule] = [
    UnguardedSharedState(),
    LockInTracedBody(),
    BlockingCallUnderLock(),
    SleepUnderLock(),
]
