"""The runtime lock-order tracer: :class:`TracedLock` and the
``make_lock`` / ``make_condition`` factories every production lock site
routes through.

Static analysis (census + lock-order graph, the rest of this package)
proves properties of the LEXICAL lock structure; this module checks the
same claims under real thread interleavings. With
``RAFT_TPU_LOCKCHECK=1`` in the environment (or :func:`set_enabled`),
``make_lock`` returns a :class:`TracedLock` that

* keeps a per-thread stack of held locks,
* asserts every acquisition against the pinned partial order in
  ``ci/checks/lock_order.json`` (an acquisition whose REVERSE path is
  blessed is an inversion; an edge the graph has never seen is drift),
* records lock-hold-time outliers into the
  ``lock_hold_ms{lock=...}`` histogram of the process registry
  (:mod:`raft_tpu.obs.metrics`), and
* flags hold-while-dispatch via :func:`note_dispatch` (the serving
  executor calls it immediately before handing a batch to the device —
  dispatching while holding any serving lock would serialize the
  pipeline behind the device queue).

Disabled (the default), ``make_lock`` returns a plain
``threading.Lock`` — the zero-cost-off discipline of the obs gate
(:data:`raft_tpu.obs.metrics._ENABLED`): production code pays one
function call at CONSTRUCTION time, nothing per acquisition.

Violations are recorded, not raised (a chaos test must observe ALL of
them, and a tracer that throws from ``release`` corrupts the state it
reports on); :func:`assert_clean` turns the record into a hard failure
at a point of the caller's choosing. The one exception is re-acquiring
a lock the SAME thread already holds — that is a certain deadlock on a
non-reentrant lock, so :meth:`TracedLock.acquire` raises instead of
parking the test suite forever.

stdlib-only on purpose: serving/obs/resilience modules import this at
module import time, and the metric/flight integrations are reached
lazily at violation/release time.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from pathlib import Path
from typing import Dict, Iterable, List, Mapping, Optional, Set, Tuple

__all__ = [
    "TracedLock", "LockOrderViolation", "HoldOutlier",
    "make_lock", "make_condition", "enabled", "set_enabled",
    "pin_order", "pinned_order", "load_pinned_order",
    "note_dispatch", "violations", "hold_outliers", "observed_edges",
    "clear", "assert_clean",
]

#: where the blessed partial order lives (written by
#: ``python -m raft_tpu.analysis --threads --write-lock-order``)
DEFAULT_LOCK_ORDER = (
    Path(__file__).resolve().parents[3] / "ci" / "checks"
    / "lock_order.json"
)


def _env_enabled() -> bool:
    return os.environ.get("RAFT_TPU_LOCKCHECK", "").strip().lower() in (
        "1", "true", "on", "yes",
    )


# same list-cell idiom as the obs gate: handles created before a
# set_enabled() flip must share the cell, not a stale bool
_ENABLED: List[bool] = [_env_enabled()]

#: hold times at or above this many milliseconds are recorded as
#: outliers (the histogram records EVERY hold; the outlier list is the
#: small, readable residue a test can assert on)
HOLD_OUTLIER_MS = float(
    os.environ.get("RAFT_TPU_LOCKCHECK_HOLD_MS", "50")
)
_MAX_RECORDED = 1024   # violations/outliers each; the tracer bounds
                       # its own memory like the flight recorder's ring


def enabled() -> bool:
    """Is lock tracing on? (``RAFT_TPU_LOCKCHECK`` env at import;
    :func:`set_enabled` at runtime — affects only locks constructed
    AFTER the flip, construction is the routing point.)"""
    return _ENABLED[0]


def set_enabled(on: bool) -> bool:
    """Flip the tracing gate; returns the previous state."""
    prev = _ENABLED[0]
    _ENABLED[0] = bool(on)
    return prev


# -- per-thread held stack ----------------------------------------------------

_tls = threading.local()


def _frames() -> list:
    fr = getattr(_tls, "frames", None)
    if fr is None:
        fr = _tls.frames = []
    return fr


def held_locks() -> Tuple[str, ...]:
    """Names of the locks the CALLING thread currently holds, in
    acquisition order (outermost first)."""
    fr = getattr(_tls, "frames", None)
    return tuple(f[0].name for f in fr) if fr else ()


# -- the pinned partial order -------------------------------------------------

_pinned: Dict[str, Set[str]] = {}
_pinned_loaded = [False]


def pin_order(edges: Mapping[str, Iterable[str]]) -> None:
    """Install the blessed partial order (``held -> may-acquire``
    adjacency), replacing any previous one."""
    _pinned.clear()
    for a, bs in edges.items():
        _pinned[str(a)] = {str(b) for b in bs}
    _pinned_loaded[0] = True


def pinned_order() -> Dict[str, Set[str]]:
    return {a: set(bs) for a, bs in _pinned.items()}


def load_pinned_order(path: Optional[Path] = None) -> bool:
    """Load ``ci/checks/lock_order.json`` (or ``path``); returns
    whether a file was found. Missing file pins the EMPTY order — every
    nested acquisition then reports as drift, which is the correct
    failure mode for a repo that lost its contract file."""
    p = Path(path) if path is not None else DEFAULT_LOCK_ORDER
    if not p.exists():
        _pinned_loaded[0] = True
        return False
    data = json.loads(p.read_text())
    pin_order(data.get("order", {}))
    return True


def _ensure_pinned() -> None:
    if not _pinned_loaded[0]:
        load_pinned_order()


def _has_path(a: str, b: str) -> bool:
    """Is there a pinned path a -> ... -> b?"""
    seen = set()
    stack = [a]
    while stack:
        n = stack.pop()
        if n == b:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(_pinned.get(n, ()))
    return False


# -- violation / outlier records ----------------------------------------------

@dataclasses.dataclass(frozen=True)
class LockOrderViolation:
    """One runtime ordering violation."""

    kind: str                  # "inversion" | "unpinned" |
                               # "hold-while-dispatch"
    held: Tuple[str, ...]      # the thread's stack, outermost first
    acquiring: str             # lock being acquired (or the dispatch
                               # site for hold-while-dispatch)
    thread: str

    def render(self) -> str:
        chain = " -> ".join(self.held) or "<none>"
        return (f"[{self.kind}] thread {self.thread!r}: holding "
                f"{chain}, acquiring {self.acquiring!r}")


@dataclasses.dataclass(frozen=True)
class HoldOutlier:
    """One lock held past :data:`HOLD_OUTLIER_MS`."""

    lock: str
    held_ms: float
    thread: str


_state_lock = threading.Lock()
_violations: List[LockOrderViolation] = []
_outliers: List[HoldOutlier] = []
_observed: Dict[str, Set[str]] = {}


def _feed_violation_counter(kind: str) -> None:
    try:
        from raft_tpu.obs import metrics as _m

        if _m.enabled():
            _m.default_registry().counter(
                "lock_order_violations_total", kind=kind,
            ).inc()
    except Exception:   # noqa: BLE001 — telemetry must not kill the
        pass            # tracer (mirrors the emitter's discipline)


def _record_violation(v: LockOrderViolation) -> None:
    with _state_lock:
        if len(_violations) < _MAX_RECORDED:
            _violations.append(v)
    _feed_violation_counter(v.kind)


def violations() -> List[LockOrderViolation]:
    with _state_lock:
        return list(_violations)


def hold_outliers() -> List[HoldOutlier]:
    with _state_lock:
        return list(_outliers)


def observed_edges() -> Dict[str, Set[str]]:
    """Every (held, acquired) pair actually seen at runtime — the
    evidence a chaos run contributes to the static graph."""
    with _state_lock:
        return {a: set(bs) for a, bs in _observed.items()}


def clear() -> None:
    """Reset violations, outliers, and observed edges (test setup)."""
    with _state_lock:
        _violations.clear()
        _outliers.clear()
        _observed.clear()


def assert_clean() -> None:
    """Raise ``AssertionError`` listing every recorded violation."""
    vs = violations()
    if vs:
        raise AssertionError(
            "lockcheck: %d lock-order violation(s):\n%s"
            % (len(vs), "\n".join(v.render() for v in vs))
        )


def _check_order(name: str, held: Tuple[str, ...]) -> None:
    top = held[-1]
    if name in _pinned.get(top, ()):
        pass                       # directly blessed
    elif _has_path(name, top):     # the REVERSE direction is blessed:
        _record_violation(         # a textbook inversion
            LockOrderViolation("inversion", held, name,
                               threading.current_thread().name))
        return
    elif _has_path(top, name):
        pass                       # transitively blessed
    else:
        _record_violation(
            LockOrderViolation("unpinned", held, name,
                               threading.current_thread().name))
        return
    with _state_lock:
        _observed.setdefault(top, set()).add(name)


# -- the traced lock ----------------------------------------------------------

class TracedLock:
    """A ``threading.Lock`` that records acquisition order and hold
    time. Duck-compatible with ``threading.Lock`` (``acquire`` /
    ``release`` / context manager / ``locked``), so
    ``threading.Condition(TracedLock(...))`` works: the Condition's
    release/re-acquire during ``wait`` flows through this wrapper and
    keeps the held stack truthful."""

    __slots__ = ("name", "_lock", "_hist")

    def __init__(self, name: str):
        self.name = str(name)
        self._lock = threading.Lock()
        self._hist = None   # lazily bound lock_hold_ms handle

    def acquire(self, blocking: bool = True, timeout: float = -1):
        frames = _frames()
        # order checks only on BLOCKING acquisitions: a try-lock that
        # fails simply moves on — it cannot deadlock, and Condition's
        # _is_owned probe uses acquire(False) as a matter of course
        if blocking and _ENABLED[0] and frames:
            for f in frames:
                if f[0] is self:
                    raise RuntimeError(
                        f"lockcheck: thread "
                        f"{threading.current_thread().name!r} "
                        f"re-acquiring {self.name!r} it already holds "
                        "— certain deadlock on a non-reentrant lock"
                    )
            _ensure_pinned()
            _check_order(self.name, tuple(f[0].name for f in frames))
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            frames.append((self, time.monotonic()))
        return ok

    def release(self) -> None:
        frames = getattr(_tls, "frames", None)
        t0 = None
        if frames:
            for i in range(len(frames) - 1, -1, -1):
                if frames[i][0] is self:
                    t0 = frames[i][1]
                    del frames[i]
                    break
        self._lock.release()
        if t0 is not None:
            self._note_hold((time.monotonic() - t0) * 1e3)

    def _note_hold(self, ms: float) -> None:
        if ms >= HOLD_OUTLIER_MS:
            with _state_lock:
                if len(_outliers) < _MAX_RECORDED:
                    _outliers.append(HoldOutlier(
                        self.name, ms,
                        threading.current_thread().name))
        h = self._hist
        if h is None:
            try:
                from raft_tpu.obs import metrics as _m
            except Exception:   # noqa: BLE001
                self._hist = False
                return
            h = self._hist = _m.default_registry().histogram(
                "lock_hold_ms", lock=self.name)
        if h is not False:
            h.observe(ms)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"TracedLock({self.name!r})"


# -- the factories production code routes through -----------------------------

def make_lock(name: str) -> "threading.Lock | TracedLock":
    """A lock named for the static census node (``Class.attr`` or
    ``module.var``): a :class:`TracedLock` when tracing is enabled, a
    plain ``threading.Lock`` otherwise."""
    if _ENABLED[0]:
        _ensure_pinned()
        return TracedLock(name)
    return threading.Lock()


def make_condition(lock, name: Optional[str] = None,
                   ) -> threading.Condition:
    """A ``Condition`` over ``lock`` (plain or traced). Conditions
    sharing a lock share its order node — ``wait`` releases and
    re-acquires through the same wrapper, so the held stack never
    lies about a parked thread. ``name`` is documentation only."""
    del name
    return threading.Condition(lock)


def note_dispatch(what: str = "dispatch") -> None:
    """Record a hold-while-dispatch violation if the calling thread
    holds ANY traced lock. The executor calls this immediately before
    handing a staged batch to the device; no-op (one list load) when
    tracing is off."""
    if not _ENABLED[0]:
        return
    fr = getattr(_tls, "frames", None)
    if fr:
        _record_violation(LockOrderViolation(
            "hold-while-dispatch",
            tuple(f[0].name for f in fr), what,
            threading.current_thread().name))
