"""Pass 1 of the thread tier: the per-class shared-state census.

Built once per file (cached on the :class:`~raft_tpu.analysis.engine
.FileContext` by :func:`get_census`) and shared by the thread rules
(:mod:`raft_tpu.analysis.threads.rules`) and the cross-module
lock-order graph (:mod:`raft_tpu.analysis.threads.lock_order`). For
every class it records:

* **lock attributes** — ``self.X = threading.Lock()`` / ``RLock`` /
  ``lockcheck.make_lock(...)`` in ``__init__``; Conditions
  (``threading.Condition(self._lock)`` / ``make_condition``) map to
  their UNDERLYING lock, so ``with self._work:`` and
  ``with self._lock:`` are the same census region (the executor's
  two-conditions-one-lock idiom);
* **guarded attributes** — assigned in ``__init__`` AND written at
  least once under an own lock outside ``__init__``. The write
  requirement is what keeps immutable configuration (``self.dim``,
  handles cached at init) out of the census: "read under a lock
  somewhere" proves nothing, "the class bothers to lock its writes"
  is the discipline being checked;
* **held-stack per AST node** — which own/foreign locks are lexically
  held at every node of every method (nested ``with`` aware; nested
  ``def`` bodies reset the stack — they run on another thread);
* **lock-held helpers** — a method whose intra-class call sites are
  ALL under a lock is treated as executing under it (the documented
  "under _lock" helper idiom: ``_flush_wait_s``, ``_sync_gauges``,
  ``_l1_put``), to fixpoint;
* **attribute classes** — ``self.admission`` →
  ``AdmissionController``, resolved from ``__init__`` parameter
  annotations (string annotations included) and direct constructions,
  so cross-object acquisitions (``with self.hedge._lock:``) and
  calls into lock-acquiring methods resolve to graph nodes;
* **metric-instrument attributes** — attrs whose init value contains
  ``registry.counter/gauge/histogram(...)`` calls (the cached-handle
  idiom); ``.inc/.set/.observe`` on them under a lock is an edge to
  the instrument leaf lock;
* **thread attributes** — ``self.X = threading.Thread(...)``, so the
  blocking-call rule flags ``.join()`` only on receivers that are
  actually threads (never ``",".join``);
* **file attributes** — ``self.X = open(...)`` / ``os.fdopen(...)``,
  so the blocking-call rule flags ``.flush()`` only on receivers that
  are actually file handles (a buffer/queue/logger ``flush()`` under a
  lock parks behind nothing).

Deliberately lexical, like :mod:`raft_tpu.analysis.facts`: dynamic
dispatch, locks passed between objects, and module-global mutation are
out of scope — suppressions and the ``lock_order.json`` baseline
absorb the residue.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from raft_tpu.analysis.facts import ModuleFacts

__all__ = ["ClassCensus", "ModuleCensus", "get_census"]

LOCK_TAILS = frozenset({"Lock", "RLock", "make_lock"})
COND_TAILS = frozenset({"Condition", "make_condition"})
EVENT_TAILS = frozenset({"Event"})
THREAD_TAILS = frozenset({"Thread"})
FILE_TAILS = frozenset({"open", "fdopen"})

# container mutators that count as WRITES to the attribute holding the
# container (the census cares about mutation, not rebinding)
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "sort",
})

# registry factory tails: a call like reg.counter(...) in an __init__
# value marks the attr as a cached instrument handle (or container of)
INSTRUMENT_FACTORY_TAILS = frozenset({"counter", "gauge", "histogram"})

# typing tokens that must not be mistaken for a class in an annotation
_TYPING_TOKENS = frozenset({
    "Optional", "Union", "Dict", "List", "Set", "Tuple", "Any",
    "Callable", "Sequence", "Mapping", "Iterable", "Iterator", "Type",
    "FrozenSet", "Deque", "None", "True", "False",
})


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"`` (exactly one level), else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _annotation_class(ann: ast.AST) -> Optional[str]:
    """The first class-looking token of an annotation, string
    annotations (``"HedgePolicy | float | None"``) included."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        text = ann.value
    else:
        try:
            text = ast.unparse(ann)
        except Exception:   # noqa: BLE001 — annotation exotica
            return None
    for tok in _tokens(text):
        tail = tok.rsplit(".", 1)[-1]
        if tail in _TYPING_TOKENS:
            continue
        if tail[:1].isupper():
            return tail
    return None


def _tokens(text: str) -> List[str]:
    out, cur = [], []
    for ch in text:
        if ch.isalnum() or ch in "._":
            cur.append(ch)
        elif cur:
            out.append("".join(cur))
            cur = []
    if cur:
        out.append("".join(cur))
    return out


@dataclasses.dataclass
class LockSite:
    """One lexical acquisition: a ``with`` item resolving to a lock."""

    node: ast.AST              # the with-statement
    expr: ast.AST              # the context expression
    key: str                   # census lock key (see ClassCensus.locks)


class ClassCensus:
    """Everything the thread tier knows about one class."""

    def __init__(self, node: ast.ClassDef, facts: ModuleFacts,
                 module: "ModuleCensus"):
        self.node = node
        self.name = node.name
        self.facts = facts
        self.module = module
        self.bases: List[str] = [
            b.rsplit(".", 1)[-1]
            for b in (facts.dotted(base) for base in node.bases)
            if b
        ]
        # attr -> canonical OWN lock attr ("_work" -> "_lock");
        # a Condition with no explicit lock canonicalizes to itself
        self.locks: Dict[str, str] = {}
        self.event_attrs: Set[str] = set()
        self.thread_attrs: Set[str] = set()
        self.file_attrs: Set[str] = set()
        self.instrument_attrs: Set[str] = set()
        self.attr_classes: Dict[str, str] = {}
        self.init_attrs: Set[str] = set()
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # node -> (own locks held, foreign lock keys held) at that node
        self.held_at: Dict[ast.AST, Tuple[str, ...]] = {}
        self.method_of: Dict[ast.AST, str] = {}
        self.acquisitions: List[Tuple[str, ast.AST, str]] = []
        #                    (method, with-node, lock key)
        # method -> own locks inferred ALWAYS held at entry (helpers)
        self.inferred_held: Dict[str, Tuple[str, ...]] = {}
        self.guarded: Set[str] = set()
        # attr accesses outside __init__: (method, node, attr, kind)
        self.accesses: List[Tuple[str, ast.AST, str, str]] = []
        self._scan_init()
        self._scan_thread_attrs()
        self._walk_methods()
        self._infer_helpers()
        self._infer_guarded()

    def owner_of(self, lock_attr: str) -> str:
        """The class whose ``__init__`` constructs ``lock_attr`` —
        this class, or the nearest same-module base (the
        ``Counter``/``_Instrument`` subclass idiom), for stable graph
        node names."""
        if lock_attr in self.locks:
            return self.name
        for base in self.bases:
            bc = self.module.classes.get(base)
            if bc is not None and lock_attr in bc.locks:
                return bc.owner_of(lock_attr)
        return self.name

    def _scan_thread_attrs(self) -> None:
        """``self.X = threading.Thread(...)`` / ``open(...)`` in ANY
        method marks a thread/file attr (the compactor assigns its
        worker in ``submit``, not ``__init__``; the WAL writer rebinds
        its segment handle on rotation)."""
        for fn in self.methods.values():
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.Assign) or not isinstance(
                        stmt.value, ast.Call):
                    continue
                callee = self.facts.callee(stmt.value)
                tail = callee.rsplit(".", 1)[-1] if callee else None
                if tail in THREAD_TAILS:
                    into = self.thread_attrs
                elif tail in FILE_TAILS:
                    into = self.file_attrs
                else:
                    continue
                for tgt in stmt.targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        into.add(attr)

    # -- __init__ scan --------------------------------------------------------

    def _scan_init(self) -> None:
        init = self.methods.get("__init__")
        if init is None:
            return
        for stmt in ast.walk(init):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                self.init_attrs.add(attr)
                self._classify_init_value(attr, value, init)

    def _classify_init_value(self, attr: str, value: ast.AST,
                             init: ast.FunctionDef) -> None:
        tail = None
        if isinstance(value, ast.Call):
            callee = self.facts.callee(value)
            tail = callee.rsplit(".", 1)[-1] if callee else None
        if tail in LOCK_TAILS:
            self.locks[attr] = attr
            return
        if tail in COND_TAILS:
            under = attr
            if isinstance(value, ast.Call) and value.args:
                base = _self_attr(value.args[0])
                if base is not None:
                    under = self.locks.get(base, base)
            self.locks[attr] = under
            return
        if tail in EVENT_TAILS:
            self.event_attrs.add(attr)
            return
        if tail in THREAD_TAILS:
            self.thread_attrs.add(attr)
            return
        # cached instrument handles: any registry-factory call inside
        # the value (covers dict/comprehension containers)
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                c = self.facts.callee(sub)
                if c and c.rsplit(".", 1)[-1] in INSTRUMENT_FACTORY_TAILS:
                    self.instrument_attrs.add(attr)
                    break
        # attr -> class: direct construction, or a parameter (possibly
        # wrapped in a default-if-None expression) with an annotation
        if tail and tail[:1].isupper():
            self.attr_classes[attr] = tail
            return
        ann_by_param = self._param_annotations(init)
        names = {n.id for n in ast.walk(value)
                 if isinstance(n, ast.Name)} if value is not None else set()
        hits = [cls for p, cls in ann_by_param.items() if p in names]
        if len(set(hits)) == 1:
            self.attr_classes[attr] = hits[0]

    def _param_annotations(self, fn: ast.FunctionDef) -> Dict[str, str]:
        out: Dict[str, str] = {}
        a = fn.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if p.annotation is not None:
                cls = _annotation_class(p.annotation)
                if cls is not None:
                    out[p.arg] = cls
        return out

    # -- lock-expression resolution -------------------------------------------

    def lock_key(self, expr: ast.AST) -> Optional[str]:
        """Resolve a with-item / receiver expression to a census lock
        key: ``"self:<canonical attr>"`` for own locks,
        ``"ext:<Class>.<attr>"`` for cross-object acquisitions,
        ``"mod:<var>"`` for module-level locks."""
        attr = _self_attr(expr)
        if attr is not None:
            canon = self.locks.get(attr)
            if canon is not None:
                return f"self:{canon}"
            # `with self._lock:` in a subclass whose lock lives in the
            # base __init__ (Counter/_Instrument): name-based fallback
            if "lock" in attr:
                return f"self:{attr}"
            return None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Attribute)):
            base = _self_attr(expr.value)
            if base is not None:
                cls = self.attr_classes.get(base)
                if cls is not None and "lock" in expr.attr:
                    return f"ext:{cls}.{expr.attr}"
        if isinstance(expr, ast.Name):
            if expr.id in self.module.module_locks:
                return f"mod:{expr.id}"
        return None

    # -- the held-stack walk --------------------------------------------------

    def _walk_methods(self) -> None:
        for name, fn in self.methods.items():
            for stmt in fn.body:
                self._walk(stmt, (), name)

    def _walk(self, node: ast.AST, held: Tuple[str, ...],
              method: str) -> None:
        self.held_at[node] = held
        self.method_of[node] = method
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested def runs later, usually on ANOTHER thread
            # (Thread(target=work)) — its body starts with nothing held
            for d in getattr(node, "decorator_list", []):
                self._walk(d, held, method)
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            for stmt in body:
                self._walk(stmt, (), method)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                self._walk(item.context_expr, held, method)
                key = self.lock_key(item.context_expr)
                if key is not None:
                    self.acquisitions.append((method, node, key))
                    inner = inner + (key,)
            for stmt in node.body:
                self._walk(stmt, inner, method)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, method)

    # -- inference ------------------------------------------------------------

    def effective_held(self, node: ast.AST) -> Tuple[str, ...]:
        """Lexical held stack plus the enclosing method's inferred
        always-held locks (helpers called only under a lock)."""
        held = self.held_at.get(node, ())
        method = self.method_of.get(node)
        if method is not None:
            inferred = self.inferred_held.get(method, ())
            held = tuple(k for k in inferred if k not in held) + held
        return held

    def own_locks_held(self, node: ast.AST) -> Tuple[str, ...]:
        return tuple(k for k in self.effective_held(node)
                     if k.startswith("self:"))

    def _infer_helpers(self) -> None:
        """Fixpoint: a method whose intra-class call sites ALL hold a
        common own lock executes under it."""
        # method -> [(caller, call node), ...]
        sites: Dict[str, List[Tuple[str, ast.AST]]] = {}
        for node, method in self.method_of.items():
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                # PRIVATE methods only: a public method with internal
                # call sites is still part of the external API, and
                # inferring "always under the lock" from internal
                # callers alone would silence real findings
                if callee in self.methods and callee != "__init__" \
                        and callee.startswith("_"):
                    sites.setdefault(callee, []).append((method, node))
        for _ in range(8):
            changed = False
            for callee, calls in sites.items():
                common: Optional[Set[str]] = None
                for caller, call in calls:
                    held = set(self.held_at.get(call, ()))
                    held |= set(self.inferred_held.get(caller, ()))
                    held = {k for k in held if k.startswith("self:")}
                    common = held if common is None else common & held
                inferred = tuple(sorted(common or ()))
                if inferred and inferred != self.inferred_held.get(callee):
                    self.inferred_held[callee] = inferred
                    changed = True
            if not changed:
                break

    def _attr_accesses(self) -> None:
        """Populate ``self.accesses`` with (method, node, attr, kind)
        for every ``self.X`` touch outside ``__init__``."""
        for name, fn in self.methods.items():
            if name == "__init__":
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for tgt in targets:
                        for t in ast.walk(tgt):
                            attr = _self_attr(t)
                            if attr is not None and isinstance(
                                    getattr(t, "ctx", None), ast.Store):
                                self.accesses.append(
                                    (name, t, attr, "write"))
                elif isinstance(node, (ast.Subscript,)):
                    if isinstance(node.ctx, (ast.Store, ast.Del)):
                        attr = _self_attr(node.value)
                        if attr is not None:
                            self.accesses.append(
                                (name, node, attr, "write"))
                elif isinstance(node, ast.Call):
                    f = node.func
                    if (isinstance(f, ast.Attribute)
                            and f.attr in MUTATOR_METHODS):
                        attr = _self_attr(f.value)
                        if attr is not None:
                            self.accesses.append(
                                (name, node, attr, "write"))
                elif isinstance(node, ast.Attribute):
                    attr = _self_attr(node)
                    if attr is not None and isinstance(
                            node.ctx, ast.Load):
                        self.accesses.append((name, node, attr, "read"))

    def _infer_guarded(self) -> None:
        if not self.locks:
            return
        self._attr_accesses()
        candidates = (self.init_attrs - set(self.locks)
                      - self.event_attrs)
        for _method, node, attr, kind in self.accesses:
            if kind == "write" and attr in candidates \
                    and self.own_locks_held(node):
                self.guarded.add(attr)


class ModuleCensus:
    """All class censuses of one module plus its module-level locks and
    module-level-function held stacks."""

    def __init__(self, tree: ast.Module, facts: ModuleFacts,
                 module_name: str = "<module>"):
        self.tree = tree
        self.facts = facts
        self.module_name = module_name
        # module-global lock vars: name -> canonical name (conditions
        # on a module lock canonicalize like class attrs)
        self.module_locks: Dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                callee = facts.callee(node.value)
                tail = callee.rsplit(".", 1)[-1] if callee else None
                if tail in LOCK_TAILS or tail in COND_TAILS:
                    name = node.targets[0].id
                    self.module_locks[name] = name
        self.classes: Dict[str, ClassCensus] = {}
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = ClassCensus(node, facts, self)
        # module-level functions get a held-stack walk through a
        # synthetic lock-less census (module locks still resolve)
        self.toplevel = _ToplevelCensus(tree, facts, self)

    def lock_node_name(self, census: Optional[ClassCensus],
                       key: str) -> str:
        """Census lock key -> global graph node name."""
        scope, _, rest = key.partition(":")
        if scope == "self" and census is not None:
            return f"{census.owner_of(rest)}.{rest}"
        if scope == "ext":
            return rest
        if scope == "mod":
            return f"{self.module_name}.{rest}"
        return rest


class _ToplevelCensus(ClassCensus):
    """Held-stack walk for module-level functions: module locks only
    (``_mseries``'s ``with _mseries_lock:`` idiom)."""

    def __init__(self, tree: ast.Module, facts: ModuleFacts,
                 module: ModuleCensus):
        # hand-rolled minimal init: no class node, no init scan
        self.node = None
        self.name = module.module_name
        self.facts = facts
        self.module = module
        self.bases = []
        self.locks = {}
        self.event_attrs = set()
        self.thread_attrs = set()
        self.file_attrs = set()
        self.instrument_attrs = set()
        self.attr_classes = {}
        self.init_attrs = set()
        self.methods = {
            n.name: n for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.held_at = {}
        self.method_of = {}
        self.acquisitions = []
        self.inferred_held = {}
        self.guarded = set()
        self.accesses = []
        self._walk_methods()
        self._infer_helpers()


def get_census(ctx) -> ModuleCensus:
    """The file's :class:`ModuleCensus`, built once and cached on the
    :class:`~raft_tpu.analysis.engine.FileContext`."""
    census = getattr(ctx, "_thread_census", None)
    if census is None:
        module_name = ctx.rel.rsplit("/", 1)[-1].removesuffix(".py")
        census = ModuleCensus(ctx.tree, ctx.facts, module_name)
        ctx._thread_census = census
    return census
