"""Rule ``metrics-in-traced-body``: a host-side metric recorder called
inside a jitted/traced body.

The obs layer (raft_tpu/obs/metrics.py, docs/observability.md) is
host-side by construction: a ``Counter.inc()``, ``Histogram.observe()``
or ``Gauge.set()`` mutates Python state under a Python lock. Called
inside a traced body, it runs ONCE — at trace time — and never again:
the compiled program contains no trace of it, the metric counts one
warmup forever, and the dashboard shows a flatline that LOOKS like a
healthy quiet system while the real traffic goes unrecorded. The same
applies to the wall-clock reads that feed recorders —
``time.time()`` / ``time.perf_counter()`` inside a traced body is a
trace-time constant, so even a recorder called later on the host would
be fed a duration measured across the TRACE, not the dispatch.

Flagged INSIDE traced bodies only (the executor threads, mutation acks,
and every other host path record freely):

* ``x.inc(...)`` / ``x.observe(...)`` — the two spellings unique to
  metric instruments;
* ``x.set(...)`` when the receiver LOOKS like a metric — its dotted
  name matches ``counter|gauge|hist(ogram)?|metric|meter`` or carries
  the repo's gauge-handle ``g`` token (``self._g_coverage``,
  ``_G_RANKS_UP``; array updates like ``arr.at[i].set(v)`` and
  ordinary setters never match), or it is directly a
  ``registry.gauge(...)`` / ``.counter(...)`` / ``.histogram(...)``
  chain;
* ``time.time()`` / ``time.perf_counter()`` whose value FEEDS a
  recorder call in the same traced body — directly as an argument, or
  through a name assigned from the clock read.

Record around the dispatch, not inside it: stamp on the host before
and after, or read values back through the executor's demux path
(which is already host-side). Genuine trace-time bookkeeping that
happens to share a spelling carries
``# jaxlint: disable=metrics-in-traced-body`` on the line.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set

from raft_tpu.analysis.rules import Rule

_RECORDER_ATTRS = {"inc", "observe"}
_REGISTRY_FACTORIES = {"counter", "gauge", "histogram"}
# metric-shaped receiver names for the `.set()` heuristic: the generic
# spellings plus the bare `g` token — this codebase's own gauge-handle
# convention is `_g_coverage` / `_G_RANKS_UP`, and the rule must catch
# its own instruments' misuse (a bare variable literally named `g` is
# rare enough in traced bodies to accept)
_METRIC_NAME = re.compile(
    r"(^|_)(g|counters?|gauges?|hist|histograms?|metrics?|meters?)($|_)"
)
_CLOCKS = {"time.time", "time.perf_counter", "time.monotonic"}


def _dotted_name(node: ast.AST) -> Optional[str]:
    """Name/Attribute chain with dots normalized to underscores
    (``self._g_coverage`` -> ``self__g_coverage``), or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return "_".join(reversed(parts))
    return None


class MetricsInTracedBodyRule(Rule):
    name = "metrics-in-traced-body"
    description = (
        "host-side metric recorder (.inc/.observe/.set) or clock read "
        "feeding one inside a traced body — records once at trace "
        "time, never at dispatch"
    )

    def _recorder_call(self, ctx, call: ast.Call) -> Optional[str]:
        """A description of the metric-recorder call this is, or None."""
        fn = call.func
        if not isinstance(fn, ast.Attribute):
            return None
        recv = fn.value
        if fn.attr in _RECORDER_ATTRS:
            label = _dotted_name(recv) or "<metric>"
            return f"{label}.{fn.attr}()"
        if fn.attr == "set":
            # only metric-shaped receivers: `arr.at[i].set(v)` (a
            # Subscript receiver) and ordinary setters must not match
            d = _dotted_name(recv)
            if d is not None and _METRIC_NAME.search(d.lower()):
                return f"{d}.set()"
            if isinstance(recv, ast.Call) and isinstance(
                recv.func, ast.Attribute
            ) and recv.func.attr in _REGISTRY_FACTORIES:
                return f"registry.{recv.func.attr}(...).set()"
        return None

    def _clock_call(self, ctx, call: ast.Call) -> Optional[str]:
        d = ctx.facts.dotted(call.func)
        if d in _CLOCKS:
            return d
        return None

    def _names_in(self, node: ast.AST) -> Set[str]:
        return {
            n.id for n in ast.walk(node) if isinstance(n, ast.Name)
        }

    def check(self, ctx) -> Iterator:
        seen: set = set()          # nested traced fns share body nodes
        for fn in ctx.facts.traced:
            recorders: List[ast.Call] = []
            clock_calls: List[ast.Call] = []
            # name -> the clock call it was assigned from
            clock_names: Dict[str, ast.Call] = {}
            body = [
                n for n in ctx.facts.traced_body_nodes(fn)
                if id(n) not in seen and not seen.add(id(n))
            ]
            for node in body:
                if isinstance(node, ast.Call):
                    if self._recorder_call(ctx, node) is not None:
                        recorders.append(node)
                    elif self._clock_call(ctx, node) is not None:
                        clock_calls.append(node)
                elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ) and self._clock_call(ctx, node.value) is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            clock_names[tgt.id] = node.value
            for call in recorders:
                what = self._recorder_call(ctx, call)
                yield ctx.finding(
                    self.name, call,
                    f"{what} inside a traced body records ONCE at "
                    "trace time and never again — the compiled program "
                    "carries no host callback; record on the host "
                    "around the dispatch (executor stage timing, "
                    "mutation ack path) instead",
                )
            # clock reads that feed a recorder: directly as an
            # argument, or through an assigned name referenced in any
            # recorder call's arguments
            fed: Set[int] = set()
            arg_names: Set[str] = set()
            for rec in recorders:
                for arg in list(rec.args) + [
                    kw.value for kw in rec.keywords
                ]:
                    arg_names |= self._names_in(arg)
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Call) and \
                                self._clock_call(ctx, sub) is not None:
                            fed.add(id(sub))
            for name, call in clock_names.items():
                if name in arg_names:
                    fed.add(id(call))
            emitted: Set[int] = set()   # a call can sit in clock_calls
            for call in clock_calls + list(clock_names.values()):
                # AND clock_names (ast.walk visits the Assign and its
                # value Call separately) — one finding per read
                if id(call) not in fed or id(call) in emitted:
                    continue
                emitted.add(id(call))
                d = self._clock_call(ctx, call)
                yield ctx.finding(
                    self.name, call,
                    f"{d}() inside a traced body is a TRACE-TIME "
                    "constant — the duration it feeds into a metric "
                    "recorder measures the trace, not the dispatch; "
                    "stamp on the host before/after the dispatch call",
                )


RULES = [MetricsInTracedBodyRule()]
