"""Rule ``x64-hygiene``: literals/dtypes that silently change width under
``jax_enable_x64``.

Complements the runtime pass in ``tests/x64_checks.py`` (which runs the
suite under x64 in its own process): the lint side catches the authoring
mistakes before they need a second process to reproduce:

* ``jnp.float64`` / ``jnp.int64`` / ``jnp.complex128`` references — under
  default config these silently canonicalize to 32-bit, under x64 they
  double memory/bandwidth; a use *guarded* by an explicit
  ``jax_enable_x64`` config read on the same line is exempt (that is the
  sanctioned pattern);
* 64-bit dtypes handed to ``jnp.*`` calls via ``dtype=`` — whether spelled
  ``np.float64``, ``"float64"``, or the Python builtin ``float``/``int``
  (which mean f64/i64 to numpy and change meaning with x64).

A module that *enables* x64 at top level (``jax.config.update(
"jax_enable_x64", True)`` or setting the env var before importing jax —
the tests/x64_checks.py harness pattern) has opted into 64-bit semantics
process-wide and is exempt wholesale.

Host-side ``np.float64`` arrays (dendrogram bookkeeping, ctypes buffers)
are intentionally NOT flagged — numpy is allowed to be 64-bit on host;
only values entering the jnp boundary are checked.
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_tpu.analysis.rules import Rule

_WIDE = {"float64", "int64", "uint64", "complex128"}
_BUILTIN_WIDE = {"float", "int", "complex"}
_GUARD = "jax_enable_x64"


class X64HygieneRule(Rule):
    name = "x64-hygiene"
    description = "64-bit literal/dtype that shifts meaning under x64"

    def _guarded(self, ctx, node: ast.AST) -> bool:
        line = getattr(node, "lineno", 0)
        if 1 <= line <= len(ctx.lines):
            return _GUARD in ctx.lines[line - 1]
        return False

    def _module_enables_x64(self, ctx) -> bool:
        """True for modules that switch x64 ON at import (x64 harnesses).

        Only an actual enable counts: `jax.config.update("jax_enable_x64",
        True)` or a truthy `os.environ["JAX_ENABLE_X64"] = ...` store.
        Setting it falsy (or touching an unrelated dict with that key)
        must NOT silence the rule."""
        for stmt in ctx.tree.body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    d = ctx.facts.dotted(node.func)
                    if d and d.endswith("config.update") and node.args and \
                            isinstance(node.args[0], ast.Constant) and \
                            node.args[0].value == _GUARD and \
                            len(node.args) > 1 and \
                            isinstance(node.args[1], ast.Constant) and \
                            node.args[1].value is True:
                        return True
                elif isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Constant) and \
                        node.value.value not in ("0", "", "false", "False",
                                                 0, False, None):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Subscript) and \
                                isinstance(tgt.slice, ast.Constant) and \
                                tgt.slice.value == "JAX_ENABLE_X64" and \
                                ctx.facts.dotted(tgt.value) == "os.environ":
                            return True
        return False

    def _is_jnp_rooted(self, ctx, call: ast.Call) -> bool:
        d = ctx.facts.dotted(call.func)
        return d is not None and (
            d.startswith("jax.numpy.") or d.startswith("jax.lax.")
        )

    def check(self, ctx) -> Iterator:
        if self._module_enables_x64(ctx):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and node.attr in _WIDE:
                d = ctx.facts.dotted(node)
                if d and d.startswith("jax.numpy.") and \
                        not self._guarded(ctx, node):
                    yield ctx.finding(
                        self.name, node,
                        f"jnp.{node.attr} canonicalizes to 32-bit without "
                        "x64 and doubles width with it — guard with an "
                        "explicit jax_enable_x64 read or pick the width",
                    )
            elif isinstance(node, ast.Call) and \
                    self._is_jnp_rooted(ctx, node):
                for kw in node.keywords:
                    if kw.arg != "dtype":
                        continue
                    v = kw.value
                    bad = None
                    if isinstance(v, ast.Attribute) and v.attr in _WIDE:
                        vd = ctx.facts.dotted(v)
                        if vd is not None and vd.startswith("jax.numpy."):
                            continue  # the attribute check already flags it
                        bad = v.attr
                    elif isinstance(v, ast.Constant) and v.value in _WIDE:
                        bad = v.value
                    elif isinstance(v, ast.Name) and v.id in _BUILTIN_WIDE \
                            and v.id not in ctx.facts.aliases:
                        bad = f"builtin {v.id} (means 64-bit)"
                    if bad is not None and not self._guarded(ctx, v):
                        yield ctx.finding(
                            self.name, v,
                            f"dtype={bad} at the jnp boundary silently "
                            "upcasts under jax_enable_x64",
                        )


RULES = [X64HygieneRule()]
