"""Rule ``sync-in-hot-path``: a host synchronization inside a
serving-loop body.

The open-loop executor's whole throughput win
(raft_tpu/serving/executor.py, docs/serving.md "Open-loop serving") is
JAX's async dispatch: the batcher keeps N compiled programs in flight
and the device never waits for the host. ONE stray host sync in a
serving-loop body — a ``block_until_ready()``, an ``.item()``, an
``np.asarray`` on a device value — serializes the pipeline silently:
every dispatch then waits for the previous result's round trip, the
in-flight window collapses to 1, and measured open-loop throughput
drops to the closed-loop number while every test still passes. This is
the async sibling of ``recompile-hazard``: not wrong, just quietly 10x
slower.

Flagged — when lexically inside a ``for``/``while`` loop body that is
itself inside a *serving-loop context*:

* ``x.block_until_ready()`` / ``jax.block_until_ready(x)``;
* ``x.item()`` / ``x.tolist()`` (host readback of a device scalar);
* ``np.asarray(x)`` / ``np.array(x)`` / ``np.copy(x)`` (implicit
  transfer + sync when ``x`` is a device array).

A *serving-loop context* is (a) any function in a module under a
``serving/`` path segment, or (b) any function named ``*_loop`` /
``serve*`` anywhere — the executor's thread bodies and anything shaped
like one. Loop bodies only: a single sync before or after the loop
(setup, final demux) is the intended pattern.

Intentional sync points — the demux conversion after readiness is
confirmed, a shutdown drain — carry
``# jaxlint: disable=sync-in-hot-path`` on the line (or live in
ci/checks/jaxlint_baseline.json); everything else is a lint error.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from raft_tpu.analysis.rules import Rule

_HOT_FN_RE = re.compile(r"(_loop$|^serve)")
_SYNC_METHODS = {"block_until_ready", "item", "tolist"}
_NUMPY_SYNCS = {"numpy.asarray", "numpy.array", "numpy.copy"}


def _in_serving_module(rel: str) -> bool:
    parts = rel.replace("\\", "/").split("/")
    return "serving" in parts[:-1]


class SyncInHotPathRule(Rule):
    name = "sync-in-hot-path"
    description = (
        "host sync (block_until_ready/.item()/np.asarray) inside a "
        "serving-loop body — silently serializes async dispatch"
    )

    def _loop_ancestor(self, ctx, node: ast.AST) -> Optional[ast.AST]:
        """The nearest enclosing for/while statement, or None. A node
        that IS the loop's test/iter (e.g. ``while x.item():``) counts:
        it runs once per iteration too."""
        cur = ctx.facts.parent.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                return cur
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return None        # don't escape the defining function
            cur = ctx.facts.parent.get(cur)
        return None

    def _hot_function(self, ctx, node: ast.AST) -> Optional[str]:
        """The name of the serving-loop function lexically enclosing
        ``node``, or None when this context is not a hot path."""
        serving_mod = _in_serving_module(ctx.rel)
        cur = ctx.facts.parent.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if serving_mod or _HOT_FN_RE.search(cur.name):
                    return cur.name
                return None        # nearest function decides
            cur = ctx.facts.parent.get(cur)
        return None

    def _sync_call(self, ctx, call: ast.Call) -> Optional[str]:
        """A human-readable description of the sync this call performs,
        or None."""
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_METHODS:
            # method spelling: x.block_until_ready() / x.item(); skip
            # module-level jax.block_until_ready (dotted path below)
            d = ctx.facts.dotted(fn)
            if d is None or not d.startswith(("jax.", "numpy.")):
                return f".{fn.attr}()"
        d = ctx.facts.dotted(fn)
        if d == "jax.block_until_ready":
            return "jax.block_until_ready()"
        if d in _NUMPY_SYNCS:
            return f"{d.replace('numpy.', 'np.')}()"
        return None

    def check(self, ctx) -> Iterator:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            what = self._sync_call(ctx, node)
            if what is None:
                continue
            if self._loop_ancestor(ctx, node) is None:
                continue
            hot = self._hot_function(ctx, node)
            if hot is None:
                continue
            yield ctx.finding(
                self.name, node,
                f"{what} inside the `{hot}` serving-loop body — one "
                "host sync per iteration serializes async dispatch "
                "(the in-flight window collapses to 1); demux AFTER "
                "readiness outside the loop, or suppress if this sync "
                "is the intentional demux point",
            )


RULES = [SyncInHotPathRule()]
