"""Rule ``data-dependent-loop-bound``: a loop inside a traced body
whose trip count flows from a traced value through a host coercion.

The beam-search retrace hazard (raft_tpu/spatial/ann/graph.py,
docs/graph_ann.md): a CPU graph-ANN implementation loops "until the
frontier converges" — a trip count read off the data. Spelled inside a
traced body that becomes ``range(int(n_active))``,
``while int(frontier_size) > 0:``, or
``lax.fori_loop(0, int(hops), ...)`` — each of which either raises a
``TracerConversionError`` at trace time or, when the value happens to
be concrete (a numpy input, a constant-folded intermediate), silently
bakes THIS batch's trip count into the compiled program, so the next
batch with a different value retraces — or worse, reuses the wrong
bound. Trip counts of traced loops must be trace-time statics derived
from shapes/params (the static ``iters`` discipline), or the loop must
be a ``lax.while_loop`` on the runtime value.

Flagged INSIDE traced bodies only (host orchestration loops freely),
when a loop-bound position contains a host coercion of a value that
flows from a NONSTATIC parameter of the traced callable:

* ``for ... in range(int(x))`` / ``range(x.item())`` — a Python loop
  bound read off a traced operand;
* ``while`` whose test coerces such a value via ``int()`` / ``bool()``
  / ``float()`` / ``.item()`` / ``.tolist()``;
* ``lax.fori_loop`` / ``lax.scan(..., length=...)`` whose static
  bound/length argument is built from such a coercion.

``int(x.shape[0])``, ``len(x)``, and ``x.ndim`` reads are exempt —
shapes are trace-time statics — as are coercions that reference only
the callable's declared STATIC parameters. Suppress with
``# jaxlint: disable=data-dependent-loop-bound`` where the coerced
value is genuinely concrete at trace time.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from raft_tpu.analysis.rules import Rule

_COERCIONS = {"int", "bool", "float"}
_HOST_METHODS = {"item", "tolist"}
_SHAPE_ATTRS = {"shape", "ndim"}
_FORI = {"jax.lax.fori_loop", "lax.fori_loop", "fori_loop"}
_SCAN = {"jax.lax.scan", "lax.scan", "scan"}


def _tainted_name(expr: ast.AST, nonstatic: Set[str]) -> Optional[str]:
    """The first nonstatic-parameter name referenced in ``expr`` outside
    a shape read (``x.shape[...]`` / ``x.ndim`` / ``len(x)``), or None.
    Shape reads are trace-time statics however traced their base is."""
    def scan(node) -> Optional[str]:
        if isinstance(node, ast.Attribute) and node.attr in _SHAPE_ATTRS:
            return None
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len"
        ):
            return None
        if isinstance(node, ast.Name) and node.id in nonstatic:
            return node.id
        for child in ast.iter_child_nodes(node):
            hit = scan(child)
            if hit is not None:
                return hit
        return None

    return scan(expr)


def _coercion_of_traced(expr: ast.AST,
                        nonstatic: Set[str]) -> Optional[str]:
    """Description of the first host coercion in ``expr`` whose operand
    flows from a nonstatic parameter — ``int(n_active)`` /
    ``frontier.item()`` — or None."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (
            isinstance(fn, ast.Name) and fn.id in _COERCIONS
            and node.args
        ):
            hit = _tainted_name(node.args[0], nonstatic)
            if hit is not None:
                return f"{fn.id}(...{hit}...)"
        elif isinstance(fn, ast.Attribute) and fn.attr in _HOST_METHODS:
            hit = _tainted_name(fn.value, nonstatic)
            if hit is not None:
                return f"{hit}.{fn.attr}()"
    return None


class DataDependentLoopBoundRule(Rule):
    name = "data-dependent-loop-bound"
    description = (
        "traced loop trip count coerced from a runtime value — the "
        "program retraces (or freezes one batch's bound) per value"
    )

    def _check_for(self, ctx, node: ast.For,
                   nonstatic: Set[str]) -> Iterator:
        it = node.iter
        # unwrap reversed(range(...)) / enumerate(range(...))
        while (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id in ("reversed", "enumerate")
            and it.args
        ):
            it = it.args[0]
        if not (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id == "range"
        ):
            return
        for arg in it.args:
            what = _coercion_of_traced(arg, nonstatic)
            if what is not None:
                yield ctx.finding(
                    self.name, node.iter,
                    f"range bound {what} inside a traced body — a "
                    "data-dependent trip count retraces per value (or "
                    "freezes this batch's); derive the bound from "
                    "shapes/static params, or use lax.while_loop on "
                    "the runtime value",
                )
                return

    def _check_while(self, ctx, node: ast.While,
                     nonstatic: Set[str]) -> Iterator:
        what = _coercion_of_traced(node.test, nonstatic)
        if what is not None:
            yield ctx.finding(
                self.name, node.test,
                f"`while` on {what} inside a traced body — the "
                "convergence test reads a traced value back to the "
                "host (retrace per value); use a static iteration "
                "budget or lax.while_loop on the runtime value",
            )

    def _check_lax_call(self, ctx, call: ast.Call,
                        nonstatic: Set[str]) -> Iterator:
        callee = ctx.facts.callee(call)
        if callee in _FORI:
            for arg in call.args[:2]:       # (lower, upper, body, init)
                what = _coercion_of_traced(arg, nonstatic)
                if what is not None:
                    yield ctx.finding(
                        self.name, call,
                        f"lax.fori_loop bound {what} — fori bounds are "
                        "trace-time statics, so a coerced runtime "
                        "value retraces per value; use a static hop "
                        "budget or lax.while_loop",
                    )
                    return
        elif callee in _SCAN:
            for kw in call.keywords:
                if kw.arg != "length":
                    continue
                what = _coercion_of_traced(kw.value, nonstatic)
                if what is not None:
                    yield ctx.finding(
                        self.name, call,
                        f"lax.scan length={what} — the scan length is "
                        "a trace-time static, so a coerced runtime "
                        "value retraces per value",
                    )
                    return

    def check(self, ctx) -> Iterator:
        seen: set = set()          # nested traced fns share body nodes
        for fn in ctx.facts.traced:
            nonstatic = ctx.facts.nonstatic_params(fn)
            for node in ctx.facts.traced_body_nodes(fn):
                if id(node) in seen:
                    continue
                seen.add(id(node))
                if isinstance(node, ast.For):
                    yield from self._check_for(ctx, node, nonstatic)
                elif isinstance(node, ast.While):
                    yield from self._check_while(ctx, node, nonstatic)
                elif isinstance(node, ast.Call):
                    yield from self._check_lax_call(ctx, node, nonstatic)


RULES = [DataDependentLoopBoundRule()]
