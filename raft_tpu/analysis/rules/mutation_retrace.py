"""Rule ``mutation-retrace``: mutation-tier state read as a Python
static inside a traced body.

The whole zero-retrace contract of the mutation subsystem
(raft_tpu/spatial/ann/mutation.py, docs/mutation.md) rests on delta
occupancy and tombstones being RUNTIME values: an upsert fills a slot, a
delete flips a mask entry, and the compiled serving program never
changes. The one way to break that silently is to read one of those
values back into Python inside a traced body — ``int(delta_counts[l])``,
``if tombstones.any():``, ``range(live_count)`` — which either raises a
``TracerConversionError`` at trace time or, worse, constant-folds a
snapshot of the mutation state into the compiled program so every
mutation forces a retrace (the recompile hazard specific to this
subsystem; its general siblings live in ``recompile-hazard``).

Flagged INSIDE traced bodies only (host-side compaction/bookkeeping
reads these freely), for names that look like mutation state
(``delta_count(s)``, ``delta_fill``, ``tombstone(s)``, ``row_mask``,
``live_count``, ``dead_count``, ``n_dead``, ``n_tombstones`` — dotted
accesses like ``delta.counts`` normalize to ``delta_counts``):

* ``int()`` / ``bool()`` / ``float()`` coercion of such a value;
* ``.item()`` / ``.tolist()`` on it;
* a Python ``if`` / ``while`` test referencing it (``is None`` /
  ``is not None`` presence tests are exempt — argument presence is
  pytree structure, a legitimate trace-time static);
* ``range()`` over it (a data-dependent trip count).

Suppress with ``# jaxlint: disable=mutation-retrace`` where the value
is genuinely a static (e.g. a capacity constant that happens to share
the naming).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from raft_tpu.analysis.rules import Rule

_PAT = re.compile(
    r"(^|_)(delta_counts?|delta_fill|tombstones?|row_mask|live_count|"
    r"dead_count|n_dead|n_tombstones)($|_)"
)
_COERCIONS = {"int", "bool", "float"}
_HOST_METHODS = {"item", "tolist"}


def _dotted_name(node: ast.AST) -> Optional[str]:
    """The dotted name of a Name/Attribute chain with dots normalized to
    underscores (``delta.counts`` -> ``delta_counts``), or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return "_".join(reversed(parts))
    return None


def _mutation_name(node: ast.AST) -> Optional[str]:
    """The first mutation-state name referenced anywhere in ``node``
    (subscripts like ``delta_counts[l]`` are looked through)."""
    for n in ast.walk(node):
        if isinstance(n, (ast.Name, ast.Attribute)):
            d = _dotted_name(n)
            if d is not None and _PAT.search(d):
                return d
    return None


class MutationRetraceRule(Rule):
    name = "mutation-retrace"
    description = (
        "delta-occupancy / tombstone value read as a Python static "
        "inside a traced body — every mutation would retrace"
    )

    def _check_call(self, ctx, call: ast.Call) -> Iterator:
        fn = call.func
        if isinstance(fn, ast.Name) and fn.id in (
            _COERCIONS | {"range"}
        ):
            if not call.args:
                return
            hit = _mutation_name(call.args[0])
            if hit is None:
                return
            what = (
                f"range({hit}) — a data-dependent trip count"
                if fn.id == "range"
                else f"{fn.id}({hit}) — host coercion of a runtime value"
            )
            yield ctx.finding(
                self.name, call,
                f"{what} inside a traced body: mutation state must stay "
                "a runtime input (upserts/tombstone flips would retrace "
                "the serving program); hoist to the host path or "
                "suppress if genuinely static",
            )
        elif isinstance(fn, ast.Attribute) and fn.attr in _HOST_METHODS:
            hit = _mutation_name(fn.value)
            if hit is not None:
                yield ctx.finding(
                    self.name, call,
                    f"{hit}.{fn.attr}() inside a traced body — host "
                    "readback of mutation state constant-folds a "
                    "snapshot into the compiled program (retrace per "
                    "mutation); keep it a runtime input",
                )

    def _is_presence_test(self, node: ast.AST) -> bool:
        """``x is None`` / ``x is not None`` (possibly under ``not``):
        an ARGUMENT-PRESENCE check — pytree structure, a legitimate
        trace-time static — not a value read."""
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return self._is_presence_test(node.operand)
        if isinstance(node, ast.BoolOp):
            return all(self._is_presence_test(v) for v in node.values)
        return isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        )

    def _check_branch(self, ctx, node) -> Iterator:
        if self._is_presence_test(node.test):
            return
        hit = _mutation_name(node.test)
        if hit is not None:
            kind = "if" if isinstance(node, ast.If) else "while"
            yield ctx.finding(
                self.name, node.test,
                f"Python `{kind}` on {hit} inside a traced body — "
                "control flow on mutation state traces one branch as a "
                "constant (retrace per mutation); use jnp.where / "
                "lax.cond on the runtime value instead",
            )

    def check(self, ctx) -> Iterator:
        seen: set = set()          # nested traced fns share body nodes
        for fn in ctx.facts.traced:
            for node in ctx.facts.traced_body_nodes(fn):
                if id(node) in seen:
                    continue
                seen.add(id(node))
                if isinstance(node, ast.Call):
                    yield from self._check_call(ctx, node)
                elif isinstance(node, (ast.If, ast.While)):
                    yield from self._check_branch(ctx, node)


RULES = [MutationRetraceRule()]
