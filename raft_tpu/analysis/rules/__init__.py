"""Rule registry. Each rule module exposes RULES; this package aggregates
them into ALL_RULES in documentation order."""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator, List

if TYPE_CHECKING:
    from raft_tpu.analysis.engine import FileContext, Finding


class Rule:
    """One named check. Subclasses set ``name``/``description`` and yield
    :class:`~raft_tpu.analysis.engine.Finding` s from :meth:`check`."""

    name: str = ""
    description: str = ""

    def check(self, ctx: "FileContext") -> Iterator["Finding"]:
        raise NotImplementedError


def _collect() -> List[Rule]:
    from raft_tpu.analysis.rules import (
        adc_gather,
        api_compat,
        data_dependent_loop_bound,
        dcn_wide_collective,
        host_fetch_in_traced_body,
        metrics_in_traced_body,
        mutation_retrace,
        prng_discipline,
        recompile_hazard,
        stale_epoch_read,
        sync_in_hot_path,
        tracer_safety,
        x64_hygiene,
    )

    out: List[Rule] = []
    for mod in (api_compat, tracer_safety, recompile_hazard,
                x64_hygiene, prng_discipline, adc_gather,
                mutation_retrace, sync_in_hot_path,
                dcn_wide_collective, metrics_in_traced_body,
                host_fetch_in_traced_body, stale_epoch_read,
                data_dependent_loop_bound):
        out.extend(mod.RULES)
    return out


ALL_RULES: List[Rule] = _collect()

__all__ = ["Rule", "ALL_RULES"]
