"""Rule ``tracer-safety``: host-side operations on traced values inside
``jit``/``shard_map``/``vmap``/``lax.scan``-family bodies.

Each of these either crashes at trace time (``TracerArrayConversionError``,
``ConcretizationTypeError``) or silently constant-folds a traced value —
the production failure mode the ROADMAP's serving story cannot afford:

* ``np.asarray`` / ``np.array`` / ``np.ascontiguousarray`` on anything —
  numpy materializes, which forces a device sync or trace error;
* ``float()`` / ``int()`` / ``bool()`` on a traced parameter or on a
  ``jnp``/``lax`` expression — concretization;
* ``.item()`` — device sync + concretization;
* Python ``if``/``while`` whose condition reads a non-static traced
  parameter directly (``x.shape``/``.ndim``/``.dtype``/``.size`` access is
  static metadata and exempt; parameters declared in ``static_argnums`` /
  ``static_argnames`` are exempt).
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from raft_tpu.analysis.rules import Rule

_NUMPY_MATERIALIZERS = {"asarray", "array", "ascontiguousarray"}
_COERCIONS = {"float", "int", "bool", "complex"}
_STATIC_METADATA = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
_TRACED_ROOTS = {"jax.numpy", "jax.lax", "jax"}


class TracerSafetyRule(Rule):
    name = "tracer-safety"
    description = (
        "host-side op on a traced value inside a jit/shard_map/vmap body"
    )

    def _is_jax_expr(self, ctx, node: ast.AST) -> bool:
        """A call rooted in jax/jnp/lax — its result is a traced array."""
        if not isinstance(node, ast.Call):
            return False
        d = ctx.facts.dotted(node.func)
        if d is None:
            return False
        return any(d == r or d.startswith(r + ".") for r in _TRACED_ROOTS)

    def _control_flow_hits(self, ctx, test: ast.AST,
                           params: Set[str]) -> Iterator[ast.Name]:
        """Non-static traced params read *as values* in a condition.

        Host-side structural checks are exempt: ``x is None``,
        ``isinstance(x, T)``/``hasattr``/``callable``, and any attribute
        access (``x.shape``, ``index.metric`` — array metadata and pytree
        static fields, not traced values)."""
        parents = ctx.facts.parent
        for n in ast.walk(test):
            if not (isinstance(n, ast.Name) and n.id in params):
                continue
            exempt = False
            cur = n
            while cur is not None and cur is not test and not exempt:
                p = parents.get(cur)
                if isinstance(p, ast.Attribute):
                    exempt = True  # branching on metadata/static field
                elif isinstance(p, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in p.ops
                ):
                    exempt = True  # identity check (x is None) is host-side
                elif isinstance(p, ast.Call):
                    d = ctx.facts.dotted(p.func)
                    if d in ("isinstance", "hasattr", "callable", "len",
                             "type"):
                        exempt = True
                cur = p
            if not exempt:
                yield n

    def check(self, ctx) -> Iterator:
        for fn in ctx.facts.traced:
            params = ctx.facts.nonstatic_params(fn)
            for node in ctx.facts.traced_body_nodes(fn):
                if isinstance(node, ast.Call):
                    d = ctx.facts.dotted(node.func)
                    if d is not None:
                        parts = d.split(".")
                        root = ".".join(parts[:-1])
                        if parts[-1] in _NUMPY_MATERIALIZERS and \
                                root == "numpy":
                            yield ctx.finding(
                                self.name, node,
                                f"numpy.{parts[-1]}() inside a traced body "
                                "materializes on host (trace error or "
                                "silent constant-fold); use jnp",
                            )
                            continue
                        if d in _COERCIONS and len(node.args) == 1:
                            arg = node.args[0]
                            traced_arg = (
                                isinstance(arg, ast.Name)
                                and arg.id in params
                            ) or self._is_jax_expr(ctx, arg)
                            if traced_arg:
                                yield ctx.finding(
                                    self.name, node,
                                    f"{d}() coercion of a traced value "
                                    "concretizes at trace time",
                                )
                                continue
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "item" and not node.args:
                        yield ctx.finding(
                            self.name, node,
                            ".item() inside a traced body forces a device "
                            "sync and concretizes",
                        )
                elif isinstance(node, (ast.If, ast.While)):
                    for hit in self._control_flow_hits(
                            ctx, node.test, params):
                        yield ctx.finding(
                            self.name, hit,
                            f"Python {'if' if isinstance(node, ast.If) else 'while'} "
                            f"on traced parameter '{hit.id}' — use lax.cond/"
                            "lax.while_loop or declare it static",
                        )


RULES = [TracerSafetyRule()]
