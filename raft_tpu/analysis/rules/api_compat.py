"""Rule ``api-compat``: version-sensitive JAX symbols must come from
``raft_tpu.compat``.

The banned spellings are read from :data:`raft_tpu.compat.COMPAT_TABLE` —
the same table the runtime shim resolves against — so the linter and the
shim can never drift apart. Both the attribute form (``jax.shard_map(...)``)
and the import form (``from jax.experimental.shard_map import shard_map``)
are flagged. ``raft_tpu/compat.py`` itself resolves symbols by dotted-path
*string*, so it never trips its own rule.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator

from raft_tpu import compat
from raft_tpu.analysis.facts import dotted_chain
from raft_tpu.analysis.rules import Rule


def _banned_map() -> Dict[str, "compat.CompatEntry"]:
    out: Dict[str, compat.CompatEntry] = {}
    for entry in compat.COMPAT_TABLE:
        for spelling in entry.banned:
            out[spelling] = entry
    return out


class ApiCompatRule(Rule):
    name = "api-compat"
    description = (
        "direct use of a version-sensitive JAX symbol; import it from "
        "raft_tpu.compat instead"
    )

    def __init__(self):
        self.banned = _banned_map()

    def _msg(self, spelling: str, entry: "compat.CompatEntry") -> str:
        return (
            f"direct use of '{spelling}' — use "
            f"raft_tpu.compat.{entry.name} ({entry.reason})"
        )

    def check(self, ctx) -> Iterator:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                chain = dotted_chain(node)
                if not chain:
                    continue
                # resolve the root through import aliases so
                # `import jax as j; j.shard_map` is still caught
                root = ctx.facts.aliases.get(chain[0], chain[0])
                dotted = ".".join([root] + chain[1:])
                entry = self.banned.get(dotted)
                # only flag the OUTERMOST matching attribute: for
                # a.b.c both `a.b.c` and `a.b` walk by; the parent check
                # keeps one finding per use
                if entry is not None and not isinstance(
                    ctx.facts.parent.get(node), ast.Attribute
                ):
                    yield ctx.finding(
                        self.name, node, self._msg(dotted, entry)
                    )
            elif isinstance(node, ast.ImportFrom):
                if not node.module or node.level:
                    continue
                for alias in node.names:
                    dotted = f"{node.module}.{alias.name}"
                    entry = self.banned.get(dotted) \
                        or self.banned.get(node.module)
                    if entry is not None:
                        spelling = dotted if dotted in self.banned \
                            else node.module
                        yield ctx.finding(
                            self.name, node, self._msg(spelling, entry)
                        )
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    entry = self.banned.get(alias.name)
                    if entry is not None:
                        yield ctx.finding(
                            self.name, node, self._msg(alias.name, entry)
                        )


RULES = [ApiCompatRule()]
