"""Rules ``adc-gather`` and ``wide-distance-materialize``: the hot-scan
HBM-materialization hazard family.

``adc-gather``: per-candidate LUT gathers on the hot scan path.

A 2^bits-entry lookup table gathered per candidate inside a jitted scan
body is the ADC anti-pattern this codebase measured twice (docs/
ivf_scale.md "ADC in VMEM"): XLA lowers it either to a per-element gather
(random HBM reads, ~50x slower than a slab read at the 10M x 96 shape) or
to a MATERIALIZED one-hot operand — (rows, M·2^bits) bf16 written to and
re-read from HBM per scanned block, hundreds of GB per batch at the 10M
bench geometry. Both spellings belong in the Pallas ADC engine
(raft_tpu/spatial/ann/pq_kernel.py), where the expansion lives in VMEM
and only sub-chunk minima reach HBM.

Like ``recompile-hazard`` this is a *perf* lint, not a correctness one:
flagged sites compute the right answer slowly. Two spellings are flagged,
both only INSIDE traced bodies (the scan path — an eager/offline gather
is fine):

* ``jnp.take_along_axis(..., axis=N)`` with a literal ``N >= 2`` — a
  trailing-axis table gather (the per-(query, probe) LUT axes come first,
  the table axis last: the per-query ADC path's exact shape);
* an ``einsum`` / ``dot_general`` / ``dot`` / ``matmul`` whose operand is
  a one-hot built by comparing against ``arange``/``broadcasted_iota``
  over a wide (>= 128 entries, or unresolvable) index set — directly, or
  via a name assigned from such a compare (``.astype``/``.reshape``
  chains are looked through).

Suppress with ``# jaxlint: disable=adc-gather`` where the gather is cold
or the table is small in practice; the remaining hot-path callers (the
per-query ADC path kept for small-batch latency, and the grouped one-hot
engine kept as the CPU/interpret fallback) are grandfathered in the
baseline and burn down with the kernel rollout.

``wide-distance-materialize`` (the family's second member, ISSUE 10):
a >= 3-subscript-output ``einsum`` — the ``(LB, qcap, L)`` batched
distance tile of a grouped scan — whose result feeds ``lax.top_k`` /
``approx_min_k`` inside the same traced body. XLA materializes the full
tile through HBM just so the selection can read it back and keep k of
every L values; both flat-scan engines (``fused_knn`` and the
``flat_kernel`` sub-chunk-min kernel) exist precisely to fuse that
distance+select so only minima reach HBM (docs/ivf_scale.md "Flat scan
in VMEM"). Taint flows from the einsum through arithmetic /
``where`` / method chains (``.reshape``/``.astype``/``.transpose``) and
stops at any other call boundary, so a 2-d scoring einsum
(``score_l2_candidates``) or a tile consumed by a reduction never
flags. The one intentional legacy caller — the XLA grouped flat scan
kept as the ``use_pallas=False`` bit-stable engine — is grandfathered
in the baseline.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from raft_tpu.analysis.rules import Rule

_GATHER_TAILS = {"take_along_axis"}
_CONTRACT_TAILS = {"einsum", "dot_general", "dot", "matmul"}
_IOTA_TAILS = {"arange", "iota", "broadcasted_iota"}

# one-hot compares against index sets narrower than this are cheap
# (probe masks, small codebooks) and stay unflagged when resolvable
_WIDE = 128


def _literal_axis(call: ast.Call) -> Optional[int]:
    """The gather's axis when given as a literal (kwarg or 3rd arg),
    including the unary-minus spelling ``axis=-1``."""
    def lit(v):
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return v.value
        if isinstance(v, ast.UnaryOp) and isinstance(v.op, ast.USub):
            inner = lit(v.operand)
            return None if inner is None else -inner
        return None

    for kw in call.keywords:
        if kw.arg == "axis":
            return lit(kw.value)
    if len(call.args) >= 3:
        return lit(call.args[2])
    return None


def _int_lit(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


def _iota_width(tail: str, call: ast.Call) -> Optional[int]:
    """The literal width of an arange/iota call, when resolvable.

    ``arange(stop)`` / ``arange(start, stop[, step])`` -> the span;
    ``iota(dtype, size)`` -> size; ``broadcasted_iota(dtype, shape,
    dimension)`` -> shape[dimension] when both are literal. None when
    the width cannot be resolved (the caller flags conservatively)."""
    if tail == "arange":
        lits = [_int_lit(a) for a in call.args]
        if len(call.args) == 1:
            return lits[0]
        if len(call.args) >= 2 and lits[0] is not None and \
                lits[1] is not None:
            return lits[1] - lits[0]
        return None
    if tail == "iota":                       # lax.iota(dtype, size)
        return _int_lit(call.args[1]) if len(call.args) >= 2 else None
    if tail == "broadcasted_iota":           # (dtype, shape, dimension)
        if len(call.args) >= 3 and isinstance(call.args[1],
                                              (ast.Tuple, ast.List)):
            dim = _int_lit(call.args[2])
            elts = call.args[1].elts
            if dim is not None and 0 <= dim < len(elts):
                return _int_lit(elts[dim])
        return None
    return None


class AdcGatherRule(Rule):
    name = "adc-gather"
    description = (
        "per-candidate LUT gather / materialized one-hot contraction "
        "inside a traced body — route through the Pallas ADC engine"
    )

    # -- one-hot detection ---------------------------------------------------

    def _wide_iota_compare(self, ctx, node: ast.AST) -> bool:
        """Does this expression contain a compare against a wide (or
        unresolvable-width) arange/iota call?"""
        for n in ast.walk(node):
            if not isinstance(n, ast.Compare):
                continue
            for side in [n.left] + list(n.comparators):
                for c in ast.walk(side):
                    if not isinstance(c, ast.Call):
                        continue
                    d = ctx.facts.dotted(c.func)
                    if d is None:
                        continue
                    tail = d.split(".")[-1]
                    if tail not in _IOTA_TAILS:
                        continue
                    w = _iota_width(tail, c)
                    if w is None or w >= _WIDE:
                        return True
        return False

    def _onehot_names(self, ctx, fn) -> Set[str]:
        """Names assigned (anywhere in the traced body) from a wide
        iota-compare expression — one-hot matrices by construction."""
        out: Set[str] = set()
        for n in ctx.facts.traced_body_nodes(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                if self._wide_iota_compare(ctx, n.value):
                    out.add(n.targets[0].id)
        return out

    def _operand_root(self, node: ast.AST) -> Optional[str]:
        """Unwrap ``name.reshape(...).astype(...)``-style chains to the
        root Name (how one-hot operands reach the dot in practice)."""
        while True:
            if isinstance(node, ast.Name):
                return node.id
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                node = node.func.value
                continue
            if isinstance(node, ast.Attribute):
                node = node.value
                continue
            return None

    # -- checks --------------------------------------------------------------

    def _check_gather(self, ctx, call: ast.Call) -> Iterator:
        d = ctx.facts.dotted(call.func)
        if d is None or d.split(".")[-1] not in _GATHER_TAILS:
            return
        axis = _literal_axis(call)
        # axis >= 2 or the explicit trailing spelling axis=-1 (the most
        # common way to write the LUT-table gather); axis 0/1 remaps are
        # the benign selection shapes
        if axis is None or (axis < 2 and axis != -1):
            return
        yield ctx.finding(
            self.name, call,
            f"trailing-axis LUT gather (take_along_axis axis={axis}) in a "
            "traced body — per-candidate table lookups bound the ADC scan; "
            "use the Pallas ADC engine (spatial/ann/pq_kernel) or suppress",
        )

    def _check_contraction(self, ctx, call: ast.Call,
                           onehot: Set[str]) -> Iterator:
        d = ctx.facts.dotted(call.func)
        if d is None or d.split(".")[-1] not in _CONTRACT_TAILS:
            return
        for arg in call.args:
            hit = self._wide_iota_compare(ctx, arg)
            if not hit:
                root = self._operand_root(arg)
                hit = root is not None and root in onehot
            if hit:
                yield ctx.finding(
                    self.name, call,
                    "one-hot contraction over a wide index set in a traced "
                    "body — XLA materializes the (rows, M*2^bits) one-hot "
                    "operand through HBM; build it in VMEM instead "
                    "(spatial/ann/pq_kernel) or suppress",
                )
                return

    def check(self, ctx) -> Iterator:
        seen: Set[int] = set()  # nested traced fns share body nodes
        for fn in ctx.facts.traced:
            onehot = self._onehot_names(ctx, fn)
            for node in ctx.facts.traced_body_nodes(fn):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                seen.add(id(node))
                yield from self._check_gather(ctx, node)
                yield from self._check_contraction(ctx, node, onehot)


# selection consumers of a materialized distance tile
_SELECT_TAILS = {"top_k", "approx_min_k", "approx_max_k"}
# calls taint flows THROUGH (element-wise selection keeps the tile a
# tile); every other call boundary stops it
_TAINT_THROUGH = {"where"}
# shape-preserving METHODS taint flows through; any other method —
# notably the reduction spellings .min()/.sum()/.max() — launders it,
# exactly as the function spellings (jnp.min(d2, ...)) do
_METHOD_THROUGH = {"reshape", "astype", "transpose", "swapaxes",
                   "copy", "clip", "view"}
# a distance tile has at least (batch, query, row) axes
_WIDE_OUT = 3


class WideDistanceMaterializeRule(Rule):
    name = "wide-distance-materialize"
    description = (
        "batched >=3-d einsum distance tile consumed by top_k in a "
        "traced body — fuse distance+select (flat_kernel/pq_kernel)"
    )

    def _einsum_out_width(self, ctx, call: ast.Call) -> Optional[int]:
        """Output-subscript count of an ``einsum`` call with a literal
        ``"...->..."`` equation; None for anything else (shape-general
        einsum spellings are rare here and stay unflagged — linter, not
        shape inference)."""
        d = ctx.facts.dotted(call.func)
        if d is None or d.split(".")[-1] != "einsum":
            return None
        if not call.args or not isinstance(call.args[0], ast.Constant) \
                or not isinstance(call.args[0].value, str):
            return None
        eq = call.args[0].value
        if "->" not in eq:
            return None
        out = eq.split("->")[-1].strip()
        return None if "." in out else len(out)

    def _wide_einsum(self, ctx, node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Call)
            and (self._einsum_out_width(ctx, node) or 0) >= _WIDE_OUT
        )

    def _tainted(self, ctx, node: ast.AST, names: Set[str]) -> bool:
        """Does this expression carry a wide-einsum tile — directly, via
        a tainted name, or through arithmetic / ``where`` / method
        chains? Any other call boundary launders the taint (a reduction
        or selection call returns something narrower)."""
        if isinstance(node, ast.Name):
            return node.id in names
        if isinstance(node, ast.BinOp):
            return self._tainted(ctx, node.left, names) or \
                self._tainted(ctx, node.right, names)
        if isinstance(node, ast.UnaryOp):
            return self._tainted(ctx, node.operand, names)
        if isinstance(node, (ast.Subscript, ast.Attribute)):
            return self._tainted(ctx, node.value, names)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._tainted(ctx, e, names) for e in node.elts)
        if isinstance(node, ast.Call):
            if self._wide_einsum(ctx, node):
                return True
            d = ctx.facts.dotted(node.func)
            tail = d.split(".")[-1] if d else None
            if tail in _TAINT_THROUGH:
                return any(self._tainted(ctx, a, names)
                           for a in node.args)
            if isinstance(node.func, ast.Attribute):
                # only shape-preserving methods carry the tile through:
                # a method-spelled reduction (d2.min(axis=2)) launders
                # exactly like its function spelling
                if node.func.attr not in _METHOD_THROUGH:
                    return False
                base = node.func.value
                if isinstance(base, ast.Call):
                    # method chained onto a call's RESULT —
                    # einsum(...).astype(...), where(...).reshape(...):
                    # taint is a property of that inner call, so
                    # re-evaluate it (a laundering call like
                    # jnp.sum(d2).reshape(...) still returns False
                    # through this same recursion)
                    return self._tainted(ctx, base, names)
                # method chain on a value: d2.reshape(...).astype(...) —
                # but a MODULE function named like one (jnp.reshape(d2))
                # must not taint through its module name; an
                # imported-alias root is a module, a plain value root
                # is not
                root = base
                while isinstance(root, (ast.Attribute, ast.Subscript)):
                    root = root.value
                if isinstance(root, ast.Name) and \
                        root.id not in ctx.facts.aliases:
                    return self._tainted(ctx, node.func.value, names)
            return False
        return False

    def check(self, ctx) -> Iterator:
        seen: Set[int] = set()  # nested traced fns share body nodes
        for fn in ctx.facts.traced:
            # taint fixpoint over single-Name assignments: order-free,
            # so `d2 = qn + mn - 2*dots; d2 = where(m, inf, d2)` chains
            # resolve without relying on statement order
            assigns = [
                (n.targets[0].id, n.value)
                for n in ctx.facts.traced_body_nodes(fn)
                if isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
            ]
            names: Set[str] = set()
            while True:
                grew = False
                for tgt, val in assigns:
                    if tgt not in names and self._tainted(ctx, val, names):
                        names.add(tgt)
                        grew = True
                if not grew:
                    break
            for node in ctx.facts.traced_body_nodes(fn):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                d = ctx.facts.dotted(node.func)
                if d is None or d.split(".")[-1] not in _SELECT_TAILS:
                    continue
                if not node.args or not self._tainted(
                    ctx, node.args[0], names
                ):
                    continue
                seen.add(id(node))
                yield ctx.finding(
                    self.name, node,
                    "a wide (>=3-d output) einsum distance tile feeds "
                    "top_k in a traced body — XLA materializes the "
                    "(·, qcap, L) tile through HBM for the selection to "
                    "re-read; fuse distance+select in the Pallas scan "
                    "engine (spatial/ann/flat_kernel) or suppress",
                )


RULES = [AdcGatherRule(), WideDistanceMaterializeRule()]
