"""Rule ``host-fetch-in-traced-body``: a host→device fetch, a tier
membership mutation, or a pinned-slab read inside a jitted/traced body.

The tier (raft_tpu/tier/, docs/tiering.md) lives on a strict split:
the COLD slab is host memory, and the ONLY paths that touch it are
host-side — :meth:`TieredListStore.fetch_slab` reads it, the install
path ``jax.device_put`` s it, and the membership methods republish the
runtime snapshot. Any of these inside a traced body breaks the design
twice over:

* ``jax.device_put(...)`` at trace time embeds the CURRENT slab as a
  compile-time CONSTANT: the program serves that frozen snapshot
  forever, every promotion after it is invisible, and a slab-sized
  constant is baked into the executable (an HBM copy per cached
  variant — the exact wall the tier exists to break);
* a tier-store call (``fetch_slab``/``promote``/``apply_moves``/
  ``sync_mutations``/...) is Python state + locks: it runs ONCE at
  trace time, so the compiled program never fetches, never promotes,
  and never sees another mutation epoch — the serving answer silently
  pins to the trace-time membership;
* a pinned host-slab subscript read (``self._data_np[...]``,
  ``host_slab[...]``, ``cold_rows[...]``) is the same constant-bake in
  disguise — numpy indexing traces to a constant operand.

The tier's contract is the executor's: fetch on the HOST (the
fetcher thread, the ``runtime_provider`` hook), hand the traced body
ONLY device arrays as runtime operands. Genuine trace-time constants
that happen to share a spelling carry
``# jaxlint: disable=host-fetch-in-traced-body`` on the line (or live
in ci/checks/jaxlint_baseline.json).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional

from raft_tpu.analysis.rules import Rule

# the host→device staging entry points — at trace time each bakes its
# operand into the program as a constant
_DEVICE_PUTS = {
    "jax.device_put",
    "jax.device_put_sharded",
    "jax.device_put_replicated",
}

# tier-store methods whose bodies are host state + locks; `fetch_slab`
# is distinctive enough to flag on ANY receiver, the rest only on a
# tier-shaped one (a generic `plan.promote()` must not match)
_TIER_ALWAYS = {"fetch_slab"}
_TIER_METHODS = {
    "promote", "demote", "apply_moves", "rebalance",
    "sync_mutations", "refresh_host", "request",
}
_TIER_RECV = re.compile(
    r"(^|_)(tier|tiered|store|slab|fetcher)($|_|s$)"
)

# pinned host-slab spellings for the subscript-read heuristic: the
# repo's own host-mirror convention is the `_np` suffix
# (`self._data_np`), plus the generic host/pinned/cold tokens
_HOST_BUF = re.compile(
    r"(_np$|(^|_)(host|pinned|cold)($|_))"
)


def _dotted_name(node: ast.AST) -> Optional[str]:
    """Name/Attribute chain with dots normalized to underscores
    (``self._data_np`` -> ``self__data_np``), or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return "_".join(reversed(parts))
    return None


class HostFetchInTracedBodyRule(Rule):
    name = "host-fetch-in-traced-body"
    description = (
        "host->device fetch (device_put), tier-store call, or pinned "
        "host-slab read inside a traced body — runs once at trace "
        "time and bakes the slab in as a constant"
    )

    def _device_put(self, ctx, call: ast.Call) -> Optional[str]:
        d = ctx.facts.dotted(call.func)
        if d in _DEVICE_PUTS:
            return d
        return None

    def _tier_call(self, ctx, call: ast.Call) -> Optional[str]:
        fn = call.func
        if not isinstance(fn, ast.Attribute):
            return None
        if fn.attr in _TIER_ALWAYS:
            recv = _dotted_name(fn.value) or "<store>"
            return f"{recv}.{fn.attr}()"
        if fn.attr in _TIER_METHODS:
            recv = _dotted_name(fn.value)
            if recv is not None and _TIER_RECV.search(recv.lower()):
                return f"{recv}.{fn.attr}()"
        return None

    def _host_read(self, node: ast.Subscript) -> Optional[str]:
        # reads only: a Store/Del context is an ordinary host mutation
        # some OTHER rule may care about, not a constant-bake
        if not isinstance(node.ctx, ast.Load):
            return None
        d = _dotted_name(node.value)
        if d is not None and _HOST_BUF.search(d.lower()):
            return d
        return None

    def check(self, ctx) -> Iterator:
        seen: set = set()          # nested traced fns share body nodes
        for fn in ctx.facts.traced:
            body = [
                n for n in ctx.facts.traced_body_nodes(fn)
                if id(n) not in seen and not seen.add(id(n))
            ]
            for node in body:
                if isinstance(node, ast.Call):
                    put = self._device_put(ctx, node)
                    if put is not None:
                        yield ctx.finding(
                            self.name, node,
                            f"{put}(...) inside a traced body embeds "
                            "its operand as a COMPILE-TIME constant — "
                            "the program serves that frozen snapshot "
                            "forever; stage on the host (the fetcher "
                            "thread / runtime_provider) and pass the "
                            "device array in as a runtime operand",
                        )
                        continue
                    tier = self._tier_call(ctx, node)
                    if tier is not None:
                        yield ctx.finding(
                            self.name, node,
                            f"{tier} inside a traced body runs ONCE at "
                            "trace time — the compiled program never "
                            "fetches, promotes, or syncs again; drive "
                            "tier membership from the host and hand "
                            "the body the published snapshot",
                        )
                elif isinstance(node, ast.Subscript):
                    buf = self._host_read(node)
                    if buf is not None:
                        yield ctx.finding(
                            self.name, node,
                            f"pinned host-slab read {buf}[...] inside "
                            "a traced body traces to a baked-in "
                            "constant operand — fetch on the host "
                            "(TieredListStore.fetch_slab) and "
                            "device_put OUTSIDE the traced body",
                        )


RULES = [HostFetchInTracedBodyRule()]
