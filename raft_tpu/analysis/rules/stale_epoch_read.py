"""Rule ``stale-epoch-read``: a result-cache lookup that does not
thread the current mutation epoch.

The hot-traffic result cache (raft_tpu/serving/result_cache.py,
docs/serving.md "Hot traffic") is invalidated by MUTATION EPOCH, not by
key: every entry is stamped with its writer's epoch, and a lookup that
presents a newer epoch treats the entry as stale. That whole contract
rests on the call site actually threading a LIVE epoch value — the one
way to silently bypass invalidation is a lookup that pins the epoch
(``cache.lookup(rows, epoch=0)``) or omits it through a forwarding
layer: every mutation still bumps the counter, but the reader never
presents it, so pre-write results keep serving forever (the exact
hazard the ISSUE 15 chaos test pins).

Flagged, for calls of a ``lookup`` method on a cache-shaped receiver
(a dotted name containing ``cache`` — ``result_cache.lookup``,
``self._rcache.lookup``):

* no argument references an epoch-carrying value (no ``epoch=`` keyword
  and no positional argument whose expression mentions an
  ``epoch``-ish name) — the lookup cannot be presenting the current
  epoch;
* ``epoch=<literal>`` (an int or ``None`` constant) — a pinned epoch
  is the invalidation bypass in its most direct spelling.

``epoch=self._epoch_fn()``, ``epoch=mindex.epoch``, ``epoch=ep`` are
all clean — any name or attribute mentioning ``epoch`` counts as
threading one. A GENUINELY frozen index (no mutation path exists, the
constant is the contract) suppresses inline with
``# jaxlint: disable=stale-epoch-read``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from raft_tpu.analysis.rules import Rule

_CACHE_RE = re.compile(r"cache", re.IGNORECASE)
_EPOCH_RE = re.compile(r"epoch", re.IGNORECASE)


def _dotted_name(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return "_".join(reversed(parts))
    return None


def _mentions_epoch(call: ast.Call) -> bool:
    """True when any ARGUMENT of ``call`` carries an epoch-ish value
    (``epoch``, ``self._rt_epoch``, ``epoch_fn()``...). Only the
    arguments are walked — an epoch-suggestive RECEIVER name
    (``epoch_cache.lookup(rows)``) threads nothing and must still
    flag."""
    roots: list = list(call.args)
    for kw in call.keywords:
        if kw.arg and _EPOCH_RE.search(kw.arg):
            return True
        roots.append(kw.value)
    for root in roots:
        for n in ast.walk(root):
            if isinstance(n, ast.Name) and _EPOCH_RE.search(n.id):
                return True
            if isinstance(n, ast.Attribute) and _EPOCH_RE.search(n.attr):
                return True
    return False


class StaleEpochReadRule(Rule):
    name = "stale-epoch-read"
    description = (
        "result-cache lookup without a live mutation epoch — "
        "invalidation bypassed, pre-write results serve forever"
    )

    def check(self, ctx) -> Iterator:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr == "lookup"):
                continue
            recv = _dotted_name(fn.value)
            if recv is None or not _CACHE_RE.search(recv):
                continue
            epoch_kw = next(
                (kw for kw in node.keywords if kw.arg == "epoch"), None
            )
            if epoch_kw is not None and isinstance(
                epoch_kw.value, ast.Constant
            ):
                yield ctx.finding(
                    self.name, node,
                    f"{recv}.lookup(epoch={epoch_kw.value.value!r}) "
                    "pins the mutation epoch to a constant — every "
                    "write still bumps the counter but this reader "
                    "never presents it, so stale entries serve "
                    "forever; thread the live epoch (suppress only "
                    "for a genuinely frozen index)",
                )
                continue
            if not _mentions_epoch(node):
                yield ctx.finding(
                    self.name, node,
                    f"{recv}.lookup(...) threads no mutation epoch — "
                    "epoch-stamped invalidation is bypassed and "
                    "pre-write results can keep serving after an "
                    "upsert/delete/compact; pass epoch=<current "
                    "epoch> (docs/serving.md 'Hot traffic')",
                )


RULES = [StaleEpochReadRule()]
