"""Rule ``prng-discipline``: a PRNG key consumed by two sampling calls
without an intervening ``split``/``fold_in`` produces *identical* streams —
statistically catastrophic and invisible to tests that only check shapes.

Tracking is per-function and flow-insensitive-but-ordered: a name becomes a
live key when assigned from ``PRNGKey``/``key``/``fold_in``/``split``; a
direct ``jax.random.<dist>`` call consumes it (passing it to ``split`` /
``fold_in`` derives, never consumes; any reassignment refreshes). The
second consumption of the same live key is flagged. Keys handed to helper
functions are not tracked across the call boundary — this is a linter, not
an escape analysis; the common bug (two ``jax.random.normal(key, ...)``
draws in one body) is exactly what it catches.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Union

from raft_tpu.analysis.rules import Rule

_KEY_MAKERS = {"PRNGKey", "key", "fold_in", "split", "wrap_key_data"}
_NON_CONSUMING = {"split", "fold_in", "PRNGKey", "key", "wrap_key_data",
                  "key_data", "clone"}
_RANDOM_NS = "jax.random"


def _walk_same_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk like ast.walk but do not descend into nested function scopes —
    a nested def runs at its own (unknown) time, so its draws cannot be
    ordered against this scope's; each nested def is scanned separately."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield from _walk_same_scope(child)


class PrngDisciplineRule(Rule):
    name = "prng-discipline"
    description = "PRNG key reused by multiple draws without split/fold_in"

    def _random_tail(self, ctx, call: ast.Call) -> Union[str, None]:
        """'normal' for jax.random.normal(...), None for non-random calls."""
        d = ctx.facts.dotted(call.func)
        if d is None:
            return None
        if d.startswith(_RANDOM_NS + "."):
            return d[len(_RANDOM_NS) + 1:]
        return None

    def _scan_body(self, ctx, body: List[ast.stmt]) -> Iterator:
        # name -> "live" (fresh key) | "consumed"
        state: Dict[str, str] = {}
        yield from self._scan_stmts(ctx, body, state)

    def _scan_stmts(self, ctx, stmts: List[ast.stmt],
                    state: Dict[str, str]) -> Iterator:
        """Statement-list scan with branch awareness: if/else arms execute
        mutually exclusively, so each scans a fork of the state; the merge
        keeps 'consumed' from either arm (a draw in one arm still blocks a
        later unconditional draw) but never counts the arms against each
        other. Loop/with/try bodies share the sequential state."""
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                yield from self._scan_flat(ctx, stmt.test, state)
                s_else = dict(state)
                yield from self._scan_stmts(ctx, stmt.body, state)
                yield from self._scan_stmts(ctx, stmt.orelse, s_else)
                for k in set(state) | set(s_else):
                    vals = {state.get(k), s_else.get(k)}
                    if "consumed" in vals:
                        state[k] = "consumed"
                    elif "live" in vals:
                        state[k] = "live"
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from self._scan_flat(ctx, stmt.iter, state)
                yield from self._scan_stmts(ctx, stmt.body, state)
                yield from self._scan_stmts(ctx, stmt.orelse, state)
            elif isinstance(stmt, ast.While):
                yield from self._scan_flat(ctx, stmt.test, state)
                yield from self._scan_stmts(ctx, stmt.body, state)
                yield from self._scan_stmts(ctx, stmt.orelse, state)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    yield from self._scan_flat(
                        ctx, item.context_expr, state)
                yield from self._scan_stmts(ctx, stmt.body, state)
            elif isinstance(stmt, ast.Try):
                yield from self._scan_stmts(ctx, stmt.body, state)
                for handler in stmt.handlers:
                    yield from self._scan_stmts(ctx, handler.body, state)
                yield from self._scan_stmts(ctx, stmt.orelse, state)
                yield from self._scan_stmts(ctx, stmt.finalbody, state)
            else:
                yield from self._scan_flat(ctx, stmt, state)

    def _scan_flat(self, ctx, node: ast.AST,
                   state: Dict[str, str]) -> Iterator:
        """Consumptions then assignments within one flat statement/expr."""
        for n in _walk_same_scope(node):
            if isinstance(n, ast.Call):
                tail = self._random_tail(ctx, n)
                if tail is None or tail in _NON_CONSUMING or "." in tail:
                    continue
                used = [
                    a for a in list(n.args) + [k.value for k in n.keywords]
                    if isinstance(a, ast.Name) and a.id in state
                ]
                for name_node in used:
                    if state[name_node.id] == "consumed":
                        yield ctx.finding(
                            self.name, n,
                            f"key '{name_node.id}' already consumed by "
                            f"an earlier draw — jax.random.{tail} will "
                            "replay the same stream; split or fold_in "
                            "first",
                        )
                    else:
                        state[name_node.id] = "consumed"
        # assignments refresh liveness AFTER uses in the same stmt
        for n in _walk_same_scope(node):
            if isinstance(n, ast.Assign):
                value_is_key = (
                    isinstance(n.value, ast.Call)
                    and self._is_key_maker(ctx, n.value)
                )
                for tgt in n.targets:
                    for t in ast.walk(tgt):
                        if isinstance(t, ast.Name):
                            if value_is_key:
                                state[t.id] = "live"
                            else:
                                state.pop(t.id, None)

    def _is_key_maker(self, ctx, call: ast.Call) -> bool:
        d = ctx.facts.dotted(call.func)
        if d is None:
            # obj.key() style (RngState.key) counts as a maker
            return isinstance(call.func, ast.Attribute) and \
                call.func.attr in _KEY_MAKERS
        return d.split(".")[-1] in _KEY_MAKERS

    def check(self, ctx) -> Iterator:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan_body(ctx, node.body)


RULES = [PrngDisciplineRule()]
