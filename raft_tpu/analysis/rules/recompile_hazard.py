"""Rule ``recompile-hazard``: patterns that defeat jit's compile cache.

Every cache miss on the serving path is a multi-second XLA compile under
traffic, so these are production hazards, not style nits:

* ``static_argnums``/``static_argnames`` given as a *dynamic expression* —
  the static spec itself must be a literal, or every call site silently
  traces its own variant;
* mutable (list/dict/set) default parameter on a jit-traced function —
  unhashable as a static and a retrace per call when captured;
* f-strings inside a traced body — host string formatting re-runs at every
  trace; the classic offender builds cache keys / debug labels from traced
  values, which forces the recompile it tried to observe;
* closure over a variable the enclosing function mutates with ``+=``-style
  augmented assignment — its value varies per call, so each call traces a
  new constant.
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_tpu.analysis.rules import Rule

_JIT_TAILS = {"jit", "pjit"}


def _is_literal_spec(node: ast.AST) -> bool:
    """Constant, or tuple/list of constants (incl. unary minus)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _is_literal_spec(node.operand)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_literal_spec(e) for e in node.elts)
    return False


class RecompileHazardRule(Rule):
    name = "recompile-hazard"
    description = "pattern that forces avoidable jit recompiles"

    def _check_jit_call(self, ctx, call: ast.Call) -> Iterator:
        d = ctx.facts.dotted(call.func)
        if d is None or d.split(".")[-1] not in _JIT_TAILS:
            return
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames") and \
                    not _is_literal_spec(kw.value):
                yield ctx.finding(
                    self.name, kw.value,
                    f"{kw.arg} is a dynamic expression — the static spec "
                    "must be a literal or every call site compiles its own "
                    "variant",
                )

    def _check_defaults(self, ctx, fn) -> Iterator:
        if isinstance(fn, ast.Lambda):
            return
        for default in fn.args.defaults + [
            d for d in fn.args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                yield ctx.finding(
                    self.name, default,
                    f"mutable default on jit-traced '{fn.name}' — "
                    "unhashable as a static argument and retraces when "
                    "its identity changes",
                )

    def _check_closure_mutation(self, ctx, fn) -> Iterator:
        """Traced nested function reading a name its enclosing scope
        mutates via augmented assignment."""
        enclosing = ctx.facts.parent.get(fn)
        while enclosing is not None and not isinstance(
                enclosing, (ast.FunctionDef, ast.AsyncFunctionDef)):
            enclosing = ctx.facts.parent.get(enclosing)
        if enclosing is None:
            return
        mutated = set()
        for n in ast.walk(enclosing):
            if isinstance(n, ast.AugAssign) and \
                    isinstance(n.target, ast.Name):
                # ignore mutations inside the traced fn itself
                inside = n
                while inside is not None and inside is not fn:
                    inside = ctx.facts.parent.get(inside)
                if inside is None:
                    mutated.add(n.target.id)
        if not mutated:
            return
        local = set()
        for n in ctx.facts.traced_body_nodes(fn):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                tgt = n.targets[0] if isinstance(n, ast.Assign) else n.target
                if isinstance(tgt, ast.Name):
                    local.add(tgt.id)
        params = set()
        a = fn.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            params.add(p.arg)
        seen = set()
        for n in ctx.facts.traced_body_nodes(fn):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) and \
                    n.id in mutated and n.id not in params and \
                    n.id not in local and n.id not in seen:
                seen.add(n.id)
                yield ctx.finding(
                    self.name, n,
                    f"traced closure reads '{n.id}', which the enclosing "
                    "function mutates — its value varies per call, so each "
                    "call traces a fresh constant (recompile)",
                )

    def check(self, ctx) -> Iterator:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                yield from self._check_jit_call(ctx, node)
        for fn in ctx.facts.traced:
            yield from self._check_defaults(ctx, fn)
            yield from self._check_closure_mutation(ctx, fn)
            for node in ctx.facts.traced_body_nodes(fn):
                if isinstance(node, ast.JoinedStr):
                    yield ctx.finding(
                        self.name, node,
                        "f-string inside a traced body — host formatting "
                        "re-runs per trace; if it feeds a cache key or "
                        "label from traced values it forces recompiles",
                    )


RULES = [RecompileHazardRule()]
