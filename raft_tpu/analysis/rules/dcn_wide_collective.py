"""Rule ``dcn-wide-collective``: a full-width collective spanning a DCN
(outer) mesh axis inside a traced serving-path body.

The whole cross-host serving budget is DCN bytes (docs/multihost.md
"Byte accounting"): ICI moves ~10-100x more bytes per second than the
links between hosts, so one collective that ships full per-chip payloads
across the dcn axis at deployment width erases the win the hierarchical
two-stage structure bought — every chip's uncompressed part crosses
every host boundary, exactly the flat-allgather shape
``_merge_across_shards`` exists to avoid on 2-level meshes. The hazard
is silent: the program is correct, compiles, and passes every
bit-identity test; only the wire meter notices.

Flagged — inside a traced body (``jit``/``shard_map``/``scan``/... per
:mod:`raft_tpu.analysis.facts`):

* ``lax.all_gather`` / ``lax.psum`` / ``lax.pmean`` / ``lax.pmax`` /
  ``lax.pmin`` / ``lax.psum_scatter`` / ``lax.all_to_all`` whose axis
  argument is a
  LITERAL tuple/list naming a dcn-ish outer axis (``dcn`` / ``outer`` /
  ``hosts``) TOGETHER with at least one other axis — the one-collective
  -over-both-levels spelling. An inner-axis pre-reduction is available
  by construction (the other named axis IS the inner one): restructure
  as inner reduce-scatter -> dcn collective on 1/inner_size of the
  bytes -> inner allgather
  (:meth:`~raft_tpu.comms.comms.HierarchicalComms.
  hierarchical_allreduce`), or for top-k merges the two-stage
  compressed-wire tail
  (:func:`raft_tpu.comms.multihost.hierarchical_merge_select_k`).

A collective over the dcn axis ALONE is not flagged: that is the
hierarchy's own DCN stage (it runs after the inner pre-reduction and
moves the already-shrunk payload). Same for single-axis inner
collectives. Intentional full-width collectives — a control-plane
barrier, a tiny scalar psum — carry
``# jaxlint: disable=dcn-wide-collective`` on the line (or live in
ci/checks/jaxlint_baseline.json).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from raft_tpu.analysis.rules import Rule

# collectives that move O(payload) bytes over every axis they name
_WIDE_COLLECTIVES = {
    "all_gather", "psum", "pmean", "pmax", "pmin", "psum_scatter",
    "all_to_all",
}
# outer-axis spellings used for the cross-host level (comms.py builds
# the 2-level mesh as axes=("dcn", "ici"); "outer"/"hosts" cover ad-hoc
# meshes in tests and benches)
_DCN_NAMES = {"dcn", "outer", "hosts", "host"}


def _axis_names(node: ast.AST) -> Optional[list]:
    """The literal axis-name list of a tuple/list AST node, or None when
    any element is not a string constant (dynamic axes are out of a
    lexical linter's reach — the baseline absorbs those)."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    names = []
    for el in node.elts:
        if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
            return None
        names.append(el.value)
    return names


class DcnWideCollectiveRule(Rule):
    name = "dcn-wide-collective"
    description = (
        "full-width collective over a dcn (outer) mesh axis in a traced "
        "body — pre-reduce over the inner axis first "
        "(hierarchical_allreduce / hierarchical_merge_select_k)"
    )

    def _axis_arg(self, ctx, call: ast.Call) -> Optional[ast.AST]:
        """The axis-name argument of a lax collective call: the second
        positional, or the ``axis_name=`` keyword."""
        for kw in call.keywords:
            if kw.arg == "axis_name":
                return kw.value
        if len(call.args) >= 2:
            return call.args[1]
        return None

    def _wide_dcn_call(self, ctx, call: ast.Call) -> Optional[str]:
        d = ctx.facts.dotted(call.func)
        if d is None:
            return None
        tail = d.split(".")[-1]
        if tail not in _WIDE_COLLECTIVES or "lax" not in d.split("."):
            return None
        axis = self._axis_arg(ctx, call)
        if axis is None:
            return None
        names = _axis_names(axis)
        if names is None or len(names) < 2:
            return None
        dcn = [n for n in names if n.lower() in _DCN_NAMES]
        rest = [n for n in names if n.lower() not in _DCN_NAMES]
        if not dcn or not rest:
            return None
        return (
            f"lax.{tail} over {tuple(names)} ships full per-chip "
            f"payloads across the {dcn[0]!r} (DCN) axis at deployment "
            f"width"
        )

    def check(self, ctx) -> Iterator:
        traced_nodes = set()
        for fn in ctx.facts.traced:
            traced_nodes.update(
                id(n) for n in ctx.facts.traced_body_nodes(fn)
            )
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if id(node) not in traced_nodes:
                continue
            what = self._wide_dcn_call(ctx, node)
            if what is None:
                continue
            yield ctx.finding(
                self.name, node,
                f"{what} — one such collective erases the hierarchical "
                "merge's DCN saving; pre-reduce over the inner (ICI) "
                "axis first: hierarchical_allreduce for reductions, "
                "hierarchical_merge_select_k for top-k merges, or "
                "suppress if the payload is a scalar/control barrier",
            )


RULES = [DcnWideCollectiveRule()]
