"""jaxlint — JAX/TPU-aware static analysis for raft_tpu, in three tiers.

**Tier 1 — the AST linter** (:mod:`raft_tpu.analysis.rules`): a
multi-pass source analyzer purpose-built for this codebase's JAX idioms
(the reference RAFT's custom ``include_checker``-style CI checks, grown
to cover the hazards a jit/shard_map codebase actually hits):

* ``api-compat`` — version-sensitive JAX symbols used directly instead of
  through :mod:`raft_tpu.compat` (driven by ``compat.COMPAT_TABLE``);
* ``tracer-safety`` — host ops on traced values inside traced bodies;
* ``recompile-hazard`` — dynamic static specs, mutable jit defaults,
  trace-time f-strings, mutated-closure captures;
* ``x64-hygiene`` — 64-bit dtypes crossing the jnp boundary unguarded;
* ``prng-discipline`` — PRNG key reuse without split/fold_in;
* ``adc-gather`` / ``wide-distance-materialize`` — HBM-materialization
  hazards on the hot scan paths;
* ``mutation-retrace`` / ``sync-in-hot-path`` /
  ``dcn-wide-collective`` — serving-tier recompile/sync/wire hazards.

**Tier 2 — the program auditor** (:mod:`raft_tpu.analysis.program`):
lints TRACED JAXPRS instead of source text — a jaxpr walker feeding five
passes (collective census, materialization model, dtype flow, donation
check, cached-program count) over the registry of fused serving
programs, with per-program contracts snapshotted in
``ci/checks/program_contracts.json`` and drift-checked by
``ci/run.sh programs``.

**Tier 3 — the concurrency auditor** (:mod:`raft_tpu.analysis.threads`):
a per-class shared-state census feeding four lock-discipline rules
(``unguarded-shared-state``, ``lock-in-traced-body``,
``blocking-call-under-lock``, ``sleep-under-lock``), a cross-module
acquired-while-held lock-order graph with cycle detection and drift
discipline against ``ci/checks/lock_order.json``, and an injectable
:class:`~raft_tpu.analysis.threads.runtime.TracedLock` runtime tracer
(``RAFT_TPU_LOCKCHECK=1``) that asserts the same pinned order under
real interleavings — gated by ``ci/run.sh threads``.

CLI: ``python -m raft_tpu.analysis [paths] [--format json] [--baseline F]
[--write-baseline] [--rules a,b] [--list-rules]`` for the source tier;
``--programs [--contracts F] [--write-contracts] [--list-programs]`` for
the program tier; ``--threads [--lock-order F] [--write-lock-order]``
for the thread tier. Per-line suppression:
``# jaxlint: disable=<rule>[,<rule>]``. See docs/static_analysis.md
("Three tiers").
"""

from raft_tpu.analysis.engine import (
    Baseline,
    Finding,
    LintResult,
    lint_file,
    lint_paths,
    lint_source,
    main,
)
from raft_tpu.analysis.facts import ModuleFacts

__all__ = [
    "Baseline",
    "Finding",
    "LintResult",
    "ModuleFacts",
    "lint_file",
    "lint_paths",
    "lint_source",
    "main",
]
