"""jaxlint — JAX/TPU-aware static analysis for raft_tpu.

A multi-pass AST analyzer purpose-built for this codebase's JAX idioms
(the reference RAFT's custom ``include_checker``-style CI checks, grown to
cover the hazards a jit/shard_map codebase actually hits):

* ``api-compat`` — version-sensitive JAX symbols used directly instead of
  through :mod:`raft_tpu.compat` (driven by ``compat.COMPAT_TABLE``);
* ``tracer-safety`` — host ops on traced values inside traced bodies;
* ``recompile-hazard`` — dynamic static specs, mutable jit defaults,
  trace-time f-strings, mutated-closure captures;
* ``x64-hygiene`` — 64-bit dtypes crossing the jnp boundary unguarded;
* ``prng-discipline`` — PRNG key reuse without split/fold_in.

CLI: ``python -m raft_tpu.analysis [paths] [--format json] [--baseline F]
[--write-baseline] [--rules a,b] [--list-rules]``. Per-line suppression:
``# jaxlint: disable=<rule>[,<rule>]``. See docs/static_analysis.md.
"""

from raft_tpu.analysis.engine import (
    Baseline,
    Finding,
    LintResult,
    lint_file,
    lint_paths,
    lint_source,
    main,
)
from raft_tpu.analysis.facts import ModuleFacts

__all__ = [
    "Baseline",
    "Finding",
    "LintResult",
    "ModuleFacts",
    "lint_file",
    "lint_paths",
    "lint_source",
    "main",
]
