"""Resource handle — TPU-native analog of ``raft::handle_t``.

The reference handle (cpp/include/raft/core/handle.hpp:54-335) carries CUDA
streams, a stream pool, lazily-created cuBLAS/cuSOLVER/cuSPARSE handles, device
properties, and an injected communicator. On TPU, XLA owns scheduling and
kernel libraries, so the handle reduces to:

* the target device(s) and an optional ``jax.sharding.Mesh`` (the comms slot:
  reference handle.hpp:239-264 ``set_comms``/``get_comms``);
* compile/runtime policy: default float dtype, matmul precision, whether to
  donate buffers;
* a stream-pool analog: independent *dispatch lanes* are expressed simply as
  separate ``jax.jit`` dispatches (async by default) — we keep an integer
  ``n_lanes`` for API parity with ``get_stream_pool_size``.

Everything is cheap, immutable-ish, and safe to share across algorithms, like
the reference object.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional

import jax
import numpy as np


@dataclasses.dataclass
class Resources:
    """Per-algorithm-invocation resource context.

    Parameters mirror the semantics (not the fields) of ``raft::handle_t``.

    Attributes
    ----------
    device : the primary jax device computations land on.
    mesh : optional device mesh used by multi-chip algorithms; the analog of
        the injected ``comms_t`` (reference core/handle.hpp:239).
    sub_meshes : named sub-communicators, analog of
        ``set_subcomm/get_subcomm`` (reference core/handle.hpp:252-262).
    dtype : default floating dtype for algorithm internals.
    matmul_precision : passed to ``jax.lax`` dot ops ("default" | "float32" |
        "bfloat16_3x" ...). f32 accumulate on MXU is always used via
        ``preferred_element_type``.
    n_lanes : stream-pool-size analog (reference handle.hpp:158-237); used by
        batched algorithms to decide how many independent dispatches to keep
        in flight.
    """

    device: Any = None
    mesh: Optional[jax.sharding.Mesh] = None
    sub_meshes: dict = dataclasses.field(default_factory=dict)
    dtype: Any = np.float32
    matmul_precision: str = "highest"
    n_lanes: int = 1

    def __post_init__(self):
        if self.device is None:
            self.device = jax.devices()[0]

    # -- comms slot ---------------------------------------------------------
    def set_mesh(self, mesh: jax.sharding.Mesh) -> None:
        """Inject the communicator (analog of handle.set_comms)."""
        self.mesh = mesh

    def get_mesh(self) -> jax.sharding.Mesh:
        if self.mesh is None:
            raise RuntimeError(
                "No mesh set on Resources (analog of 'ERROR: communicator was not initialized')"
            )
        return self.mesh

    @property
    def has_mesh(self) -> bool:
        return self.mesh is not None

    def set_sub_mesh(self, key: str, mesh: jax.sharding.Mesh) -> None:
        self.sub_meshes[key] = mesh

    def get_sub_mesh(self, key: str) -> jax.sharding.Mesh:
        return self.sub_meshes[key]

    # -- stream-pool parity --------------------------------------------------
    def get_n_lanes(self) -> int:
        return max(1, int(self.n_lanes))

    # -- device properties ---------------------------------------------------
    def device_kind(self) -> str:
        return getattr(self.device, "device_kind", "cpu")

    def is_tpu(self) -> bool:
        return getattr(self.device, "platform", "cpu") == "tpu"

    def sync(self, *arrays) -> None:
        """Block until the given arrays (or, with no args, all dispatched
        side-effecting computations) are done.

        Analog of ``handle.sync_stream()``. JAX gives no global barrier over
        *pure* in-flight computations that you hold no reference to — pass
        the outputs you need ordered: ``res.sync(out)``.
        """
        if arrays:
            jax.block_until_ready(arrays)
        else:
            jax.effects_barrier()


# Backwards-compatible alias mirroring raft 22.08's rename handle_t -> device_resources
DeviceResources = Resources

_default_lock = threading.Lock()
_default_resources: Optional[Resources] = None


def get_default_resources() -> Resources:
    """Process-wide default handle (lazily created), for API convenience.

    The reference requires an explicit handle everywhere; we accept ``None``
    in public APIs and fall back to this.
    """
    global _default_resources
    with _default_lock:
        if _default_resources is None:
            _default_resources = Resources()
        return _default_resources


def ensure_resources(res: Optional[Resources]) -> Resources:
    return res if res is not None else get_default_resources()
