"""Resource handle — TPU-native analog of ``raft::handle_t``.

The reference handle (cpp/include/raft/core/handle.hpp:54-335) carries CUDA
streams, a stream pool, lazily-created cuBLAS/cuSOLVER/cuSPARSE handles, device
properties, and an injected communicator. On TPU, XLA owns scheduling and
kernel libraries, so the handle reduces to:

* the target device(s) and an optional ``jax.sharding.Mesh`` (the comms slot:
  reference handle.hpp:239-264 ``set_comms``/``get_comms``);
* compile/runtime policy: default float dtype, matmul precision, whether to
  donate buffers;
* a stream-pool analog: independent *dispatch lanes* are expressed simply as
  separate ``jax.jit`` dispatches (async by default) — we keep an integer
  ``n_lanes`` for API parity with ``get_stream_pool_size``.

Everything is cheap, immutable-ish, and safe to share across algorithms, like
the reference object.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional

import jax
import numpy as np


@dataclasses.dataclass
class Resources:
    """Per-algorithm-invocation resource context.

    Parameters mirror the semantics (not the fields) of ``raft::handle_t``.

    Attributes
    ----------
    device : the primary jax device computations land on.
    mesh : optional device mesh used by multi-chip algorithms; the analog of
        the injected ``comms_t`` (reference core/handle.hpp:239).
    sub_meshes : named sub-communicators, analog of
        ``set_subcomm/get_subcomm`` (reference core/handle.hpp:252-262).
    dtype : default floating dtype for algorithm internals.
    matmul_precision : passed to ``jax.lax`` dot ops ("default" | "float32" |
        "bfloat16_3x" ...). f32 accumulate on MXU is always used via
        ``preferred_element_type``.
    n_lanes : stream-pool-size analog (reference handle.hpp:158-237); used by
        batched algorithms to decide how many independent dispatches to keep
        in flight.
    compilation_cache_dir : opt-in path for JAX's persistent compilation
        cache. When set, :func:`enable_compilation_cache` runs with this
        path — the cache is process-global, so EVERY builder/search entry
        (all of them jit-compiled programs) transparently reads and writes
        it from then on: a fresh process rebuilding a same-shape index pays
        executable deserialization instead of XLA compilation (the serving
        cold-start path, docs/serving.md "Warm start").
    """

    device: Any = None
    mesh: Optional[jax.sharding.Mesh] = None
    sub_meshes: dict = dataclasses.field(default_factory=dict)
    dtype: Any = np.float32
    matmul_precision: str = "highest"
    n_lanes: int = 1
    compilation_cache_dir: Optional[str] = None

    def __post_init__(self):
        if self.device is None:
            self.device = jax.devices()[0]
        if self.compilation_cache_dir is not None:
            enable_compilation_cache(self.compilation_cache_dir)

    # -- comms slot ---------------------------------------------------------
    def set_mesh(self, mesh: jax.sharding.Mesh) -> None:
        """Inject the communicator (analog of handle.set_comms)."""
        self.mesh = mesh

    def get_mesh(self) -> jax.sharding.Mesh:
        if self.mesh is None:
            raise RuntimeError(
                "No mesh set on Resources (analog of 'ERROR: communicator was not initialized')"
            )
        return self.mesh

    @property
    def has_mesh(self) -> bool:
        return self.mesh is not None

    def set_sub_mesh(self, key: str, mesh: jax.sharding.Mesh) -> None:
        self.sub_meshes[key] = mesh

    def get_sub_mesh(self, key: str) -> jax.sharding.Mesh:
        return self.sub_meshes[key]

    # -- stream-pool parity --------------------------------------------------
    def get_n_lanes(self) -> int:
        return max(1, int(self.n_lanes))

    # -- device properties ---------------------------------------------------
    def device_kind(self) -> str:
        return getattr(self.device, "device_kind", "cpu")

    def is_tpu(self) -> bool:
        return getattr(self.device, "platform", "cpu") == "tpu"

    def sync(self, *arrays) -> None:
        """Block until the given arrays (or, with no args, all dispatched
        side-effecting computations) are done.

        Analog of ``handle.sync_stream()``. JAX gives no global barrier over
        *pure* in-flight computations that you hold no reference to — pass
        the outputs you need ordered: ``res.sync(out)``.
        """
        if arrays:
            jax.block_until_ready(arrays)
        else:
            jax.effects_barrier()


# Backwards-compatible alias mirroring raft 22.08's rename handle_t -> device_resources
DeviceResources = Resources

_cache_lock = threading.Lock()
_cache_dir_enabled: Optional[str] = None


def enable_compilation_cache(
    path: str,
    *,
    min_compile_time_secs: float = 0.0,
    min_entry_size_bytes: int = -1,
) -> None:
    """Enable JAX's persistent compilation cache at ``path`` (idempotent).

    Every jitted program compiled after this call — index builds, search
    programs, the shard_map mesh programs — is serialized under ``path``
    and deserialized by later processes instead of recompiled. The r5
    bench showed compile, not compute, dominating builds (cold 125-250 s
    vs 1.6-15 s warm); this turns that cold start into a disk read.

    Two defaults differ deliberately from JAX's:

    * ``min_compile_time_secs=0``: JAX skips caching programs that
      compiled in under 1 s, but this library dispatches many small
      helper programs per build whose compiles add up;
    * ``min_entry_size_bytes=-1``: no size floor.

    The enable decision is memoized by JAX at the FIRST compile of the
    process (``is_cache_used``), so enabling after any jit has run needs a
    cache reset — compat.compilation_cache_reset does that; in-memory
    executables are unaffected. Thread-safe; re-enabling with the same
    path is a no-op, a different path switches the cache over.
    """
    global _cache_dir_enabled
    with _cache_lock:
        if _cache_dir_enabled == path:
            return
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(min_compile_time_secs),
        )
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes",
            int(min_entry_size_bytes),
        )
        # drop the memoized "cache disabled" decision a pre-enable compile
        # may have locked in (observed on jax 0.4.37: enabling after
        # backend init silently writes nothing without this)
        from raft_tpu import compat

        compat.compilation_cache_reset()
        _cache_dir_enabled = path


def compilation_cache_dir() -> Optional[str]:
    """The persistent-cache path enabled through this module, or None."""
    with _cache_lock:
        return _cache_dir_enabled


_default_lock = threading.Lock()
_default_resources: Optional[Resources] = None


def get_default_resources() -> Resources:
    """Process-wide default handle (lazily created), for API convenience.

    The reference requires an explicit handle everywhere; we accept ``None``
    in public APIs and fall back to this.
    """
    global _default_resources
    with _default_lock:
        if _default_resources is None:
            _default_resources = Resources()
        return _default_resources


def ensure_resources(res: Optional[Resources]) -> Resources:
    return res if res is not None else get_default_resources()
