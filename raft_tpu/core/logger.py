"""Logger — analog of the reference spdlog wrapper.

Reference: cpp/include/raft/core/logger.hpp:113-317 (singleton logger with
set_level/set_pattern/set_callback/flush and RAFT_LOG_* macros, plus a
callback sink so Python can capture C++ log lines). Here the host language is
Python, so we wrap :mod:`logging` with the same surface: named levels
(off/error/warn/info/debug/trace), a pattern string, and an optional callback
sink receiving formatted records.
"""

from __future__ import annotations

import logging
import sys
from typing import Callable, Optional

# level numbering mirrors the reference's RAFT_LEVEL_* (logger.hpp:36-42)
OFF = 0
CRITICAL = 1
ERROR = 2
WARN = 3
INFO = 4
DEBUG = 5
TRACE = 6

_TO_PY = {
    OFF: logging.CRITICAL + 10,
    CRITICAL: logging.CRITICAL,
    ERROR: logging.ERROR,
    WARN: logging.WARNING,
    INFO: logging.INFO,
    DEBUG: logging.DEBUG,
    TRACE: 5,
}

_FROM_PY = {py: raft for raft, py in _TO_PY.items()}


def _to_raft_level(levelno: int) -> int:
    """Map a Python levelno back to reference numbering for callbacks."""
    if levelno in _FROM_PY:
        return _FROM_PY[levelno]
    if levelno >= logging.CRITICAL:
        return CRITICAL
    if levelno >= logging.ERROR:
        return ERROR
    if levelno >= logging.WARNING:
        return WARN
    if levelno >= logging.INFO:
        return INFO
    if levelno >= logging.DEBUG:
        return DEBUG
    return TRACE

logging.addLevelName(5, "TRACE")

_logger = logging.getLogger("raft_tpu")
_handler: Optional[logging.Handler] = None
_callback: Optional[Callable[[int, str], None]] = None
_flush_fn: Optional[Callable[[], None]] = None
_pattern = "[%(levelname)s] [%(asctime)s] %(message)s"
_level = INFO


class _CallbackHandler(logging.Handler):
    """Analog of the callback sink (common/detail/callback_sink.hpp)."""

    def emit(self, record: logging.LogRecord) -> None:
        msg = self.format(record)
        if _callback is not None:
            # callbacks receive reference-numbered levels (logger.hpp:36-42:
            # higher = more verbose), not Python levelnos
            _callback(_to_raft_level(record.levelno), msg)
        else:
            sys.stderr.write(msg + "\n")

    def flush(self) -> None:
        if _flush_fn is not None:
            _flush_fn()


def _ensure_handler() -> None:
    global _handler
    if _handler is None:
        _handler = _CallbackHandler()
        _handler.setFormatter(logging.Formatter(_pattern, datefmt="%H:%M:%S"))
        _logger.addHandler(_handler)
        _logger.propagate = False
        set_level(_level)


def set_level(level: int) -> None:
    """Set verbosity using reference level numbering (0=off .. 6=trace)."""
    global _level
    _level = level
    _ensure_handler()
    _logger.setLevel(_TO_PY.get(level, logging.INFO))


def get_level() -> int:
    return _level


def should_log_for(level: int) -> bool:
    return level <= _level and _level != OFF


def set_pattern(pattern: str) -> None:
    """Set the format pattern (printf-ish in the reference; %-style here)."""
    global _pattern
    _pattern = pattern
    _ensure_handler()
    assert _handler is not None
    _handler.setFormatter(logging.Formatter(pattern, datefmt="%H:%M:%S"))


def set_callback(cb: Optional[Callable[[int, str], None]]) -> None:
    """Redirect formatted log lines to ``cb(level, message)``."""
    global _callback
    _callback = cb
    _ensure_handler()


def set_flush(fn: Optional[Callable[[], None]]) -> None:
    global _flush_fn
    _flush_fn = fn


def flush() -> None:
    _ensure_handler()
    assert _handler is not None
    _handler.flush()


def _log(level: int, msg: str, *args) -> None:
    _ensure_handler()
    if should_log_for(level):
        _logger.log(_TO_PY[level], msg % args if args else msg)


def trace(msg: str, *args) -> None:
    _log(TRACE, msg, *args)


def debug(msg: str, *args) -> None:
    _log(DEBUG, msg, *args)


def info(msg: str, *args) -> None:
    _log(INFO, msg, *args)


def warn(msg: str, *args) -> None:
    _log(WARN, msg, *args)


def error(msg: str, *args) -> None:
    _log(ERROR, msg, *args)


def critical(msg: str, *args) -> None:
    _log(CRITICAL, msg, *args)
