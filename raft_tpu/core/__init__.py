"""Core runtime: resources handle, logging, profiling ranges, interruptible.

TPU-native analog of the reference core layer (cpp/include/raft/core/):
``handle_t`` -> :class:`Resources`; spdlog logger -> :mod:`logger`;
NVTX ranges -> :mod:`annotate` (jax.profiler traces); ``interruptible`` ->
:mod:`interruptible` (cooperative cancellation of host loops).
"""

from raft_tpu.core.resources import (
    Resources,
    DeviceResources,
    compilation_cache_dir,
    enable_compilation_cache,
    get_default_resources,
)
from raft_tpu.core import logger
from raft_tpu.core.annotate import annotate, push_range, pop_range
from raft_tpu.core.interruptible import Interruptible, InterruptedException as RaftInterruptedError

__all__ = [
    "Resources",
    "DeviceResources",
    "enable_compilation_cache",
    "compilation_cache_dir",
    "get_default_resources",
    "logger",
    "annotate",
    "push_range",
    "pop_range",
    "Interruptible",
    "RaftInterruptedError",
]
