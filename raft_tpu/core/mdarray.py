"""mdarray/mdspan analog — typed nd-array views + factories.

Reference: cpp/include/raft/core/mdarray.hpp (owning ``mdarray``, non-owning
``mdspan`` with ``row_major``/``col_major`` layouts and host/device accessor
policies; factories ``make_device_matrix/vector/scalar`` further down the same
file; storage policies in cpp/include/raft/detail/mdarray.hpp:142,195).

On TPU, ``jax.Array`` already *is* an owning, device-resident nd array with
XLA-managed layout, and numpy covers host arrays — so the useful residue is:

* layout tags (XLA picks physical tiling; we track *logical* C/F order the way
  the reference's pairwise APIs accept ``isRowMajor``);
* factory helpers that allocate on the right device with the right dtype;
* light validation helpers (``expect_matrix``/``expect_vector``) that the
  algorithm layers use the way the reference uses static mdspan extents.

Rather than wrap ``jax.Array`` in a class (which would fight every jnp
function), layout is carried as a plain argument where it matters.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# layout tags (reference mdarray.hpp:45-56)
ROW_MAJOR = "row_major"
COL_MAJOR = "col_major"


def _device_of(res) -> Any:
    from raft_tpu.core.resources import ensure_resources

    return ensure_resources(res).device


# -- owning factories (reference make_device_* / make_host_*) ----------------

def make_device_matrix(res, n_rows: int, n_cols: int, dtype=jnp.float32) -> jax.Array:
    return jax.device_put(jnp.zeros((n_rows, n_cols), dtype=dtype), _device_of(res))


def make_device_vector(res, n: int, dtype=jnp.float32) -> jax.Array:
    return jax.device_put(jnp.zeros((n,), dtype=dtype), _device_of(res))


def make_device_scalar(res, value, dtype=None) -> jax.Array:
    return jax.device_put(jnp.asarray(value, dtype=dtype), _device_of(res))


def make_host_matrix(n_rows: int, n_cols: int, dtype=np.float32) -> np.ndarray:
    return np.zeros((n_rows, n_cols), dtype=dtype)


def make_host_vector(n: int, dtype=np.float32) -> np.ndarray:
    return np.zeros((n,), dtype=dtype)


# -- conversion (host_mdspan <-> device_mdspan analog) -----------------------

def to_device(res, x) -> jax.Array:
    return jax.device_put(jnp.asarray(x), _device_of(res))


def to_host(x) -> np.ndarray:
    return np.asarray(x)


# -- validation helpers (static-extent checks) -------------------------------

def expect_matrix(x, name: str = "x") -> None:
    if x.ndim != 2:
        raise ValueError(f"{name}: expected a matrix (2d), got shape {x.shape}")


def expect_vector(x, name: str = "x") -> None:
    if x.ndim != 1:
        raise ValueError(f"{name}: expected a vector (1d), got shape {x.shape}")


def expect_same_dtype(*arrays) -> None:
    dts = {np.dtype(a.dtype) for a in arrays}
    if len(dts) > 1:
        raise TypeError(f"dtype mismatch: {sorted(map(str, dts))}")


def as_layout(x, layout: str) -> jax.Array:
    """Return ``x`` with the given *logical* order.

    XLA controls physical layout; a col-major logical matrix is represented as
    its transpose flagged by the caller, matching how the reference passes
    ``isRowMajor`` into kernels rather than reordering memory.
    """
    if layout not in (ROW_MAJOR, COL_MAJOR):
        raise ValueError(f"unknown layout {layout}")
    return jnp.asarray(x)
