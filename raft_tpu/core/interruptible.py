"""Cooperative cancellation — analog of ``raft::interruptible``.

Reference: cpp/include/raft/core/interruptible.hpp:66-270. There, a per-thread
token lets one thread cancel another's *stream wait*: ``synchronize(stream)``
polls ``cudaStreamQuery`` in a yield loop checking the token, so CTRL+C can
break out of a long GPU wait.

On TPU under JAX there is no user-visible stream to poll, but the same need
exists for long *host-side* algorithm loops (Lanczos restarts, kmeans
iterations, IVF build batches): they should be cancellable from another
thread or a signal handler without killing the process. This module provides
the per-thread token registry + ``yield_now``/``cancel`` with identical
semantics; device work already dispatched completes (as in the reference —
cancellation is cooperative, not preemptive).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import jax


class InterruptedException(RuntimeError):
    """Raised inside the cancelled thread (reference: raft::interrupted_exception).

    Deliberately NOT named ``InterruptedError`` — that would shadow the
    Python builtin (an OSError subclass) and change exception-handling
    semantics for importers.
    """


class Interruptible:
    """Per-thread cancellation token (reference interruptible.hpp:66)."""

    _registry: "Dict[int, Interruptible]" = {}
    _registry_lock = threading.Lock()

    def __init__(self) -> None:
        self._cancelled = threading.Event()

    # -- token API -----------------------------------------------------------
    def cancel(self) -> None:
        """Request cancellation; the owner thread observes it at its next
        ``yield_now`` (reference: cancel() sets the flag, :219)."""
        self._cancelled.set()

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def clear(self) -> None:
        self._cancelled.clear()

    # -- static per-thread API ----------------------------------------------
    @classmethod
    def get_token(cls, thread_id: Optional[int] = None) -> "Interruptible":
        """Get (creating if needed) the token for a thread
        (reference interruptible.hpp:84 get_token())."""
        tid = threading.get_ident() if thread_id is None else thread_id
        with cls._registry_lock:
            # prune tokens of dead threads so the registry stays bounded and
            # a reused OS thread id cannot inherit a stale cancelled token
            # (the reference uses a weak-pointer registry for the same reason,
            # interruptible.hpp:140-168).
            live = {t.ident for t in threading.enumerate()}
            for dead in [k for k in cls._registry if k not in live and k != tid]:
                del cls._registry[dead]
            tok = cls._registry.get(tid)
            if tok is None:
                tok = cls()
                cls._registry[tid] = tok
            return tok

    @classmethod
    def yield_now(cls) -> None:
        """Check this thread's token; raise if cancelled
        (reference: yield() / yield_no_throw)."""
        tok = cls.get_token()
        if tok.cancelled():
            tok.clear()
            raise InterruptedException("raft_tpu: thread interrupted")

    @classmethod
    def yield_no_throw(cls) -> bool:
        tok = cls.get_token()
        if tok.cancelled():
            tok.clear()
            return False
        return True

    @classmethod
    def cancel_thread(cls, thread_id: int) -> None:
        cls.get_token(thread_id).cancel()

    @classmethod
    def synchronize(cls, x, *, poll_interval_s: float = 0.001,
                    max_poll_interval_s: float = 0.05,
                    timeout_s: Optional[float] = None) -> None:
        """Cancellable, optionally deadline-bounded wait on a jax array /
        pytree.

        The exact analog of the reference's polling loop
        (interruptible.hpp:66-120: ``cudaStreamQuery`` + token check +
        ``std::this_thread::yield``): poll ``Array.is_ready()`` on every
        leaf, checking this thread's token between polls, so ``cancel()``
        from another thread breaks an IN-FLIGHT wait — the dispatched
        device work itself still completes (cancellation is cooperative,
        as in the reference). Leaves without ``is_ready`` (plain numpy /
        scalars) are treated as ready.

        ``timeout_s`` bounds the wait: if the work is still not ready
        after that many seconds, :class:`raft_tpu.errors.RaftTimeoutError`
        is raised (the dispatched work still completes — the deadline
        abandons the WAIT, exactly like cancellation). Cancellation and
        the deadline compose: the token is checked before the clock every
        iteration, so whichever fires first wins and a cancel can never
        be masked by an elapsed deadline. The serving path's
        deadline/retry recipe builds on this
        (``raft_tpu.resilience.dispatch_with_deadline``,
        docs/robustness.md).

        The poll interval backs off exponentially from
        ``poll_interval_s`` toward ``max_poll_interval_s`` so a
        multi-second kernel doesn't burn a host core in 1 ms wakeups;
        cancellation (and deadline) latency stays bounded by the cap.
        """
        leaves = [
            leaf for leaf in jax.tree.leaves(x) if hasattr(leaf, "is_ready")
        ]
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        interval = poll_interval_s
        while True:
            cls.yield_now()
            leaves = [leaf for leaf in leaves if not leaf.is_ready()]
            if not leaves:
                return
            if deadline is not None and time.monotonic() >= deadline:
                from raft_tpu import errors

                raise errors.RaftTimeoutError(
                    "synchronize: dispatched work not ready within "
                    f"{timeout_s:.3g}s ({len(leaves)} leaves pending)"
                )
            time.sleep(interval)  # the std::this_thread::yield slot
            interval = min(interval * 2.0, max_poll_interval_s)
