"""Profiler range annotations — analog of the reference NVTX layer.

Reference: cpp/include/raft/core/nvtx.hpp:48-91 and
common/detail/nvtx.hpp:23-206 (RAII ``nvtx::range``, push_range/pop_range,
per-domain colored ranges, compiled out when NVTX disabled). The TPU analog
uses ``jax.profiler``: ``TraceAnnotation`` shows up on the XLA trace viewer
timeline and ``jax.named_scope`` tags HLO ops so ranges survive into compiled
profiles.

Like the reference's ``NVTX_ENABLED`` compile-out, ranges honor a GLOBAL
enable flag: when profiling is off (the default — set ``RAFT_TPU_PROFILE=1``
to force it on), :func:`annotate` and :func:`push_range` are TRUE no-ops —
no ``TraceAnnotation``, no ``ExitStack``, no stack append — so the hot
serving path pays one module-attribute load per range
(tests/test_obs.py pins the no-allocation claim). :func:`start_trace`
flips the flag on for the duration of a capture (and :func:`stop_trace`
restores it), so an SLO-triggered capture
(:class:`raft_tpu.obs.ProfileTrigger`) sees every range without anyone
paying for them between captures.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, List

import jax

from raft_tpu.core import logger

# the global range-enable gate (the NVTX_ENABLED analog): a list cell so
# every reader shares it by reference. Default off — ranges cost nothing
# until a trace capture (or RAFT_TPU_PROFILE=1) wants them.
_ENV_DEFAULT: bool = (
    os.environ.get("RAFT_TPU_PROFILE", "").strip().lower()
    in ("1", "on", "true", "yes")
)
_ENABLED: List[bool] = [_ENV_DEFAULT]
_stack: List[contextlib.ExitStack] = []
# profiling state before start_trace flipped it, restored by stop_trace
_pre_trace: List[bool] = []


def profiling_enabled() -> bool:
    """Are ranges currently being emitted?"""
    return _ENABLED[0]


def set_profiling(on: bool) -> bool:
    """Flip the global range gate; returns the PREVIOUS state. Ranges
    pushed while disabled are not tracked — a ``pop_range`` crossing an
    enable flip logs instead of popping someone else's range."""
    prev = _ENABLED[0]
    _ENABLED[0] = bool(on)
    return prev


@contextlib.contextmanager
def annotate(name: str, *args) -> Iterator[None]:
    """RAII-style range, usable as a decorator or context manager.

    ``args`` are %-formatted into ``name`` like the reference's printf-style
    range names (nvtx.hpp:54 ``range(const char* format, Args... args)``).
    A no-op (no profiler objects constructed) while profiling is off.
    """
    if not _ENABLED[0]:
        yield
        return
    label = name % args if args else name
    with jax.profiler.TraceAnnotation(label), jax.named_scope(label):
        yield


def push_range(name: str, *args) -> None:
    """Imperative begin (reference nvtx.hpp push_range). A true no-op —
    nothing allocated, nothing stacked — while profiling is off."""
    if not _ENABLED[0]:
        return
    label = name % args if args else name
    es = contextlib.ExitStack()
    es.enter_context(jax.profiler.TraceAnnotation(label))
    _stack.append(es)


def pop_range() -> None:
    """Imperative end (reference nvtx.hpp pop_range). Popping an empty
    stack — an unbalanced pop, or ranges pushed while profiling was
    disabled — is a LOUD no-op (debug log), never an exception: range
    bookkeeping must not take down the path it annotates."""
    if _stack:
        _stack.pop().close()
    else:
        logger.debug(
            "pop_range: range stack empty (unbalanced pop, or the "
            "matching push_range ran while profiling was disabled)"
        )


def start_trace(log_dir: str) -> None:
    """Start an XLA profiler trace capture (output viewable in
    TensorBoard) and enable range emission for its duration. The
    profiler starts FIRST: if it refuses (a capture is already
    running), the range gate and its restore stack are untouched — a
    failed start must not leave every later range permanently paid
    for."""
    jax.profiler.start_trace(log_dir)
    _pre_trace.append(set_profiling(True))


def stop_trace() -> None:
    """Stop the capture and restore the range gate to its pre-capture
    state (an explicitly-enabled process stays enabled). An UNBALANCED
    stop — a capture someone started through ``jax.profiler`` directly
    — falls back to the env-derived default, never a hard False: a
    ``RAFT_TPU_PROFILE=1`` process must not be silently disabled by
    one stray stop."""
    try:
        jax.profiler.stop_trace()
    finally:
        set_profiling(_pre_trace.pop() if _pre_trace else _ENV_DEFAULT)
