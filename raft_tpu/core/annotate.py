"""Profiler range annotations — analog of the reference NVTX layer.

Reference: cpp/include/raft/core/nvtx.hpp:48-91 and
common/detail/nvtx.hpp:23-206 (RAII ``nvtx::range``, push_range/pop_range,
per-domain colored ranges, compiled out when NVTX disabled). The TPU analog
uses ``jax.profiler``: ``TraceAnnotation`` shows up on the XLA trace viewer
timeline and ``jax.named_scope`` tags HLO ops so ranges survive into compiled
profiles. Disabled (near-zero cost) unless profiling is active.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, List

import jax

_stack: List[contextlib.ExitStack] = []


@contextlib.contextmanager
def annotate(name: str, *args) -> Iterator[None]:
    """RAII-style range, usable as a decorator or context manager.

    ``args`` are %-formatted into ``name`` like the reference's printf-style
    range names (nvtx.hpp:54 ``range(const char* format, Args... args)``).
    """
    label = name % args if args else name
    with jax.profiler.TraceAnnotation(label), jax.named_scope(label):
        yield


def push_range(name: str, *args) -> None:
    """Imperative begin (reference nvtx.hpp push_range)."""
    label = name % args if args else name
    es = contextlib.ExitStack()
    es.enter_context(jax.profiler.TraceAnnotation(label))
    _stack.append(es)


def pop_range() -> None:
    """Imperative end (reference nvtx.hpp pop_range)."""
    if _stack:
        _stack.pop().close()


def start_trace(log_dir: str) -> None:
    """Start an XLA profiler trace capture (output viewable in TensorBoard)."""
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()
