"""k-selection — analog of the reference top-k family
(cpp/include/raft/spatial/knn/knn.cuh:68-165 ``select_k`` + ``SelectKAlgo``;
implementations: FAISS block/warp-select detail/{block,warp}_select_faiss.cuh,
radix top-k detail/topk/radix_topk.cuh:148-630, warp-sort bitonic queues
detail/topk/warpsort_topk.cuh:132-834).

On TPU the tuned primitive is XLA's ``lax.top_k`` (hardware sort networks,
the analog of the warp-sort path); a full ``sort`` path exists for k close to
n (the radix path's regime), and a streaming blocked variant
(:func:`select_k_blocked`) handles rows too long to keep resident — the
analog of the reference's multi-pass radix filtering.
"""

from __future__ import annotations

import enum
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import errors

__all__ = [
    "SelectKAlgo", "merge_parts_provenance_select_k",
    "merge_parts_select_k", "merge_topk", "select_k", "select_k_blocked",
]


class SelectKAlgo(enum.IntEnum):
    """Mirror of the reference algo enum (knn.cuh:68-79); names map to the
    TPU strategies that fill the same niches."""

    AUTO = -1
    TOPK = 0        # lax.top_k — warp-sort / faiss block-select niche
    SORT = 1        # full sort — radix 11-bit niche (k ~ n)
    BLOCKED = 2     # streaming blocked top-k — radix 8-bit multi-pass niche
    CHUNK_MIN = 3   # exact two-stage: chunk mins -> gather -> select
    APPROX = 4      # lax.approx_min_k — TPU PartialReduce hardware path,
                    # ~0.95 recall (memory-bandwidth-bound, ~7x faster
                    # than TOPK on wide rows). CAVEAT: inside shard_map
                    # (manual partitioning) the ApproxTopK custom call
                    # loses this lowering and measured 3.4x SLOWER than
                    # TOPK — prefer exact selection in mesh programs
                    # (docs/ivf_scale.md "shard_map approx-top-k tax")


def _resolve(algo: SelectKAlgo, n: int, k: int) -> SelectKAlgo:
    if algo in (SelectKAlgo.AUTO, None):
        if k * 4 >= n:
            return SelectKAlgo.SORT
        return SelectKAlgo.TOPK
    return algo


@functools.partial(jax.jit, static_argnames=("k", "select_min", "algo"))
def select_k(
    dists,
    k: int,
    *,
    select_min: bool = True,
    indices=None,
    algo: SelectKAlgo = SelectKAlgo.AUTO,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row k smallest (or largest) values and their indices.

    dists: (m, n); optional ``indices`` (m, n) carries source labels
    (the in-k payload of the reference's key-value selection); defaults to
    column positions.

    Returns (values (m, k), indices (m, k)), sorted best-first — matching
    ``raft::spatial::knn::select_k`` (knn.cuh:105-165).
    """
    dists = jnp.asarray(dists)
    errors.check_matrix(dists, "dists")
    m, n = dists.shape
    errors.check_k(k, n, "row length")
    errors.expects(
        indices is None or tuple(indices.shape) == (m, n),
        "indices: expected shape %s, got %s",
        (m, n), None if indices is None else tuple(indices.shape),
    )
    algo = _resolve(algo, n, k)

    if algo == SelectKAlgo.SORT:
        order = jnp.argsort(dists if select_min else -dists, axis=1)[:, :k]
        vals = jnp.take_along_axis(dists, order, axis=1)
        idxs = order
    elif algo == SelectKAlgo.CHUNK_MIN:
        vals, idxs = chunk_min_select_k(dists, k, select_min=select_min)
    elif algo == SelectKAlgo.APPROX:
        if select_min:
            vals, idxs = lax.approx_min_k(dists, k)
        else:
            vals, idxs = lax.approx_max_k(dists, k)
    else:
        vals, idxs = lax.top_k(-dists if select_min else dists, k)
        if select_min:
            vals = -vals
    if indices is not None:
        idxs = jnp.take_along_axis(jnp.asarray(indices), idxs, axis=1)
    return vals, idxs.astype(jnp.int32)


def chunk_min_select_k(dists, k: int, *, select_min: bool = True,
                       chunk: int = 128):
    """Exact two-stage selection: per-chunk extrema → top-k chunks →
    gather → final top-k over k·chunk candidates.

    Exactness: the true top-k values occupy at most k chunks (each chunk
    holding one of them has an extremum at least as good as the kth value,
    so it ranks in the top-k chunks). ~25% faster than ``lax.top_k`` on
    wide rows (the VPU does the chunk reduction at memory bandwidth).
    """
    dists = jnp.asarray(dists)
    q, n = dists.shape
    if n % chunk or n // chunk < k:
        v, i = lax.top_k(-dists if select_min else dists, k)
        return (-v if select_min else v), i
    nc = n // chunk
    xr = dists.reshape(q, nc, chunk)
    ext = jnp.min(xr, axis=2) if select_min else jnp.max(xr, axis=2)
    _, cidx = lax.top_k(-ext if select_min else ext, k)          # (q, k)
    cand = jnp.take_along_axis(xr, cidx[:, :, None], axis=1)     # (q, k, chunk)
    flat = cand.reshape(q, k * chunk)
    nv, p = lax.top_k(-flat if select_min else flat, k)
    which = jnp.take_along_axis(cidx, p // chunk, axis=1)
    return (-nv if select_min else nv), which * chunk + p % chunk


def merge_parts_select_k(part_vals, part_ids, k: int, *, ways=None,
                         select_min: bool = True):
    """k-way merge of per-part top-k payloads in one :func:`select_k`
    call — the reference's ``knn_merge_parts``
    (knn_brute_force_faiss.cuh:289-368) as the sharded engines'
    IN-PROGRAM cross-shard merge tail (each part's ids are already
    global; the payloads arrive from one comms allgather).

    ``part_vals`` / ``part_ids``: (P, nq, kk) stacked per-part results.
    ``ways``: pad the part axis with +inf/-1 (worst-value / invalid)
    absent-peer entries up to this many parts before selecting — the
    merge then runs at a DEPLOYMENT's width on a smaller mesh with
    bit-identical results (an absent peer contributes nothing, the same
    contract as a down shard). Returns (vals (nq, k), ids (nq, k)),
    best-first.
    """
    n_parts, nq, kk = part_vals.shape
    if ways is not None and ways > n_parts:
        extra = ways - n_parts
        fill = jnp.inf if select_min else -jnp.inf
        part_vals = jnp.concatenate(
            [part_vals,
             jnp.full((extra, nq, kk), fill, part_vals.dtype)]
        )
        part_ids = jnp.concatenate(
            [part_ids, jnp.full((extra, nq, kk), -1, part_ids.dtype)]
        )
    flat_v = part_vals.transpose(1, 0, 2).reshape(nq, -1)
    flat_i = part_ids.transpose(1, 0, 2).reshape(nq, -1)
    return select_k(flat_v, k, select_min=select_min, indices=flat_i)


def merge_parts_provenance_select_k(part_vals, part_ids, k: int, *,
                                    select_min: bool = True):
    """:func:`merge_parts_select_k` that also reports WHICH part each
    selected entry came from — the DCN-level merge of the hierarchical
    cross-host tail needs the provenance to recover exact f32 values
    from the owning slice after selecting on compressed bf16 wire keys
    (:func:`raft_tpu.comms.multihost.hierarchical_merge_select_k`;
    docs/multihost.md "The two-stage merge").

    ``part_vals`` / ``part_ids``: (P, nq, kk) stacked per-part top-k
    payloads, each part best-first. Returns ``(vals (nq, k),
    ids (nq, k), part (nq, k), slot (nq, k))`` — ``part[i, j]`` is the
    source part of entry j and ``slot[i, j]`` its row position within
    that part's payload.
    """
    n_parts, nq, kk = part_vals.shape
    flat_v = part_vals.transpose(1, 0, 2).reshape(nq, -1)
    flat_i = part_ids.transpose(1, 0, 2).reshape(nq, -1)
    vals, pos = select_k(flat_v, k, select_min=select_min)
    ids = jnp.take_along_axis(flat_i, pos, axis=1)
    return (
        vals, ids,
        (pos // kk).astype(jnp.int32),
        (pos % kk).astype(jnp.int32),
    )


def merge_topk(vals_a, idx_a, vals_b, idx_b, *, select_min: bool = True):
    """Merge two best-first top-k lists per row into one (the reference's
    in-register merge used by warp-sort and ``knn_merge_parts``)."""
    k = vals_a.shape[-1]
    vals = jnp.concatenate([vals_a, vals_b], axis=-1)
    idxs = jnp.concatenate([idx_a, idx_b], axis=-1)
    mvals, pos = lax.top_k(-vals if select_min else vals, k)
    if select_min:
        mvals = -mvals
    return mvals, jnp.take_along_axis(idxs, pos, axis=-1)


@functools.partial(jax.jit, static_argnames=("k", "select_min", "block_n"))
def select_k_blocked(
    dists,
    k: int,
    *,
    select_min: bool = True,
    block_n: int = 2048,
) -> Tuple[jax.Array, jax.Array]:
    """Streaming top-k over column blocks for very wide rows.

    Scans (m, block_n) slabs and folds each slab's local top-k into a
    running list — the TPU analog of the reference's multi-pass radix
    filtering (radix_topk.cuh: survivors shrink each pass); here the
    working set is 2k per row, never n.
    """
    dists = jnp.asarray(dists)
    m, n = dists.shape
    if block_n >= n:
        return select_k(dists, k, select_min=select_min)
    nb = -(-n // block_n)
    pad = nb * block_n - n
    fill = jnp.inf if select_min else -jnp.inf
    dp = jnp.pad(dists, ((0, 0), (0, pad)), constant_values=fill)
    blocks = dp.reshape(m, nb, block_n).transpose(1, 0, 2)  # (nb, m, bn)

    def body(carry, blk):
        bvals, bidx, j0 = blk
        rvals, ridx = carry
        out = merge_topk(rvals, ridx, bvals, bidx + j0, select_min=select_min)
        return out, None

    def local(blk):
        v, i = lax.top_k(-blk if select_min else blk, k)
        return (-v if select_min else v), i

    v0, i0 = local(blocks[0])
    rest = blocks[1:]
    bv, bi = jax.vmap(local)(rest)
    (vals, idxs), _ = lax.scan(
        body, (v0, i0), (bv, bi, (jnp.arange(1, nb)) * block_n)
    )
    return vals, idxs.astype(jnp.int32)
