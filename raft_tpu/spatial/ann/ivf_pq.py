"""IVF-PQ ANN index — first-class TPU implementation (the reference wraps
FAISS GpuIndexIVFPQ, cpp/include/raft/spatial/knn/detail/
ann_quantized_faiss.cuh:115-206 + ``IVFPQParam`` ann_common.h; native here).

Build: coarse k-means → per-list residuals → product quantization: the d
dims split into M subspaces, each with its own 2^bits-entry codebook
trained by k-means on residual sub-vectors (a Python loop of M small
k-means fits — M is single-digit-to-low-tens, and each fit reuses the
jitted kmeans program across subspaces of equal shape). Codes pack to
(n, M) uint8.

Search (ADC — asymmetric distance computation): per (query, probed list) a
(M, 2^bits) lookup table of squared sub-distances between the query
residual and every codebook entry — one batched MXU/VPU computation — then
candidate scores are M gathered-LUT sums, and ``lax.top_k`` selects.

Refinement (``refine_ratio`` > 1, the FAISS IndexRefineFlat niche the
reference's FAISS build exposes downstream): the index keeps the raw
vectors in list-sorted order; search takes the top ``refine_ratio * k``
ADC candidates, rescores them with exact f32 L2 (a c ≪ n gather + MXU
batched dot), and re-selects k — recovering near-exact recall at PQ
speed."""

from __future__ import annotations

import dataclasses
import functools
import math
import typing
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import errors
from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit, kmeans_predict
from raft_tpu.spatial.ann.common import ListStorage, build_list_storage

__all__ = [
    "IVFPQParams", "IVFPQIndex", "ivf_pq_build", "ivf_pq_search",
    "ivf_pq_search_grouped",
]


@dataclasses.dataclass(frozen=True)
class IVFPQParams:
    """Analog of IVFPQParam (reference ann_common.h: nlist, M=n_subquantizers,
    n_bits, usePrecomputedTables)."""

    n_lists: int = 64
    pq_dim: int = 8           # M subspaces (reference n_subquantizers)
    pq_bits: int = 8          # 2^bits codebook entries
    kmeans_n_iters: int = 20
    pq_kmeans_n_iters: int = 20
    seed: int = 0
    store_raw: bool = True    # keep raw vectors for exact refinement
    kmeans_init: str = "k-means++"  # "random": cheap coarse/code books


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class IVFPQIndex:
    centroids: jax.Array      # (n_lists, d)
    codebooks: jax.Array      # (M, 2^bits, ds)
    codes_sorted: jax.Array   # (n + 1, M) uint8 — sentinel row appended
    storage: ListStorage
    # (n + 1, d) raw vectors in list-sorted order (sentinel row appended),
    # or None when built with store_raw=False (pure-PQ memory footprint)
    vectors_sorted: typing.Optional[jax.Array]
    pq_dim: int = dataclasses.field(metadata=dict(static=True))
    pq_bits: int = dataclasses.field(metadata=dict(static=True))


def ivf_pq_build(x, params: IVFPQParams = IVFPQParams()) -> IVFPQIndex:
    x = jnp.asarray(x)
    errors.check_matrix(x, "x", min_rows=2)
    n, d = x.shape
    M = params.pq_dim
    errors.check_k(params.n_lists, n, "n_lists vs dataset rows")
    errors.expects(d % M == 0, "d=%d not divisible by pq_dim=%d", d, M)
    errors.expects(
        1 <= params.pq_bits <= 8,
        "pq_bits=%d out of range [1, 8] — codes are stored as uint8",
        params.pq_bits,
    )
    ds = d // M
    n_codes = 1 << params.pq_bits

    coarse = kmeans_fit(
        x,
        KMeansParams(
            n_clusters=params.n_lists,
            max_iter=params.kmeans_n_iters,
            seed=params.seed,
            init=params.kmeans_init,
        ),
    )
    labels = coarse.labels
    residuals = x - coarse.centroids[labels]

    # batched PQ codebook training across the M subspaces
    sub = residuals.reshape(n, M, ds).transpose(1, 0, 2)   # (M, n, ds)

    if n >= n_codes:
        # ONE vmapped Lloyd over all M subspaces: the per-subspace matmuls
        # are skinny ((n, ds) x (ds, K) with ds in the single digits —
        # poor MXU fill); batching them into (M, n, K) contractions keeps
        # the MXU busy and replaces M sequential fits with one program
        from raft_tpu.cluster.kmeans import kmeans_fit_batched

        outs = kmeans_fit_batched(
            sub,
            KMeansParams(
                n_clusters=n_codes,
                max_iter=params.pq_kmeans_n_iters,
                seed=params.seed + 1,
                init=params.kmeans_init,
            ),
        )
        codebooks = outs.centroids                          # (M, K, ds)
        # vmapped encode: one dispatch (M sequential predicts measured
        # ~9 s of pure dispatch overhead at the 500k bench shape)
        codes = (
            jax.vmap(kmeans_predict)(sub, codebooks).T.astype(jnp.uint8)
        )                                                   # (n, M)
    else:
        # tiny datasets (n < 2^bits): per-subspace fits with inf padding
        def fit_sub(subx, seed):
            out = kmeans_fit(
                subx,
                KMeansParams(
                    n_clusters=min(n_codes, subx.shape[0]),
                    max_iter=params.pq_kmeans_n_iters,
                    seed=seed,
                    init=params.kmeans_init,
                ),
            )
            cents = out.centroids
            pad = n_codes - cents.shape[0]
            if pad > 0:
                cents = jnp.concatenate(
                    [cents, jnp.full((pad, ds), jnp.inf, cents.dtype)]
                )
            return cents

        codebooks = jnp.stack(
            [fit_sub(sub[m], params.seed + m) for m in range(M)]
        )                                                   # (M, K, ds)

        def encode_sub(subx, cb):
            return kmeans_predict(subx, jnp.where(jnp.isfinite(cb), cb, 1e30))

        codes = jnp.stack(
            [encode_sub(sub[m], codebooks[m]) for m in range(M)], axis=1
        ).astype(jnp.uint8)                                 # (n, M)

    storage = build_list_storage(np.asarray(labels), params.n_lists)
    codes_sorted = jnp.concatenate(
        [codes[storage.sorted_ids], jnp.zeros((1, M), jnp.uint8)]
    )
    vectors_sorted = None
    if params.store_raw:
        vectors_sorted = jnp.concatenate(
            [x[storage.sorted_ids], jnp.zeros((1, d), x.dtype)]
        )
    return IVFPQIndex(
        coarse.centroids, codebooks, codes_sorted, storage, vectors_sorted,
        M, params.pq_bits,
    )


@functools.partial(
    jax.jit, static_argnames=("k", "n_probes", "block_q", "refine_ratio")
)
def ivf_pq_search(
    index: IVFPQIndex, queries, k: int, *, n_probes: int = 8,
    block_q: int = 256, refine_ratio: float = 2.0,
) -> Tuple[jax.Array, jax.Array]:
    """ADC search; returns (squared L2 dists, original row ids).
    Query batches run in ``block_q`` blocks so the per-(query, list) LUTs
    and the (q, p, L, M) code gather stay HBM-bounded.

    ``refine_ratio`` > 1 (and an index built with ``store_raw``) rescores
    the top ``ceil(refine_ratio * k)`` ADC candidates with exact f32
    distances before the final k-selection; returned distances are then
    exact. ``refine_ratio <= 1`` returns raw ADC approximations."""
    from raft_tpu.spatial.ann.common import (
        check_candidate_pool, coarse_probe, map_query_blocks,
        score_l2_candidates, select_candidates,
    )

    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, index.centroids, "queries", "index")
    d = q.shape[1]
    M = index.pq_dim
    ds = d // M
    check_candidate_pool(k, n_probes, index.storage)
    refine = index.vectors_sorted is not None and refine_ratio > 1.0
    c = max(k, min(int(math.ceil(refine_ratio * k)),
                   n_probes * index.storage.max_list))
    f32 = jnp.float32
    cents = index.centroids.astype(f32)
    cb = jnp.where(jnp.isfinite(index.codebooks), index.codebooks, 0.0)
    cb_n = jnp.sum(cb * cb, axis=2)                          # (M, K)

    def one_block(qb):
        nq = qb.shape[0]
        qf = qb.astype(f32)
        probes, _ = coarse_probe(qf, cents, n_probes)        # (q, p)

        # LUTs: residual of q wrt each probed centroid, per subspace vs
        # codebook; (q, p, d) residuals -> (q, p, M, ds)
        res = qf[:, None, :] - cents[probes]
        res = res.reshape(nq, n_probes, M, ds)
        dots = jnp.einsum("qpmd,mkd->qpmk", res, cb,
                          preferred_element_type=f32)
        res_n = jnp.sum(res * res, axis=3)                   # (q, p, M)
        lut = res_n[..., None] + cb_n[None, None] - 2.0 * dots  # (q,p,M,K)

        # candidates: padded probed lists, gather codes, sum LUT entries
        cand_pos = index.storage.list_index[probes]          # (q, p, L)
        codes = index.codes_sorted[cand_pos].astype(jnp.int32)  # (q,p,L,M)
        # dist[q,p,l] = sum_m lut[q,p,m,codes[q,p,l,m]]
        lut_t = lut.transpose(0, 1, 3, 2)                    # (q, p, K, M)
        gath = jnp.take_along_axis(lut_t, codes, axis=2)     # (q, p, L, M)
        d2 = jnp.sum(gath, axis=3)                           # (q, p, L)

        valid = cand_pos < index.storage.n
        d2 = jnp.where(valid, d2, jnp.inf).reshape(nq, -1)
        flat_pos = cand_pos.reshape(nq, -1)

        if not refine:
            return select_candidates(index.storage, flat_pos, d2, k)

        # refinement: top-c by ADC score, exact f32 rescore, re-select k
        adc, cpos = jax.lax.top_k(-d2, c)                    # (q, c)
        rpos = jnp.take_along_axis(flat_pos, cpos, axis=1)   # (q, c)
        raw = index.vectors_sorted[rpos].astype(f32)         # (q, c, d)
        exact = score_l2_candidates(
            qf, raw, jnp.isfinite(-adc) & (rpos < index.storage.n)
        )
        return select_candidates(index.storage, rpos, exact, k)

    return map_query_blocks(one_block, q, block_q)


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "qcap", "list_block", "refine_ratio"),
)
def _pq_grouped_impl(index, q, k, n_probes, qcap, list_block, refine_ratio):
    from raft_tpu.spatial.ann.common import (
        coarse_probe, invert_probe_map, regroup_pairs, score_l2_candidates,
        select_candidates,
    )

    storage = index.storage
    n_lists = index.centroids.shape[0]
    L = storage.max_list
    nq, d = q.shape
    p = n_probes
    M = index.pq_dim
    ds = d // M
    K = 1 << index.pq_bits
    f32 = jnp.float32
    bf16 = jnp.bfloat16
    qf = q.astype(f32)
    cents = index.centroids.astype(f32)
    cb = jnp.where(jnp.isfinite(index.codebooks), index.codebooks, 0.0)
    cb_n = jnp.sum(cb * cb, axis=2)                          # (M, K)

    probes, _ = coarse_probe(qf, cents, p)                   # (nq, p)
    qmat, l_flat, slot = invert_probe_map(probes, n_lists, qcap)

    q_pad = jnp.concatenate([qf, jnp.zeros((1, d), f32)])    # sentinel query
    # per-(list, query) partial width: must cover the REFINE pool, not just
    # k — on clustered data a query's home list can hold most of the
    # global top-c ADC candidates, and truncating it to k caps recall
    # (measured: 0.73 vs 0.95 at the 500k bench shape with kk = k)
    refine = index.vectors_sorted is not None and refine_ratio > 1.0
    kk = min(max(k, int(math.ceil(refine_ratio * k)) if refine else k), L)

    def block_fn(lblk):                                      # (LB,) list ids
        LB = lblk.shape[0]
        qids = qmat[lblk]                                    # (LB, qcap)
        qv = q_pad[qids]                                     # (LB, qcap, d)

        # per-(list, query) ADC lookup tables from the residual vs THIS
        # list's centroid — same math as the per-query path, but each
        # centroid's LUT batch is built once per list
        res = qv - cents[lblk][:, None, :]                   # (LB, qcap, d)
        res = res.reshape(LB, qcap, M, ds)
        dots = jnp.einsum("bqmd,mkd->bqmk", res, cb,
                          preferred_element_type=f32)
        res_n = jnp.sum(res * res, axis=3)                   # (LB, qcap, M)
        lut = res_n[..., None] + cb_n[None, None] - 2.0 * dots

        # THE grouped-PQ trick: dist[b,q,l] = sum_m lut[b,q,m,codes[b,l,m]]
        # is a matmul between the flattened LUT and the one-hot code
        # matrix — dense MXU work replacing the per-candidate (q,p,L,M)
        # random gather that bounds the per-query path
        mpos = storage.list_index[lblk]                      # (LB, L)
        codes = index.codes_sorted[mpos]                     # (LB, L, M) u8
        onehot = (
            codes[..., None] == jnp.arange(K, dtype=jnp.uint8)
        ).astype(bf16)                                       # (LB, L, M, K)
        d2 = jax.lax.dot_general(
            lut.reshape(LB, qcap, M * K).astype(bf16),
            onehot.reshape(LB, L, M * K),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=f32,
        )                                                    # (LB, qcap, L)

        invalid = (qids >= nq)[:, :, None] | (mpos >= storage.n)[:, None, :]
        d2 = jnp.where(invalid, jnp.inf, d2)
        vals, sel = lax.top_k(-d2, kk)                       # (LB, qcap, kk)
        memp = jnp.take_along_axis(
            jnp.broadcast_to(mpos[:, None, :], d2.shape), sel, axis=2
        )
        return -vals, memp

    lids = jnp.arange(n_lists, dtype=jnp.int32).reshape(-1, list_block)
    vals, mem = lax.map(block_fn, lids)
    vals = vals.reshape(n_lists, qcap, kk)
    mem = mem.reshape(n_lists, qcap, kk)

    pv, pm = regroup_pairs(vals, mem, l_flat, slot, nq, p, qcap)

    if not refine:
        return select_candidates(storage, pm, pv, k)

    # exact refinement: top-c of the pooled ADC candidates, f32 rescore
    c = max(k, min(int(math.ceil(refine_ratio * k)), p * kk))
    adc, cpos = lax.top_k(-pv, c)                            # (nq, c)
    rpos = jnp.take_along_axis(pm, cpos, axis=1)             # (nq, c)
    raw = index.vectors_sorted[rpos].astype(f32)             # (nq, c, d)
    exact = score_l2_candidates(
        qf, raw, jnp.isfinite(-adc) & (rpos < storage.n)
    )
    return select_candidates(storage, rpos, exact, k)


def ivf_pq_search_grouped(
    index: IVFPQIndex, queries, k: int, *, n_probes: int = 8,
    qcap: typing.Optional[int] = None, list_block: int = 8,
    refine_ratio: float = 2.0,
) -> Tuple[jax.Array, jax.Array]:
    """Throughput-mode IVF-PQ search, grouped by LIST (the PQ counterpart
    of :func:`ivf_flat_search_grouped`; SURVEY.md §7 hard part №3).

    Two structural wins over :func:`ivf_pq_search` at large batch:

    * each list's codes are loaded ONCE per batch (not once per probing
      query), and
    * the ADC table lookup ``sum_m lut[q, m, codes[l, m]]`` is computed as
      a matmul between the flattened per-query LUT (qcap, M*2^bits) and the
      one-hot code matrix (L, M*2^bits) — dense MXU work replacing the
      random gather that bounds the per-query path (measured: the gather
      moves ~6 GB per 4096-query batch at the 500k x 96 bench shape).

    The bf16 one-hot contraction only affects ADC *candidate ranking*;
    ``refine_ratio`` > 1 rescores the top candidates with exact f32
    distances (HIGHEST precision), so returned distances are exact.

    ``qcap`` caps queries per list (static shape), default 2x mean
    occupancy; overflow pairs are dropped (tiny recall cost, same contract
    as the flat grouped search).
    """
    from raft_tpu.spatial.ann.common import check_candidate_pool, default_qcap

    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, index.centroids, "queries", "index")
    check_candidate_pool(k, n_probes, index.storage)
    n_lists = index.centroids.shape[0]
    nq = q.shape[0]
    if qcap is None:
        qcap = default_qcap(nq, n_probes, n_lists)
    list_block = max(1, min(list_block, n_lists))
    while n_lists % list_block:
        list_block -= 1
    return _pq_grouped_impl(
        index, q, k, n_probes, qcap, list_block, refine_ratio
    )
