"""IVF-PQ ANN index — first-class TPU implementation (the reference wraps
FAISS GpuIndexIVFPQ, cpp/include/raft/spatial/knn/detail/
ann_quantized_faiss.cuh:115-206 + ``IVFPQParam`` ann_common.h; native here).

Build: coarse k-means → per-list residuals → product quantization: the d
dims split into M subspaces, each with its own 2^bits-entry codebook
trained by k-means on residual sub-vectors (a Python loop of M small
k-means fits — M is single-digit-to-low-tens, and each fit reuses the
jitted kmeans program across subspaces of equal shape). Codes pack to
(n, M) uint8.

Search (ADC — asymmetric distance computation): per (query, probed list) a
(M, 2^bits) lookup table of squared sub-distances between the query
residual and every codebook entry — one batched MXU/VPU computation — then
candidate scores are M gathered-LUT sums, and ``lax.top_k`` selects.

Refinement (``refine_ratio`` > 1, the FAISS IndexRefineFlat niche the
reference's FAISS build exposes downstream): the index keeps the raw
vectors in list-sorted order; search takes the top ``refine_ratio * k``
ADC candidates, rescores them with exact f32 L2 (a c ≪ n gather + MXU
batched dot), and re-selects k — recovering near-exact recall at PQ
speed."""

from __future__ import annotations

import dataclasses
import functools
import math
import typing
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import compat, errors
from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit, kmeans_predict
from raft_tpu.spatial.ann.common import (
    ListStorage,
    build_list_storage,
    split_oversized_lists as _split_oversized_lists,
)

__all__ = [
    "IVFPQParams", "IVFPQIndex", "ivf_pq_build", "ivf_pq_search",
    "ivf_pq_search_grouped",
]


@dataclasses.dataclass(frozen=True)
class IVFPQParams:
    """Analog of IVFPQParam (reference ann_common.h: nlist, M=n_subquantizers,
    n_bits, usePrecomputedTables)."""

    n_lists: int = 64
    pq_dim: int = 8           # M subspaces (reference n_subquantizers)
    pq_bits: int = 8          # 2^bits codebook entries
    kmeans_n_iters: int = 20
    pq_kmeans_n_iters: int = 20
    seed: int = 0
    store_raw: bool = True    # keep raw vectors for exact refinement
    kmeans_init: str = "k-means++"  # "random": cheap coarse/code books
    # Training-set cap for the coarse quantizer + PQ codebooks: datasets
    # beyond this size train on a uniform subsample and encode in
    # streaming blocks (the 10M+ regime; quantizer quality saturates far
    # below that — FAISS trains its 100M indexes the same way). None =
    # max(2^20, 64 * n_lists).
    train_size: typing.Optional[int] = None
    encode_block: int = 1 << 20  # rows per streaming-encode block
    # Longest allowed inverted list: lists beyond the cap are split into
    # sublists sharing the parent's centroid (probing spends adjacent
    # top-k slots on them — centroid distances tie). Padded-list compute
    # in the grouped searches scales with n_lists * max_list, so one
    # swollen list (a dense cluster swallowed whole) would otherwise tax
    # every list block. Tradeoff: a heavily split cluster consumes
    # several of a query's n_probes slots (raise n_probes on very skewed
    # data). None = auto, max(256, 2 * ceil(n / n_lists)) — applied only
    # on the large-n blocked-build path, where the padding tax is the
    # scaling blocker; small one-shot builds split only when an explicit
    # cap is given. 0 = off.
    max_list_cap: typing.Optional[int] = None


@compat.register_dataclass
@dataclasses.dataclass
class IVFPQIndex:
    centroids: jax.Array      # (n_lists, d)
    codebooks: jax.Array      # (M, 2^bits, ds)
    codes_sorted: jax.Array   # (n + 1, M) uint8 — sentinel row appended
    storage: ListStorage
    # (n + 1, d) raw vectors in list-sorted order (sentinel row appended),
    # or None when built with store_raw=False (pure-PQ memory footprint)
    vectors_sorted: typing.Optional[jax.Array]
    pq_dim: int = dataclasses.field(metadata=dict(static=True))
    pq_bits: int = dataclasses.field(metadata=dict(static=True))

    def warmup(self, nq: int, *, k: int = 10, n_probes: int = 8,
               qcap=None, list_block: int = 8, refine_ratio: float = 2.0,
               refine_dataset=None, exact_selection: bool = False,
               approx_recall_target: float = 0.95,
               stream_partials=None,
               use_pallas: typing.Optional[bool] = None,
               audit: bool = False) -> int:
        """Pre-compile the grouped serving program for (nq, d) float32
        batches by dispatching one all-zeros batch through the exact
        serving entry (in-process jit cache + persistent compilation
        cache when enabled) — the PQ sibling of
        :meth:`raft_tpu.spatial.ann.ivf_flat.IVFFlatIndex.warmup`.

        Returns the shape-only-resolved qcap; pass exactly that integer
        on serving dispatches (see IVFFlatIndex.warmup for why).
        ``audit=True`` runs the jaxpr-level program auditor over the
        warmed program and raises on findings
        (:mod:`raft_tpu.analysis.program`; see IVFFlatIndex.warmup)."""
        from raft_tpu.spatial.ann.common import static_qcap

        qc = static_qcap(qcap, nq, n_probes, self.centroids.shape[0])
        q0 = jnp.zeros((nq, self.centroids.shape[1]), jnp.float32)
        out = ivf_pq_search_grouped(
            self, q0, k, n_probes=n_probes, qcap=qc,
            list_block=list_block, refine_ratio=refine_ratio,
            refine_dataset=refine_dataset,
            exact_selection=exact_selection,
            approx_recall_target=approx_recall_target,
            stream_partials=stream_partials,
            use_pallas=use_pallas,
        )
        jax.block_until_ready(out)
        if audit:
            from raft_tpu.analysis.program import audit_warmed
            from raft_tpu.analysis.program.registry import (
                trace_pq_grouped,
            )

            refine_active = (
                self.vectors_sorted is not None
                or refine_dataset is not None
            ) and refine_ratio > 1.0
            up = _resolve_adc_engine(
                use_pallas, refine_active, self.pq_dim, self.pq_bits, qc
            )
            audit_warmed(trace_pq_grouped(
                self, nq, k, n_probes, qc, list_block=list_block,
                refine_ratio=refine_ratio,
                exact_selection=exact_selection,
                approx_recall_target=approx_recall_target,
                use_pallas=up, name="ivf_pq_grouped_warm",
            ))
        return qc


def _cdiv_host(a: int, b: int) -> int:
    return -(-a // b)




def _train_pq_codebooks(xt, coarse, params, ds, n_codes):
    """PQ codebooks from the TRAINING SUBSAMPLE's residuals only — the
    shared quantizer-training tail of the blocked single-chip build and
    the distributed per-rank build (comms/mnmg_ivf.py). ``coarse`` must
    have been fit on ``xt`` (its labels ARE the subsample assignments —
    no second (train_n, n_lists, d) pass)."""
    from raft_tpu.cluster.kmeans import kmeans_fit_batched

    M = params.pq_dim
    train_n = xt.shape[0]
    res_t = xt - coarse.centroids[coarse.labels]
    sub_t = res_t.reshape(train_n, M, ds).transpose(1, 0, 2)  # (M, tn, ds)
    outs = kmeans_fit_batched(
        sub_t,
        KMeansParams(
            n_clusters=n_codes,
            max_iter=params.pq_kmeans_n_iters,
            seed=params.seed + 1,
            init=params.kmeans_init,
            compute_dtype="bfloat16",
        ),
    )
    return outs.centroids                                     # (M, K, ds)


def _encode_rows(blk, coarse_centroids, codebooks, M, ds):
    """Label + PQ-encode one row block against replicated quantizers —
    the per-block body of the streaming encode, shared with the
    distributed build's per-rank shard_map encode."""
    lbl = kmeans_predict(blk, coarse_centroids)
    res = blk - coarse_centroids[lbl]
    s = res.reshape(blk.shape[0], M, ds).transpose(1, 0, 2)
    codes = jax.vmap(kmeans_predict)(s, codebooks).T.astype(jnp.uint8)
    return lbl.astype(jnp.int32), codes


@functools.partial(jax.jit, static_argnames=("M", "ds"))
def _encode_block_jit(blk, coarse_centroids, codebooks, M, ds):
    """Module-level jit of :func:`_encode_rows`: quantizers are ARGUMENTS
    (not trace-time constants), so a same-shape rebuild reuses the
    compiled executable — the warm-build path the bench's
    ``build_warm_s`` measures."""
    return _encode_rows(blk, coarse_centroids, codebooks, M, ds)


def _train_pq_and_encode_blocked(x, xt, coarse, params, ds, n_codes):
    """Subsample-trained codebooks + streaming full-dataset encode.

    PQ codebooks train on the residuals of the training subsample only;
    the full dataset is then labeled and coded in ``encode_block``-row
    blocks by one jitted program (block shape is static, so every block
    reuses the same executable). Peak transient memory is
    O(encode_block * d) instead of O(n * d) — the property that lets a
    16 GB chip build a 10M+ index.
    """
    n, d = x.shape
    M = params.pq_dim
    codebooks = _train_pq_codebooks(xt, coarse, params, ds, n_codes)

    def encode_one(blk):
        return _encode_block_jit(blk, coarse.centroids, codebooks, M, ds)

    B = params.encode_block
    lbl_parts, code_parts = [], []
    for s0 in range(0, n, B):
        blk = x[s0:min(s0 + B, n)]
        if blk.shape[0] < B:
            blk = jnp.pad(blk, ((0, B - blk.shape[0]), (0, 0)))
        lbl, codes = encode_one(blk)
        take = min(B, n - s0)
        lbl_parts.append(lbl[:take])
        code_parts.append(codes[:take])
    labels = jnp.concatenate(lbl_parts)
    codes = jnp.concatenate(code_parts)
    return labels, codes, codebooks


def _train_coarse(x, params: IVFPQParams):
    """Training-subsample selection + coarse quantizer fit — the shared
    front of the single-chip and sharded (comms/mnmg_ivf.py) builds.

    Large-n path (the DEEP-100M regime): train on a uniform subsample,
    encode the full dataset later in streaming blocks — the same
    train-on-subsample / add-in-batches structure FAISS uses under the
    reference (ann_quantized_faiss.cuh:115-206 wraps GpuIndexIVFPQ whose
    train() subsamples internally). One-shot training never needs more
    rows than saturates quantizer quality.

    ``x`` may be a host np.ndarray (the sharded build keeps the full
    dataset on host): subsample selection then happens host-side so only
    train_n rows ever materialize on device. Returns (xt, coarse, train_n).
    """
    n = x.shape[0]
    train_n = min(
        n,
        params.train_size
        if params.train_size is not None
        else max(1 << 20, 64 * params.n_lists),
    )
    if train_n < n:
        sel = jax.random.permutation(jax.random.PRNGKey(params.seed), n)[
            :train_n
        ]
        if isinstance(x, np.ndarray):
            xt = jnp.asarray(x[np.sort(np.asarray(sel))])
        else:
            xt = jnp.take(x, jnp.sort(sel), axis=0)
    else:
        xt = jnp.asarray(x)

    coarse = kmeans_fit(
        xt,
        KMeansParams(
            n_clusters=params.n_lists,
            max_iter=params.kmeans_n_iters,
            seed=params.seed,
            init=params.kmeans_init,
            # quantizer training tolerates bf16-rounded centroid updates
            # (intra-cluster averaging washes out operand rounding) and
            # the 2x MXU rate matters at the 10M-build scale
            compute_dtype="bfloat16",
        ),
    )
    return xt, coarse, train_n


def ivf_pq_build(x, params: IVFPQParams = IVFPQParams()) -> IVFPQIndex:
    x = jnp.asarray(x)
    errors.check_matrix(x, "x", min_rows=2)
    n, d = x.shape
    M = params.pq_dim
    errors.check_k(params.n_lists, n, "n_lists vs dataset rows")
    errors.expects(d % M == 0, "d=%d not divisible by pq_dim=%d", d, M)
    errors.expects(
        1 <= params.pq_bits <= 8,
        "pq_bits=%d out of range [1, 8] — codes are stored as uint8",
        params.pq_bits,
    )
    ds = d // M
    n_codes = 1 << params.pq_bits

    xt, coarse, train_n = _train_coarse(x, params)

    blocked = train_n < n or n > params.encode_block
    if params.max_list_cap is not None:
        cap = params.max_list_cap
    else:
        # auto cap only where it is the scaling blocker (see IVFPQParams)
        cap = max(256, 2 * _cdiv_host(n, params.n_lists)) if blocked else 0

    if blocked:
        labels, codes, codebooks = _train_pq_and_encode_blocked(
            x, xt, coarse, params, ds, n_codes
        )
        labels_np, cents_out = np.asarray(labels), coarse.centroids
        if cap:
            labels_np, cents_out = _split_oversized_lists(
                labels_np, cents_out, cap
            )
        storage = build_list_storage(labels_np, cents_out.shape[0])
        codes_sorted = jnp.concatenate(
            [jnp.take(codes, storage.sorted_ids, axis=0),
             jnp.zeros((1, M), jnp.uint8)]
        )
        vectors_sorted = None
        if params.store_raw:
            vectors_sorted = jnp.concatenate(
                [jnp.take(x, storage.sorted_ids, axis=0),
                 jnp.zeros((1, d), x.dtype)]
            )
        return IVFPQIndex(
            cents_out, codebooks, codes_sorted, storage,
            vectors_sorted, M, params.pq_bits,
        )

    labels = coarse.labels
    residuals = x - coarse.centroids[labels]

    # batched PQ codebook training across the M subspaces
    sub = residuals.reshape(n, M, ds).transpose(1, 0, 2)   # (M, n, ds)

    if n >= n_codes:
        # ONE vmapped Lloyd over all M subspaces: the per-subspace matmuls
        # are skinny ((n, ds) x (ds, K) with ds in the single digits —
        # poor MXU fill); batching them into (M, n, K) contractions keeps
        # the MXU busy and replaces M sequential fits with one program
        from raft_tpu.cluster.kmeans import kmeans_fit_batched

        outs = kmeans_fit_batched(
            sub,
            KMeansParams(
                n_clusters=n_codes,
                max_iter=params.pq_kmeans_n_iters,
                seed=params.seed + 1,
                init=params.kmeans_init,
            ),
        )
        codebooks = outs.centroids                          # (M, K, ds)
        # vmapped encode: one dispatch (M sequential predicts measured
        # ~9 s of pure dispatch overhead at the 500k bench shape)
        codes = (
            jax.vmap(kmeans_predict)(sub, codebooks).T.astype(jnp.uint8)
        )                                                   # (n, M)
    else:
        # tiny datasets (n < 2^bits): per-subspace fits with inf padding
        def fit_sub(subx, seed):
            out = kmeans_fit(
                subx,
                KMeansParams(
                    n_clusters=min(n_codes, subx.shape[0]),
                    max_iter=params.pq_kmeans_n_iters,
                    seed=seed,
                    init=params.kmeans_init,
                ),
            )
            cents = out.centroids
            pad = n_codes - cents.shape[0]
            if pad > 0:
                cents = jnp.concatenate(
                    [cents, jnp.full((pad, ds), jnp.inf, cents.dtype)]
                )
            return cents

        codebooks = jnp.stack(
            [fit_sub(sub[m], params.seed + m) for m in range(M)]
        )                                                   # (M, K, ds)

        def encode_sub(subx, cb):
            return kmeans_predict(subx, jnp.where(jnp.isfinite(cb), cb, 1e30))

        codes = jnp.stack(
            [encode_sub(sub[m], codebooks[m]) for m in range(M)], axis=1
        ).astype(jnp.uint8)                                 # (n, M)

    labels_np, cents_out = np.asarray(labels), coarse.centroids
    if cap:
        labels_np, cents_out = _split_oversized_lists(
            labels_np, cents_out, cap
        )
    storage = build_list_storage(labels_np, cents_out.shape[0])
    codes_sorted = jnp.concatenate(
        [codes[storage.sorted_ids], jnp.zeros((1, M), jnp.uint8)]
    )
    vectors_sorted = None
    if params.store_raw:
        vectors_sorted = jnp.concatenate(
            [x[storage.sorted_ids], jnp.zeros((1, d), x.dtype)]
        )
    return IVFPQIndex(
        cents_out, codebooks, codes_sorted, storage, vectors_sorted,
        M, params.pq_bits,
    )


@functools.partial(
    jax.jit, static_argnames=("k", "n_probes", "block_q", "refine_ratio")
)
def ivf_pq_search(
    index: IVFPQIndex, queries, k: int, *, n_probes: int = 8,
    block_q: int = 256, refine_ratio: float = 2.0,
    refine_dataset=None,
) -> Tuple[jax.Array, jax.Array]:
    """ADC search; returns (squared L2 dists, original row ids).
    Query batches run in ``block_q`` blocks so the per-(query, list) LUTs
    and the (q, p, L, M) code gather stay HBM-bounded.

    ``refine_ratio`` > 1 (and an index built with ``store_raw``) rescores
    the top ``ceil(refine_ratio * k)`` ADC candidates with exact f32
    distances before the final k-selection; returned distances are then
    exact. ``refine_ratio <= 1`` returns raw ADC approximations.

    ``refine_dataset``: the original (n, d) dataset, enabling exact
    refinement for an index built with ``store_raw=False`` (codes-only
    memory, M bytes/row) — the caller keeps the dataset, the index stays
    small; candidates gather through ``storage.sorted_ids``. Ignored when
    the index stores raw vectors."""
    from raft_tpu.spatial.ann.common import (
        check_candidate_pool, coarse_probe, map_query_blocks,
        score_l2_candidates, select_candidates,
    )

    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, index.centroids, "queries", "index")
    d = q.shape[1]
    M = index.pq_dim
    ds = d // M
    check_candidate_pool(k, n_probes, index.storage)
    refine = (
        index.vectors_sorted is not None or refine_dataset is not None
    ) and refine_ratio > 1.0
    c = max(k, min(int(math.ceil(refine_ratio * k)),
                   n_probes * index.storage.max_list))
    f32 = jnp.float32
    cents = index.centroids.astype(f32)
    cb = jnp.where(jnp.isfinite(index.codebooks), index.codebooks, 0.0)
    cb_n = jnp.sum(cb * cb, axis=2)                          # (M, K)

    def one_block(qb):
        nq = qb.shape[0]
        qf = qb.astype(f32)
        probes, _ = coarse_probe(qf, cents, n_probes)        # (q, p)

        # LUTs: residual of q wrt each probed centroid, per subspace vs
        # codebook; (q, p, d) residuals -> (q, p, M, ds)
        res = qf[:, None, :] - cents[probes]
        res = res.reshape(nq, n_probes, M, ds)
        dots = jnp.einsum("qpmd,mkd->qpmk", res, cb,
                          preferred_element_type=f32)
        res_n = jnp.sum(res * res, axis=3)                   # (q, p, M)
        lut = res_n[..., None] + cb_n[None, None] - 2.0 * dots  # (q,p,M,K)

        # candidates: padded probed lists, gather codes, sum LUT entries
        cand_pos = index.storage.list_index[probes]          # (q, p, L)
        codes = index.codes_sorted[cand_pos].astype(jnp.int32)  # (q,p,L,M)
        # dist[q,p,l] = sum_m lut[q,p,m,codes[q,p,l,m]]
        lut_t = lut.transpose(0, 1, 3, 2)                    # (q, p, K, M)
        # the INTENTIONAL per-query LUT gather, kept for small-batch
        # latency. Proved bounded by the program auditor: the
        # `ivf_pq_per_query` entry in ci/checks/program_contracts.json
        # pins this program's peak per-equation intermediate at the
        # block_q-blocked (blk, p, L, M) gather tile — the
        # materialization-model pass would flag any regression that
        # widens it (docs/static_analysis.md "Two tiers"), so the AST
        # grandfather entry is retired for this inline proof.
        gath = jnp.take_along_axis(  # jaxlint: disable=adc-gather
            lut_t, codes, axis=2
        )                                                    # (q, p, L, M)
        d2 = jnp.sum(gath, axis=3)                           # (q, p, L)

        valid = cand_pos < index.storage.n
        d2 = jnp.where(valid, d2, jnp.inf).reshape(nq, -1)
        flat_pos = cand_pos.reshape(nq, -1)

        if not refine:
            return select_candidates(index.storage, flat_pos, d2, k)

        # refinement: top-c by ADC score, exact f32 rescore, re-select k
        adc, cpos = jax.lax.top_k(-d2, c)                    # (q, c)
        rpos = jnp.take_along_axis(flat_pos, cpos, axis=1)   # (q, c)
        raw = _gather_refine_rows(index, refine_dataset, rpos, f32)
        exact = score_l2_candidates(
            qf, raw, jnp.isfinite(-adc) & (rpos < index.storage.n)
        )
        return select_candidates(index.storage, rpos, exact, k)

    return map_query_blocks(one_block, q, block_q)


def _gather_refine_rows(index, refine_dataset, rpos, f32):
    """Candidate raw vectors for exact refinement: from the index's
    list-sorted copy when stored, else from the caller-held dataset via
    the sorted-order -> original-id map (codes-only indexes)."""
    if index.vectors_sorted is not None:
        return index.vectors_sorted[rpos].astype(f32)
    oid = index.storage.sorted_ids[
        jnp.clip(rpos, 0, index.storage.n - 1)
    ]
    return jnp.take(refine_dataset, oid, axis=0).astype(f32)


def _resolve_adc_engine(use_pallas, refine_active: bool, pq_dim: int,
                        pq_bits: int, qcap: int) -> bool:
    """Resolve the ``use_pallas`` knob of the grouped searches to a
    concrete engine choice (a trace-time static).

    ``None`` (auto): the Pallas ADC engine (spatial/ann/pq_kernel) on a
    TPU backend whenever the exact-refine tail is active and the config
    fits the kernel's VMEM plan; the XLA one-hot path otherwise — so
    ``JAX_PLATFORMS=cpu`` never imports, let alone compiles, the kernel
    unless a caller opts in explicitly. ``True`` validates the
    requirements and raises with the reason when they do not hold
    (explicit opt-in must not silently fall back)."""
    if use_pallas is None:
        if jax.default_backend() != "tpu" or not refine_active:
            return False
        from raft_tpu.spatial.ann.pq_kernel import pq_adc_supported

        return pq_adc_supported(pq_dim, pq_bits, qcap)
    if use_pallas:
        from raft_tpu.spatial.ann.pq_kernel import pq_adc_supported

        errors.expects(
            refine_active,
            "use_pallas=True requires the exact refine tail "
            "(refine_ratio > 1 and stored raw vectors or a "
            "refine_dataset): the kernel emits sub-chunk ADC minima to "
            "build the refine pool, not per-row ADC distances",
        )
        errors.expects(
            pq_adc_supported(pq_dim, pq_bits, qcap),
            "use_pallas=True unsupported at pq_dim=%d pq_bits=%d qcap=%d "
            "(one LUT block + one-hot tile exceeds the kernel's VMEM "
            "plan); use the one-hot path", pq_dim, pq_bits, qcap,
        )
    return bool(use_pallas)


# refine-pool gather budget per lax.map block on the Pallas path: the
# (blk_q, c*8, d) raw-row gather stays under this regardless of nq
_REFINE_BLOCK_BYTES = 256 << 20


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "n_probes", "qcap", "list_block", "refine_ratio",
        "exact_selection", "approx_recall_target", "stream_partials",
        "use_pallas", "pallas_interpret",
    ),
)
def _pq_grouped_impl(index, q, k, n_probes, qcap, list_block, refine_ratio,
                     refine_dataset=None, probes=None,
                     exact_selection=False, approx_recall_target=0.95,
                     stream_partials=None, use_pallas=False,
                     pallas_interpret=False, row_mask=None):
    # ``row_mask``: optional (n + 1,) RUNTIME live mask over slab
    # positions (tombstone deletion, spatial/ann/mutation.py). The
    # one-hot engine folds it into the scan's validity mask; the Pallas
    # kernel path applies it at the exact-refine tail instead (the
    # kernel emits sub-chunk minima, so a tombstoned row can still crowd
    # a pool slot there — it can never SURFACE, and compaction bounds
    # the density; docs/mutation.md). Runtime input: flips never
    # recompile.
    from raft_tpu.spatial.ann.common import (
        coarse_probe, invert_probe_map_ranked, regroup_pairs,
        score_l2_candidates, select_candidates,
    )

    storage = index.storage
    n_lists = index.centroids.shape[0]
    L = storage.max_list
    nq, d = q.shape
    p = n_probes
    M = index.pq_dim
    ds = d // M
    K = 1 << index.pq_bits
    f32 = jnp.float32
    bf16 = jnp.bfloat16
    qf = q.astype(f32)
    cents = index.centroids.astype(f32)
    cb = jnp.where(jnp.isfinite(index.codebooks), index.codebooks, 0.0)
    cb_n = jnp.sum(cb * cb, axis=2)                          # (M, K)

    if probes is None:
        probes, _ = coarse_probe(qf, cents, p)               # (nq, p)
    qmat, rmat, l_flat, slot = invert_probe_map_ranked(
        probes, n_lists, qcap
    )

    q_pad = jnp.concatenate([qf, jnp.zeros((1, d), f32)])    # sentinel query
    # per-(list, query) partial width: must cover the REFINE pool, not just
    # k — on clustered data a query's home list can hold most of the
    # global top-c ADC candidates, and truncating it to k caps recall
    # (measured: 0.73 vs 0.95 at the 500k bench shape with kk = k)
    refine = (
        index.vectors_sorted is not None or refine_dataset is not None
    ) and refine_ratio > 1.0
    kk = min(max(k, int(math.ceil(refine_ratio * k)) if refine else k), L)
    # the Pallas ADC engine builds the refine pool from sub-chunk minima,
    # so it only applies when the exact refine tail runs (entry points
    # enforce this; the AND is belt-and-braces)
    use_kernel = bool(use_pallas) and refine

    def block_luts(lblk):
        """Per-(list, query-slot) ADC lookup tables for one list block —
        residual of each slot's query vs THIS list's centroid, scored
        against every codebook entry, INCLUDING the residual-norm
        constant (so summed/contracted entries are complete squared
        distances, comparable across lists in the pooled selection).
        The single LUT authority for BOTH ADC engines: the one-hot
        contraction and the Pallas kernel must never drift.
        Returns (qids (LB, qcap), lut (LB, qcap, M, K) f32)."""
        LB = lblk.shape[0]
        qids = qmat[lblk]                                    # (LB, qcap)
        qv = q_pad[qids]                                     # (LB, qcap, d)
        res = qv - cents[lblk][:, None, :]                   # (LB, qcap, d)
        res = res.reshape(LB, qcap, M, ds)
        dots = jnp.einsum("bqmd,mkd->bqmk", res, cb,
                          preferred_element_type=f32)
        res_n = jnp.sum(res * res, axis=3)                   # (LB, qcap, M)
        return qids, res_n[..., None] + cb_n[None, None] - 2.0 * dots

    def block_fn(lblk):                                      # (LB,) list ids
        LB = lblk.shape[0]
        qids, lut = block_luts(lblk)                         # (LB, qcap, M, K)

        # Each list is CONTIGUOUS in sorted storage, so its codes read as
        # one dynamic_slice slab — row-granular list_index gathers of
        # M-byte code rows measured ~50x slower at the 10M x 96 shape
        # (the same contiguity the fused-kNN phase-2 DMA exploits).
        offs = storage.list_offsets[lblk]                    # (LB,)
        szs = storage.list_sizes[lblk]
        o_c = jnp.minimum(offs, storage.n + 1 - L)           # slice clamp
        codes = jax.vmap(
            lambda s: lax.dynamic_slice(index.codes_sorted, (s, 0), (L, M))
        )(o_c)                                               # (LB, L, M) u8
        pos = o_c[:, None] + jnp.arange(L, dtype=jnp.int32)[None, :]
        in_list = (pos >= offs[:, None]) & (pos < (offs + szs)[:, None])
        if row_mask is not None:
            in_list = in_list & (row_mask[pos] > 0)

        # THE grouped-PQ trick: dist[b,q,l] = sum_m lut[b,q,m,codes[b,l,m]]
        # is a matmul between the flattened LUT and the one-hot code
        # matrix — dense MXU work replacing the per-candidate (q,p,L,M)
        # random gather that bounds the per-query path
        onehot = (
            codes[..., None] == jnp.arange(K, dtype=jnp.uint8)
        ).astype(bf16)                                       # (LB, L, M, K)
        # the INTENTIONAL legacy one-hot engine, kept as the
        # use_pallas=False CPU/interpret fallback. Proved pinned by the
        # program auditor: the `ivf_pq_grouped_onehot` entry in
        # ci/checks/program_contracts.json snapshots this engine's
        # scan-path f32 tiles and peak intermediate bytes, and the
        # Pallas serving entry (`ivf_pq_grouped_pallas`) pins ZERO wide
        # tiles — a new one-hot spelling anywhere else fails the AST
        # rule outright now that the baseline entry is retired for this
        # inline proof (docs/static_analysis.md "Two tiers").
        d2 = jax.lax.dot_general(  # jaxlint: disable=adc-gather
            lut.reshape(LB, qcap, M * K).astype(bf16),
            onehot.reshape(LB, L, M * K),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=f32,
        )                                                    # (LB, qcap, L)

        invalid = (qids >= nq)[:, :, None] | (~in_list)[:, None, :]
        d2 = jnp.where(invalid, jnp.inf, d2)
        # per-(list, slot) partial selection: when exact refinement runs
        # downstream, use the TPU hardware approx top-k (lax.approx_min_k,
        # ~0.95 per-call recall) — this selection only shapes the ADC
        # candidate pool, and exact lax.top_k here measured ~14x the cost
        # of everything else in the block at the 10M shape. The UNREFINED
        # path keeps exact selection: its per-block picks ARE the results.
        # ``exact_selection`` restores exact candidate selection without
        # disabling refinement; ``approx_recall_target`` tunes the
        # approximate stages' per-call recall.
        if refine and not exact_selection:
            vals, sel = lax.approx_min_k(
                d2, kk, recall_target=approx_recall_target
            )                                                # (LB, qcap, kk)
        else:
            nv, sel = lax.top_k(-d2, kk)
            vals = -nv
        # kk-wide selection remap, not a LUT gather:
        memp = jnp.take_along_axis(  # jaxlint: disable=adc-gather
            jnp.broadcast_to(pos[:, None, :], d2.shape),
            sel.astype(jnp.int32), axis=2,
        )
        return vals, memp

    if use_kernel:
        from raft_tpu.spatial.ann import pq_kernel, scan_core

        sub = pq_kernel.SUBCHUNK
        # the shared rounding + profile pq_adc_supported validated the
        # VMEM plan with (tile_profile auto-selects the latency plan for
        # qcap-1/8 serving shapes — docs/ivf_scale.md "One scan-kernel
        # core")
        q_kpad = scan_core.pad_queries(qcap)
        # capped at the code slab's own lane-rounded height (see the
        # flat twin: a wide profile start must not widen the per-list
        # window past max_list)
        l_tile = pq_kernel.plan_l_tile(
            M * K, q_kpad,
            l_tile=-(-L // scan_core.LANE) * scan_core.LANE,
            profile=scan_core.tile_profile(qcap),
        )
        l_pad = -(-L // l_tile) * l_tile
        nsc = l_pad // sub
        rows = index.codes_sorted.shape[0]    # n + 1 (sentinel row)
        rows_pad = max(rows, l_pad)
        # tiny indexes whose whole slab is shorter than one padded list
        # window: extend the slab so the clamped dynamic_slice stays in
        # range (static condition — big indexes never pay the copy)
        codes_src = (
            index.codes_sorted if rows_pad == rows
            else jnp.pad(index.codes_sorted,
                         ((0, rows_pad - rows), (0, 0)))
        )

        def block_fn_pallas(lblk):            # (LB,) list ids
            LB = lblk.shape[0]
            _, lut = block_luts(lblk)         # shared LUT authority
            lutf = lut.reshape(LB, qcap, M * K)
            if q_kpad > qcap:
                lutf = jnp.pad(
                    lutf, ((0, 0), (0, q_kpad - qcap), (0, 0))
                )
            offs = storage.list_offsets[lblk]                # (LB,)
            szs = storage.list_sizes[lblk]
            o_c = jnp.minimum(offs, rows_pad - l_pad)        # slice clamp
            codes_t = jax.vmap(
                lambda s: lax.dynamic_slice(codes_src, (s, 0), (l_pad, M))
            )(o_c).transpose(0, 2, 1)                        # (LB, M, l_pad)
            lo = offs - o_c
            bounds = jnp.stack([lo, lo + szs], axis=1)       # (LB, 2)
            mins = pq_kernel.pq_adc_subchunk_min(
                lutf.astype(jnp.bfloat16), codes_t, bounds,
                interpret=pallas_interpret, l_tile=l_tile,
            )[:, :qcap]                                      # (LB, qcap, nsc)
            # positions are NOT returned: a sub-chunk's slab base is
            # fully derivable from (probe slot, chunk index) after
            # selection, so the kernel path pools VALUES ONLY — half
            # the pool memory and scatter traffic of the legacy path
            return mins

        width, scan_fn = nsc, block_fn_pallas
    else:
        width, scan_fn = kk, block_fn

    # pad the list axis up to a multiple of list_block (clamped ids — the
    # padded slots recompute the last list; regroup never references
    # them, and the streamed scatter re-writes identical values)
    # instead of shrinking list_block, which collapses to 1-list blocks
    # when n_lists is prime-ish (e.g. after oversized-list splitting)
    nl_pad = -(-n_lists // list_block) * list_block
    lids = jnp.minimum(
        jnp.arange(nl_pad, dtype=jnp.int32), n_lists - 1
    ).reshape(-1, list_block)

    if stream_partials is None:
        # auto: stream once the materialized partials pass ~2 GB. qcap
        # must cover the HOT list, so on skewed probe maps
        # n_lists * qcap can exceed the true pair count nq * p by 30x+ —
        # the buffer compile-OOM'd at 11.8 GB at 3M x 768 rr=16
        # (docs/ivf_scale.md; VERDICT r4 weak-5). The kernel path pools
        # values only (no int32 positions), hence the smaller footprint.
        per_entry = 4 if use_kernel else 8
        stream_partials = n_lists * qcap * width * per_entry > (1 << 31)
    if stream_partials:
        # stream list blocks through the query-major pool: scatter each
        # block's (LB, qcap, width) partials straight to their (query,
        # probe-rank) rows via the slot inverse — peak extra memory is
        # ONE block's partials, the reference's grid-stride bounding of
        # the same intermediate (pairwise_distance_base.cuh:122-134)
        if use_kernel:
            def scan_body_v(pvc, lblk):
                v = scan_fn(lblk)
                qi, ri = qmat[lblk], rmat[lblk]      # sentinels drop
                return pvc.at[qi, ri].set(v, mode="drop"), None

            pv, _ = lax.scan(
                scan_body_v,
                jnp.full((nq, p, width), jnp.inf, jnp.float32), lids,
            )
            pv, pm = pv.reshape(nq, p * width), None
        else:
            def scan_body(carry, lblk):
                pvc, pmc = carry
                v, mp = scan_fn(lblk)
                qi, ri = qmat[lblk], rmat[lblk]      # sentinels drop
                pvc = pvc.at[qi, ri].set(v, mode="drop")
                pmc = pmc.at[qi, ri].set(mp, mode="drop")
                return (pvc, pmc), None

            init = (
                jnp.full((nq, p, width), jnp.inf, jnp.float32),
                jnp.full((nq, p, width), storage.n, jnp.int32),
            )
            (pv, pm), _ = lax.scan(scan_body, init, lids)
            pv = pv.reshape(nq, p * width)
            pm = pm.reshape(nq, p * width)
    elif use_kernel:
        vals = lax.map(scan_fn, lids)
        vals = vals.reshape(nl_pad, qcap, width)[:n_lists]
        # values-only regroup (the slot inverse of regroup_pairs)
        ok = slot < qcap
        safe_slot = jnp.minimum(slot, qcap - 1)
        pv = jnp.where(
            ok[:, None], vals[l_flat, safe_slot], jnp.inf
        ).reshape(nq, p * width)
        pm = None
    else:
        vals, mem = lax.map(scan_fn, lids)
        vals = vals.reshape(nl_pad, qcap, width)[:n_lists]
        mem = mem.reshape(nl_pad, qcap, width)[:n_lists]
        pv, pm = regroup_pairs(vals, mem, l_flat, slot, nq, p, qcap)

    if not refine:
        return select_candidates(storage, pm, pv, k)

    if use_kernel:
        # kernel path: pool entries are SUB-CHUNK minima. Select the
        # top-c sub-chunks — the fused_knn cover argument at 8-row
        # granularity: every ADC-rank-c row lives in a sub-chunk whose
        # minimum is <= the c-th best ADC value, so the selected
        # sub-chunks' rows are a SUPERSET of the one-hot path's top-c
        # row pool at the same refine_ratio — then rescore their rows
        # with exact f32 (refine semantics and precision unchanged).
        # Clamp to the pool width LAST: a large k (> p*width) must not
        # ask top_k for more sub-chunks than exist — the clamped pool
        # still covers k rows (c*8 = p*l_pad >= p*max_list >= k, the
        # check_candidate_pool precondition).
        c = min(p * width, max(k, int(math.ceil(refine_ratio * k))))
        if exact_selection:
            nv, cpos = lax.top_k(-pv, c)
            nadc = -nv
        else:
            nadc, cpos = lax.approx_min_k(
                pv, c, recall_target=approx_recall_target
            )                                                # (nq, c)
        cpos = cpos.astype(jnp.int32)
        # slab positions are DERIVED, not pooled: pool index -> (probe
        # slot, chunk), and the sub-chunk's base replays the block's
        # clamped dynamic-slice origin o_c = min(offset, rows_pad-l_pad)
        offs_q = storage.list_offsets[probes]                # (nq, p)
        szs_q = storage.list_sizes[probes]
        slot_sel = cpos // width
        off_sel = jnp.take_along_axis(offs_q, slot_sel, axis=1)
        end_sel = off_sel + jnp.take_along_axis(szs_q, slot_sel, axis=1)
        base_sel = (
            jnp.minimum(off_sel, rows_pad - l_pad)
            + sub * (cpos % width)
        )                                                    # (nq, c)
        # per-row validity: a sub-chunk window can overhang its list's
        # tail into the NEXT list's slab rows — mask against the exact
        # [offset, offset+size) range of the probe slot it came from
        rows_sel = base_sel[:, :, None] + jnp.arange(sub, dtype=jnp.int32)
        validf = (
            (rows_sel >= off_sel[:, :, None])
            & (rows_sel < end_sel[:, :, None])
            & (jnp.isfinite(nadc) & (nadc < pq_kernel.BIG))[:, :, None]
        )
        if row_mask is not None:
            # tombstones are applied per ROW at the refine tail on the
            # kernel path (the in-kernel sub-chunk minima are unmasked)
            validf = validf & (
                row_mask[jnp.clip(rows_sel, 0, storage.n)] > 0
            )
        validf = validf.reshape(nq, c * sub)
        rpos = rows_sel.reshape(nq, c * sub)

        def refine_blk(args):
            qb, rp, vl = args
            raw = _gather_refine_rows(
                index, refine_dataset, jnp.clip(rp, 0, storage.n), f32
            )
            exact = score_l2_candidates(qb, raw, vl & (rp < storage.n))
            return select_candidates(storage, rp, exact, k)

        # block the (blk_q, c*8, d) raw-row gather over queries so the
        # 8x-wider kernel-path pool never materializes a multi-GB
        # transient at serving batch sizes (zero-padded rows compute on
        # all-invalid candidates and are sliced away)
        blk_q = max(8, min(nq, _REFINE_BLOCK_BYTES // (c * sub * d * 4)))
        from raft_tpu.spatial.ann.common import map_query_blocks

        return map_query_blocks(refine_blk, (qf, rpos, validf), blk_q)

    # exact refinement: top-c of the pooled ADC candidates, f32 rescore
    # (pool selection rides the hardware approx top-k too — same
    # already-approximate-stage argument as the per-block selection)
    c = max(k, min(int(math.ceil(refine_ratio * k)), p * kk))
    if exact_selection:
        nv, cpos = lax.top_k(-pv, c)
        nadc = -nv                                           # min-k convention
    else:
        nadc, cpos = lax.approx_min_k(
            pv, c, recall_target=approx_recall_target
        )                                                    # (nq, c)
    adc = -nadc
    rpos = jnp.take_along_axis(pm, cpos.astype(jnp.int32), axis=1)
    raw = _gather_refine_rows(index, refine_dataset, rpos, f32)
    exact = score_l2_candidates(
        qf, raw, jnp.isfinite(-adc) & (rpos < storage.n)
    )
    return select_candidates(storage, rpos, exact, k)


def ivf_pq_search_grouped(
    index: IVFPQIndex, queries, k: int, *, n_probes: int = 8,
    qcap: typing.Union[int, str, None] = None, list_block: int = 8,
    refine_ratio: float = 2.0, refine_dataset=None,
    exact_selection: bool = False, approx_recall_target: float = 0.95,
    stream_partials: typing.Optional[bool] = None,
    qcap_max_drop_frac: typing.Optional[float] = None,
    use_pallas: typing.Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Throughput-mode IVF-PQ search, grouped by LIST (the PQ counterpart
    of :func:`ivf_flat_search_grouped`; SURVEY.md §7 hard part №3).

    Two structural wins over :func:`ivf_pq_search` at large batch:

    * each list's codes are loaded ONCE per batch (not once per probing
      query), and
    * the ADC table lookup ``sum_m lut[q, m, codes[l, m]]`` is computed as
      a matmul between the flattened per-query LUT (qcap, M*2^bits) and the
      one-hot code matrix (L, M*2^bits) — dense MXU work replacing the
      random gather that bounds the per-query path (measured: the gather
      moves ~6 GB per 4096-query batch at the 500k x 96 bench shape).

    The bf16 one-hot contraction only affects ADC *candidate ranking*;
    ``refine_ratio`` > 1 rescores the top candidates with exact f32
    distances (HIGHEST precision), so returned distances are exact.
    WITHOUT refinement (``refine_ratio <= 1``, or a codes-only index and
    no ``refine_dataset``) the returned distances carry the bf16 ADC
    rounding — coarser than :func:`ivf_pq_search`'s f32 per-query LUT
    path, which ``approx_knn_search``'s auto mode may select at small
    batch; pass an explicit ``mode=`` there if bit-stable unrefined
    distances across batch sizes matter.

    ``qcap`` caps queries per list (static shape); overflow pairs are
    dropped. Default (``qcap=None``): auto-sized from the actual probe
    map so at most 2% of (query, probe) pairs drop, with any residual
    logged — never silent (common.resolve_qcap). The auto path costs one
    eager coarse probe + host sync per call, and a shifting query mix
    that crosses a qcap doubling boundary recompiles the grouped
    program — serving workloads that need fully-async dispatch should
    pass an explicit ``qcap`` and audit it with common.probe_drop_stats.
    ``qcap="throughput"`` picks ~0.75x the mean probe occupancy (block
    compute is linear in qcap; measured 4.6x QPS at flat recall on
    clustered workloads — common.throughput_qcap documents when it is
    NOT safe).

    ``refine_dataset``: caller-held (n, d) dataset enabling exact
    refinement for codes-only (``store_raw=False``) indexes — see
    :func:`ivf_pq_search`.

    Candidate selection inside the REFINED path uses the TPU hardware
    approximate top-k (``lax.approx_min_k``) at two stages (per-block and
    pooled) — a throughput choice that slightly thins the ADC candidate
    pool. ``exact_selection=True`` restores exact ``lax.top_k`` at both
    stages without disabling refinement (the pre-r03 behavior);
    ``approx_recall_target`` tunes the approximate stages instead
    (default 0.95). Unrefined searches always select exactly.

    ``stream_partials``: stream list blocks through the query-major
    candidate pool instead of materializing the (n_lists, qcap, kk)
    per-block partials — bounds HBM to one block's partials + the pool
    when hot-list-skewed probe maps force qcap far above the mean
    occupancy (the 3M x 768 rr=16 regime that otherwise compile-OOMs at
    11.8 GB). ``None`` (default) auto-streams past a ~2 GB partials
    footprint; the materialized path is kept for small buffers where the
    one-shot regroup measures faster.

    ``use_pallas`` selects the ADC engine (docs/ivf_scale.md "ADC in
    VMEM"): ``None`` (auto) runs the Pallas sub-chunk-min kernel
    (spatial/ann/pq_kernel) on a TPU backend whenever the exact refine
    tail is active and the config fits its VMEM plan — the one-hot code
    expansion then lives only in VMEM and only (qcap, max_list/8)
    sub-chunk minima reach HBM, instead of the XLA path's one-hot +
    distance-tile round trips. ``False`` pins the XLA one-hot path (the
    CPU/interpret fallback — bit-stable with previous releases);
    ``True`` opts in explicitly (interpret mode off-TPU) and raises when
    the requirements do not hold. Returned candidates are value-exact
    between engines at the same refine_ratio (the kernel's refine pool
    is a superset — sub-chunk cover); tied candidates may order
    differently.
    """
    from raft_tpu.spatial.ann.common import (
        check_candidate_pool, resolve_qcap_arg,
    )

    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, index.centroids, "queries", "index")
    check_candidate_pool(k, n_probes, index.storage)
    errors.expects(
        0.0 < approx_recall_target <= 1.0,
        "approx_recall_target=%s out of range (0, 1]", approx_recall_target,
    )
    n_lists = index.centroids.shape[0]
    qcap, probes = resolve_qcap_arg(
        qcap, q, index.centroids, n_lists, n_probes,
        max_drop_frac=qcap_max_drop_frac,
    )
    list_block = max(1, min(list_block, n_lists))
    refine_active = (
        index.vectors_sorted is not None or refine_dataset is not None
    ) and refine_ratio > 1.0
    use_pallas = _resolve_adc_engine(
        use_pallas, refine_active, index.pq_dim, index.pq_bits, qcap
    )
    return _pq_grouped_impl(
        index, q, k, n_probes, qcap, list_block, refine_ratio,
        refine_dataset=refine_dataset, probes=probes,
        exact_selection=exact_selection,
        approx_recall_target=approx_recall_target,
        stream_partials=stream_partials,
        use_pallas=use_pallas,
        pallas_interpret=jax.default_backend() != "tpu",
    )
