"""Generic ANN entry points — the analog of the reference's
``approx_knn_build_index`` / ``approx_knn_search``
(cpp/include/raft/spatial/knn/detail/ann_quantized_faiss.cuh:115-206,
public spatial/knn/ann.cuh), which dispatch on the dynamic type of the
``knnIndexParam`` subclass (ann_common.h: IVFFlatParam / IVFPQParam /
IVFSQParam). Here the dispatch key is the params dataclass type at build
and the index pytree type at search.
"""

from __future__ import annotations

import inspect
from typing import Tuple

import jax

from raft_tpu import errors
from raft_tpu.spatial.ann.ivf_flat import (
    IVFFlatIndex, IVFFlatParams, ivf_flat_build, ivf_flat_search,
    ivf_flat_search_grouped,
)
from raft_tpu.spatial.ann.ivf_pq import (
    IVFPQIndex, IVFPQParams, ivf_pq_build, ivf_pq_search,
    ivf_pq_search_grouped,
)
from raft_tpu.spatial.ann.ivf_sq import (
    IVFSQIndex, IVFSQParams, ivf_sq_build, ivf_sq_search,
)

__all__ = ["approx_knn_build_index", "approx_knn_search"]

_BUILDERS = {
    IVFFlatParams: ivf_flat_build,
    IVFPQParams: ivf_pq_build,
    IVFSQParams: ivf_sq_build,
}

# (per-query latency path, grouped throughput path or None)
_SEARCHERS = {
    IVFFlatIndex: (ivf_flat_search, ivf_flat_search_grouped),
    IVFPQIndex: (ivf_pq_search, ivf_pq_search_grouped),
    IVFSQIndex: (ivf_sq_search, None),
}


def approx_knn_build_index(x, params):
    """Build the ANN index selected by the dynamic params type
    (reference approx_knn_build_index:115 — `dynamic_cast<IVFFlatParam*>`
    etc.)."""
    builder = _BUILDERS.get(type(params))
    errors.expects(
        builder is not None,
        "approx_knn_build_index: unknown params type %s (expected one of %s)",
        type(params).__name__, sorted(c.__name__ for c in _BUILDERS),
    )
    return builder(x, params)


def approx_knn_search(
    index, queries, k: int, *, n_probes: int = 8, mode: str = "auto",
    **kw,
) -> Tuple[jax.Array, jax.Array]:
    """Search any ANN index (reference approx_knn_search:169).

    ``mode``: "latency" (per-query path), "throughput" (grouped list-major
    path where the index family has one), or "auto" — throughput when the
    batch is large (>= 1024 queries), matching the measured regime split
    in bench/bench_ann.py.
    """
    entry = _SEARCHERS.get(type(index))
    errors.expects(
        entry is not None,
        "approx_knn_search: unknown index type %s (expected one of %s)",
        type(index).__name__, sorted(c.__name__ for c in _SEARCHERS),
    )
    errors.expects(
        mode in ("auto", "latency", "throughput"),
        "approx_knn_search: unknown mode %r", mode,
    )
    per_query, grouped = entry
    nq = queries.shape[0]

    def _params(fn):
        return inspect.signature(
            inspect.unwrap(getattr(fn, "__wrapped__", fn))
        ).parameters

    # a kwarg NEITHER path accepts is a user error (e.g. a refine_ratio
    # typo) — silently dropping it would hide the mistake; a kwarg only
    # the other mode accepts is legitimately ignored (logged, not fatal)
    known = set(_params(per_query))
    if grouped is not None:
        known |= set(_params(grouped))
    unknown = sorted(set(kw) - known)
    errors.expects(
        not unknown,
        "approx_knn_search: unknown kwarg(s) %s (no search path accepts "
        "them; valid tuning kwargs: %s)",
        ", ".join(unknown), ", ".join(sorted(known - {"index", "queries", "k"})),
    )

    def call(fn):
        # forward only the kwargs the chosen path accepts — auto dispatch
        # must not turn a valid call into a TypeError because the OTHER
        # path's tuning knob was supplied (block_q vs qcap/list_block)
        params = _params(fn)
        dropped = sorted(n for n in kw if n not in params)
        if dropped:
            from raft_tpu.core import logger

            logger.info(
                "approx_knn_search: kwarg(s) %s apply to the other search "
                "mode and were ignored by the selected path",
                ", ".join(dropped),
            )
        return fn(
            index, queries, k, n_probes=n_probes,
            **{n: v for n, v in kw.items() if n in params},
        )

    if mode == "throughput" or (mode == "auto" and nq >= 1024):
        errors.expects(
            grouped is not None or mode == "auto",
            "approx_knn_search: %s has no throughput (grouped) path",
            type(index).__name__,
        )
        if grouped is not None:
            return call(grouped)
    return call(per_query)
