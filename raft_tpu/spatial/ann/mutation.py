"""Online index mutation — upsert / delete / streaming ingest for the
IVF engines (ROADMAP item 2; the reference IVF builders carry per-list
extend paths for exactly this workload — FAISS ``add_core`` under
ann_quantized_faiss.cuh — while every engine shipped here so far served
a frozen checkpoint).

The TPU-native translation keeps the padded-static-shape discipline that
made degraded search and failover free at runtime (docs/robustness.md):

* **Delta segments** — every list owns a ``(cap,)`` padded delta segment
  with STATIC capacity (:class:`DeltaStore`, the same padded-pytree
  discipline as ``sparse/coo.py`` and the list slabs). An upsert is one
  in-graph scatter into the assigned list's segment: no recompile, no
  host-side layout change, visible to the very next search (the delta is
  scanned densely — it is small by construction, and a fresh row is
  therefore visible regardless of the probe map).
* **Tombstone deletion** — a ``(n + 1,)`` runtime row mask folded into
  the grouped scans exactly like ``shard_mask`` (the same trick applied
  to rows): a delete flips one mask entry; the row scores +inf and can
  never surface. Zero retrace on delete (trace-audited).
* **Background compaction** — :func:`compact` merges full deltas and
  tombstones into fresh main slabs (host-side, like every index build),
  optionally refreshing centroids via ``cluster/kmeans.py`` WARM-STARTED
  from the current centroids, with a ``coarse_probe_recall``-style drift
  guardrail (:func:`probe_overlap`). Compaction is the ONE operation
  that may change static shapes — slab heights and ``max_list`` are
  bucketed so steady-state recompaction usually keeps the compiled
  programs — and :class:`BackgroundCompactor` runs it off-thread while
  searches continue on the old state.
* **Incremental checkpointing** — format v4 extends the CRC-manifested
  serialization to the mutation tier: a full v4 checkpoint via
  :func:`raft_tpu.spatial.ann.save_index`, plus
  :func:`save_delta_checkpoint` / :func:`apply_delta_checkpoint` that
  rewrite ONLY dirty lists' delta segments (v3/v2/v1 read-compat and
  the lowest-version writer rule are preserved in serialize.py).

docs/mutation.md states the full lifecycle contract; the sharded
(replica-routed) tier lives in :mod:`raft_tpu.comms.mnmg_mutation`.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import threading
import time
import typing

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import compat, errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.obs import crash as obs_crash
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit, kmeans_predict
from raft_tpu.spatial.ann.common import (
    ListStorage,
    build_list_storage,
    coarse_probe,
    static_qcap,
)
from raft_tpu.spatial.ann.ivf_flat import (
    IVFFlatIndex,
    _grouped_impl,
    _resolve_scan_engine,
)
from raft_tpu.spatial.ann.ivf_pq import (
    IVFPQIndex,
    _encode_block_jit,
    _pq_grouped_impl,
    _resolve_adc_engine,
)
from raft_tpu.spatial.ann.ivf_sq import (
    IVFSQIndex,
    _flat_view,
    _resolve_sq_engine,
)

__all__ = [
    "DeltaStore",
    "MutableIndex",
    "CompactionPolicy",
    "BackgroundCompactor",
    "apply_delta_checkpoint",
    "compact",
    "compaction_stats",
    "delete",
    "delta_checkpoint_watermark",
    "lists_changed_since",
    "mutable_search",
    "mutable_warmup",
    "probe_overlap",
    "save_delta_checkpoint",
    "upsert",
    "wrap_mutable",
]


# mutation-tier telemetry (ISSUE 13, docs/observability.md): host-wall
# durations of the three ops (the upsert/delete stamps INCLUDE their ack
# sync — that is the latency an ingest client sees) plus the delta-fill
# / tombstone-pressure gauges compaction decisions read. Every series
# carries an ``index=<MutableIndex.name>`` label — a process serving
# several mutable indexes must not interleave their pressure gauges —
# and handles are cached per name so the ack path pays one dict get.
# RAFT_TPU_OBS=off no-ops them all.
_mseries_cache: dict = {}
_mseries_lock = lockcheck.make_lock("mutation._mseries_lock")


def _mseries(index_name: str) -> dict:
    s = _mseries_cache.get(index_name)
    if s is not None:
        return s
    reg = obs_metrics.default_registry()
    with _mseries_lock:
        if index_name not in _mseries_cache:
            _mseries_cache[index_name] = {
                "op_ms": {
                    op: reg.histogram("mutation_op_ms",
                                      index=index_name, op=op)
                    for op in ("upsert", "delete", "compact")
                },
                "rows": {
                    key: reg.counter("mutation_rows_total",
                                     index=index_name, op=op, result=res)
                    for key, (op, res) in {
                        "accepted": ("upsert", "accepted"),
                        "rejected": ("upsert", "rejected"),
                        "deleted": ("delete", "found"),
                        "missing": ("delete", "missing"),
                    }.items()
                },
                "compactions": reg.counter("mutation_compactions_total",
                                           index=index_name),
                "journal_compacted": reg.counter(
                    "mutation_journal_compacted_total",
                    index=index_name),
                "fill": reg.gauge("mutation_delta_fill",
                                  index=index_name),
                "max_fill": reg.gauge("mutation_delta_max_fill",
                                      index=index_name),
                "tombstone": reg.gauge("mutation_tombstone_frac",
                                       index=index_name),
            }
        return _mseries_cache[index_name]


@compat.register_dataclass
@dataclasses.dataclass
class DeltaStore:
    """Per-list delta segments with static padded capacity.

    ``counts[l]`` is the number of APPENDED rows in list ``l``'s segment
    (tombstoned delta rows still hold their slot until compaction —
    slots are append-only between compactions so upserts stay one
    in-graph scatter). ``ids`` carries the caller's GLOBAL row ids
    (``-1`` = empty slot); ``live`` drops to 0 when a delta row is
    deleted or superseded by a re-upsert. ``cap`` is the static
    capacity: a full segment REJECTS further upserts (the accepted mask
    reports it) rather than silently dropping or recompiling.
    """

    vecs: jax.Array    # (n_lists, cap, d) f32
    ids: jax.Array     # (n_lists, cap) int32, -1 = empty
    live: jax.Array    # (n_lists, cap) int8
    counts: jax.Array  # (n_lists,) int32
    cap: int = dataclasses.field(metadata=dict(static=True))


@dataclasses.dataclass
class MutableIndex:
    """A frozen IVF index plus its mutation state.

    NOT a pytree — it carries host-side bookkeeping (``dirty_lists`` for
    incremental checkpointing). The jitted mutation ops take the array
    members (``index``/``delta``/``row_mask``/``id_to_pos``) explicitly;
    every op is functional and returns fresh state.

    ``row_mask``: (n + 1,) int8 LIVE mask over main-slab positions (the
    tombstone input of the grouped scans). ``id_to_pos``: (id_span,)
    int32 map from a global row id to its main-slab position (-1 =
    not in the main slab) — what lets upsert/delete tombstone a row's
    previous main copy in-graph.
    """

    index: typing.Union[IVFFlatIndex, IVFPQIndex, IVFSQIndex]
    delta: DeltaStore
    row_mask: jax.Array   # (n + 1,) int8 live mask
    id_to_pos: jax.Array  # (id_span,) int32, -1 = absent

    def __post_init__(self):
        # host-side incremental-checkpoint bookkeeping (lists whose
        # delta segment changed since the last checkpoint write)
        self.dirty_lists: set = set()
        # host-side telemetry label (NOT serialized — a loaded
        # checkpoint re-labels at wrap/load time): the ``index=`` label
        # on every mutation_* series, so several mutable indexes in one
        # process keep distinct pressure gauges (docs/observability.md)
        self.name: str = "mutable"
        # the MUTATION EPOCH (ISSUE 15, docs/serving.md "Hot traffic"):
        # a host-side monotone counter bumped by every APPLIED
        # upsert/delete batch and by compaction — the result-cache
        # invalidation input (raft_tpu.serving.result_cache): entries
        # stamped with an older epoch die on their first post-write
        # lookup. Host state only; never serialized (a loaded
        # checkpoint restarts at 0 with an empty cache beside it).
        self.epoch: int = 0
        # the EPOCH JOURNAL (ISSUE 17, docs/tiering.md "Epoch
        # invalidation"): per-bump ``(epoch, lists|None)`` entries
        # naming which lists' serving state changed (None = everything
        # — compaction rewrote the slab). The tiered store's
        # ``sync_mutations`` reads it through
        # :func:`lists_changed_since`; bounded — entries past the cap
        # fall off and queries below the floor answer None (refresh
        # everything, the safe direction). Host state only.
        self._epoch_journal: list = []
        self._journal_floor: int = 0
        # optional host-side flight recorder (set at wrap/load time
        # like ``name``; never serialized): where journal-compaction
        # events land so a forced full tier refresh is attributable
        self.flight = None

    @property
    def n_lists(self) -> int:
        return self.index.centroids.shape[0]

    @property
    def engine(self) -> str:
        if isinstance(self.index, IVFPQIndex):
            return "pq"
        if isinstance(self.index, IVFSQIndex):
            return "sq"
        return "flat"


def _with(mindex: MutableIndex, **kw) -> MutableIndex:
    """dataclasses.replace that PRESERVES the host-side dirty set,
    telemetry label, and mutation epoch (``__post_init__`` would reset
    them; the mutation ops bump the epoch explicitly AFTER _with)."""
    out = dataclasses.replace(mindex, **kw)
    out.dirty_lists = set(mindex.dirty_lists)
    out.name = mindex.name
    out.epoch = mindex.epoch
    out._epoch_journal = list(mindex._epoch_journal)
    out._journal_floor = mindex._journal_floor
    out.flight = mindex.flight
    return out


_EPOCH_JOURNAL_CAP = 1024


def _journal_note(mindex: MutableIndex, changed) -> None:
    """Append one epoch-journal entry for ``mindex.epoch`` (call AFTER
    the bump). ``changed``: the list ids whose serving state the write
    touched, or None = everything (compaction). Bounded at
    ``_EPOCH_JOURNAL_CAP`` — dropped entries raise the floor, below
    which :func:`lists_changed_since` answers None."""
    j = mindex._epoch_journal
    j.append((mindex.epoch,
              None if changed is None else frozenset(changed)))
    if len(j) > _EPOCH_JOURNAL_CAP:
        drop = len(j) - _EPOCH_JOURNAL_CAP
        mindex._journal_floor = j[drop - 1][0]
        del j[:drop]
        # an overflow silently downgrades every reader below the new
        # floor to "refresh everything" — count it + flight-mark it so
        # a forced full resync is attributable (docs/observability.md)
        _mseries(mindex.name)["journal_compacted"].inc(drop)
        if mindex.flight is not None:
            mindex.flight.record(
                "mutation_journal_compacted", index=mindex.name,
                dropped=drop, floor=mindex._journal_floor,
                epoch=mindex.epoch)


def lists_changed_since(mindex: MutableIndex, epoch: int):
    """The tier-invalidation query (docs/tiering.md): the set of list
    ids whose serving state changed in epochs ``(epoch,
    mindex.epoch]``, or ``None`` when the answer is "assume
    everything" — a compaction sits in the window, or the window
    predates the bounded journal. An up-to-date reader gets an empty
    set. The set may OVER-approximate (a delete of an already-dead id
    can name its list) — safe for invalidation, never under-reports."""
    if epoch >= mindex.epoch:
        return set()
    if epoch < mindex._journal_floor:
        return None
    out: set = set()
    for e, changed in mindex._epoch_journal:
        if e <= epoch:
            continue
        if changed is None:
            return None
        out |= changed
    return out


def _main_slab_lists(mindex: MutableIndex, ids_np: np.ndarray) -> set:
    """Lists owning the MAIN-slab rows of the given ids (host
    searchsorted over the list offsets) — the row_mask tombstone side
    of an epoch-journal entry. Over-approximates: an id whose main row
    was already dead still names its list."""
    span = int(mindex.id_to_pos.shape[0])
    inb = (ids_np >= 0) & (ids_np < span)
    if not inb.any():
        return set()
    pos = np.asarray(mindex.id_to_pos[jnp.asarray(ids_np[inb])])
    pos = pos[pos >= 0]
    if pos.size == 0:
        return set()
    offs = np.asarray(mindex.index.storage.list_offsets)
    lists = np.searchsorted(offs, pos, side="right") - 1
    return set(int(x) for x in lists)


def wrap_mutable(index, *, delta_cap: int = 32,
                 name: str = "mutable") -> MutableIndex:
    """Wrap a frozen :class:`IVFFlatIndex` / :class:`IVFPQIndex` /
    :class:`IVFSQIndex` for online mutation. Host-side (one
    inverse-permutation pass over ``sorted_ids``); the wrapped index's
    arrays are aliased, not copied. SQ delta rows are stored as exact
    f32 until compaction re-quantizes them — a fresh row serves at full
    precision, and only the fold pays the affine rounding.

    ``delta_cap``: static per-list delta capacity. Upserts into a full
    segment are REJECTED (reported via the accepted mask) until
    compaction drains it — size it from the expected ingest rate between
    compactions (docs/mutation.md "Capacity tuning").

    ``name``: the ``index=`` label on this index's ``mutation_*``
    telemetry series (docs/observability.md) — give each mutable index
    in a process its own so their pressure gauges stay distinct. Host
    state only; never serialized."""
    errors.expects(
        isinstance(index, (IVFFlatIndex, IVFPQIndex, IVFSQIndex)),
        "wrap_mutable: expected an IVFFlatIndex, IVFPQIndex, or "
        "IVFSQIndex, got %s",
        type(index).__name__,
    )
    errors.expects(delta_cap >= 1, "delta_cap=%d < 1", delta_cap)
    storage = index.storage
    n = storage.n
    d = index.centroids.shape[1]
    nl = index.centroids.shape[0]
    sids = np.asarray(storage.sorted_ids)
    valid = sids >= 0
    span = int(sids[valid].max()) + 1 if valid.any() else 1
    # the in-graph id→position map is DENSE over [0, max_id]: global ids
    # must stay dense-ish (the builds number 0..n-1 and compaction
    # preserves ids), or the map's memory scales with the largest id,
    # not the row count — fail loudly instead of silently allocating GBs
    errors.expects(
        span <= max(1 << 22, 16 * max(n, 1)),
        "wrap_mutable: max global id %d is far beyond the row count %d "
        "— the in-graph id→pos map is dense over [0, max_id]; use "
        "dense-ish ids (docs/mutation.md)", span - 1, n,
    )
    id_to_pos = np.full(span, -1, np.int32)
    id_to_pos[sids[valid]] = np.nonzero(valid)[0].astype(np.int32)
    delta = DeltaStore(
        vecs=jnp.zeros((nl, delta_cap, d), jnp.float32),
        ids=jnp.full((nl, delta_cap), -1, jnp.int32),
        live=jnp.zeros((nl, delta_cap), jnp.int8),
        counts=jnp.zeros((nl,), jnp.int32),
        cap=int(delta_cap),
    )
    out = MutableIndex(
        index=index,
        delta=delta,
        row_mask=jnp.ones((n + 1,), jnp.int8),
        id_to_pos=jnp.asarray(id_to_pos),
    )
    out.name = str(name)
    return out


# ------------------------------------------------------------- mutation ops
@jax.jit
def _upsert_impl(centroids, delta, row_mask, id_to_pos, vecs, ids):
    """In-graph upsert of a (B, d) batch: assign each row to its nearest
    centroid, decide ACCEPTANCE first, then — for accepted rows only —
    tombstone any previous copy (main slab via ``id_to_pos``, delta via
    an id match) and scatter into the lists' delta segments. A rejected
    row is a strict NO-OP: its previous copy keeps serving (the ack
    contract — False means "compact, then retry", never a lost row).
    Everything is a runtime value — a non-full upsert never recompiles.

    Also returns ``dirty_sup`` (n_lists,) — lists whose EXISTING delta
    copy was superseded — so incremental checkpoints rewrite the old
    copy's list too, not just the new one."""
    f32 = jnp.float32
    B = ids.shape[0]
    n_lists = centroids.shape[0]
    cap = delta.ids.shape[1]
    lbl = kmeans_predict(vecs.astype(f32), centroids).astype(jnp.int32)

    # 1) acceptance: slot = current count + within-batch rank among
    # same-list rows (two-pass stable sort, the
    # invert_probe_map_ranked idiom), capped by the static capacity
    order = jnp.argsort(lbl, stable=True)
    ls = lbl[order]
    starts = jnp.searchsorted(
        ls, jnp.arange(n_lists, dtype=ls.dtype)
    ).astype(jnp.int32)
    within = jnp.zeros((B,), jnp.int32).at[order].set(
        jnp.arange(B, dtype=jnp.int32) - starts[ls]
    )
    slot = delta.counts[lbl] + within
    accepted = (slot < cap) & (ids >= 0)
    ok_ids = jnp.where(accepted, ids, -1)

    # 2) tombstone the previous MAIN copy of each ACCEPTED id
    span = id_to_pos.shape[0]
    inr = (ok_ids >= 0) & (ok_ids < span)
    pos = jnp.where(inr, id_to_pos[jnp.clip(ok_ids, 0, span - 1)], -1)
    tgt_pos = jnp.where(pos >= 0, pos, row_mask.shape[0])    # OOB drops
    row_mask = row_mask.at[tgt_pos].set(0, mode="drop")

    # 3) supersede matching EXISTING delta entries of ACCEPTED ids
    match = (delta.ids[:, :, None] == ok_ids[None, None, :]) & (
        (ok_ids >= 0)[None, None, :]
    )
    superseded = match.any(axis=2)
    dirty_sup = (superseded & (delta.live > 0)).any(axis=1)  # (n_lists,)
    live = jnp.where(superseded, 0, delta.live).astype(delta.live.dtype)

    # 4) append accepted rows
    tgt = jnp.where(accepted, slot, cap)                     # cap drops
    new = DeltaStore(
        vecs=delta.vecs.at[lbl, tgt].set(
            vecs.astype(delta.vecs.dtype), mode="drop"
        ),
        ids=delta.ids.at[lbl, tgt].set(ids, mode="drop"),
        live=live.at[lbl, tgt].set(1, mode="drop"),
        counts=delta.counts.at[lbl].add(accepted.astype(jnp.int32)),
        cap=delta.cap,
    )
    return new, row_mask, accepted, lbl, dirty_sup


@jax.jit
def _delete_impl(delta, row_mask, id_to_pos, ids):
    """In-graph tombstone delete of a (B,) id batch: flip the main-slab
    mask entry and kill matching live delta entries. Returns the new
    state plus ``found`` (the id existed live somewhere) and a per-list
    dirty flag for incremental checkpointing."""
    span = id_to_pos.shape[0]
    inr = (ids >= 0) & (ids < span)
    pos = jnp.where(inr, id_to_pos[jnp.clip(ids, 0, span - 1)], -1)
    safe = jnp.clip(pos, 0, row_mask.shape[0] - 1)
    main_found = (pos >= 0) & (row_mask[safe] > 0)
    tgt = jnp.where(pos >= 0, pos, row_mask.shape[0])        # OOB drops
    row_mask = row_mask.at[tgt].set(0, mode="drop")

    match = (delta.ids[:, :, None] == ids[None, None, :]) & (
        (ids >= 0)[None, None, :]
    )
    m_live = match & (delta.live > 0)[:, :, None]
    delta_found = m_live.any(axis=(0, 1))                    # (B,)
    dirty = m_live.any(axis=(1, 2))                          # (n_lists,)
    live = jnp.where(m_live.any(axis=2), 0, delta.live).astype(
        delta.live.dtype
    )
    return (
        dataclasses.replace(delta, live=live),
        row_mask,
        main_found | delta_found,
        dirty,
    )


def upsert(mindex: MutableIndex, vectors, ids):
    """Upsert a batch of rows. Returns ``(new_mindex, accepted)`` where
    ``accepted`` is a host (B,) bool array — True is the ACK: the row is
    durably in its list's delta segment and visible to the next search.
    False means the assigned list's segment is full (compact, then
    retry) or the id was negative — and the rejection is a strict
    NO-OP: the id's previous copy (main slab or delta) keeps serving.

    A row whose id already exists (main slab or delta) supersedes the
    old copy — the previous version is tombstoned in the same dispatch.
    Ids must be unique WITHIN one batch (duplicates both land and the
    search may surface either; split such batches). The ack requires one
    small host sync per batch — batch upserts accordingly."""
    vecs = jnp.asarray(vectors)
    idarr = jnp.asarray(ids, jnp.int32)
    errors.check_matrix(vecs, "vectors")
    errors.check_same_cols(vecs, mindex.index.centroids, "vectors", "index")
    errors.expects(
        idarr.shape == (vecs.shape[0],),
        "ids: expected shape (%d,), got %s", vecs.shape[0],
        tuple(idarr.shape),
    )
    t0 = time.perf_counter()
    delta, row_mask, accepted, lbl, dirty_sup = _upsert_impl(
        mindex.index.centroids, mindex.delta, mindex.row_mask,
        mindex.id_to_pos, vecs, idarr,
    )
    accepted_np = np.asarray(accepted)
    ms = _mseries(mindex.name)
    ms["op_ms"]["upsert"].observe((time.perf_counter() - t0) * 1e3)
    n_acc = int(accepted_np.sum())
    ms["rows"]["accepted"].inc(n_acc)
    ms["rows"]["rejected"].inc(int(accepted_np.size) - n_acc)
    out = _with(mindex, delta=delta, row_mask=row_mask)
    out.dirty_lists.update(np.asarray(lbl)[accepted_np].tolist())
    # a superseded delta copy dirties ITS list too — an incremental
    # checkpoint that misses it would resurrect the stale copy on replay
    out.dirty_lists.update(np.nonzero(np.asarray(dirty_sup))[0].tolist())
    if n_acc:
        # an APPLIED write bumps the mutation epoch — pre-write cached
        # results must go stale (docs/serving.md "Hot traffic"); an
        # all-rejected batch changed nothing and keeps the cache warm
        out.epoch = mindex.epoch + 1
        changed = set(np.asarray(lbl)[accepted_np].tolist())
        changed |= set(
            np.nonzero(np.asarray(dirty_sup))[0].tolist())
        # a superseded MAIN copy flips a tombstone in its list's slab
        # range — the tier journal must name that list too
        changed |= _main_slab_lists(
            mindex, np.asarray(idarr)[accepted_np])
        _journal_note(out, changed)
    return out, accepted_np


def delete(mindex: MutableIndex, ids):
    """Tombstone-delete a batch of ids. Returns ``(new_mindex, found)``;
    ``found[i]`` is True when the id existed live (main slab or delta).
    One runtime mask flip — never a recompile."""
    idarr = jnp.asarray(ids, jnp.int32)
    errors.expects(
        idarr.ndim == 1, "ids: expected a 1-d batch, got shape %s",
        tuple(idarr.shape),
    )
    t0 = time.perf_counter()
    delta, row_mask, found, dirty = _delete_impl(
        mindex.delta, mindex.row_mask, mindex.id_to_pos, idarr
    )
    out = _with(mindex, delta=delta, row_mask=row_mask)
    out.dirty_lists.update(np.nonzero(np.asarray(dirty))[0].tolist())
    found_np = np.asarray(found)
    if bool(found_np.any()):
        # a delete that actually removed a live row invalidates cached
        # results, exactly like an applied upsert
        out.epoch = mindex.epoch + 1
        changed = set(np.nonzero(np.asarray(dirty))[0].tolist())
        # a main-slab hit flips row_mask inside its list's slab range;
        # the journal must name that list for tier invalidation
        changed |= _main_slab_lists(mindex, np.asarray(idarr))
        _journal_note(out, changed)
    ms = _mseries(mindex.name)
    ms["op_ms"]["delete"].observe((time.perf_counter() - t0) * 1e3)
    n_found = int(found_np.sum())
    ms["rows"]["deleted"].inc(n_found)
    ms["rows"]["missing"].inc(int(found_np.size) - n_found)
    return out, found_np


# --------------------------------------------------------------- search
def delta_merge_topk(qf, vals, ids, dvec, dids, valid, k):
    """The shared exact-delta-scan + fold tail of EVERY mutable search
    (single-chip ``_mut_search_impl`` and the sharded engines'
    ``_merge_local_delta``): score the flattened (DL, d) delta rows
    exactly (HIGHEST precision — delta distances merge against the
    engines' exact/refined distances), mask by ``valid``, and fold the
    top-k into the caller's (nq, k) candidates. One implementation so
    the two tiers can never drift."""
    f32 = jnp.float32
    dv = dvec.astype(f32)
    qn = jnp.sum(qf * qf, axis=1)
    vn = jnp.sum(dv * dv, axis=1)
    dots = jax.lax.dot_general(
        qf, dv, (((1,), (1,)), ((), ())), preferred_element_type=f32,
        precision=jax.lax.Precision.HIGHEST,
    )
    d2 = jnp.where(
        valid[None, :], qn[:, None] + vn[None, :] - 2.0 * dots, jnp.inf
    )
    kd = min(k, dids.shape[0])
    nv, dp = jax.lax.top_k(-d2, kd)
    dvals = -nv
    dsel = jnp.where(jnp.isfinite(dvals), dids[dp], -1)
    fv, fp = jax.lax.top_k(-jnp.concatenate([vals, dvals], axis=1), k)
    fi = jnp.take_along_axis(
        jnp.concatenate([ids, dsel], axis=1), fp, axis=1
    )
    return -fv, fi


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "n_probes", "qcap", "list_block", "engine", "refine_ratio",
        "exact_selection", "approx_recall_target", "use_pallas",
        "pallas_interpret",
    ),
)
def _mut_search_impl(index, delta, row_mask, q, k, n_probes, qcap,
                     list_block, engine, refine_ratio, exact_selection,
                     approx_recall_target, use_pallas, pallas_interpret):
    f32 = jnp.float32
    qf = q.astype(f32)
    if engine == "flat":
        # the kernel path masks tombstones at its exact rerank tail
        # (the in-kernel sub-chunk minima are unmasked — same contract
        # as the PQ branch below; docs/mutation.md)
        mv, mi = _grouped_impl(
            index, qf, k, n_probes, qcap, list_block, row_mask=row_mask,
            use_pallas=use_pallas, pallas_interpret=pallas_interpret,
        )
    elif engine == "sq":
        # the SQ mode of the one grouped scan body: same tombstone
        # contract as the flat branch (kernel path masks per ROW at the
        # exact rerank tail, which also dequantizes through the affine
        # map — a dead row can crowd a pool slot, never surface)
        mv, mi = _grouped_impl(
            _flat_view(index), qf, k, n_probes, qcap, list_block,
            row_mask=row_mask, use_pallas=use_pallas,
            pallas_interpret=pallas_interpret,
            dequant=(index.vmin.astype(f32), index.vscale.astype(f32)),
        )
    else:
        mv, mi = _pq_grouped_impl(
            index, qf, k, n_probes, qcap, list_block, refine_ratio,
            None, None, exact_selection, approx_recall_target, None,
            use_pallas, pallas_interpret, row_mask=row_mask,
        )
    # dense exact scan of the delta segments: the delta is small by
    # construction (compaction drains it), and a dense scan makes every
    # fresh row visible regardless of the probe map — no delta probe
    # misses during ingest
    nl, cap, d = delta.vecs.shape
    dids = delta.ids.reshape(nl * cap)
    valid = (dids >= 0) & (delta.live.reshape(nl * cap) > 0)
    return delta_merge_topk(
        qf, mv, mi, delta.vecs.reshape(nl * cap, d), dids, valid, k
    )


def mutable_search(
    mindex: MutableIndex, queries, k: int, *, n_probes: int = 8,
    qcap: typing.Union[int, str, None] = None,
    list_block: typing.Optional[int] = None,
    refine_ratio: float = 2.0, exact_selection: bool = False,
    approx_recall_target: float = 0.95,
    use_pallas: typing.Optional[bool] = None,
):
    """Grouped search over a mutable index: the frozen engine's scan with
    the tombstone mask folded in, merged with a dense exact scan of the
    delta segments. Same return convention as the engine's own grouped
    search (IVF-Flat applies sqrt for ``metric='l2'``; IVF-PQ returns
    squared distances, exact when refinement is active).

    Upserts, deletes, and this search share ONE compiled program per
    static config: every mutation is a runtime value, so the
    upsert→search→delete cycle never recompiles (trace-audited in
    tests/test_mutation.py). ``qcap`` resolves SHAPE-ONLY
    (:func:`...common.static_qcap`) — the mutation tier is a serving
    workload, and the data-dependent auto path would host-sync per
    dispatch. ``use_pallas`` selects the frozen scan's engine for ALL
    THREE index kinds (the PQ ADC kernel / the flat sub-chunk-min
    kernel / the int8 SQ dequant+scan kernel); every kernel path
    applies the tombstone ``row_mask`` at its exact rerank tail — a
    dead row can crowd a pool slot, never surface. SQ returns squared
    distances over the dequantized vectors, like its grouped search."""
    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, mindex.index.centroids, "queries", "index")
    index = mindex.index
    engine = mindex.engine
    storage = index.storage
    errors.expects(
        k <= n_probes * storage.max_list,
        "k=%d exceeds the candidate pool (n_probes*max_list=%d)",
        k, n_probes * storage.max_list,
    )
    nl = index.centroids.shape[0]
    qc = static_qcap(qcap, q.shape[0], n_probes, nl)
    lb = list_block if list_block is not None else (8 if engine == "pq"
                                                   else 32)
    lb = max(1, min(lb, nl))
    if engine == "pq":
        refine_active = (
            index.vectors_sorted is not None and refine_ratio > 1.0
        )
        up = _resolve_adc_engine(
            use_pallas, refine_active, index.pq_dim, index.pq_bits, qc
        )
        vals, ids = _mut_search_impl(
            index, mindex.delta, mindex.row_mask, q, k, n_probes, qc, lb,
            "pq", refine_ratio, exact_selection, approx_recall_target,
            up, jax.default_backend() != "tpu",
        )
        return vals, ids
    if engine == "sq":
        up = _resolve_sq_engine(use_pallas, index.centroids.shape[1], qc)
        return _mut_search_impl(
            index, mindex.delta, mindex.row_mask, q, k, n_probes, qc, lb,
            "sq", refine_ratio, exact_selection, approx_recall_target,
            up, jax.default_backend() != "tpu",
        )
    up = _resolve_scan_engine(use_pallas, index.centroids.shape[1], qc)
    vals, ids = _mut_search_impl(
        index, mindex.delta, mindex.row_mask, q, k, n_probes, qc, lb,
        "flat", refine_ratio, exact_selection, approx_recall_target,
        up, jax.default_backend() != "tpu",
    )
    if index.metric == "l2":
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    return vals, ids


def mutable_warmup(mindex: MutableIndex, nq: int, *, k: int = 10,
                   n_probes: int = 8, qcap=None,
                   ingest_batch: int = 0, **search_kw) -> int:
    """Pre-compile the mutable serving programs for (nq, d) batches —
    the mutation sibling of ``index.warmup(nq)``: one all-zeros search
    batch plus (when ``ingest_batch`` > 0) one all-rejected upsert and
    one no-op delete of that batch size, so the first real mixed
    read/write traffic pays dispatch, not trace+compile. Returns the
    shape-only-resolved qcap to pass on every serving dispatch."""
    d = mindex.index.centroids.shape[1]
    qc = static_qcap(qcap, nq, n_probes, mindex.n_lists)
    out = mutable_search(
        mindex, jnp.zeros((nq, d), jnp.float32), k, n_probes=n_probes,
        qcap=qc, **search_kw,
    )
    jax.block_until_ready(out)
    if ingest_batch > 0:
        # ids = -1: the dispatch runs the full program but accepts (and
        # mutates) nothing — warm-up must not consume delta slots
        z = jnp.zeros((ingest_batch, d), jnp.float32)
        neg = jnp.full((ingest_batch,), -1, jnp.int32)
        jax.block_until_ready(_upsert_impl(
            mindex.index.centroids, mindex.delta, mindex.row_mask,
            mindex.id_to_pos, z, neg,
        ))
        jax.block_until_ready(_delete_impl(
            mindex.delta, mindex.row_mask, mindex.id_to_pos, neg
        ))
    return qc


# ----------------------------------------------------------- compaction
def compaction_stats(mindex: MutableIndex) -> dict:
    """Host-side mutation-pressure stats (syncs the SMALL bookkeeping
    arrays only): delta fill fractions, live delta rows, and the
    tombstoned fraction of the main slab."""
    delta = mindex.delta
    counts = np.asarray(delta.counts)
    live = (np.asarray(delta.live) > 0) & (np.asarray(delta.ids) >= 0)
    sids = np.asarray(mindex.index.storage.sorted_ids)
    real = sids >= 0
    n_real = max(int(real.sum()), 1)
    rm = np.asarray(mindex.row_mask)[: sids.shape[0]] > 0
    dead = int((real & ~rm).sum())
    out = {
        "delta_fill": float(counts.sum() / max(counts.size * delta.cap, 1)),
        "delta_max_fill": float(counts.max() / delta.cap)
        if counts.size else 0.0,
        "delta_live_rows": int(live.sum()),
        "tombstone_frac": dead / n_real,
        "main_rows": n_real,
    }
    # the mutation-pressure gauges: every reader of these stats (the
    # BackgroundCompactor cycle, an operator poll) refreshes the live
    # values an alert can watch between compactions
    ms = _mseries(mindex.name)
    ms["fill"].set(out["delta_fill"])
    ms["max_fill"].set(out["delta_max_fill"])
    ms["tombstone"].set(out["tombstone_frac"])
    return out


@dataclasses.dataclass(frozen=True)
class CompactionPolicy:
    """When the background compactor should fold the mutation state back
    into the main slabs: any list's delta segment past ``max_fill_frac``
    of its capacity (the next upserts into it would be rejected), or the
    tombstoned fraction past ``max_tombstone_frac`` (dead rows tax every
    padded scan). ``refresh_every``: run the warm-started centroid
    refresh on every N-th compaction (0 = never)."""

    max_fill_frac: float = 0.5
    max_tombstone_frac: float = 0.25
    refresh_every: int = 4

    def should_compact(self, stats: dict) -> bool:
        return (
            stats["delta_max_fill"] >= self.max_fill_frac
            or stats["tombstone_frac"] >= self.max_tombstone_frac
        )


def probe_overlap(old_centroids, new_centroids, queries,
                  n_probes: int = 8) -> float:
    """The centroid-refresh drift guardrail (the
    ``coarse_probe_recall`` idiom applied across a refresh): mean
    per-query fraction of probed CENTROID POSITIONS shared by the old
    and refreshed centroid sets on ``queries``. Warm-started refreshes
    move centroids gently, so positions keep their identity; a low
    overlap means the refresh redistributed lists enough that recall
    should be re-measured before serving resumes (eager, host sync — an
    audit, not a serving-path call)."""
    qf = jnp.asarray(queries, jnp.float32)
    a, _ = coarse_probe(qf, jnp.asarray(old_centroids, jnp.float32),
                        n_probes)
    b, _ = coarse_probe(qf, jnp.asarray(new_centroids, jnp.float32),
                        n_probes)
    a, b = np.asarray(a), np.asarray(b)
    hits = sum(
        len(set(x.tolist()) & set(y.tolist())) for x, y in zip(a, b)
    )
    return hits / a.size


def _padded_storage(labels_np, gids, n_lists, list_bucket, row_bucket):
    """Build a ListStorage whose statics are BUCKETED: ``max_list`` and
    the slab height round up to coarse multiples so a steady-state
    compact→ingest→compact cycle usually re-lands on the same statics
    and reuses every compiled program (the ``_slab_height`` idiom from
    the sharded builds). Returns (storage, order, n_real)."""
    base = build_list_storage(labels_np, n_lists)
    n_real = labels_np.shape[0]
    ml = -(-max(int(base.max_list), 1) // list_bucket) * list_bucket
    nb = -(-max(n_real, 1) // row_bucket) * row_bucket
    # ml <= nb always: both round up, ml from a count <= n_real and
    # row_bucket is a multiple of list_bucket
    sizes = np.asarray(base.list_sizes)
    offsets = np.asarray(base.list_offsets)
    list_index = np.full((n_lists, ml), nb, np.int32)
    for l in range(n_lists):
        c = int(sizes[l])
        list_index[l, :c] = np.arange(offsets[l], offsets[l] + c)
    order = np.asarray(base.sorted_ids)              # positions into input
    sorted_gids = np.concatenate(
        [gids[order], np.full(nb - n_real, -1, np.int32)]
    )
    storage = ListStorage(
        sorted_ids=jnp.asarray(sorted_gids),
        list_offsets=jnp.asarray(offsets),
        list_index=jnp.asarray(list_index),
        list_sizes=jnp.asarray(sizes),
        n=int(nb),
        max_list=int(ml),
    )
    return storage, order, n_real


def compact(
    mindex: MutableIndex, *, refresh_centroids: bool = False,
    kmeans_n_iters: int = 4, drift_queries=None, n_probes: int = 8,
    min_probe_overlap: float = 0.5, list_bucket: int = 64,
    row_bucket: int = 256,
):
    """Merge delta segments and drop tombstoned rows into fresh main
    slabs (host-side, like every index build). Returns
    ``(new_mindex, stats)`` — the new state has empty deltas, an
    all-live mask, and every surviving row under its (possibly
    refreshed) list with its GLOBAL id preserved.

    ``refresh_centroids=True`` re-fits the coarse quantizer WARM-STARTED
    from the current centroids (``kmeans_fit(..., centroids=old)``) so
    drifted ingest re-balances lists without a from-scratch retrain;
    when ``drift_queries`` is given, the :func:`probe_overlap` drift
    guardrail ASSERTS the refreshed probe map overlaps the old one by at
    least ``min_probe_overlap`` (raise the refresh cadence — or lower
    ``kmeans_n_iters`` — when it trips). PQ codebooks are kept; survivor
    rows are re-encoded against them (requires ``store_raw``).

    Compaction is the one mutation-tier operation allowed to change
    static shapes; ``list_bucket``/``row_bucket`` coarsen ``max_list``
    and the slab height so steady-state cycles usually keep the compiled
    programs (re-run :func:`mutable_warmup` before swapping the state in
    when they do change — :class:`BackgroundCompactor` leaves the old
    state serving until then)."""
    t_compact0 = time.perf_counter()
    index = mindex.index
    engine = mindex.engine
    storage = index.storage
    d = index.centroids.shape[1]
    sids = np.asarray(storage.sorted_ids)
    rm = np.asarray(mindex.row_mask)[: sids.shape[0]] > 0
    keep = np.nonzero(rm & (sids >= 0))[0]
    if engine == "flat":
        base_rows = np.asarray(index.data_sorted)[keep]
    elif engine == "sq":
        # survivors keep their stored codes VERBATIM through the fold
        # (stashed here, re-permuted below — decode->re-encode would
        # drift a code unit when |vmin| dwarfs the dimension's range);
        # the dequantized rows are needed only for (re)assignment
        from raft_tpu.spatial.ann.ivf_sq import sq_decode

        codes_keep = np.asarray(index.codes_sorted)[keep]
        base_rows = np.asarray(sq_decode(
            codes_keep.astype(np.float32), index.vmin, index.vscale,
        ))
    else:
        errors.expects(
            index.vectors_sorted is not None,
            "compact: a codes-only IVF-PQ index cannot be compacted — "
            "survivor rows must be re-encoded from raw vectors "
            "(build with store_raw=True)",
        )
        base_rows = np.asarray(index.vectors_sorted)[keep]
    ids_main = sids[keep]
    dlive = (np.asarray(mindex.delta.live) > 0) & (
        np.asarray(mindex.delta.ids) >= 0
    )
    dvecs = np.asarray(mindex.delta.vecs)[dlive]
    ids_delta = np.asarray(mindex.delta.ids)[dlive]
    x = np.concatenate(
        [base_rows.astype(np.float32), dvecs.astype(np.float32)]
    )
    gids = np.concatenate([ids_main, ids_delta]).astype(np.int32)
    errors.expects(
        x.shape[0] >= 1,
        "compact: no rows survive (everything tombstoned) — an empty "
        "index cannot be compacted; rebuild instead",
    )
    cents_old = np.asarray(index.centroids, np.float32)
    stats = dict(compaction_stats(mindex))
    stats["survivors"] = int(x.shape[0])
    if refresh_centroids:
        out = kmeans_fit(
            jnp.asarray(x),
            KMeansParams(
                n_clusters=cents_old.shape[0], max_iter=kmeans_n_iters,
                init="random", compute_dtype="bfloat16",
            ),
            centroids=cents_old,                     # warm start
        )
        cents_new = np.asarray(out.centroids, np.float32)
        stats["refreshed"] = True
        if drift_queries is not None:
            ov = probe_overlap(cents_old, cents_new, drift_queries,
                               n_probes)
            stats["probe_overlap"] = ov
            errors.expects(
                ov >= min_probe_overlap,
                "compact: centroid refresh drifted the probe map — "
                "probe_overlap %.3f < min_probe_overlap %.3f; refresh "
                "more often (smaller drift per refresh) or re-measure "
                "recall before serving", ov, min_probe_overlap,
            )
    else:
        cents_new = cents_old
        stats["refreshed"] = False

    nl = cents_new.shape[0]
    xj = jnp.asarray(x)
    cj = jnp.asarray(cents_new)
    if engine == "pq":
        M = index.pq_dim
        ds = d // M
        lbl, codes = _encode_block_jit(xj, cj, index.codebooks, M, ds)
        labels_np = np.asarray(lbl)
        codes_np = np.asarray(codes)
    else:
        labels_np = np.asarray(kmeans_predict(xj, cj))
    st, order, n_real = _padded_storage(
        labels_np, gids, nl, list_bucket, row_bucket
    )
    nb = st.n
    pad = nb - n_real
    rows_sorted = np.concatenate(
        [x[order], np.zeros((pad + 1, d), np.float32)]
    )
    if engine == "flat":
        new_index = IVFFlatIndex(
            centroids=jnp.asarray(cents_new),
            data_sorted=jnp.asarray(rows_sorted.astype(
                np.asarray(index.data_sorted).dtype
            )),
            storage=st,
            metric=index.metric,
        )
    elif engine == "sq":
        # survivors carry their stored codes verbatim; ONLY the delta
        # rows pay the quantization step they deferred at ingest,
        # against the KEPT stats through THE shared encoder (the
        # PQ-codebook rule applied to the affine map: compaction never
        # retrains the quantizer, only the coarse centroids may refresh)
        from raft_tpu.spatial.ann.ivf_sq import sq_encode

        codes_all = np.concatenate([
            codes_keep,
            np.asarray(sq_encode(dvecs, index.vmin, index.vscale)),
        ])                                       # aligned with x's rows
        codes_new = np.concatenate([
            codes_all[order],
            np.zeros((pad + 1, d), np.int8),     # pad + sentinel rows
        ])
        new_index = IVFSQIndex(
            centroids=jnp.asarray(cents_new),
            codes_sorted=jnp.asarray(codes_new),
            vmin=index.vmin,
            vscale=index.vscale,
            storage=st,
        )
    else:
        codes_sorted = np.concatenate(
            [codes_np[order],
             np.zeros((pad + 1, index.pq_dim), np.uint8)]
        )
        new_index = IVFPQIndex(
            centroids=jnp.asarray(cents_new),
            codebooks=index.codebooks,
            codes_sorted=jnp.asarray(codes_sorted),
            storage=st,
            vectors_sorted=jnp.asarray(rows_sorted.astype(
                np.asarray(index.vectors_sorted).dtype
            )),
            pq_dim=index.pq_dim,
            pq_bits=index.pq_bits,
        )
    out = wrap_mutable(new_index, delta_cap=mindex.delta.cap,
                       name=mindex.name)
    out.dirty_lists = set(range(nl))   # every list changed on disk
    # compaction continues (and bumps) the epoch chain: the fold can
    # re-encode rows and refresh centroids, so pre-compaction cached
    # results must die exactly like post-upsert ones — and the counter
    # must not RESET (wrap_mutable starts at 0; a reset would mark old
    # cache entries fresh again)
    out.epoch = mindex.epoch + 1
    # compaction re-sorts every slab: a None journal entry tells
    # :func:`lists_changed_since` "everything" — tier consumers must do
    # a full host refresh, not a per-list invalidation
    _journal_note(out, None)
    stats["max_list"] = st.max_list
    stats["n_slab"] = nb
    ms = _mseries(mindex.name)
    ms["op_ms"]["compact"].observe(
        (time.perf_counter() - t_compact0) * 1e3)
    ms["compactions"].inc()
    return out, stats


class BackgroundCompactor:
    """Runs :func:`compact` off-thread while the CALLER keeps serving
    searches on the old state (state is functional — readers never see a
    half-compacted index).

    Swap protocol (docs/mutation.md "Lifecycle"): ``maybe_submit`` a
    SNAPSHOT of the current state; keep serving and BUFFER subsequent
    writes (or re-apply them after the swap — upsert/delete are
    idempotent by id); when ``poll`` returns the compacted state, warm
    it (:func:`mutable_warmup` — compaction may have re-bucketed the
    statics) and swap it in. One compaction in flight at a time."""

    def __init__(self, policy: CompactionPolicy = CompactionPolicy(),
                 **compact_kw):
        self.policy = policy
        self._kw = compact_kw
        self._lock = lockcheck.make_lock("BackgroundCompactor._lock")
        self._thread: typing.Optional[threading.Thread] = None
        self._result = None
        self._error: typing.Optional[BaseException] = None
        self._n_compactions = 0

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    def submit(self, mindex: MutableIndex) -> bool:
        """Start a compaction of ``mindex`` (a snapshot); False when one
        is already in flight or an unpolled result is pending."""
        with self._lock:
            if (self._thread is not None and self._thread.is_alive()) or \
                    self._result is not None or self._error is not None:
                return False
            kw = dict(self._kw)
            if self.policy.refresh_every:
                due = (self._n_compactions + 1) % self.policy.refresh_every
                kw.setdefault("refresh_centroids", due == 0)

            def work():
                try:
                    res = compact(mindex, **kw)
                except BaseException as e:  # noqa: BLE001 — surfaced on poll
                    with self._lock:
                        self._error = e
                    return
                with self._lock:
                    self._result = res
                    self._n_compactions += 1

            obs_crash.install_excepthook()
            self._thread = threading.Thread(
                target=work, daemon=True, name="ann-compactor")
            self._thread.start()
            return True

    def maybe_submit(self, mindex: MutableIndex) -> bool:
        """Submit iff the policy says the state needs compaction."""
        if self.busy:
            return False
        if not self.policy.should_compact(compaction_stats(mindex)):
            return False
        return self.submit(mindex)

    def poll(self):
        """``(new_mindex, stats)`` when a compaction finished, else
        None. Re-raises a failed compaction's error."""
        with self._lock:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            if self._result is None:
                return None
            res, self._result = self._result, None
            return res

    def join(self, timeout: typing.Optional[float] = None) -> None:
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout)

    def stop(self, timeout_s: float = 30.0) -> None:
        """Join the in-flight compaction (bounded) and RE-RAISE a
        stored worker exception instead of dropping it — the shutdown
        analog of :meth:`poll`: a compaction that crashed after the
        caller stopped polling must not vanish with the process.
        Raises ``TimeoutError`` if the worker outlives ``timeout_s``
        (the thread ref is read under the lock; the join itself blocks
        without it)."""
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout_s)
            if t.is_alive():
                raise TimeoutError(
                    f"BackgroundCompactor: worker still running after "
                    f"{timeout_s:.1f}s")
        with self._lock:
            self._thread = None
            if self._error is not None:
                err, self._error = self._error, None
                raise err


# ------------------------------------------- incremental checkpoint (v4)
_DELTA_KIND = "mutation-delta"
_DELTA_VERSION = 4


def save_delta_checkpoint(mindex: MutableIndex, path,
                          *, lists=None, wal_lsn=None) -> list:
    """Write an INCREMENTAL v4 checkpoint: only dirty lists' delta
    segments (``lists`` overrides the tracked dirty set), plus the small
    full ``row_mask``/``counts`` arrays, each CRC32-manifested like the
    main serialization (docs/mutation.md "Checkpoint v4"). Pair with a
    full base checkpoint (:func:`raft_tpu.spatial.ann.save_index`, which
    stamps v4 for mutable payloads); replay newest-last with
    :func:`apply_delta_checkpoint`, which is idempotent — a duplicated
    flush re-applies to the same state. Clears the dirty set; returns
    the list ids written.

    ``wal_lsn`` (optional) stamps the durable-ingest watermark into the
    header: the checkpoint captures every WAL record up to and
    including that LSN, so recovery replays only the tail past it and
    :meth:`raft_tpu.durability.wal.WalWriter.prune` may retire
    segments behind it (docs/robustness.md "Durability"). Readable
    back via :func:`delta_checkpoint_watermark`; absent on
    non-durable-path checkpoints (older files load unchanged)."""
    from raft_tpu.spatial.ann.serialize import _array_crc

    ls = sorted(set(mindex.dirty_lists if lists is None else lists))
    delta = mindex.delta
    arrays = {
        "row_mask": np.asarray(mindex.row_mask),
        "counts": np.asarray(delta.counts),
    }
    dv = np.asarray(delta.vecs)
    di = np.asarray(delta.ids)
    dl = np.asarray(delta.live)
    for l in ls:
        errors.expects(
            0 <= l < di.shape[0],
            "save_delta_checkpoint: list %d out of range [0, %d)",
            l, di.shape[0],
        )
        arrays[f"list.{l}.vecs"] = dv[l]
        arrays[f"list.{l}.ids"] = di[l]
        arrays[f"list.{l}.live"] = dl[l]
    header = {
        "kind": _DELTA_KIND,
        "version": _DELTA_VERSION,
        "n_lists": int(di.shape[0]),
        "cap": int(delta.cap),
        "lists": [int(l) for l in ls],
        **({} if wal_lsn is None else {"wal_lsn": int(wal_lsn)}),
        "integrity": {
            key: {
                "crc32": _array_crc(arr),
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            }
            for key, arr in arrays.items()
        },
    }
    with open(path, "wb") as f:
        np.savez(
            f,
            __header__=np.frombuffer(
                json.dumps(header).encode("utf-8"), dtype=np.uint8
            ),
            **arrays,
        )
    mindex.dirty_lists.clear()
    return ls


def delta_checkpoint_watermark(path):
    """Read a delta checkpoint's ``wal_lsn`` watermark (the highest WAL
    LSN the checkpoint captures) without loading its arrays — what
    recovery uses to start the tail replay. ``None`` when the file
    predates the durability tier or was written without a WAL."""
    try:
        with np.load(path) as npz:
            header = json.loads(bytes(npz["__header__"]).decode("utf-8"))
    except Exception as e:
        raise errors.CorruptIndexError(
            f"delta_checkpoint_watermark: header unreadable ({e})",
            field="__header__",
        ) from e
    lsn = header.get("wal_lsn")
    return None if lsn is None else int(lsn)


def apply_delta_checkpoint(mindex: MutableIndex, path) -> MutableIndex:
    """Splice a :func:`save_delta_checkpoint` file into ``mindex``
    (idempotent — set semantics per list, so a duplicated flush is
    harmless). Damage — a torn write, a duplicated/stale block beneath
    the container checksums, a future format version — raises
    :class:`raft_tpu.errors.CorruptIndexError` naming the field, exactly
    like the main ``load_index`` path; recovery then falls back to the
    base checkpoint + a replica resync (docs/mutation.md)."""
    from raft_tpu.spatial.ann.serialize import _VerifiedArchive

    try:
        npz_file = np.load(path)
    except Exception as e:
        raise errors.CorruptIndexError(
            f"apply_delta_checkpoint: archive unreadable ({e}) — torn "
            "write or not a delta checkpoint", field="__header__"
        ) from e
    with npz_file as npz:
        try:
            header = json.loads(bytes(npz["__header__"]).decode("utf-8"))
        except Exception as e:
            raise errors.CorruptIndexError(
                f"apply_delta_checkpoint: header unreadable ({e})",
                field="__header__",
            ) from e
        if header.get("kind") != _DELTA_KIND:
            raise errors.CorruptIndexError(
                f"apply_delta_checkpoint: kind {header.get('kind')!r} is "
                f"not {_DELTA_KIND!r}", field="__header__",
            )
        v = header.get("version")
        if v != _DELTA_VERSION:
            raise errors.CorruptIndexError(
                f"apply_delta_checkpoint: format version {v!r} is not "
                f"readable by this release (expected {_DELTA_VERSION}); "
                "upgrade before restoring", field="__header__",
            )
        delta = mindex.delta
        nl = delta.ids.shape[0]
        if header.get("n_lists") != nl or header.get("cap") != delta.cap:
            raise errors.CorruptIndexError(
                "apply_delta_checkpoint: geometry mismatch (checkpoint "
                f"n_lists={header.get('n_lists')} cap={header.get('cap')}"
                f", index n_lists={nl} cap={delta.cap})",
                field="__header__",
            )
        archive = _VerifiedArchive(npz, header.get("integrity"))
        row_mask = jnp.asarray(archive["row_mask"])
        if row_mask.shape != mindex.row_mask.shape:
            raise errors.CorruptIndexError(
                f"apply_delta_checkpoint: row_mask shape "
                f"{tuple(row_mask.shape)} != index "
                f"{tuple(mindex.row_mask.shape)}", field="row_mask",
            )
        counts = jnp.asarray(archive["counts"])
        dv, di, dl = delta.vecs, delta.ids, delta.live
        for l in header.get("lists", []):
            dv = dv.at[l].set(jnp.asarray(archive[f"list.{l}.vecs"]))
            di = di.at[l].set(jnp.asarray(archive[f"list.{l}.ids"]))
            dl = dl.at[l].set(jnp.asarray(archive[f"list.{l}.live"]))
        new_delta = DeltaStore(
            vecs=dv, ids=di, live=dl, counts=counts, cap=delta.cap
        )
    return _with(mindex, delta=new_delta, row_mask=row_mask)
