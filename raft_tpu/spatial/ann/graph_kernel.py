"""Pallas distance engine for the graph-ANN beam search (ISSUE 19) — a
thin instantiation of the shared scan-kernel core
(:mod:`raft_tpu.spatial.ann.scan_core`), exactly like ``flat_kernel``:
the tile planner, the [lo, hi) masking, the 8-row sub-chunk-min select,
and the lax-mirror discipline all live in the core; this module
contributes only the beam search's operand layout.

The beam search's per-iteration hot loop scores each query's gathered
candidate rows (``beam x degree`` of them) against that one query. The
batch axis of the scan is therefore the *query block* (LB = padded query
count), not an IVF list block:

* the **resident** operand is each query's own row, padded to the bf16
  sublane granule — ``(NQ, Q_GRANULE, d)`` with only slot 0 live;
* the **tiled** operand is the gathered candidate rows, transposed so
  the candidate axis is lane-aligned — ``(NQ, d, Cpad)`` bf16 streamed
  as ``(d, l_tile)`` blocks;
* ``bounds`` (NQ, 2) int32 marks the per-query valid candidate range
  ``[0, c_valid)``; padded/invalid candidates score the finite BIG and
  order last in the pooled merge.

Only the ``(NQ, Q_GRANULE, Cpad/8)`` sub-chunk minima reach HBM — the
same fused_knn cover argument as the grouped engines: every rank-``c``
candidate lives in a sub-chunk whose minimum is <= the c-th best value,
so the top sub-chunks by minimum contain the top rows, and the beam's
pool merge plus the exact f32 rerank tail (``score_l2_candidates``, the
grouped engines' one rerank authority) absorb the bf16 ranking noise at
the pool boundary. Returned distances are exact.

CPU/tier-1: the kernel runs under ``interpret=True`` and
:func:`beam_scan_subchunk_min_lax` is the op-for-op XLA mirror the
tests pin the kernel against bitwise. Importing this module never
builds a TPU program; ``JAX_PLATFORMS=cpu`` callers reach it only when
they explicitly opt in with ``use_pallas=True``.
"""

from __future__ import annotations

import functools
import typing

import jax.numpy as jnp

from raft_tpu.spatial.ann import scan_core
from raft_tpu.spatial.ann.scan_core import (
    BIG as BIG,  # re-export: callers read the masked-row constant here
    SUBCHUNK,
    pad_queries,
)

__all__ = [
    "SUBCHUNK", "pad_queries", "plan_l_tile", "beam_scan_subchunk_min",
    "beam_scan_subchunk_min_lax", "beam_scan_supported",
]


def _step_bytes(d: int, q_pad: int, l_tile: int) -> int:
    # candidate tile (d, Lt) bf16 (x2: pipelined block) + the query's
    # padded row block (Qp, d) bf16 (x2: resident, double-buffered per
    # query) + d2 (Qp, Lt) f32
    return 2 * 2 * d * l_tile + 2 * 2 * q_pad * d + 4 * q_pad * l_tile


def plan_l_tile(d: int, q_pad: int,
                l_tile: typing.Optional[int] = None,
                profile: str = "latency"):
    """The beam engine's byte model handed to the ONE shared planner
    (:func:`raft_tpu.spatial.ann.scan_core.plan_l_tile`). The default
    profile is ``"latency"``: the beam search IS the qcap-small serving
    regime (one query row per batch slot), so the wider start tile is
    always affordable."""
    return scan_core.plan_l_tile(
        functools.partial(_step_bytes, d), q_pad, l_tile, profile
    )


def beam_scan_supported(d: int, c_pad: int) -> bool:
    """Whether the Pallas beam-scan engine applies at this config: one
    (query, candidate-tile) step fits the VMEM plan. The query block is
    a single padded row (``pad_queries(1)``), so this only fails at an
    extreme d; ``c_pad`` must land on the lane granule (the caller pads
    the candidate buffer once at build time)."""
    if d < 1 or c_pad < 1 or c_pad % scan_core.LANE:
        return False
    return plan_l_tile(d, pad_queries(1)) is not None


def beam_scan_subchunk_min(qrows, cands_t, bounds, *, interpret: bool,
                           l_tile: int = 256):
    """(NQ, Q, d) padded query rows x (NQ, d, Cpad) gathered candidate
    rows -> (NQ, Q, Cpad/8) f32 sub-chunk squared-L2 minima (bf16
    operands, f32 accumulation/norms).

    ``bounds`` (NQ, 2) int32: per-query valid candidate range [lo, hi)
    (columns outside score BIG). Q must be a multiple of 16 (bf16
    sublane tile; only slot 0 carries a live query — the rest are
    padding the caller drops) and Cpad a multiple of ``l_tile`` (itself
    a multiple of 128)."""
    nq, q_pad, d = qrows.shape
    d_c = cands_t.shape[1]
    if d_c != d:
        raise ValueError(
            f"beam_scan_subchunk_min: query dim {d} != candidate dim {d_c}"
        )

    def tile_fn(res, til, bc):
        # (Qp, d) bf16 query block x (d, Lt) bf16 candidate tile -> the
        # shared flat-family distance body
        return scan_core.l2_gram_tile(res[0], til[0])

    return scan_core.subchunk_scan(
        tile_fn, bounds,
        [qrows.astype(jnp.bfloat16)], [cands_t.astype(jnp.bfloat16)],
        l_tile=l_tile, interpret=interpret,
        name="beam_scan_subchunk_min",
    )


def beam_scan_subchunk_min_lax(qrows, cands_t, bounds):
    """Op-for-op XLA mirror of :func:`beam_scan_subchunk_min` (same bf16
    contraction with f32 accumulation, same f32 norm terms, same masking
    and sub-chunk reduce via ``scan_core.mask_subchunk_min_lax``) — the
    bit-compat reference the tier-1 tests pin the interpret-mode kernel
    against, and the engine's fallback wherever ``pallas_call`` is
    unavailable."""
    d2 = scan_core.l2_gram_tile(
        qrows.astype(jnp.bfloat16), cands_t.astype(jnp.bfloat16)
    )                                                  # (NQ, Qp, Cp) f32
    return scan_core.mask_subchunk_min_lax(d2, bounds)
