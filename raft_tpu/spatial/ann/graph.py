"""Fixed-degree kNN-graph ANN index + one-dispatch batched beam search
(ISSUE 19; ROADMAP item 3) — the TPU rework of the reference lineage's
low-latency answer (RAFT grew into CAGRA, Ootomo et al.; itself the GPU
rework of graph methods like HNSW).

IVF is a throughput design: its serving cost is dominated by scanning
``n_probes`` whole lists per query, which amortizes beautifully across a
batch and poorly at batch size 1. A fixed-degree graph index walks
toward the query instead: each hop gathers ``beam x degree`` candidate
rows, scores them, and keeps the best ``beam`` — touching
``iters x beam x degree`` rows total, orders of magnitude fewer than an
IVF probe at the same recall in the qcap<=8 regime.

**Construction** (:func:`graph_build`): start from
:func:`raft_tpu.sparse.knn_graph.knn_graph` (whose ``symmetrize=True``
IS the reverse-edge augment — A ∪ Aᵀ), then a degree-bounded
rank/detour prune (the CAGRA/DiskANN occlusion rule: a candidate ``v``
is dropped when some already-kept closer neighbor ``w`` gives a shorter
detour, ``d(w, v) < d(u, v)``), then pad every row to a static
``(n + 1, degree)`` int32 adjacency with ``-1`` (CAGRA-style; the extra
row is the sentinel node's all-invalid edge list). Construction is a
host-side (numpy) one-off, exactly like the IVF builders' k-means
labeling; only search is a traced program.

**Search** (:func:`graph_search`): batched greedy beam search as ONE
jitted program — no host round-trips, no data-dependent shapes:

* a fixed-width candidate pool of ``P = max(k, beam) + beam`` slots per
  query carries (distance, id, expanded?) triples; every iteration
  expands the ``beam`` best unexpanded entries (static trip count
  ``iters`` — the data-dependent "converged?" loop of CPU HNSW is
  exactly the retrace/host-sync hazard the ``data-dependent-loop-bound``
  lint rule exists for);
* the visited set is a bounded hash table — one byte per slot,
  ``2^hash_bits + 1`` slots per query, marked with a duplicate-safe
  scatter-max — so membership is O(1) with static shape; a collision
  can only DROP a candidate (bounded recall loss, never a wrong
  distance), and the ``+1`` slot is the sentinel's dump bucket;
* distance evaluation routes through the scan-kernel core on the Pallas
  path (:mod:`raft_tpu.spatial.ann.graph_kernel`: bf16 MXU distances,
  8-row sub-chunk minima, top sub-chunks reranked) and through plain
  XLA on the default path — BOTH tails score candidates with
  :func:`raft_tpu.spatial.ann.common.score_l2_candidates`, the grouped
  engines' one exact-rerank authority, so returned distances are exact
  f32 at HIGHEST precision in every configuration;
* the tombstone ``row_mask`` is a runtime operand folded ONLY at the
  exact tail — a dead row still guides navigation (the standard
  graph-index deletion semantics: the walk may pass through it, it can
  never be returned) — so delete/restore flips never retrace. True
  inserts rebuild the graph (the static-adjacency trade the reference
  makes too); the mutation tier's delete/upsert-by-restore cycle is a
  mask flip.

The ``graph_beam`` program-contract entry
(:mod:`raft_tpu.analysis.program.registry`) pins the warmed program's
zero-retrace behavior across health/mutation/route flips, and
``GraphIndex.warmup(audit=True)`` re-audits the exact warmed program
in-process. Serialization rides :mod:`raft_tpu.spatial.ann.serialize`
as its own kind (``graph``, nested ``GraphStorage``) with the CRC
manifest. See docs/graph_ann.md.

Importing this module never imports the kernel modules;
``JAX_PLATFORMS=cpu`` callers reach ``graph_kernel``/``scan_core`` only
through an explicit ``use_pallas`` opt-in (the CPU-subprocess
never-imports test pins this).
"""

from __future__ import annotations

import dataclasses
import functools
import math
import typing
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import compat, errors

__all__ = [
    "GraphParams",
    "GraphStorage",
    "GraphIndex",
    "graph_build",
    "graph_search",
    "graph_live_mask",
    "graph_delete",
    "graph_restore",
]

# Sentinel-row fill value: the padded data row every invalid candidate
# id gathers. Large enough that its squared distance (~d * 1e30) orders
# after every real row in the kernel's sub-chunk minima, finite so no
# inf - inf NaN can form on the VPU (scan_core's BIG discipline), and
# exactly representable in bf16 so the kernel and lax mirrors agree.
_SENTINEL_VAL = 1e15

# Knuth multiplicative hash constant (2^32 / phi) for the visited table.
_HASH_MULT = 2654435761


@dataclasses.dataclass(frozen=True)
class GraphParams:
    """Build knobs of the fixed-degree graph (CAGRA's graph_degree /
    intermediate_graph_degree pair)."""

    degree: int = 16
    # candidate pool per node handed to the occlusion prune (the
    # pre-prune kNN width); None = 2x degree, the CAGRA default ratio
    intermediate_degree: typing.Optional[int] = None
    seed: int = 0
    # deterministic entry points seeding every walk (CAGRA uses random
    # hashes per query; fixed seeded entries keep search reproducible)
    n_entry: int = 4


@compat.register_dataclass
@dataclasses.dataclass
class GraphStorage:
    """The graph half of the index — the nested serialization kind
    (:mod:`raft_tpu.spatial.ann.serialize` registers it like
    ``ListStorage``/``CoarseIndex``)."""

    adjacency: jax.Array   # (n + 1, degree) int32, -1 padded; row n all -1
    entries: jax.Array     # (n_entry,) int32 — seeded walk entry points

    @property
    def n(self) -> int:
        return self.adjacency.shape[0] - 1

    @property
    def degree(self) -> int:
        return self.adjacency.shape[1]


@compat.register_dataclass
@dataclasses.dataclass
class GraphIndex:
    data_padded: jax.Array   # (n + 1, d) — last row is the sentinel
    storage: GraphStorage
    metric: str = dataclasses.field(metadata=dict(static=True))

    @property
    def n(self) -> int:
        return self.storage.n

    def warmup(self, nq: int, *, k: int = 10, beam: int = 32,
               iters: typing.Optional[int] = None,
               hash_bits: typing.Optional[int] = None,
               use_pallas: typing.Optional[bool] = None,
               pallas_interpret: bool = False,
               with_mask: bool = False, audit: bool = False) -> int:
        """Pre-compile the beam-search serving program for (nq, d)
        float32 batches: one all-zeros batch is dispatched through the
        exact serving entry and blocked on, so the first real query
        batch pays dispatch, not trace+compile (docs/serving.md) — the
        graph sibling of ``IVFFlatIndex.warmup``.

        ``iters`` resolves shape-only (None -> :func:`_auto_iters`) and
        the resolved value is returned: pass exactly that integer on
        every serving dispatch, the warmed program is keyed on it.
        ``with_mask=True`` warms the tombstone variant instead (a
        ``row_mask`` operand in the signature is a different traced
        program; mask VALUE flips never retrace — the ``graph_beam``
        contract pins this).

        ``audit=True`` additionally traces the warmed program through
        the jaxpr-level program auditor (docs/static_analysis.md "Two
        tiers") and raises listing the findings if it violates the
        serving-tier invariants."""
        n, d = self.n, self.data_padded.shape[1]
        it = _auto_iters(n) if iters is None else iters
        hb = _auto_hash_bits(it, beam, self.storage.degree,
                             self.storage.entries.shape[0]) \
            if hash_bits is None else hash_bits
        q0 = jnp.zeros((nq, d), jnp.float32)
        mask = graph_live_mask(self) if with_mask else None
        out = graph_search(
            self, q0, k, beam=beam, iters=it, hash_bits=hb,
            row_mask=mask, use_pallas=use_pallas,
            pallas_interpret=pallas_interpret,
        )
        jax.block_until_ready(out)
        if audit:
            from raft_tpu.analysis.program import audit_warmed
            from raft_tpu.analysis.program.registry import (
                trace_graph_beam,
            )

            up = _resolve_beam_engine(
                use_pallas, d, beam * self.storage.degree
            )
            audit_warmed(trace_graph_beam(
                self, nq, k, beam, it, hb, with_mask=with_mask,
                use_pallas=up, pallas_interpret=pallas_interpret,
                name="graph_beam_warm",
            ))
        return it


def _auto_iters(n: int) -> int:
    """Default hop budget: the walk covers a small-world graph in
    O(log n) hops; the +4 margin absorbs prune-induced detours. Static
    (a trace-time constant) by construction — the convergence test a
    CPU implementation would loop on is the exact retrace hazard."""
    return min(32, max(4, int(math.ceil(math.log2(max(n, 2)))) + 4))


def _auto_hash_bits(iters: int, beam: int, degree: int,
                    n_entry: int) -> int:
    """Visited-table width: ~8 slots per possible insertion keeps the
    birthday-collision drop rate (a bounded recall effect, never a
    correctness one) low; clamped so the per-query table stays between
    1 KiB and 1 MiB."""
    marks = max(2, n_entry + iters * beam * degree)
    return min(20, max(10, int(math.ceil(math.log2(8 * marks)))))


def _resolve_beam_engine(use_pallas, d: int, c: int) -> bool:
    """Resolve the ``use_pallas`` knob of the beam search to a concrete
    engine choice (a trace-time static) — the graph sibling of
    ``ivf_flat._resolve_scan_engine``. ``c`` is the per-iteration
    candidate count (``beam * degree``).

    ``None`` (auto): the Pallas beam-scan engine on a TPU backend
    whenever the config fits the kernel's VMEM plan; the XLA scorer
    otherwise — so ``JAX_PLATFORMS=cpu`` never imports, let alone
    compiles, the kernel unless a caller opts in explicitly. ``True``
    validates and raises with the reason when unsupported (explicit
    opt-in must not silently fall back)."""
    if use_pallas is None:
        if jax.default_backend() != "tpu":
            return False
        from raft_tpu.spatial.ann import graph_kernel as gk

        c_pad = gk.scan_core.round_up(c, gk.scan_core.LANE)
        return gk.beam_scan_supported(d, c_pad)
    if use_pallas:
        from raft_tpu.spatial.ann import graph_kernel as gk

        c_pad = gk.scan_core.round_up(c, gk.scan_core.LANE)
        errors.expects(
            gk.beam_scan_supported(d, c_pad),
            "use_pallas=True unsupported at d=%d candidates=%d (one "
            "query block + candidate tile exceeds the kernel's VMEM "
            "plan); use the XLA scorer (use_pallas=False)", d, c,
        )
    return bool(use_pallas)


# ---------------------------------------------------------------------------
# construction


def graph_build(x, params: GraphParams = GraphParams(), *,
                metric: str = "l2") -> GraphIndex:
    """Build the fixed-degree graph index: kNN graph (reverse-edge
    augmented via ``symmetrize``) -> occlusion prune -> static padded
    adjacency. Deterministic for a given (x, params): the kNN stage,
    the prune, and the seeded entry points all are."""
    from raft_tpu.sparse.knn_graph import knn_graph

    x = jnp.asarray(x)
    errors.check_matrix(x, "x", min_rows=2)
    n, d = x.shape
    deg = min(params.degree, n - 1)
    errors.expects(deg >= 1, "degree must be >= 1, got %d", params.degree)
    idg = params.intermediate_degree
    idg = 2 * deg if idg is None else idg
    idg = min(max(idg, deg), n - 1)

    g = knn_graph(x, idg, symmetrize=True)
    nnz = int(g.nnz)
    rows = np.asarray(g.rows)[:nnz].astype(np.int64)
    cols = np.asarray(g.cols)[:nnz].astype(np.int64)
    xf = np.asarray(x, dtype=np.float32)
    adjacency = _occlusion_prune(xf, rows, cols, deg, 2 * idg)

    rng = np.random.default_rng(params.seed)
    n_entry = max(1, min(params.n_entry, n))
    entries = np.sort(
        rng.choice(n, size=n_entry, replace=False)
    ).astype(np.int32)
    adjacency = _patch_reachability(adjacency, entries, xf)

    adj_pad = np.concatenate(
        [adjacency, np.full((1, deg), -1, np.int32)]
    )
    data_padded = jnp.concatenate(
        [x, jnp.full((1, d), _SENTINEL_VAL, x.dtype)]
    )
    storage = GraphStorage(jnp.asarray(adj_pad), jnp.asarray(entries))
    return GraphIndex(data_padded, storage, metric)


def _occlusion_prune(xf: np.ndarray, rows: np.ndarray, cols: np.ndarray,
                     degree: int, m_cap: int,
                     block: int = 1024) -> np.ndarray:
    """Degree-bounded rank/detour prune of a (row-sorted) COO edge list
    to a dense (n, degree) int32 adjacency, -1 padded.

    Per node ``u``, candidates are visited in ascending d(u, ·) order;
    candidate ``v`` is kept unless an already-kept ``w`` occludes it
    (``d(w, v) < d(u, v)`` — the detour through ``w`` is shorter).
    Slots left after the prune are back-filled with the nearest
    occluded candidates (CAGRA keeps the degree fixed: the diversity
    rule picks WHICH edges, the budget is spent regardless), so rows
    only pad with -1 when the node has fewer candidates than slots.
    Host-side numpy, blocked to bound the (B, m, m) pairwise tile."""
    n, _ = xf.shape
    counts = np.bincount(rows, minlength=n)
    starts = np.concatenate([[0], np.cumsum(counts)])
    m = int(min(counts.max(initial=1), max(degree, m_cap)))
    cand = np.full((n, m), -1, np.int64)
    within = np.arange(len(rows)) - starts[rows]
    sel = within < m
    cand[rows[sel], within[sel]] = cols[sel]

    out = np.full((n, degree), -1, np.int32)
    arange_m = np.arange(m)
    for b0 in range(0, n, block):
        b1 = min(b0 + block, n)
        B = b1 - b0
        cb = cand[b0:b1]
        valid = (cb >= 0) & (cb != (np.arange(b0, b1)[:, None]))
        cv = np.where(valid, cb, 0)
        # drop duplicate ids (symmetrize combines, but stay safe): keep
        # the first occurrence in id order
        ido = np.argsort(cv + np.where(valid, 0, n + 1), axis=1,
                         kind="stable")
        sid = np.take_along_axis(cv, ido, axis=1)
        dup_sorted = np.zeros((B, m), bool)
        dup_sorted[:, 1:] = sid[:, 1:] == sid[:, :-1]
        dup = np.zeros((B, m), bool)
        np.put_along_axis(dup, ido, dup_sorted, axis=1)
        valid &= ~dup

        diff = xf[b0:b1, None, :] - xf[cv]                 # (B, m, d)
        cd = np.einsum("bmd,bmd->bm", diff, diff)
        cd[~valid] = np.inf
        order = np.argsort(cd, axis=1, kind="stable")      # by distance
        cs = np.take_along_axis(cv, order, axis=1)
        cdist = np.take_along_axis(cd, order, axis=1)
        vs = np.take_along_axis(valid, order, axis=1)

        cvecs = xf[cs]                                     # (B, m, d)
        nn = np.einsum("bmd,bmd->bm", cvecs, cvecs)
        pw = (nn[:, :, None] + nn[:, None, :]
              - 2.0 * np.einsum("bmd,bnd->bmn", cvecs, cvecs))

        kept = np.zeros((B, m), bool)
        occl = ~vs
        kept_count = np.zeros(B, np.int64)
        rng_b = np.arange(B)
        for _ in range(m):
            avail = ~occl & ~kept
            has = avail.any(axis=1) & (kept_count < degree)
            if not has.any():
                break
            first = np.argmax(avail, axis=1)
            kept[rng_b[has], first[has]] = True
            kept_count += has
            newocc = has[:, None] & (pw[rng_b, first] < cdist)
            occl |= newocc
        # kept first, then occluded-but-valid back-fill, both in
        # distance order; invalid last
        klass = np.where(kept, 0, np.where(vs, 1, 2))
        fill = np.argsort(klass, axis=1, kind="stable")[:, :degree]
        ids = np.take_along_axis(cs, fill, axis=1)
        bad = np.take_along_axis(klass, fill, axis=1) == 2
        out[b0:b1] = np.where(bad, -1, ids).astype(np.int32)
    return out


def _patch_reachability(adj: np.ndarray, entries: np.ndarray,
                        xf: np.ndarray) -> np.ndarray:
    """Guarantee every row is reachable from the seeded entries — an
    unreachable row can never be returned at ANY beam width, a permanent
    recall hole. The occlusion prune is per-node (directed): a node can
    lose all its IN-edges even though ``symmetrize`` gave it candidates.
    For each unreached node, overwrite the LAST unclaimed adjacency slot
    (the farthest kept edge — the least diversity lost) of its nearest
    reached node with a reverse edge to it; re-BFS and repeat, since new
    edges cascade. Each slot is claimed at most once, so this
    terminates; deterministic (pure argsort/argmin on distances)."""
    n, degree = adj.shape
    claimed: dict = {}
    for _ in range(n):
        seen = np.zeros(n, bool)
        seen[entries] = True
        frontier = np.asarray(entries, np.int64)
        while frontier.size:
            nxt = adj[frontier].ravel()
            nxt = nxt[nxt >= 0]
            nxt = np.unique(nxt[~seen[nxt]])
            seen[nxt] = True
            frontier = nxt
        miss = np.flatnonzero(~seen)
        if not miss.size:
            break
        reach = np.flatnonzero(seen)
        progressed = False
        for u in miss:
            d2 = ((xf[reach] - xf[u]) ** 2).sum(axis=1)
            for w in reach[np.argsort(d2, kind="stable")]:
                slot = degree - 1 - claimed.get(int(w), 0)
                if slot < 0:
                    continue
                adj[w, slot] = u
                claimed[int(w)] = claimed.get(int(w), 0) + 1
                progressed = True
                break
        if not progressed:      # every reached row fully claimed —
            break               # degenerate; leave the remainder
    return adj


# ---------------------------------------------------------------------------
# mutation (tombstone) helpers — the mask is a RUNTIME operand of the
# beam program; flipping values never retraces (the graph_beam contract
# pins it). True inserts rebuild the graph.


def graph_live_mask(index: GraphIndex) -> jax.Array:
    """All-live (n,) int8 tombstone mask for ``index``."""
    return jnp.ones((index.n,), jnp.int8)


def graph_delete(row_mask: jax.Array, ids) -> jax.Array:
    """Tombstone rows: deleted rows still guide the walk, never appear
    in results (folded at the exact rerank tail only)."""
    return row_mask.at[jnp.asarray(ids)].set(0)


def graph_restore(row_mask: jax.Array, ids) -> jax.Array:
    """Un-tombstone rows (the upsert-by-restore half of the mutation
    cycle)."""
    return row_mask.at[jnp.asarray(ids)].set(1)


# ---------------------------------------------------------------------------
# search


def graph_search(index: GraphIndex, queries, k: int, *, beam: int = 32,
                 iters: typing.Optional[int] = None,
                 hash_bits: typing.Optional[int] = None,
                 row_mask: typing.Optional[jax.Array] = None,
                 use_pallas: typing.Optional[bool] = None,
                 pallas_interpret: bool = False,
                 ) -> Tuple[jax.Array, jax.Array]:
    """Batched greedy beam search — ONE jitted dispatch per call.

    Returns (dists, ids) with original row ids, -1 where fewer than
    ``k`` reachable live rows exist; L2 metric family (squared
    distances, sqrt applied for metric='l2'), exact f32 at HIGHEST
    precision via the shared rerank tail."""
    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, index.data_padded, "queries", "index")
    n = index.n
    errors.check_k(k, n, "k vs graph rows")
    errors.expects(beam >= 1, "beam must be >= 1, got %d", beam)
    it = _auto_iters(n) if iters is None else iters
    hb = _auto_hash_bits(it, beam, index.storage.degree,
                         index.storage.entries.shape[0]) \
        if hash_bits is None else hash_bits
    up = _resolve_beam_engine(
        use_pallas, index.data_padded.shape[1],
        beam * index.storage.degree,
    )
    vals, ids = _beam_impl(
        index, q, k=k, beam=beam, iters=it, hash_bits=hb,
        row_mask=row_mask, use_pallas=up,
        pallas_interpret=pallas_interpret,
    )
    if index.metric == "l2":
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    return vals, ids


@functools.partial(
    jax.jit,
    static_argnames=("k", "beam", "iters", "hash_bits", "use_pallas",
                     "pallas_interpret"),
)
def _beam_impl(index, q, k, beam, iters, hash_bits, row_mask=None,
               use_pallas=False, pallas_interpret=False):
    # The whole walk — init, ``iters`` expansion rounds, exact tail —
    # is one traced program. Static geometry: pool width
    # P = max(k, beam) + beam (>= beam unexpanded slots survive a full
    # expansion round, >= k for the tail), candidate buffer
    # C = beam * degree, visited table 2^hash_bits + 1 bytes/query.
    # ``row_mask`` (n,) int8 is a RUNTIME operand folded only at the
    # tail; ``None`` omits the operand (a separate warmed program).
    n = index.storage.adjacency.shape[0] - 1
    degree = index.storage.adjacency.shape[1]
    nq = q.shape[0]
    qf = q.astype(jnp.float32)
    P = max(k, beam) + beam
    C = beam * degree
    T = 1 << hash_bits
    rows = jnp.arange(nq, dtype=jnp.int32)[:, None]

    from raft_tpu.spatial.ann.common import score_l2_candidates

    def _hash(ids):
        # Knuth multiplicative hash, uint32 throughout (the program
        # contracts forbid 64-bit dtype flow); sentinel -> dump slot T
        u = ids.astype(jnp.uint32) * jnp.uint32(_HASH_MULT)
        h = (u >> np.uint32(32 - hash_bits)).astype(jnp.int32)
        return jnp.where(ids < n, h, T)

    def _score_exact(cand):
        # the one rerank authority: exact f32, +inf where invalid
        cvec = index.data_padded[cand].astype(jnp.float32)
        return score_l2_candidates(qf, cvec, cand < n)

    if use_pallas:
        from raft_tpu.spatial.ann import graph_kernel as gk

        c_pad = gk.scan_core.round_up(C, gk.scan_core.LANE)
        l_tile = gk.plan_l_tile(index.data_padded.shape[1],
                                gk.pad_queries(1))
        while c_pad % l_tile:
            l_tile -= gk.scan_core.LANE
        q_pad = gk.pad_queries(1)
        qrows = jnp.zeros((nq, q_pad, qf.shape[1]), jnp.float32)
        qrows = qrows.at[:, 0, :].set(qf)
        bounds = jnp.broadcast_to(
            jnp.array([0, c_pad], jnp.int32), (nq, 2)
        )
        # cover argument: the top-s sub-chunks by minimum contain the
        # top-s candidate rows, so s = P sub-chunks cover every row the
        # pool merge could keep
        s = min(c_pad // gk.SUBCHUNK, P)

        def _score_new(cand):
            cp = jnp.concatenate(
                [cand, jnp.full((nq, c_pad - C), n, jnp.int32)], axis=1
            )
            cvec = index.data_padded[cp]                  # (nq, Cp, d)
            mins = gk.beam_scan_subchunk_min(
                qrows, cvec.transpose(0, 2, 1), bounds,
                interpret=pallas_interpret, l_tile=l_tile,
            )[:, 0]                                       # (nq, Cp/8)
            _, sub = lax.top_k(-mins, s)
            pos = (
                sub[:, :, None] * gk.SUBCHUNK
                + jnp.arange(gk.SUBCHUNK, dtype=jnp.int32)
            ).reshape(nq, s * gk.SUBCHUNK)
            csel = jnp.take_along_axis(cp, pos, axis=1)
            csub = jnp.take_along_axis(
                cvec, pos[:, :, None], axis=1
            ).astype(jnp.float32)
            return score_l2_candidates(qf, csub, csel < n), csel
    else:

        def _score_new(cand):
            return _score_exact(cand), cand

    # init: seeded entries fill the first pool slots (scored exactly),
    # the rest hold the sentinel at +inf
    e = index.storage.entries[: min(index.storage.entries.shape[0], P)]
    E = e.shape[0]
    ed = _score_exact(jnp.broadcast_to(e[None, :], (nq, E)))
    pool_d = jnp.full((nq, P), jnp.inf, jnp.float32).at[:, :E].set(ed)
    pool_i = jnp.full((nq, P), n, jnp.int32).at[:, :E].set(
        jnp.broadcast_to(e, (nq, E))
    )
    pool_x = jnp.zeros((nq, P), bool)
    visited = jnp.zeros((nq, T + 1), jnp.uint8).at[:, _hash(e)].max(
        jnp.uint8(1)
    )

    def body(_, state):
        pool_d, pool_i, pool_x, visited = state
        # frontier: best `beam` unexpanded live entries
        sel_key = jnp.where(pool_x | (pool_i >= n), jnp.inf, pool_d)
        neg, sel = lax.top_k(-sel_key, beam)
        fvalid = jnp.isfinite(neg)
        pool_x = pool_x.at[rows, sel].set(True)
        fids = jnp.take_along_axis(pool_i, sel, axis=1)
        fids = jnp.where(fvalid, fids, n)
        # gather neighbors (sentinel frontier row is all -1)
        cand = index.storage.adjacency[fids].reshape(nq, C)
        cand = jnp.where(cand < 0, n, cand)
        # within-round dedup: sort, tombstone equal neighbors
        cand = jnp.sort(cand, axis=1)
        dup = jnp.concatenate(
            [jnp.zeros((nq, 1), bool), cand[:, 1:] == cand[:, :-1]],
            axis=1,
        )
        cand = jnp.where(dup, n, cand)
        # visited filter + duplicate-safe mark
        seen = jnp.take_along_axis(visited, _hash(cand), axis=1) > 0
        cand = jnp.where(seen, n, cand)
        visited = visited.at[rows, _hash(cand)].max(jnp.uint8(1))
        # score + merge: keep the best P of pool ∪ new
        new_d, new_i = _score_new(cand)
        all_d = jnp.concatenate([pool_d, new_d], axis=1)
        all_i = jnp.concatenate([pool_i, new_i], axis=1)
        all_x = jnp.concatenate(
            [pool_x, jnp.zeros(new_i.shape, bool)], axis=1
        )
        top, idx = lax.top_k(-all_d, P)
        return (
            -top,
            jnp.take_along_axis(all_i, idx, axis=1),
            jnp.take_along_axis(all_x, idx, axis=1),
            visited,
        )

    pool_d, pool_i, pool_x, visited = lax.fori_loop(
        0, iters, body, (pool_d, pool_i, pool_x, visited)
    )

    # exact tail — the ONLY place tombstones fold, so mask flips are
    # pure runtime and the walk still navigates through dead rows
    live = pool_i < n
    if row_mask is not None:
        live &= row_mask[jnp.clip(pool_i, 0, n - 1)] > 0
    cvec = index.data_padded[pool_i].astype(jnp.float32)
    d2 = score_l2_candidates(qf, cvec, live)
    neg, pos = lax.top_k(-d2, k)
    vals = -neg
    ids = jnp.take_along_axis(pool_i, pos, axis=1)
    ids = jnp.where(jnp.isfinite(vals), ids, -1)
    return vals, ids.astype(jnp.int32)
