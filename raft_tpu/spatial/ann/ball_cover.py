"""Random ball cover — analog of
cpp/include/raft/spatial/knn/ball_cover.cuh:34-144 (``BallCoverIndex``
ball_cover_common.h:38-90, rbc_build_index / rbc_knn_query /
rbc_all_knn_query; registers kernels detail/ball_cover/registers.cuh).

Build (reference rbc_build_index): sample √n landmarks, assign every
point to its closest landmark (the "ball"), store balls with the shared
sorted-list layout, record per-ball radii.

Metrics: the reference dispatches the whole pipeline on the index metric
— ``HaversineFunc`` vs ``EuclideanFunc`` (ball_cover.cuh:38-42, 88-94,
155); ball cover is largely *about* geospatial data. Here the same
dispatch: ``metric="l2"`` (k-means-refined landmarks, squared-L2 probe
with exact sqrt at the end) or ``metric="haversine"`` ((lat, lon) radian
rows, data-point landmarks — Euclidean centroid averages are not
meaningful in great-circle geometry — and haversine bounds throughout).
Both are true metrics, so the same triangle-inequality machinery prunes.

Query (reference's two-pass triangle-inequality strategy): balls are probed
in order of d(q, landmark); a ball can contain a better neighbor only if
d(q, L) - radius_L < kth_best, so after scoring the closest ``n_probes``
balls the kth distance certifies, per query, whether the result is exact.
``rbc_knn_query`` returns that certificate mask; with
``n_probes = n_landmarks`` the search is exhaustively exact (the
reference's guarantee)."""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import compat, errors
from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.distance.pairwise import haversine_core, haversine_distance
from raft_tpu.spatial.ann.common import ListStorage, build_list_storage

__all__ = ["BallCoverIndex", "rbc_build_index", "rbc_knn_query", "rbc_all_knn_query"]


@compat.register_dataclass
@dataclasses.dataclass
class BallCoverIndex:
    """Analog of BallCoverIndex (ball_cover_common.h:38)."""

    landmarks: jax.Array      # (n_landmarks, d)
    radii: jax.Array          # (n_landmarks,) TRUE metric distances
    data_sorted: jax.Array    # (n + 1, d) sentinel row appended
    storage: ListStorage
    metric: str = dataclasses.field(default="l2", metadata=dict(static=True))


def _haversine_rows(q, cand, valid):
    """Row-batched haversine: q (nq, 2) vs cand (nq, C, 2) radian pairs,
    +inf where invalid (the haversine counterpart of
    common.score_l2_candidates; formula shared via
    distance.pairwise.haversine_core)."""
    d = haversine_core(
        q[:, 0][:, None], q[:, 1][:, None], cand[..., 0], cand[..., 1]
    )
    return jnp.where(valid, d, jnp.inf)


def rbc_build_index(
    x, *, n_landmarks: int = 0, seed: int = 0, metric: str = "l2"
) -> BallCoverIndex:
    """Build (reference rbc_build_index, ball_cover.cuh:34): √n landmarks
    by default. ``metric="haversine"`` expects (lat, lon) RADIAN rows."""
    x = jnp.asarray(x)
    errors.check_matrix(x, "x")
    errors.expects(
        metric in ("l2", "haversine"),
        "metric must be 'l2' or 'haversine', got %r", metric,
    )
    n = x.shape[0]
    if n_landmarks <= 0:
        n_landmarks = max(int(np.sqrt(n)), 1)

    if metric == "haversine":
        errors.expects(
            x.shape[1] == 2,
            "haversine expects (lat, lon) pairs, got %d columns", x.shape[1],
        )
        # landmarks are SAMPLED data points (the reference's random ball
        # cover; Euclidean centroid averages are meaningless on the sphere)
        sel = jax.random.choice(
            jax.random.PRNGKey(seed), n, (min(n_landmarks, n),),
            replace=False,
        )
        landmarks = jnp.take(x, jnp.sort(sel), axis=0)
        hd = haversine_distance(x, landmarks)          # (n, L) true dists
        labels = jnp.argmin(hd, axis=1)
        member_d = jnp.min(hd, axis=1)
    else:
        out = kmeans_fit(
            x, KMeansParams(n_clusters=n_landmarks, max_iter=10, seed=seed)
        )
        landmarks = out.centroids
        labels = out.labels
        member_d = jnp.sqrt(
            jnp.maximum(
                jnp.sum((x - landmarks[labels]) ** 2, axis=1), 0.0
            )
        )

    storage = build_list_storage(np.asarray(labels), landmarks.shape[0])
    data_sorted = jnp.concatenate(
        [x[storage.sorted_ids], jnp.zeros((1, x.shape[1]), x.dtype)]
    )
    # radius of each ball: max member TRUE distance to its landmark
    radii = jnp.zeros((landmarks.shape[0],), jnp.float32).at[labels].max(
        member_d.astype(jnp.float32)
    )
    return BallCoverIndex(landmarks, radii, data_sorted, storage, metric)


@functools.partial(jax.jit, static_argnames=("k", "n_probes"))
def rbc_knn_query(
    index: BallCoverIndex, queries, k: int, *, n_probes: int = 16
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """kNN query. Returns (dists (true metric), ids, exact (nq,) bool
    certificate).

    exact[i] is True when the triangle inequality proves no unprobed ball
    can contain a closer neighbor — the reference's pruning criterion
    (detail/ball_cover.cuh perform_post_filter_registers) used here as a
    per-query certificate. Valid for both metrics: L2 and great-circle
    distance each satisfy the triangle inequality."""
    from raft_tpu.spatial.ann.common import (
        check_candidate_pool, coarse_probe, score_l2_candidates,
        select_candidates,
    )

    q = jnp.asarray(queries)
    nq, d = q.shape
    n_land = index.landmarks.shape[0]
    n_probes = min(n_probes, n_land)
    check_candidate_pool(k, n_probes, index.storage)
    qf = q.astype(jnp.float32)

    if index.metric == "haversine":
        all_ld = haversine_distance(qf, index.landmarks.astype(jnp.float32))
        _, probes = jax.lax.top_k(-all_ld, n_probes)
    else:
        # landmark distances at HIGHEST precision (one matmul serves both
        # probe selection and the certificate): the default-precision
        # gram carries bf16 operand rounding on TPU, and a ~1e-3-relative
        # error in d(q, L) could falsely certify a query whose margin is
        # inside that band (the kth side comes from the exact scorer)
        probes, ld2 = coarse_probe(
            qf, index.landmarks, n_probes,
            precision=jax.lax.Precision.HIGHEST,
        )
        all_ld = jnp.sqrt(jnp.maximum(ld2, 0.0))       # (nq, n_land) true

    cand_pos = index.storage.list_index[probes].reshape(nq, -1)
    cand = index.data_sorted[cand_pos].astype(jnp.float32)
    valid = cand_pos < index.storage.n
    if index.metric == "haversine":
        dist = _haversine_rows(qf, cand, valid)
        dists, ids = select_candidates(index.storage, cand_pos, dist, k)
    else:
        d2 = score_l2_candidates(qf, cand, valid)
        vals, ids = select_candidates(index.storage, cand_pos, d2, k)
        dists = jnp.sqrt(jnp.maximum(vals, 0.0))

    # exactness certificate: every UNPROBED ball satisfies
    # d(q, L) - radius_L >= kth  (probed balls were fully scored)
    kth = dists[:, k - 1]
    probed = jnp.zeros((nq, n_land), bool).at[
        jnp.arange(nq)[:, None], probes
    ].set(True)
    bound = all_ld - index.radii[None, :]
    exact = jnp.all(probed | (bound >= kth[:, None]), axis=1)
    return dists, ids.astype(jnp.int32), exact


def rbc_all_knn_query(index: BallCoverIndex, k: int, *, n_probes: int = 16):
    """All-points kNN over the index's own data
    (reference rbc_all_knn_query, ball_cover.cuh:69)."""
    x = index.data_sorted[: index.storage.n]
    # un-permute so row i queries original point i
    inv = jnp.argsort(index.storage.sorted_ids)
    return rbc_knn_query(index, x[inv], k, n_probes=n_probes)
