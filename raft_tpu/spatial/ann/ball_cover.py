"""Random ball cover — analog of
cpp/include/raft/spatial/knn/ball_cover.cuh:34-144 (``BallCoverIndex``
ball_cover_common.h:38-90, rbc_build_index / rbc_knn_query /
rbc_all_knn_query; registers kernels detail/ball_cover/registers.cuh).

Build (reference rbc_build_index): sample √n landmarks (k-means refined),
assign every point to its closest landmark (the "ball"), store balls with
the shared sorted-list layout, record per-ball radii.

Query (reference's two-pass triangle-inequality strategy): balls are probed
in order of d(q, landmark); a ball can contain a better neighbor only if
d(q, L) - radius_L < kth_best, so after scoring the closest ``n_probes``
balls the kth distance certifies, per query, whether the result is exact.
``rbc_knn_query`` returns that certificate mask; with
``n_probes = n_landmarks`` the search is exhaustively exact (the
reference's guarantee)."""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.spatial.ann.common import ListStorage, build_list_storage

__all__ = ["BallCoverIndex", "rbc_build_index", "rbc_knn_query", "rbc_all_knn_query"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BallCoverIndex:
    """Analog of BallCoverIndex (ball_cover_common.h:38)."""

    landmarks: jax.Array      # (n_landmarks, d)
    radii: jax.Array          # (n_landmarks,)
    data_sorted: jax.Array    # (n + 1, d) sentinel row appended
    storage: ListStorage


def rbc_build_index(x, *, n_landmarks: int = 0, seed: int = 0) -> BallCoverIndex:
    """Build (reference rbc_build_index, ball_cover.cuh:34): √n landmarks
    by default."""
    x = jnp.asarray(x)
    n = x.shape[0]
    if n_landmarks <= 0:
        n_landmarks = max(int(np.sqrt(n)), 1)
    out = kmeans_fit(
        x, KMeansParams(n_clusters=n_landmarks, max_iter=10, seed=seed)
    )
    labels = out.labels
    storage = build_list_storage(np.asarray(labels), n_landmarks)
    data_sorted = jnp.concatenate(
        [x[storage.sorted_ids], jnp.zeros((1, x.shape[1]), x.dtype)]
    )
    # radius of each ball: max member distance to its landmark
    d2 = jnp.sum((x - out.centroids[labels]) ** 2, axis=1)
    radii = jnp.sqrt(
        jnp.zeros((n_landmarks,), jnp.float32).at[labels].max(d2)
    )
    return BallCoverIndex(out.centroids, radii, data_sorted, storage)


@functools.partial(jax.jit, static_argnames=("k", "n_probes"))
def rbc_knn_query(
    index: BallCoverIndex, queries, k: int, *, n_probes: int = 16
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """kNN query. Returns (dists (L2), ids, exact (nq,) bool certificate).

    exact[i] is True when the triangle inequality proves no unprobed ball
    can contain a closer neighbor — the reference's pruning criterion
    (detail/ball_cover.cuh perform_post_filter_registers) used here as a
    per-query certificate."""
    from raft_tpu.spatial.ann.common import (
        check_candidate_pool, coarse_probe, score_l2_candidates,
        select_candidates,
    )

    q = jnp.asarray(queries)
    nq, d = q.shape
    n_land = index.landmarks.shape[0]
    n_probes = min(n_probes, n_land)
    check_candidate_pool(k, n_probes, index.storage)
    qf = q.astype(jnp.float32)

    probes, ld2 = coarse_probe(qf, index.landmarks, n_probes)
    ld = jnp.sqrt(jnp.maximum(ld2, 0.0))  # true landmark distances for the bound

    cand_pos = index.storage.list_index[probes].reshape(nq, -1)
    cand = index.data_sorted[cand_pos].astype(jnp.float32)
    d2 = score_l2_candidates(qf, cand, cand_pos < index.storage.n)
    vals, ids = select_candidates(index.storage, cand_pos, d2, k)
    dists = jnp.sqrt(jnp.maximum(vals, 0.0))

    # exactness certificate: every UNPROBED ball satisfies
    # d(q, L) - radius_L >= kth  (probed balls were fully scored)
    kth = dists[:, k - 1]
    probed = jnp.zeros((nq, n_land), bool).at[
        jnp.arange(nq)[:, None], probes
    ].set(True)
    bound = ld - index.radii[None, :]
    exact = jnp.all(probed | (bound >= kth[:, None]), axis=1)
    return dists, ids.astype(jnp.int32), exact


def rbc_all_knn_query(index: BallCoverIndex, k: int, *, n_probes: int = 16):
    """All-points kNN over the index's own data
    (reference rbc_all_knn_query, ball_cover.cuh:69)."""
    x = index.data_sorted[: index.storage.n]
    # un-permute so row i queries original point i
    inv = jnp.argsort(index.storage.sorted_ids)
    return rbc_knn_query(index, x[inv], k, n_probes=n_probes)
