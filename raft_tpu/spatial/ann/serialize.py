"""Index serialization — save/load for every ANN index and the sparse
column-blocked index.

The reference (~22.06) keeps its FAISS-backed indexes in memory only
(ann_common.h — no serialization in this version); build cost at scale
makes persistence a practical necessity, so raft_tpu provides it
natively: one ``.npz`` per index, arrays + a small JSON header carrying
the static fields. Loading returns device-resident pytrees.

Format: numpy ``.npz`` with keys ``__header__`` (JSON: index type,
version, static fields) and one entry per array leaf. Portable across
hosts; no pickle.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import errors
from raft_tpu.spatial.ann.common import ListStorage
from raft_tpu.spatial.ann.ivf_flat import IVFFlatIndex
from raft_tpu.spatial.ann.ivf_pq import IVFPQIndex
from raft_tpu.spatial.ann.ivf_sq import IVFSQIndex
from raft_tpu.sparse.distance import SparseColBlockIndex

__all__ = ["save_index", "load_index"]

_VERSION = 1

_TYPES = {
    "ivf_flat": IVFFlatIndex,
    "ivf_pq": IVFPQIndex,
    "ivf_sq": IVFSQIndex,
    "sparse_colblock": SparseColBlockIndex,
}


def _register_sharded() -> None:
    # lazy: comms imports spatial.ann, so a top-level import here would
    # be circular. The sharded index loads onto the default device;
    # re-place onto a mesh with comms.mnmg_ivf.place_index before search.
    if "mnmg_ivf_pq" not in _TYPES:
        from raft_tpu.comms.mnmg_ivf import MnmgIVFPQIndex
        from raft_tpu.comms.mnmg_ivf_flat import MnmgIVFFlatIndex

        _TYPES["mnmg_ivf_pq"] = MnmgIVFPQIndex
        _NAMES[MnmgIVFPQIndex] = "mnmg_ivf_pq"
        _TYPES["mnmg_ivf_flat"] = MnmgIVFFlatIndex
        _NAMES[MnmgIVFFlatIndex] = "mnmg_ivf_flat"


_NAMES = {v: k for k, v in _TYPES.items()}
# nested dataclasses that may appear inside an index payload
_NESTED = {"ListStorage": ListStorage}


def _flatten(obj: Any, prefix: str, arrays: dict, static: dict) -> None:
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        key = f"{prefix}{f.name}"
        if v is None:
            static[key] = None
        elif dataclasses.is_dataclass(v):
            errors.expects(
                _NESTED.get(type(v).__name__) is type(v),
                "save_index: nested dataclass %s is not registered in "
                "serialize._NESTED (it could not be rebuilt at load time)",
                type(v).__name__,
            )
            static[key] = {"__nested__": type(v).__name__}
            _flatten(v, key + ".", arrays, static)
        elif isinstance(v, (jax.Array, np.ndarray)):
            arr = np.asarray(v)
            if arr.dtype.kind == "V":
                # ml_dtypes extension floats (bfloat16 etc.): np.savez
                # would store raw void bytes that cannot round-trip; save
                # the bits with the dtype name tagged in the header
                static[key + ".__dtype__"] = arr.dtype.name
                arr = arr.view(np.uint16 if arr.dtype.itemsize == 2
                               else np.uint8)
            arrays[key] = arr
        else:
            static[key] = v if not isinstance(v, tuple) else list(v)


def save_index(index, path) -> None:
    """Serialize an ANN / sparse index to ``path`` (``.npz``)."""
    if type(index) not in _NAMES:
        _register_sharded()
    errors.expects(
        type(index) in _NAMES,
        "save_index: unsupported index type %s (supported: %s)",
        type(index).__name__, sorted(_TYPES),
    )
    arrays: dict = {}
    static: dict = {}
    _flatten(index, "", arrays, static)
    header = {
        "type": _NAMES[type(index)],
        "version": _VERSION,
        "static": static,
    }
    # write straight to the file object: np.savez accepts one (and then
    # does not append ".npz" to the name), and the archive is not
    # duplicated in RAM — index payloads run to hundreds of MB
    with open(path, "wb") as f:
        np.savez(
            f,
            __header__=np.frombuffer(
                json.dumps(header).encode("utf-8"), dtype=np.uint8
            ),
            **arrays,
        )


def _default_placer(name, arr):
    return jnp.asarray(arr)


def _rebuild(cls, prefix: str, npz, static: dict, placer=_default_placer):
    kwargs = {}
    for f in dataclasses.fields(cls):
        key = f"{prefix}{f.name}"
        if key in npz:
            arr = npz[key]
            tagged = static.get(key + ".__dtype__")
            if tagged is not None:
                import ml_dtypes

                arr = arr.view(np.dtype(getattr(ml_dtypes, tagged)))
            kwargs[f.name] = placer(f.name, arr)
        else:
            v = static.get(key)
            if isinstance(v, dict) and "__nested__" in v:
                errors.expects(
                    v["__nested__"] in _NESTED,
                    "load_index: unknown nested type %r", v["__nested__"],
                )
                nested_cls = _NESTED[v["__nested__"]]
                kwargs[f.name] = _rebuild(nested_cls, key + ".", npz, static)
            elif isinstance(v, list):
                kwargs[f.name] = tuple(v)
            else:
                kwargs[f.name] = v
    return cls(**kwargs)


def load_index(path, comms=None):
    """Load an index saved by :func:`save_index`; arrays land on the
    default device.

    ``comms``: for a sharded ``mnmg_ivf_pq`` index, stream each slab
    DIRECTLY to its mesh placement as it is read — the 100M ``store_raw``
    regime's raw-vector slabs exceed one chip's HBM, so materializing on
    the default device first (then :func:`place_index`) would OOM exactly
    where the sharded index matters. With ``comms=None`` such an index
    loads onto the default device and needs
    :func:`raft_tpu.comms.mnmg_ivf.place_index` before searching."""
    with np.load(path) as npz:
        header = json.loads(bytes(npz["__header__"]).decode("utf-8"))
        errors.expects(
            header.get("version") == _VERSION,
            "load_index: version %s unsupported (expected %d)",
            header.get("version"), _VERSION,
        )
        if header.get("type") not in _TYPES:
            _register_sharded()
        errors.expects(
            header.get("type") in _TYPES,
            "load_index: unknown index type %r", header.get("type"),
        )
        placer = _default_placer
        if comms is not None and header["type"] in (
            "mnmg_ivf_pq", "mnmg_ivf_flat",
        ):
            import jax

            from raft_tpu.comms.mnmg_ivf import (
                _SHARDED_FIELDS, field_sharding,
            )

            def placer(name, arr):
                # mirror place_index's rank-count guard: a mismatched
                # mesh whose size divides the slab axis would otherwise
                # place silently and drop shards inside the search
                errors.expects(
                    name not in _SHARDED_FIELDS
                    or arr.shape[0] == comms.size,
                    "load_index: sharded index built for %d ranks, "
                    "mesh has %d", arr.shape[0], comms.size,
                )
                return jax.device_put(
                    arr, field_sharding(comms, name, arr.ndim)
                )
        return _rebuild(
            _TYPES[header["type"]], "", npz, header["static"], placer
        )
