"""Index serialization — save/load for every ANN index and the sparse
column-blocked index.

The reference (~22.06) keeps its FAISS-backed indexes in memory only
(ann_common.h — no serialization in this version); build cost at scale
makes persistence a practical necessity, so raft_tpu provides it
natively: one ``.npz`` per index, arrays + a small JSON header carrying
the static fields. Loading returns device-resident pytrees.

Format (v4): numpy ``.npz`` with keys ``__header__`` (JSON: index type,
version, static fields, integrity manifest) and one entry per array
leaf. Portable across hosts; no pickle. The integrity manifest stamps
each array's CRC32/shape/dtype at save time; ``load_index`` verifies
every array against it and raises a structured
:class:`raft_tpu.errors.CorruptIndexError` NAMING the damaged field
instead of returning garbage — at serving scale a checkpoint that sat
on disk through a torn write or bit-rot must fail loudly at load, not
as silently wrong neighbors (docs/robustness.md "Checkpoint
integrity"). v3 adds the sharded indexes' optional two-level coarse
quantizer (:class:`raft_tpu.spatial.ann.common.CoarseIndex`, nested
under ``coarse.*`` keys and CRC-manifested like every other array);
v4 adds the mutation tier (a
:class:`raft_tpu.spatial.ann.mutation.MutableIndex` payload — delta
segments, tombstone mask, id map; docs/mutation.md "Checkpoint v4");
v5 adds the graph-ANN index (a
:class:`raft_tpu.spatial.ann.graph.GraphIndex` payload with its nested
``GraphStorage`` adjacency; docs/graph_ann.md).
Older files still load (``coarse`` comes back ``None`` from v2/v1),
the writer stamps the LOWEST version representing the payload, and a
FUTURE version is rejected with a ``CorruptIndexError`` naming it — a
rolled-back reader must never fill a newer checkpoint's unknown fields
from missing-key defaults. Incremental (dirty-list) mutation
checkpoints ride next to this format in
:func:`raft_tpu.spatial.ann.mutation.save_delta_checkpoint`.
"""

from __future__ import annotations

import dataclasses
import json
import zlib
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import errors
from raft_tpu.spatial.ann.common import CoarseIndex, ListStorage
from raft_tpu.spatial.ann.graph import GraphIndex, GraphStorage
from raft_tpu.spatial.ann.ivf_flat import IVFFlatIndex
from raft_tpu.spatial.ann.ivf_pq import IVFPQIndex
from raft_tpu.spatial.ann.ivf_sq import IVFSQIndex
from raft_tpu.sparse.distance import SparseColBlockIndex

__all__ = ["save_index", "load_index"]

_VERSION = 5
# v1 = no integrity manifest (read-compat: loads without verification);
# v2 = manifest but no two-level coarse quantizer (loads, coarse=None);
# v3 = + coarse quantizer; v4 = + mutation tier (a MutableIndex payload
# with DeltaStore segments — spatial/ann/mutation.py); v5 = + graph-ANN
# index (a GraphIndex payload with nested GraphStorage —
# spatial/ann/graph.py)
_READABLE_VERSIONS = (1, 2, 3, 4, 5)

_TYPES = {
    "ivf_flat": IVFFlatIndex,
    "ivf_pq": IVFPQIndex,
    "ivf_sq": IVFSQIndex,
    "sparse_colblock": SparseColBlockIndex,
    "graph": GraphIndex,
}


def _register_sharded() -> None:
    # lazy: comms imports spatial.ann, so a top-level import here would
    # be circular. The sharded index loads onto the default device;
    # re-place onto a mesh with comms.mnmg_ivf.place_index before search.
    if "mnmg_ivf_pq" not in _TYPES:
        from raft_tpu.comms.mnmg_ivf import MnmgIVFPQIndex
        from raft_tpu.comms.mnmg_ivf_flat import (
            MnmgIVFFlatIndex,
            MnmgIVFSQIndex,
        )

        _TYPES["mnmg_ivf_pq"] = MnmgIVFPQIndex
        _NAMES[MnmgIVFPQIndex] = "mnmg_ivf_pq"
        _TYPES["mnmg_ivf_flat"] = MnmgIVFFlatIndex
        _NAMES[MnmgIVFFlatIndex] = "mnmg_ivf_flat"
        _TYPES["mnmg_ivf_sq"] = MnmgIVFSQIndex
        _NAMES[MnmgIVFSQIndex] = "mnmg_ivf_sq"


def _register_mutable() -> None:
    # lazy: mutation.py imports the engine modules; registering at
    # module load would lengthen every import chain for a tier most
    # processes never touch
    if "mutable_ivf" not in _TYPES:
        from raft_tpu.spatial.ann.mutation import DeltaStore, MutableIndex

        _TYPES["mutable_ivf"] = MutableIndex
        _NAMES[MutableIndex] = "mutable_ivf"
        _NESTED["DeltaStore"] = DeltaStore
        # the wrapped engine index nests inside the mutable payload
        _NESTED["IVFFlatIndex"] = IVFFlatIndex
        _NESTED["IVFPQIndex"] = IVFPQIndex
        _NESTED["IVFSQIndex"] = IVFSQIndex


_NAMES = {v: k for k, v in _TYPES.items()}
# nested dataclasses that may appear inside an index payload
_NESTED = {
    "ListStorage": ListStorage,
    "CoarseIndex": CoarseIndex,
    "GraphStorage": GraphStorage,
}


def _flatten(obj: Any, prefix: str, arrays: dict, static: dict) -> None:
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        key = f"{prefix}{f.name}"
        if v is None:
            static[key] = None
        elif dataclasses.is_dataclass(v):
            errors.expects(
                _NESTED.get(type(v).__name__) is type(v),
                "save_index: nested dataclass %s is not registered in "
                "serialize._NESTED (it could not be rebuilt at load time)",
                type(v).__name__,
            )
            static[key] = {"__nested__": type(v).__name__}
            _flatten(v, key + ".", arrays, static)
        elif isinstance(v, (jax.Array, np.ndarray)):
            arr = np.asarray(v)
            if arr.dtype.kind == "V":
                # ml_dtypes extension floats (bfloat16 etc.): np.savez
                # would store raw void bytes that cannot round-trip; save
                # the bits with the dtype name tagged in the header
                static[key + ".__dtype__"] = arr.dtype.name
                arr = arr.view(np.uint16 if arr.dtype.itemsize == 2
                               else np.uint8)
            arrays[key] = arr
        else:
            static[key] = v if not isinstance(v, tuple) else list(v)


def _array_crc(arr: np.ndarray) -> int:
    """CRC32 of the array's raw bytes (C order). tobytes() transiently
    copies the largest slab; acceptable next to the archive write that
    follows it."""
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def save_index(index, path) -> None:
    """Serialize an ANN / sparse index to ``path`` (``.npz``; the header
    carries a per-array CRC32/shape/dtype integrity manifest that
    :func:`load_index` verifies). The stamped version is the LOWEST one
    that can represent the payload — v5 only for a graph-ANN payload,
    v4 only for a mutation-tier payload, v3 only when a two-level
    coarse quantizer is attached, v2 otherwise — so checkpoints without
    the new fields stay loadable by previous releases
    (rollback/mixed-version fleets)."""
    if type(index) not in _NAMES:
        _register_sharded()
        _register_mutable()
    errors.expects(
        type(index) in _NAMES,
        "save_index: unsupported index type %s (supported: %s)",
        type(index).__name__, sorted(_TYPES),
    )
    arrays: dict = {}
    static: dict = {}
    _flatten(index, "", arrays, static)
    # lowest version representing the payload (rollback/mixed-version
    # fleets): v5 only for a graph payload, v4 only for a mutation-tier
    # payload, v3 only when a coarse quantizer is attached, v2 otherwise
    nested = {
        v.get("__nested__")
        for v in static.values() if isinstance(v, dict)
    }
    version = (
        5 if "GraphStorage" in nested
        else 4 if "DeltaStore" in nested
        else 3 if "CoarseIndex" in nested
        else 2
    )
    # manifest over the bytes actually archived (post bfloat16->uint16
    # view), so verification needs no dtype knowledge to run
    integrity = {
        key: {
            "crc32": _array_crc(arr),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
        for key, arr in arrays.items()
    }
    header = {
        "type": _NAMES[type(index)],
        "version": version,
        "static": static,
        "integrity": integrity,
    }
    # write straight to the file object: np.savez accepts one (and then
    # does not append ".npz" to the name), and the archive is not
    # duplicated in RAM — index payloads run to hundreds of MB
    with open(path, "wb") as f:
        np.savez(
            f,
            __header__=np.frombuffer(
                json.dumps(header).encode("utf-8"), dtype=np.uint8
            ),
            **arrays,
        )


def _default_placer(name, arr):
    return jnp.asarray(arr)


class _MeshMismatch(Exception):
    """Internal: sharded archive's rank count != target mesh size —
    load_index falls back to a host-side load + reshard."""


class _VerifiedArchive:
    """npz access with integrity verification per array read.

    Every read is checked two ways: container-level damage (a zip member
    that no longer decodes — zipfile CRC failures, torn npy headers)
    converts to :class:`CorruptIndexError` naming the field, and for
    format v2+ the decoded bytes are verified against the header's
    CRC32/shape/dtype manifest — which catches SILENT corruption the
    container cannot (a rewritten archive whose zip CRCs match the
    damaged payload; see raft_tpu.testing.faults.corrupt_bytes).
    """

    def __init__(self, npz, manifest: Optional[dict]):
        self._npz = npz
        self._manifest = manifest

    def __contains__(self, key: str) -> bool:
        return key in self._npz

    def __getitem__(self, key: str) -> np.ndarray:
        try:
            arr = self._npz[key]
        except Exception as e:  # zipfile.BadZipFile, ValueError, OSError
            raise errors.CorruptIndexError(
                f"load_index: array {key!r} unreadable ({e})", field=key
            ) from e
        if self._manifest is not None:
            want = self._manifest.get(key)
            if want is None:
                raise errors.CorruptIndexError(
                    f"load_index: array {key!r} missing from the "
                    "integrity manifest (truncated or foreign header)",
                    field=key,
                )
            if (
                list(arr.shape) != want["shape"]
                or str(arr.dtype) != want["dtype"]
            ):
                raise errors.CorruptIndexError(
                    f"load_index: array {key!r} is {arr.dtype}{arr.shape}, "
                    f"manifest says {want['dtype']}{tuple(want['shape'])}",
                    field=key,
                )
            if _array_crc(arr) != want["crc32"]:
                raise errors.CorruptIndexError(
                    f"load_index: array {key!r} failed CRC32 verification "
                    "— the checkpoint is corrupt; rebuild or restore from "
                    "a replica",
                    field=key,
                )
        return arr


def _rebuild(cls, prefix: str, npz, static: dict, placer=_default_placer):
    kwargs = {}
    for f in dataclasses.fields(cls):
        key = f"{prefix}{f.name}"
        if key in npz:
            arr = npz[key]
            tagged = static.get(key + ".__dtype__")
            if tagged is not None:
                import ml_dtypes

                arr = arr.view(np.dtype(getattr(ml_dtypes, tagged)))
            kwargs[f.name] = placer(f.name, arr)
        else:
            if key not in static:
                # field absent from the archive entirely: a checkpoint
                # written before the field existed (e.g. the sharded
                # indexes' replication statics) — leave it to the
                # dataclass default rather than forcing None
                continue
            v = static[key]
            if isinstance(v, dict) and "__nested__" in v:
                errors.expects(
                    v["__nested__"] in _NESTED,
                    "load_index: unknown nested type %r", v["__nested__"],
                )
                nested_cls = _NESTED[v["__nested__"]]
                kwargs[f.name] = _rebuild(
                    nested_cls, key + ".", npz, static, placer
                )
            elif isinstance(v, list):
                kwargs[f.name] = tuple(v)
            else:
                kwargs[f.name] = v
    return cls(**kwargs)


def load_index(path, comms=None):
    """Load an index saved by :func:`save_index`, verifying the v2+
    integrity manifest; arrays land on the default device. Damage — an
    unreadable archive/header, a field that fails its CRC32, a
    shape/dtype that disagrees with the manifest — raises
    :class:`raft_tpu.errors.CorruptIndexError` naming the field (v1
    files predate the manifest and load unverified).

    ``comms``: for a sharded ``mnmg_ivf_pq`` index, stream each slab
    DIRECTLY to its mesh placement as it is read — the 100M ``store_raw``
    regime's raw-vector slabs exceed one chip's HBM, so materializing on
    the default device first (then :func:`place_index`) would OOM exactly
    where the sharded index matters. With ``comms=None`` such an index
    loads onto the default device and needs
    :func:`raft_tpu.comms.mnmg_ivf.place_index` before searching. A
    sharded index built for a DIFFERENT rank count than ``comms`` loads
    host-side and re-partitions via the ``place_index`` re-shard path —
    the recovery story after losing a rank (docs/robustness.md); note
    that path does materialize the slabs host-side first.
    """
    try:
        return _load(path, comms)
    except _MeshMismatch:
        from raft_tpu.comms.mnmg_ivf import place_index

        return place_index(comms, _load(path, None))


def _load(path, comms):
    try:
        npz_file = np.load(path)
    except Exception as e:  # not a zip / truncated central directory
        raise errors.CorruptIndexError(
            f"load_index: archive unreadable ({e})", field="__header__"
        ) from e
    with npz_file as npz:
        try:
            header = json.loads(bytes(npz["__header__"]).decode("utf-8"))
        except Exception as e:  # missing key, bad zip member, bad JSON
            raise errors.CorruptIndexError(
                f"load_index: header unreadable ({e}) — not a raft_tpu "
                "index archive, or one damaged beyond recovery",
                field="__header__",
            ) from e
        if header.get("version") not in _READABLE_VERSIONS:
            # a structured, version-NAMING rejection: an unknown FUTURE
            # version must fail loudly here — falling through would read
            # fields this release has never heard of as missing-key
            # defaults and serve silently wrong state (the v3-reader-
            # meets-v4-checkpoint rollback scenario)
            raise errors.CorruptIndexError(
                f"load_index: format version {header.get('version')!r} "
                f"is not readable by this release (readable: "
                f"{list(_READABLE_VERSIONS)}) — written by a newer "
                "release; upgrade before restoring this checkpoint",
                field="__header__",
            )
        if header.get("type") not in _TYPES:
            _register_sharded()
            _register_mutable()
        errors.expects(
            header.get("type") in _TYPES,
            "load_index: unknown index type %r", header.get("type"),
        )
        archive = _VerifiedArchive(npz, header.get("integrity"))
        placer = _default_placer
        if comms is not None and header["type"] in (
            "mnmg_ivf_pq", "mnmg_ivf_flat",
        ):
            import jax

            from raft_tpu.comms.mnmg_ivf import (
                _SHARDED_FIELDS, field_sharding,
            )

            # rank-count check BEFORE any array is read: a mismatch
            # must not first decompress + CRC-verify a multi-GB archive
            # only to restart the whole load on the fallback path. v2
            # headers carry every shape in the manifest; v1 pays one
            # small sorted_ids read.
            man = header.get("integrity") or {}
            entry = man.get("sorted_ids")
            n_ranks = (
                int(entry["shape"][0]) if entry is not None
                else int(archive["sorted_ids"].shape[0])
            )
            if n_ranks != comms.size:
                raise _MeshMismatch(
                    f"{n_ranks} ranks vs mesh {comms.size}"
                )

            def placer(name, arr):
                # per-array guard kept as defense in depth: an archive
                # whose slab fields disagree with the manifest would
                # otherwise place silently and drop shards in the search
                if name in _SHARDED_FIELDS and arr.shape[0] != comms.size:
                    raise _MeshMismatch(
                        f"{arr.shape[0]} ranks vs mesh {comms.size}"
                    )
                return jax.device_put(
                    arr, field_sharding(comms, name, arr.ndim)
                )
        return _rebuild(
            _TYPES[header["type"]], "", archive, header["static"], placer
        )
