"""ANN indexes — first-class TPU implementations (the reference wraps FAISS,
cpp/include/raft/spatial/knn/detail/ann_quantized_faiss.cuh; SURVEY.md §2
#19-20 mandates native IVF here): IVF-Flat, IVF-PQ, IVF-SQ, random ball
cover, all on a shared sorted-by-list storage layout — plus the
fixed-degree graph-ANN index (graph.py, CAGRA-style) for the
low-latency regime.
"""

from raft_tpu.spatial.ann.common import ListStorage, build_list_storage
from raft_tpu.spatial.ann.ivf_flat import (
    IVFFlatParams,
    IVFFlatIndex,
    ivf_flat_build,
    ivf_flat_search,
    ivf_flat_search_grouped,
)
from raft_tpu.spatial.ann.ivf_pq import (
    IVFPQParams,
    IVFPQIndex,
    ivf_pq_build,
    ivf_pq_search,
    ivf_pq_search_grouped,
)
from raft_tpu.spatial.ann.ivf_sq import (
    IVFSQParams,
    IVFSQIndex,
    ivf_sq_build,
    ivf_sq_search,
    ivf_sq_search_grouped,
)
from raft_tpu.spatial.ann.approx import (
    approx_knn_build_index, approx_knn_search,
)
from raft_tpu.spatial.ann.graph import (
    GraphIndex,
    GraphParams,
    GraphStorage,
    graph_build,
    graph_delete,
    graph_live_mask,
    graph_restore,
    graph_search,
)
from raft_tpu.spatial.ann.serialize import save_index, load_index
from raft_tpu.spatial.ann.mutation import (
    BackgroundCompactor,
    CompactionPolicy,
    DeltaStore,
    MutableIndex,
    apply_delta_checkpoint,
    compact,
    compaction_stats,
    delete,
    mutable_search,
    mutable_warmup,
    probe_overlap,
    save_delta_checkpoint,
    upsert,
    wrap_mutable,
)
from raft_tpu.spatial.ann.ball_cover import (
    BallCoverIndex,
    rbc_build_index,
    rbc_knn_query,
    rbc_all_knn_query,
)

__all__ = [
    "ListStorage", "build_list_storage",
    "IVFFlatParams", "IVFFlatIndex", "ivf_flat_build", "ivf_flat_search",
    "ivf_flat_search_grouped",
    "IVFPQParams", "IVFPQIndex", "ivf_pq_build", "ivf_pq_search",
    "ivf_pq_search_grouped",
    "IVFSQParams", "IVFSQIndex", "ivf_sq_build", "ivf_sq_search",
    "ivf_sq_search_grouped",
    "BallCoverIndex", "rbc_build_index", "rbc_knn_query", "rbc_all_knn_query",
    "GraphParams", "GraphStorage", "GraphIndex", "graph_build",
    "graph_search", "graph_live_mask", "graph_delete", "graph_restore",
    "save_index", "load_index",
    "approx_knn_build_index", "approx_knn_search",
    "MutableIndex", "DeltaStore", "wrap_mutable", "upsert", "delete",
    "mutable_search", "mutable_warmup", "compact", "compaction_stats",
    "CompactionPolicy", "BackgroundCompactor", "probe_overlap",
    "save_delta_checkpoint", "apply_delta_checkpoint",
]
