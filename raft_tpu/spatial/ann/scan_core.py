"""Shared scan-kernel core — the one implementation of the fused
distance+selection recipe every Pallas scan engine in this codebase is
built from (ISSUE 11; ROADMAP item 2 "one scan-kernel framework").

PRs 6 and 10 grew two sibling engines (``pq_kernel``, ``flat_kernel``)
that copy-pasted the same five pieces; this module extracts them as the
single authority, and ``sq_kernel`` (the int8 IVF-SQ engine) plus the
kernelized two-level coarse probe (``common.two_level_probe``) are built
directly on it:

* **The VMEM step-budget tile planner** (:func:`plan_l_tile`): the
  largest lane-aligned slab/code-tile width whose per-grid-step working
  set fits the VMEM budget, halving from the profile's start width.
  Each engine supplies only its *byte model* (a ``step_bytes(q_pad,
  l_tile)`` callable) — the shrink loop, the lane re-alignment on halve
  (the pq-kernel review regression), and the None-when-nothing-fits
  contract live here once.
* **Tile profiles** (:func:`tile_profile`): ``"throughput"`` starts the
  plan at 512 rows (the PR 6/10 behavior, bit-for-bit); ``"latency"``
  starts at 1024 for the qcap-1/8 serving shapes — a tiny query block
  leaves the VMEM budget almost untouched, so a wider tile halves the
  grid-step count (and its per-step overhead) exactly where the
  open-loop p99 regime lives. The grouped engines auto-select the
  profile from the static qcap, so the latency regime stops paying
  throughput-shape tiles (docs/ivf_scale.md "One scan-kernel core").
* **Query padding** (:func:`pad_queries`): THE bf16-sublane rounding of
  a query-slot count. Every engine's ``*_supported`` predicate and its
  serving plan call this one function, so a resolver's approval and the
  plan it approved can never round differently.
* **The [lo, hi) slab-range masking idiom** (:func:`mask_slab_range` in
  kernel bodies, :func:`mask_subchunk_min_lax` in the op-for-op lax
  mirrors): rows outside a list's valid range score a finite BIG —
  never +inf (inf - inf NaNs on the VPU) — so masked sub-chunks order
  last in every pooled selection.
* **The 8-row sub-chunk-min select** (:func:`subchunk_min` +
  :func:`subchunk_scan`): the tile is min-reduced over
  :data:`SUBCHUNK`-row granules in the same kernel, so only the
  (Q, Lpad/8) minima ever reach HBM — the fused_knn cover argument at
  8-row granularity makes the downstream rerank pool a superset of the
  row-granular top-c (each engine's module docstring carries its own
  exactness contract).
* **The pinned-bitwise lax-mirror discipline**: every engine ships an
  op-for-op XLA mirror built from the same masking+reduce pieces
  (:func:`mask_subchunk_min_lax`), and the tier-1 suite pins the
  interpret-mode kernel against it bitwise — the mirror is also the
  fallback wherever ``pallas_call`` is unavailable.

:func:`subchunk_scan` is the shared ``pallas_call`` driver: an engine
provides its distance computation for ONE (list, tile) step — the MXU
contraction plus whatever VPU preprocessing its storage format needs
(one-hot expansion for PQ codes, affine int8 dequant for SQ) — and the
driver owns the grid, the block specs, the scalar-prefetched bounds, the
masking, and the sub-chunk reduce.

Importing this module never builds a TPU program; ``JAX_PLATFORMS=cpu``
callers reach it only through an engine's explicit ``use_pallas`` opt-in
(the engines' CPU-subprocess never-imports tests pin this transitively).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "BIG", "LANE", "Q_GRANULE", "SUBCHUNK", "VMEM_BUDGET",
    "l2_gram_tile", "mask_slab_range", "mask_subchunk_min_lax",
    "pad_queries", "plan_l_tile", "round_up", "subchunk_min",
    "subchunk_scan", "tile_profile",
]

SUBCHUNK = 8      # rows per selection granule (f32 sublane width)
LANE = 128        # slab/code-tile rows must be lane-aligned
Q_GRANULE = 16    # bf16 sublane tile: the query axis pads to this

# Masked rows score a finite BIG (never +inf: inf - inf NaNs on the VPU,
# and pooled selection must still order masked sub-chunks last).
BIG = 1e30

# VMEM working-set budget for one grid step, double-buffering headroom
# included. ~16 MB/core total.
VMEM_BUDGET = 10 * 2**20

# Tile-plan start widths per profile: "throughput" is the PR 6/10
# default; "latency" doubles it for tiny (qcap-1/8) query blocks, whose
# step working set is planner-dominated by the tile itself — fewer,
# wider grid steps at the same VMEM budget.
_PROFILE_START = {"throughput": 512, "latency": 1024}

# qcap at or below which the grouped engines auto-select the latency
# profile (the open-loop serving buckets: qcap 1..8)
_LATENCY_QCAP = 8


def round_up(a: int, b: int) -> int:
    return -(-a // b) * b


def pad_queries(qcap: int) -> int:
    """Round a query-slot count up to the kernels' bf16 sublane granule
    — THE q_pad. Every engine's ``*_supported`` predicate and its
    grouped serving path call this, so a resolver's approval and the
    serving plan can never round differently."""
    return round_up(max(qcap, 1), Q_GRANULE)


def tile_profile(qcap: int) -> str:
    """The tile-plan profile a grouped engine should use at a static
    qcap: ``"latency"`` for the qcap-1/8 open-loop serving shapes (the
    planner starts at a 1024-row tile — half the grid steps at a VMEM
    cost the tiny query block easily affords), ``"throughput"``
    otherwise (the PR 6/10 plan, unchanged). Derived from the SAME
    static qcap the warm-up resolves (``common.static_qcap``), so the
    profile is a trace-time constant and can never flip at serve
    time."""
    return "latency" if qcap <= _LATENCY_QCAP else "throughput"


def plan_l_tile(step_bytes: Callable[[int, int], int], q_pad: int,
                l_tile: Optional[int] = None,
                profile: str = "throughput") -> Optional[int]:
    """Largest tile width (a multiple of :data:`LANE`, at most the
    profile's start width / the explicit ``l_tile`` cap) whose per-step
    working set — ``step_bytes(q_pad, lt)``, the engine's byte model —
    fits :data:`VMEM_BUDGET`; None when even a 128-row tile does not
    fit (the caller falls back to its XLA scan).

    The ONE shared planner (ISSUE 11 acceptance): engines keep their
    byte models, this keeps the shrink loop — halving re-aligned down
    to the lane width, so a non-128-multiple start like 384 can never
    yield an unusable 192-row tile (the pq_kernel review regression,
    owned here once)."""
    start = _PROFILE_START[profile]
    lt = max(LANE, round_up(min(start if l_tile is None else l_tile,
                                start), LANE))
    while lt > LANE and step_bytes(q_pad, lt) > VMEM_BUDGET:
        lt = max(LANE, (lt // 2) // LANE * LANE)
    if step_bytes(q_pad, lt) > VMEM_BUDGET:
        return None
    return lt


def l2_gram_tile(qv, y):
    """THE flat-family distance body: ``‖q‖² + ‖y‖² − 2 qᵀy`` for one
    (..., Q, d) × (..., d, Lt) step — bf16 operands on the MXU with f32
    accumulation, norm terms in f32 on the VPU. Shared by the flat and
    SQ engines' in-kernel ``tile_fn``s (2-d operands) AND their batched
    lax mirrors (3-d operands), so the two engines — and each engine's
    kernel/mirror pair — can never drift by an op."""
    nb = qv.ndim - 2
    batch = tuple(range(nb))
    dots = jax.lax.dot_general(
        qv, y, (((qv.ndim - 1,), (y.ndim - 2,)), (batch, batch)),
        preferred_element_type=jnp.float32,
    )
    qf = qv.astype(jnp.float32)
    qn = jnp.sum(qf * qf, axis=-1)[..., :, None]
    yf = y.astype(jnp.float32)
    yn = jnp.sum(yf * yf, axis=-2)[..., None, :]
    return qn + yn - 2.0 * dots


def mask_slab_range(d2, col0, lo, hi, big: float = BIG):
    """In-kernel [lo, hi) slab-range masking: ``d2`` is one (Q, Lt)
    distance tile whose column 0 sits at absolute slab column ``col0``
    (= tile index x l_tile); rows outside the list's valid range score
    the finite ``big``."""
    col = col0 + jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1)
    return jnp.where((col >= lo) & (col < hi), d2, jnp.float32(big))


def subchunk_min(d2, sub: int = SUBCHUNK):
    """Min-reduce one (Q, Lt) tile over ``sub``-row granules — the only
    thing a scan kernel writes out: (Q, Lt/sub) minima."""
    q_pad, lt = d2.shape
    return jnp.min(d2.reshape(q_pad, lt // sub, sub), axis=2)


def mask_subchunk_min_lax(d2, bounds, sub: int = SUBCHUNK,
                          big: float = BIG):
    """The lax-mirror half of the masking+reduce discipline: the same
    [lo, hi) masking and sub-chunk min as the kernel, over the full
    batched (LB, Q, Lpad) distance tile — every engine's op-for-op XLA
    mirror ends with this call, so the piece the tier-1 suite pins the
    interpret-mode kernels against bitwise is shared too."""
    lb, q_pad, l_pad = d2.shape
    col = jnp.arange(l_pad, dtype=jnp.int32)[None, None, :]
    lo = bounds[:, 0][:, None, None]
    hi = bounds[:, 1][:, None, None]
    d2 = jnp.where((col >= lo) & (col < hi), d2, jnp.float32(big))
    return jnp.min(d2.reshape(lb, q_pad, l_pad // sub, sub), axis=3)


def validate_scan_shapes(name: str, q_pad: int, l_pad: int, l_tile: int):
    """The shared shape preconditions of every sub-chunk scan entry
    point (Q on the bf16 sublane granule, Lpad on the tile, the tile on
    the lane) — callers pad; the message leads with the engine's entry
    name so a violation reads like the engine raised it."""
    if q_pad % Q_GRANULE or l_pad % l_tile or l_tile % LANE:
        raise ValueError(
            f"{name}: Q={q_pad} must be a multiple of "
            f"{Q_GRANULE} and Lpad={l_pad} a multiple of "
            f"l_tile={l_tile} (itself a multiple of {LANE})"
        )


def subchunk_scan(tile_fn, bounds, resident: Sequence, tiled: Sequence,
                  broadcast: Sequence = (), *, l_tile: int,
                  interpret: bool, sub: int = SUBCHUNK,
                  name: str = "subchunk_scan"):
    """The shared ``pallas_call`` driver of every scan engine: a
    (list b, tile t) grid where

    * ``bounds`` (LB, 2) int32 rides the scalar-prefetch slot (the
      per-list [lo, hi) valid range);
    * each ``resident`` array (LB, A, B) is loaded once per list and stays
      VMEM-resident across its tiles (query rows, ADC LUTs);
    * each ``tiled`` array (LB, A, Lpad) streams as (A, l_tile) blocks
      (slab rows, code columns);
    * each ``broadcast`` array is small, whole-array resident across the
      grid (codebook index columns, dequant scale/offset);
    * ``tile_fn(resident_blocks, tiled_blocks, broadcast_blocks)``
      returns the (Q, l_tile) f32 distance tile for one step — the ONLY
      thing an engine writes; the driver owns the slab-range masking and
      the sub-chunk min, and nothing but the (LB, Q, Lpad/sub) minima
      ever reaches HBM.

    The q_pad is taken from ``resident[0].shape[1]`` (every engine's
    first resident operand carries the query axis)."""
    lb = tiled[0].shape[0]
    l_pad = tiled[0].shape[2]
    q_pad = resident[0].shape[1]
    validate_scan_shapes(name, q_pad, l_pad, l_tile)
    n_res, n_til = len(resident), len(tiled)

    def kernel(bounds_ref, *refs):
        b = pl.program_id(0)
        t = pl.program_id(1)
        res = [refs[i][0] for i in range(n_res)]
        til = [refs[n_res + i][0] for i in range(n_til)]
        bc = [refs[n_res + n_til + i][...] for i in range(len(broadcast))]
        o_ref = refs[-1]
        d2 = tile_fn(res, til, bc)
        d2 = mask_slab_range(d2, t * l_tile, bounds_ref[b, 0],
                             bounds_ref[b, 1])
        o_ref[0] = subchunk_min(d2, sub)

    def _res_spec(a):
        nd = a.ndim
        return pl.BlockSpec(
            (1,) + a.shape[1:],
            lambda b, t, bnd, _nd=nd: (b,) + (0,) * (_nd - 1),
        )

    def _til_spec(a):
        return pl.BlockSpec(
            (1, a.shape[1], l_tile), lambda b, t, bnd: (b, 0, t)
        )

    def _bc_spec(a):
        nd = a.ndim
        return pl.BlockSpec(
            a.shape, lambda b, t, bnd, _nd=nd: (0,) * _nd
        )

    in_specs = (
        [_res_spec(a) for a in resident]
        + [_til_spec(a) for a in tiled]
        + [_bc_spec(a) for a in broadcast]
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(lb, l_pad // l_tile),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, q_pad, l_tile // sub),
                                   lambda b, t, bnd: (b, 0, t)),
        ),
        out_shape=jax.ShapeDtypeStruct(
            (lb, q_pad, l_pad // sub), jnp.float32
        ),
        interpret=interpret,
    )(bounds.astype(jnp.int32), *resident, *tiled, *broadcast)
    return out
