"""IVF-Flat ANN index — first-class TPU implementation (the reference wraps
FAISS GpuIndexIVFFlat, cpp/include/raft/spatial/knn/detail/
ann_quantized_faiss.cuh:115-206 ``approx_knn_build_index``/``approx_knn_search``
with ``IVFFlatParam`` ann_common.h; here native, per the north star).

Build: k-means coarse quantizer → vectors permuted into contiguous lists
(:mod:`common`). Search: (1) one MXU gram scores queries × centroids,
(2) top-nprobe lists per query, (3) rectangular gather of the padded probed
lists, (4) batched MXU distance on the candidates, (5) ``lax.top_k``.
Everything static-shape; sentinel slots score +inf.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.spatial.ann.common import ListStorage, build_list_storage

__all__ = ["IVFFlatParams", "IVFFlatIndex", "ivf_flat_build", "ivf_flat_search"]


@dataclasses.dataclass(frozen=True)
class IVFFlatParams:
    """Analog of IVFFlatParam (reference ann_common.h: nlist, nprobe)."""

    n_lists: int = 64
    kmeans_n_iters: int = 20
    seed: int = 0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class IVFFlatIndex:
    centroids: jax.Array      # (n_lists, d)
    data_sorted: jax.Array    # (n + 1, d) — last row is the sentinel (zeros)
    storage: ListStorage
    metric: str = dataclasses.field(metadata=dict(static=True))


def ivf_flat_build(x, params: IVFFlatParams = IVFFlatParams(), *,
                   metric: str = "l2") -> IVFFlatIndex:
    """Build (reference approx_knn_build_index:115 — FAISS train+add;
    here kmeans + list permutation)."""
    x = jnp.asarray(x)
    out = kmeans_fit(
        x,
        KMeansParams(
            n_clusters=params.n_lists,
            max_iter=params.kmeans_n_iters,
            seed=params.seed,
        ),
    )
    storage = build_list_storage(np.asarray(out.labels), params.n_lists)
    data_sorted = jnp.concatenate(
        [x[storage.sorted_ids], jnp.zeros((1, x.shape[1]), x.dtype)]
    )
    return IVFFlatIndex(out.centroids, data_sorted, storage, metric)


@functools.partial(jax.jit, static_argnames=("k", "n_probes"))
def ivf_flat_search(
    index: IVFFlatIndex, queries, k: int, *, n_probes: int = 8
) -> Tuple[jax.Array, jax.Array]:
    """Search (reference approx_knn_search:169). Returns (dists, ids) with
    original row ids; L2 metric family (squared distances like FAISS's
    default compute, sqrt applied for metric='l2')."""
    q = jnp.asarray(queries)
    nq, d = q.shape
    if k > n_probes * index.storage.max_list:
        raise ValueError(
            f"k={k} exceeds the candidate pool "
            f"(n_probes*max_list = {n_probes * index.storage.max_list}); "
            "raise n_probes"
        )
    f32 = jnp.float32
    qf = q.astype(f32)

    # (1) coarse scoring on the MXU
    cents = index.centroids.astype(f32)
    qn = jnp.sum(qf * qf, axis=1)
    cn = jnp.sum(cents * cents, axis=1)
    gc = lax.dot_general(qf, cents, (((1,), (1,)), ((), ())),
                         preferred_element_type=f32)
    cd = qn[:, None] + cn[None, :] - 2.0 * gc
    # (2) probe the nprobe closest lists
    _, probes = lax.top_k(-cd, n_probes)                    # (nq, p)

    # (3) rectangular gather of padded probed lists
    cand_pos = index.storage.list_index[probes]             # (nq, p, L)
    cand_pos = cand_pos.reshape(nq, -1)                     # (nq, C)
    cand_vecs = index.data_sorted[cand_pos].astype(f32)     # (nq, C, d)
    valid = cand_pos < index.storage.n

    # (4) batched candidate scoring: d2 = |q|² + |c|² - 2 q·c
    cvn = jnp.sum(cand_vecs * cand_vecs, axis=2)
    dots = jnp.einsum("qcd,qd->qc", cand_vecs, qf,
                      preferred_element_type=f32)
    d2 = qn[:, None] + cvn - 2.0 * dots
    d2 = jnp.where(valid, d2, jnp.inf)

    # (5) select
    vals, pos = lax.top_k(-d2, k)
    vals = -vals
    ids = index.storage.sorted_ids[
        jnp.clip(jnp.take_along_axis(cand_pos, pos, axis=1), 0,
                 index.storage.n - 1)
    ]
    ids = jnp.where(jnp.isfinite(vals), ids, -1)
    if index.metric == "l2":
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    return vals, ids.astype(jnp.int32)
