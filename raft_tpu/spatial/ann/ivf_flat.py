"""IVF-Flat ANN index — first-class TPU implementation (the reference wraps
FAISS GpuIndexIVFFlat, cpp/include/raft/spatial/knn/detail/
ann_quantized_faiss.cuh:115-206 ``approx_knn_build_index``/``approx_knn_search``
with ``IVFFlatParam`` ann_common.h; here native, per the north star).

Build: k-means coarse quantizer → vectors permuted into contiguous lists
(:mod:`common`). Search: (1) one MXU gram scores queries × centroids,
(2) top-nprobe lists per query, (3) rectangular gather of the padded probed
lists, (4) batched MXU distance on the candidates, (5) ``lax.top_k``.
Everything static-shape; sentinel slots score +inf.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.spatial.ann.common import ListStorage, build_list_storage

__all__ = ["IVFFlatParams", "IVFFlatIndex", "ivf_flat_build", "ivf_flat_search"]


@dataclasses.dataclass(frozen=True)
class IVFFlatParams:
    """Analog of IVFFlatParam (reference ann_common.h: nlist, nprobe)."""

    n_lists: int = 64
    kmeans_n_iters: int = 20
    seed: int = 0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class IVFFlatIndex:
    centroids: jax.Array      # (n_lists, d)
    data_sorted: jax.Array    # (n + 1, d) — last row is the sentinel (zeros)
    storage: ListStorage
    metric: str = dataclasses.field(metadata=dict(static=True))


def ivf_flat_build(x, params: IVFFlatParams = IVFFlatParams(), *,
                   metric: str = "l2") -> IVFFlatIndex:
    """Build (reference approx_knn_build_index:115 — FAISS train+add;
    here kmeans + list permutation)."""
    x = jnp.asarray(x)
    out = kmeans_fit(
        x,
        KMeansParams(
            n_clusters=params.n_lists,
            max_iter=params.kmeans_n_iters,
            seed=params.seed,
        ),
    )
    storage = build_list_storage(np.asarray(out.labels), params.n_lists)
    data_sorted = jnp.concatenate(
        [x[storage.sorted_ids], jnp.zeros((1, x.shape[1]), x.dtype)]
    )
    return IVFFlatIndex(out.centroids, data_sorted, storage, metric)


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "block_q"))
def ivf_flat_search(
    index: IVFFlatIndex, queries, k: int, *, n_probes: int = 8,
    block_q: int = 512,
) -> Tuple[jax.Array, jax.Array]:
    """Search (reference approx_knn_search:169). Returns (dists, ids) with
    original row ids; L2 metric family (squared distances like FAISS's
    default compute, sqrt applied for metric='l2'). Query batches are
    processed in ``block_q`` blocks to bound the candidate-gather HBM."""
    from raft_tpu.spatial.ann.common import (
        check_candidate_pool, coarse_probe, map_query_blocks,
        score_l2_candidates, select_candidates,
    )

    q = jnp.asarray(queries)
    check_candidate_pool(k, n_probes, index.storage)

    def one_block(qb):
        qf = qb.astype(jnp.float32)
        probes, _ = coarse_probe(qf, index.centroids, n_probes)
        cand_pos = index.storage.list_index[probes].reshape(qb.shape[0], -1)
        cand_vecs = index.data_sorted[cand_pos].astype(jnp.float32)
        d2 = score_l2_candidates(qf, cand_vecs, cand_pos < index.storage.n)
        return select_candidates(index.storage, cand_pos, d2, k)

    vals, ids = map_query_blocks(one_block, q, block_q)
    if index.metric == "l2":
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    return vals, ids
